"""Benchmark harness — the 5 BASELINE.json configs plus the
multi-tenant ``multistream_32g`` config (megabatch coalescer vs serial
per-stream dispatch at 32 concurrent warm streams).

Prints exactly ONE JSON line to stdout (the driver contract):
``{"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}`` where the
headline metric is the assign wall-time at the north-star scale (100k
partitions / 1k consumers, BASELINE.json:5) on the attached accelerator,
and ``vs_baseline`` is the speedup factor versus the reference algorithm —
the O(P*C) linear-min greedy loop (LagBasedPartitionAssignor.java:240-263)
— implemented as an efficient vectorized host baseline on this same
machine (the reference publishes no numbers of its own, BASELINE.md).

Everything else (per-config results, imbalance ratios, streaming p50/p95)
goes to stderr and BENCH_DETAILS.json.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def device_reachable(timeout_s: float = 240.0, attempts: int = 2) -> bool:
    """Probe the accelerator in a SUBPROCESS with a hard timeout.

    The tunneled chip can wedge such that even ``jax.devices()`` blocks
    forever (observed in practice: >550 s with no progress); a hung probe
    in-process would hang the whole benchmark and break the one-JSON-line
    driver contract.  A subprocess can be killed; in-process jax calls
    cannot.  The probe makes ``attempts`` tries — a transient relay drop
    should not condemn the whole run to the CPU number — and goes past
    ``jax.devices()`` to an actual computation + readback, since device
    discovery succeeding does not prove the transport can execute."""
    import subprocess

    code = (
        "import jax, numpy as np;"
        "jax.devices();"
        "x = jax.device_put(np.arange(8, dtype=np.int32));"
        "print(int(jax.jit(lambda v: (v + 1).sum())(x)))"
    )
    for attempt in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                timeout=timeout_s,
                capture_output=True,
            )
            if r.returncode == 0 and b"36" in r.stdout:
                return True
            log(f"bench: device probe attempt {attempt + 1} failed "
                f"(rc={r.returncode})")
        except Exception as exc:
            log(f"bench: device probe attempt {attempt + 1}: "
                f"{type(exc).__name__}")
    return False


def host_baseline_greedy(lags: np.ndarray, C: int) -> tuple[np.ndarray, float]:
    """The reference's algorithm at reference fidelity, on host: sort by lag
    desc, then per partition a linear min over consumers keyed by
    (count, total, rank) — numpy-vectorized inner scan (generous to the
    baseline vs. the JVM original).  Returns (member totals, wall ms)."""
    order = np.argsort(-lags, kind="stable")
    counts = np.zeros(C, dtype=np.int64)
    totals = np.zeros(C, dtype=np.int64)
    t0 = time.perf_counter()
    for p in order:
        # lexicographic argmin (count, total, index): indices are the
        # tiebreak via argmin's first-minimum rule on the masked pass
        min_count = counts.min()
        cand = counts == min_count
        masked = np.where(cand, totals, np.iinfo(np.int64).max)
        who = int(np.argmin(masked))
        counts[who] += 1
        totals[who] += int(lags[p])
    return totals, (time.perf_counter() - t0) * 1000.0


def rtt_floor_ms(iters: int = 6) -> float:
    """Measure the harness's device->host synchronization floor: fetching a
    freshly computed 4-byte scalar.  Through a tunneled/remote chip this can
    be tens of ms and bounds ANY implementation's end-to-end latency here;
    on a locally attached TPU it is microseconds."""
    import jax

    x = jax.device_put(np.arange(1024, dtype=np.int32))
    f = jax.jit(lambda x: (x * 2 + 1).sum())
    float(f(x))
    times = []
    for _ in range(iters):
        r = f(x)
        t0 = time.perf_counter()
        float(r)
        times.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(times))


def _stream_args(lags: np.ndarray, C: int):
    """THE payload rule, from the library itself: the floor/phase probes
    must upload the identical payload (dtype) and use the identical
    static kernel args (pack shift, rank bits) as the benchmarked solve,
    or they measure a different thing than production runs."""
    from kafka_lag_based_assignor_tpu.ops.batched import (
        stream_payload,
        totals_rank_bits_for,
    )

    payload, shift = stream_payload(lags)
    return payload, shift, totals_rank_bits_for(payload, C)


def make_transport_floor(lags: np.ndarray, C: int):
    """A TRIVIAL solve with the identical I/O contract to the real one:
    lags[P] uploaded from host numpy at the SAME dtype the real path
    uploads (int32 when the range allows, else int64), int16 choices[P]
    read back — upload + one dispatch round-trip + readback, essentially
    zero device compute.  ANY single-dispatch implementation of the solve
    pays at least this much on this harness.  Returns a ``once()``
    callable performing one full floor round-trip."""
    import jax
    import jax.numpy as jnp

    payload, _, _ = _stream_args(lags, C)

    @jax.jit
    def trivial(v):
        return (v % C).astype(jnp.int16)

    return lambda: np.asarray(trivial(payload))


def interleaved_floor(real_once, floor_once, iters: int = 20):
    """Measure the real solve and the zero-work floor ALTERNATELY, pairing
    each sample with its temporal neighbour: the tunnel's latency drifts
    on the scale of minutes (observed 40-70 ms session swings), so floor
    and solve measured in separate phases can differ by more than the
    solve's whole device compute.  The per-pair difference cancels the
    drift; its median is the honest above-floor cost.

    Returns dict with assign/floor medians + mins and above_floor_ms."""
    real_once(), floor_once()  # warm-up/compile both
    real_ts, floor_ts = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        floor_once()
        floor_ts.append((time.perf_counter() - t0) * 1000.0)
        t0 = time.perf_counter()
        out = real_once()
        real_ts.append((time.perf_counter() - t0) * 1000.0)
    diffs = [r - f for r, f in zip(real_ts, floor_ts)]
    return {
        "assign_ms": float(np.median(real_ts)),
        "assign_min_ms": float(np.min(real_ts)),
        "transport_floor_ms": float(np.median(floor_ts)),
        "transport_floor_min_ms": float(np.min(floor_ts)),
        "above_floor_ms": float(np.median(diffs)),
    }, out


def device_compute_amortized_ms(
    lags: np.ndarray, C: int, n_hi: int = 8, kernel: str = "xla",
    wide: bool = False,
):
    """Isolate the solve's pure device compute: run the full kernel n
    times over independent inputs INSIDE one executable (lax.map is a
    sequential scan) ending in a scalar fetch, at n=1 and n=n_hi; the
    difference divided by (n_hi - 1) cancels both the round-trip and the
    dispatch overhead.  (block_until_ready is NOT a valid clock on this
    tunneled platform — it returns at dispatch, measured in
    a retired probe (git history) — so the fetch is the only real sync.)

    ``kernel`` selects the XLA rounds scan or the Pallas in-VMEM round
    scan (the caller checks the Pallas gates first)."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    from kafka_lag_based_assignor_tpu.ops.batched import (
        _stream_device,
        _stream_device_pallas,
    )

    payload, shift, rb = _stream_args(lags, C)
    batch = jax.device_put(
        np.stack([np.roll(payload, 7919 * i) for i in range(n_hi)])
    )

    if kernel == "pallas":
        def solve(v):
            return _stream_device_pallas(
                v, num_consumers=C, pack_shift=shift, wide=wide
            )
    else:
        def solve(v):
            return _stream_device(
                v, num_consumers=C, pack_shift=shift, totals_rank_bits=rb
            )

    @functools.partial(jax.jit, static_argnames=("n",))
    def many(b, n):
        f = lambda v: solve(v).astype(jnp.int32).sum()  # noqa: E731
        return lax.map(f, b[:n]).sum()

    def timed(n, iters=8):
        int(many(batch, n=n))  # warm-up/compile
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            int(many(batch, n=n))
            ts.append((time.perf_counter() - t0) * 1000.0)
        return float(np.median(ts))

    t_lo, t_hi = timed(1), timed(n_hi)
    return max(0.0, (t_hi - t_lo) / (n_hi - 1))


def phase_breakdown(lags: np.ndarray, C: int, iters: int = 10) -> dict:
    """Phase timings for the north-star solve (VERDICT r3 item 1):
    host->device upload alone, solve from device-RESIDENT input (dispatch +
    compute + readback, no upload), and the full numpy-in path for
    comparison — each median over ``iters``.  On a tunneled chip the phases
    overlap inside one round-trip, so they need not sum to the e2e time;
    the deltas against ``transport_floor`` are the engineering signal.
    Uploads use the same dtype as the real path (see ``_stream_args``)."""
    import jax

    from kafka_lag_based_assignor_tpu.ops.batched import _stream_device

    payload, shift, rb = _stream_args(lags, C)

    h2d = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(jax.device_put(payload))
        h2d.append((time.perf_counter() - t0) * 1000.0)

    resident = jax.block_until_ready(jax.device_put(payload))

    def res_once():
        return np.asarray(
            _stream_device(
                resident, num_consumers=C, pack_shift=shift,
                totals_rank_bits=rb,
            )
        )

    res_once()
    res = []
    for _ in range(iters):
        t0 = time.perf_counter()
        res_once()
        res.append((time.perf_counter() - t0) * 1000.0)

    return {
        "h2d_upload_ms": float(np.median(h2d)),
        "resident_solve_ms": float(np.median(res)),
        "resident_solve_min_ms": float(np.min(res)),
    }


def timed_solve(once, iters=20):
    """The one timing harness every config uses: ``once()`` performs a full
    solve ending in its single blocking device->host readback and returns
    the materialized result.  One untimed warm-up call pays the compile,
    then the median of ``iters`` timed calls is reported.  The min is
    stashed on ``timed_solve.last_min_ms`` for configs that record it —
    with the tunnel's +/-20 ms session noise (BASELINE.md), median and
    min together locate where a run sat in the noise band.

    Returns (median_ms, last_result)."""
    once()  # warm-up/compile
    times, out = [], None
    for _ in range(iters):
        t0 = time.perf_counter()
        out = once()
        times.append((time.perf_counter() - t0) * 1000.0)
    timed_solve.last_min_ms = float(np.min(times))
    return float(np.median(times)), out


def totals_from_choice(choice: np.ndarray, lags: np.ndarray, C: int):
    """Per-consumer lag totals for a dense single-topic choice vector."""
    totals = np.zeros(C, dtype=np.int64)
    np.add.at(totals, choice.astype(np.int64), lags)
    return totals


def imbalance(member_totals: np.ndarray) -> float:
    mean = member_totals.mean()
    return float(member_totals.max() / mean) if mean > 0 else 1.0


def quality_ratio(imb: float, bound: float) -> float:
    """Achieved max/mean imbalance normalized to the input-driven lower
    bound (clamped at 1): no assignment can score below the bound, so
    ratio 1.0 means provably optimal for the draw.  The <=1.05 quality
    target is judged against THIS ratio — on skewed draws the raw
    imbalance is input-infeasible (a single partition can exceed a fair
    share many times over) and would misread as a miss."""
    return imb / max(bound, 1.0)


def imbalance_bound(lags: np.ndarray, C: int) -> float:
    """Count-constrained lower bound on max/mean imbalance — the shared
    library implementation (one definition of "optimal" for both the
    bench's quality_ratio and the streaming guardrail); see
    utils/observability.count_constrained_bound for the derivation."""
    from kafka_lag_based_assignor_tpu.utils.observability import (
        count_constrained_bound,
    )

    return count_constrained_bound(lags, C)


def zipf_lags(rng, P, a=1.1, scale=1000):
    # Bounded Zipf via inverse-power sampling (np.random.zipf can overflow).
    ranks = rng.permutation(P) + 1
    return (scale * (P / ranks) ** (1.0 / a)).astype(np.int64)


def config1_readme():
    """1 topic, 3 partitions, 2 consumers — correctness gate."""
    from kafka_lag_based_assignor_tpu import TopicPartition, TopicPartitionLag
    from kafka_lag_based_assignor_tpu.ops.dispatch import assign_device

    lags = {
        "t0": [
            TopicPartitionLag("t0", 0, 100_000),
            TopicPartitionLag("t0", 1, 50_000),
            TopicPartitionLag("t0", 2, 60_000),
        ]
    }
    result = assign_device(lags, {"C0": ["t0"], "C1": ["t0"]})
    ok = result["C0"] == [TopicPartition("t0", 0)] and set(result["C1"]) == {
        TopicPartition("t0", 1),
        TopicPartition("t0", 2),
    }
    if not ok:
        raise AssertionError(f"config1 parity failed: {result}")
    return {"config": "readme_3p_2c", "parity": "exact"}


def config2_zipf():
    """1 topic, 1k partitions, 16 consumers, Zipf(1.1) — the config where
    greedy leaves real slack (imbalance ~2.15 vs bound ~1.57), so the
    quality modes are benchmarked HERE, not only on config 4 where greedy
    already sits at the optimum plateau."""
    from kafka_lag_based_assignor_tpu.models.sinkhorn import (
        assign_topic_sinkhorn,
    )
    from kafka_lag_based_assignor_tpu.ops.packing import pad_topic_rows

    from kafka_lag_based_assignor_tpu.ops.batched import assign_stream

    rng = np.random.default_rng(2)
    P, C = 1000, 16
    lags1d = zipf_lags(rng, P)

    def once():
        return np.asarray(assign_stream(lags1d, num_consumers=C))

    ms, choice = timed_solve(once)
    totals = totals_from_choice(choice, lags1d, C)
    bound = imbalance_bound(lags1d, C)
    imb = imbalance(totals)

    # Default-path quality variant (VERDICT r4 item 2): the SAME rounds
    # kernel plus the exchange refinement, chained into one dispatch —
    # the <=1.05 quality target at a fraction of Sinkhorn's latency.
    from kafka_lag_based_assignor_tpu.ops.batched import (
        assign_stream_refined,
    )

    REFINED_ITERS = 64

    def refined_once():
        return np.asarray(
            assign_stream_refined(
                lags1d, num_consumers=C, refine_iters=REFINED_ITERS
            )
        )

    r_ms, r_choice = timed_solve(refined_once)
    r_imb = imbalance(totals_from_choice(r_choice, lags1d, C))

    lags_p, pids_p, valid_p = pad_topic_rows(lags1d)

    def sink_once():
        _, _, s_totals = assign_topic_sinkhorn(
            lags_p, pids_p, valid_p, num_consumers=C
        )
        return np.asarray(s_totals)  # the one blocking readback

    s_ms, s_totals = timed_solve(sink_once, iters=10)
    s_imb = imbalance(s_totals)

    return {
        "config": "zipf1.1_1k_16c",
        "assign_ms": ms,
        "max_mean_imbalance": imb,
        "bound": bound,
        "quality_ratio": quality_ratio(imb, bound),
        "refined_assign_ms": r_ms,
        "refined_iters": REFINED_ITERS,
        "refined_max_mean_imbalance": r_imb,
        "refined_quality_ratio": quality_ratio(r_imb, bound),
        "sinkhorn_assign_ms": s_ms,
        "sinkhorn_max_mean_imbalance": s_imb,
        "sinkhorn_quality_ratio": quality_ratio(s_imb, bound),
        "sinkhorn_vs_greedy_imbalance_gain": imb - s_imb,
    }


def config3_vmap():
    """256 topics x 64 partitions, 64 consumers, uniform lag.

    Uses the dense transfer-lean batch path (lags-only upload — pids and
    validity are derived on device for dense topics; the general
    assign_batched_rounds path exists for ragged/sparse groups and is
    parity-pinned against this one in tests/test_fast_paths.py)."""
    from kafka_lag_based_assignor_tpu.ops.batched import assign_stream_batch

    rng = np.random.default_rng(3)
    T, P, C = 256, 64, 64
    lags = rng.integers(0, 1000, size=(T, P)).astype(np.int64)
    pids = np.tile(np.arange(P, dtype=np.int32), (T, 1))
    valid = np.ones((T, P), dtype=bool)

    def once():
        return np.asarray(assign_stream_batch(lags, num_consumers=C))

    ms, choice = timed_solve(once)
    totals = np.zeros((T, C), dtype=np.int64)
    for t in range(T):
        np.add.at(totals[t], choice[t].astype(np.int64), lags[t])
    member_load = totals.sum(axis=0)

    # Cross-topic global-balance quality mode (beyond-reference): same
    # per-topic count invariant, lag totals carried across topics — via
    # the dense transfer-lean path (lags-only upload).
    from kafka_lag_based_assignor_tpu.ops.batched import (
        assign_stream_global,
    )

    def global_once():
        _, g_totals = assign_stream_global(lags, num_consumers=C)
        return np.asarray(g_totals)  # the one blocking readback

    g_ms, g_totals = timed_solve(global_once, iters=10)

    return {
        "config": "vmap_256t_64p_64c",
        "assign_ms": ms,
        "max_mean_imbalance_global": imbalance(member_load),
        "global_mode_assign_ms": g_ms,
        "global_mode_max_mean_imbalance": imbalance(g_totals),
    }


def config4_skew():
    """10k partitions, 512 consumers, 90% zero-lag / 10% hot."""
    from kafka_lag_based_assignor_tpu.ops.batched import assign_stream

    rng = np.random.default_rng(4)
    P, C = 10_000, 512
    lags = np.zeros(P, dtype=np.int64)
    hot = rng.choice(P, size=P // 10, replace=False)
    lags[hot] = rng.integers(10**5, 10**7, size=hot.size)

    def once():
        return np.asarray(assign_stream(lags, num_consumers=C))

    ms, choice = timed_solve(once)
    totals = totals_from_choice(choice, lags, C)

    # Sinkhorn quality mode on the same instance (the BASELINE config-4
    # comparison): implicit-plan OT relaxation + exchange refinement.
    from kafka_lag_based_assignor_tpu.models.sinkhorn import (
        assign_topic_sinkhorn,
    )
    from kafka_lag_based_assignor_tpu.ops.packing import pad_topic_rows

    lags_p, pids, valid = pad_topic_rows(lags)

    def sink_once():
        _, _, s_totals = assign_topic_sinkhorn(
            lags_p, pids, valid, num_consumers=C
        )
        return np.asarray(s_totals)  # the one blocking readback

    s_ms, s_totals = timed_solve(sink_once, iters=5)

    bound = imbalance_bound(lags, C)
    imb = imbalance(totals)
    s_imb = imbalance(s_totals)
    return {
        "config": "skew_10k_512c",
        "assign_ms": ms,
        "max_mean_imbalance": imb,
        "bound": bound,
        "quality_ratio": quality_ratio(imb, bound),
        "sinkhorn_assign_ms": s_ms,
        "sinkhorn_max_mean_imbalance": s_imb,
        "sinkhorn_quality_ratio": quality_ratio(s_imb, bound),
    }


def config5_northstar():
    """100k partitions, 1k consumers + streaming rebalance under drift.

    Returns the headline assign wall-time and the baseline comparison."""
    from kafka_lag_based_assignor_tpu.ops.batched import assign_stream

    rng = np.random.default_rng(5)
    P, C = 100_000, 1000
    lags0 = zipf_lags(rng, P)

    # Transfer-lean streaming path: exact-shape lags in, int16 choices out.
    def stream_once(arr):
        t0 = time.perf_counter()
        choice = np.asarray(assign_stream(arr, num_consumers=C))
        return (time.perf_counter() - t0) * 1000.0, choice

    # Transport-floor analysis (VERDICT r3 item 1): the zero-work kernel
    # with the identical I/O contract, measured INTERLEAVED with the real
    # solve so the tunnel's minute-scale latency drift cancels pairwise.
    floor_once = make_transport_floor(lags0, C)
    flr, choice = interleaved_floor(
        lambda: np.asarray(assign_stream(lags0, num_consumers=C)),
        floor_once,
    )
    ms = flr["assign_ms"]
    imb = imbalance(totals_from_choice(choice, lags0, C))
    bound = imbalance_bound(lags0, C)

    phases = phase_breakdown(lags0, C)
    # Device-named fields must not carry CPU-backend artifacts (a fallback
    # run's BENCH_DETAILS would otherwise be misread as hardware numbers):
    # on the CPU fallback the amortized-compute figure is recorded under an
    # explicitly backend-labeled key and the device key stays absent.
    import jax

    amortized_key = (
        "device_compute_amortized_ms"
        if jax.default_backend() != "cpu"
        else "cpu_fallback_compute_amortized_ms"
    )
    phases[amortized_key] = device_compute_amortized_ms(lags0, C)
    # The headline path may route through the Pallas round scan on
    # hardware (batched.assign_stream's gates); record ITS amortized
    # compute too so both kernels have a datapoint.
    from kafka_lag_based_assignor_tpu.ops.rounds_pallas import (
        pallas_mode_for,
        rounds_pallas_available,
    )

    pallas_mode = pallas_mode_for(lags0, C, -(-len(lags0) // C))
    if pallas_mode and rounds_pallas_available(mode=pallas_mode):
        phases["device_compute_amortized_pallas_ms"] = (
            device_compute_amortized_ms(
                lags0, C, kernel="pallas",
                wide=(pallas_mode == "wide"),
            )
        )

    # Reference-algorithm baseline on host (same machine, same input).
    base_totals, base_ms = host_baseline_greedy(lags0, C)
    base_imb = imbalance(base_totals)

    # Streaming: rebalance repeatedly under multiplicative drift + churn,
    # reusing the compiled kernel (stable exact shape).  Run both modes:
    # from-scratch each epoch, and the warm-start engine (previous choice
    # kept, fused refine dispatched only past the quality threshold).
    # Runs BEFORE the sinkhorn single-shot so its numbers are measured in
    # the same transport window as the headline (the tunnel's latency
    # drifts over minutes; the sinkhorn first call alone holds it for
    # ~70 s).
    from kafka_lag_based_assignor_tpu.ops.streaming import StreamingAssignor
    from kafka_lag_based_assignor_tpu.utils.observability import (
        compile_count,
        install_compile_counter,
        static_drift_count,
    )

    install_compile_counter()
    lags = lags0.astype(np.float64)
    stream_times = []
    warm_times, warm_churn, warm_ratio = [], [], []
    warm_refine_times, warm_noop_times = [], []
    warm_refine_ratio, warm_noop_ratio = [], []
    warm_refine_rounds, warm_refine_ex = [], []
    warm_trips, warm_refines = 0, 0
    # Guardrail 1.25x the per-epoch input bound: the bounded-churn warm
    # path re-solves cold if its quality drifts past the allowance
    # (exercises the guardrail feature in the recorded numbers).
    engine = StreamingAssignor(
        num_consumers=C, refine_iters=512, imbalance_guardrail=1.25
    )
    # Pre-compile the fused warm-path executable OUT of the timed loop
    # with a throwaway always-refine engine (the production engine's
    # threshold may legitimately skip every dispatch, so its first real
    # dispatch — wherever it lands — must not pay the compile).
    warmer = StreamingAssignor(
        num_consumers=C, refine_iters=512, refine_threshold=None
    )
    warmer.rebalance(lags0)
    warmer.rebalance(lags0)
    choice = engine.rebalance(lags0)  # cold start (all compiled now)
    # Steady-state compile regression gate: from here to the end of the
    # drift loop ZERO fresh XLA compiles may happen — a warm epoch that
    # recompiles is exactly the r5 regression this field exists to catch.
    compiles_before = compile_count()
    drift_before = static_drift_count()
    # Epoch schedule (VERDICT r4 item 6): the first half drifts mildly
    # (lognormal sigma 0.2 — stays under the 1.02 refine threshold, so
    # those epochs exercise the zero-traffic no-op path); in the second
    # half the drift CONCENTRATES on the currently-heaviest consumer's
    # partitions (+15% — the hot-topic pattern: co-owned partitions heat
    # up together, which i.i.d. drift averages away at ~100 partitions
    # per consumer), so the kept assignment reliably breaks the threshold
    # and the BOUNDED device refine actually dispatches.  Its epoch
    # latency is recorded separately (warm_refine_p50_ms) so the
    # bounded-refine cost has a datapoint on every backend.
    for epoch in range(10):
        drift = rng.lognormal(0.0, 0.2, size=P)
        lags = lags * drift + rng.integers(0, 1000, size=P)
        if epoch == 5:
            # The hot partitions DRAIN (consumers caught up): the
            # input-driven bound collapses from ~43 to ~1.6, turning the
            # instance from bound-pinned (where the kept assignment can
            # essentially never drift — r4 recorded zero refine
            # dispatches) into one where balance is actually contested.
            top = np.argsort(lags)[-100:]
            lags[top] *= 0.02
        if epoch >= 5:
            # ...and one mid-load consumer's partitions heat up together
            # (co-owned partitions of a hot topic), breaking the kept
            # assignment past the refine threshold each epoch.
            totals = np.bincount(
                choice.astype(np.int64), weights=lags, minlength=C
            )
            mid = np.argsort(totals)[C // 2]
            lags[choice == mid] *= 1.5
        arr = lags.astype(np.int64)
        t, _ = stream_once(arr)
        stream_times.append(t)
        t0 = time.perf_counter()
        choice = engine.rebalance(arr)
        epoch_ms = (time.perf_counter() - t0) * 1000.0
        warm_times.append(epoch_ms)
        s = engine.last_stats
        q = quality_ratio(s.max_mean_imbalance, s.imbalance_bound)
        # Trip epochs (cold re-solve) stay out of BOTH buckets so the
        # refine p50 records the bounded dispatch alone.
        if not s.guardrail_tripped:
            if s.refined:
                warm_refine_times.append(epoch_ms)
                warm_refine_ratio.append(q)
                warm_refine_rounds.append(s.refine_rounds)
                warm_refine_ex.append(s.refine_exchanges)
            else:
                warm_noop_times.append(epoch_ms)
                warm_noop_ratio.append(q)
        warm_churn.append(s.churn)
        warm_ratio.append(q)
        warm_trips += int(s.guardrail_tripped)
        warm_refines += int(s.refined)
    warm_compile_count = compile_count() - compiles_before
    warm_static_drift = static_drift_count() - drift_before

    # Quality mode at north-star scale (single shot — a quality record,
    # not a latency one): the implicit-plan Sinkhorn + refinement.
    from kafka_lag_based_assignor_tpu.models.sinkhorn import (
        assign_topic_sinkhorn,
    )
    from kafka_lag_based_assignor_tpu.ops.packing import pad_topic_rows

    lags_p, pids_p, valid_p = pad_topic_rows(lags0)
    t0 = time.perf_counter()
    _, _, s_tot = assign_topic_sinkhorn(
        lags_p, pids_p, valid_p, num_consumers=C
    )
    s_tot = np.asarray(s_tot)
    s_first_ms = (time.perf_counter() - t0) * 1000.0  # includes compile
    # Amortized per-call cost: the compiled-executable steady state (the
    # regime a quality-mode deployment actually lives in) — median of
    # repeat calls after the compile call above.  sinkhorn_assign_ms
    # stays the prior rounds' single-second-call timing so
    # round-over-round comparisons remain apples-to-apples.
    s_amortized = []
    for _ in range(2):
        t0 = time.perf_counter()
        _, _, s_tot2 = assign_topic_sinkhorn(
            lags_p, pids_p, valid_p, num_consumers=C
        )
        s_tot2 = np.asarray(s_tot2)
        s_amortized.append((time.perf_counter() - t0) * 1000.0)
    s_ms = s_amortized[0]
    s_amortized_ms = float(np.median(s_amortized))
    s_imb = imbalance(s_tot2)

    return {
        "config": "northstar_100k_1kc",
        **flr,
        **phases,
        "max_mean_imbalance": imb,
        "imbalance_bound": bound,
        "quality_ratio": quality_ratio(imb, bound),
        "baseline_host_greedy_ms": base_ms,
        "baseline_imbalance": base_imb,
        "speedup_vs_baseline": base_ms / ms,
        "sinkhorn_assign_ms": s_ms,
        "sinkhorn_amortized_ms": s_amortized_ms,
        "sinkhorn_first_call_ms": s_first_ms,
        "sinkhorn_max_mean_imbalance": s_imb,
        "sinkhorn_quality_ratio": quality_ratio(s_imb, bound),
        # Machine-normalized quality-mode cost: amortized sinkhorn over
        # the same run's cold assign — comparable across hosts of very
        # different speed (the recorded 8.2 s baseline was ~396x its
        # run's 20.7 ms assign).
        "sinkhorn_over_assign": s_amortized_ms / max(ms, 1e-9),
        "streaming_p50_ms": float(np.percentile(stream_times, 50)),
        "streaming_p95_ms": float(np.percentile(stream_times, 95)),
        "warm_p50_ms": float(np.percentile(warm_times, 50)),
        "warm_churn_p50": float(np.percentile(warm_churn, 50)),
        "warm_quality_ratio_p50": float(np.percentile(warm_ratio, 50)),
        "warm_quality_ratio_max": float(np.max(warm_ratio)),
        "warm_refine_dispatches": warm_refines,
        # Per-epoch-type buckets: the schedule mixes still-balanced
        # epochs (no-op path) with concentrated-drift epochs (bounded
        # refine), so blended p50s would hide both stories.  A refined
        # epoch's ratio is bounded by its exchange budget, not the
        # threshold — churn-vs-quality is the trade being measured.
        "warm_refine_p50_ms": (
            float(np.percentile(warm_refine_times, 50))
            if warm_refine_times else None
        ),
        "warm_refine_quality_ratio_p50": (
            float(np.percentile(warm_refine_ratio, 50))
            if warm_refine_ratio else None
        ),
        "warm_noop_p50_ms": (
            float(np.percentile(warm_noop_times, 50))
            if warm_noop_times else None
        ),
        "warm_noop_quality_ratio_p50": (
            float(np.percentile(warm_noop_ratio, 50))
            if warm_noop_ratio else None
        ),
        "warm_guardrail_trips": warm_trips,
        # Fused-dispatch observability: rounds/exchanges the resident
        # refine actually ran (exchange-budget accounting bounds churn by
        # 2x exchanges), and the steady-state compile regression gates —
        # warm_compile_count MUST be 0 after warm-up (asserted in main).
        "warm_refine_rounds_p50": (
            float(np.percentile(warm_refine_rounds, 50))
            if warm_refine_rounds else None
        ),
        "warm_refine_exchanges_p50": (
            float(np.percentile(warm_refine_ex, 50))
            if warm_refine_ex else None
        ),
        "warm_compile_count": warm_compile_count,
        "warm_static_drift_count": warm_static_drift,
        "guardrail": 1.25,
        "target_ms": 50.0,
        "quality_target_ratio": 1.05,
    }


def config6_multistream():
    """32 concurrent warm streams: ONE vmapped megabatch dispatch per
    rebalance wave (ops/coalesce) versus the same 32 engines dispatched
    serially — the multi-tenant amortization story.  Both paths run the
    IDENTICAL lag sequences (same seeds), always-refine engines
    (refine_threshold=None), and the same exchange budget, so the only
    difference is dispatch shape.  A third phase probes the
    ROSTER-LOCKED steady state (lock_waves=1): the same wave loop once
    the stream set has locked, where every flush is one donated-buffer
    dispatch over the resident [G, ...] batch with zero re-stacks.
    Gates (see main): zero fresh XLA compiles in both steady-state
    loops, zero re-stack dispatches in the locked loop, locked
    throughput >= the re-stack loop (>= 1.3x on hardware), and — on
    real hardware, where the serialized round-trips are the cost being
    amortized — >= 3x aggregate epochs/sec vs serial.  Also records the
    single-stream inline warm no-op p50 (the coalescer bypass path) as
    the lone-tenant regression reference."""
    import concurrent.futures as cf

    from kafka_lag_based_assignor_tpu.ops.coalesce import (
        MegabatchCoalescer,
    )
    from kafka_lag_based_assignor_tpu.ops.streaming import StreamingAssignor
    from kafka_lag_based_assignor_tpu.utils import metrics as klba_metrics
    from kafka_lag_based_assignor_tpu.utils.observability import (
        compile_count,
        install_compile_counter,
    )

    install_compile_counter()
    G, P, C, BUDGET, ROUNDS = 32, 4096, 16, 64, 6

    def stream_rngs():
        return [np.random.default_rng(6000 + g) for g in range(G)]

    def fresh_lags(rng):
        # Stable int32 payload range: the upload dtype is part of the
        # coalescer's shape-bucket key and must not flip mid-run.
        return rng.integers(10**6, 10**8, P).astype(np.int64)

    def mk_engines():
        return [
            StreamingAssignor(
                num_consumers=C, refine_iters=BUDGET,
                refine_threshold=None,
            )
            for _ in range(G)
        ]

    # -- serial baseline: one inline dispatch per stream per epoch ------
    serial = mk_engines()
    rngs = stream_rngs()
    for g in range(G):
        serial[g].rebalance(fresh_lags(rngs[g]))  # cold (compiles once)
    for _ in range(2):  # warm-up: fused warm executable out of the loop
        for g in range(G):
            serial[g].rebalance(fresh_lags(rngs[g]))
    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        for g in range(G):
            serial[g].rebalance(fresh_lags(rngs[g]))
    serial_s = time.perf_counter() - t0
    serial_eps = G * ROUNDS / serial_s

    # -- coalesced: same seeds, one vmapped megabatch per wave ----------
    # lock_waves is set past the horizon so this phase measures the
    # ROUND-9 coalescer exactly (re-stack every flush) — the reference
    # the roster-locked probe below is gated against.
    co = mk_engines()
    rngs = stream_rngs()  # identical sequences as the serial phase
    coal = MegabatchCoalescer(
        window_s=0.25, max_batch=G, lock_waves=1 << 30
    )
    pool = cf.ThreadPoolExecutor(max_workers=G)
    hist = klba_metrics.REGISTRY.histogram("klba_coalesce_batch_size")

    def wave(target):
        arrs = [fresh_lags(rngs[g]) for g in range(G)]
        futs = [
            pool.submit(co[g].submit_epoch, arrs[g], target)
            for g in range(G)
        ]
        for f in futs:
            f.result()

    try:
        for g in range(G):
            co[g].rebalance(fresh_lags(rngs[g]))  # cold, inline (cached)
        for _ in range(2):  # warm-up: megabatch executable compile
            wave(coal)
        hist_before = hist.state()
        compiles_before = compile_count()
        t0 = time.perf_counter()
        for _ in range(ROUNDS):
            wave(coal)
        co_s = time.perf_counter() - t0
        warm_compiles = compile_count() - compiles_before
        hist_after = hist.state()
    finally:
        coal.close()
    co_eps = G * ROUNDS / co_s
    flushes = hist_after["count"] - hist_before["count"]
    batched_rows = hist_after["sum"] - hist_before["sum"]

    # -- roster-stable steady state: same engines, locked fast path -----
    # lock_waves=1 locks the roster on the first megabatch flush; after
    # the second wave (which compiles the locked executable) the loop
    # must run with ZERO re-stack dispatches and ZERO fresh compiles —
    # every flush is one donated-buffer dispatch over the resident
    # [G, ...] batch (ops/coalesce roster fast path).
    restack_c = klba_metrics.REGISTRY.counter(
        "klba_coalesce_restack_total"
    )
    hits_c = klba_metrics.REGISTRY.counter(
        "klba_coalesce_roster_hits_total"
    )
    coal2 = MegabatchCoalescer(window_s=0.25, max_batch=G, lock_waves=1)
    try:
        for _ in range(2):  # wave 1 re-stacks + locks; wave 2 compiles
            wave(coal2)     # the locked executable
        restack_before = restack_c.value
        hits_before = hits_c.value
        compiles_before = compile_count()
        t0 = time.perf_counter()
        for _ in range(ROUNDS):
            wave(coal2)
        locked_s = time.perf_counter() - t0
        locked_compiles = compile_count() - compiles_before
        locked_restacks = restack_c.value - restack_before
        locked_hits = hits_c.value - hits_before
    finally:
        coal2.close()
        pool.shutdown(wait=True)
    locked_eps = G * ROUNDS / locked_s

    # -- lone-tenant regression reference: inline warm no-op p50 --------
    solo = StreamingAssignor(num_consumers=C, refine_iters=BUDGET)
    rng = np.random.default_rng(6999)
    base = fresh_lags(rng).astype(np.float64)
    solo.rebalance(base.astype(np.int64))
    noop_times, noop_epochs = [], 0
    for _ in range(15):
        drifted = np.maximum(
            base * rng.lognormal(0, 0.003, P), 1
        ).astype(np.int64)
        t0 = time.perf_counter()
        solo.rebalance(drifted)
        noop_times.append((time.perf_counter() - t0) * 1000.0)
        noop_epochs += int(not solo.last_stats.refined)

    return {
        "config": "multistream_32g",
        "streams": G,
        "partitions": P,
        "consumers": C,
        "refine_iters": BUDGET,
        "rounds": ROUNDS,
        "serial_epochs_per_s": serial_eps,
        "coalesced_epochs_per_s": co_eps,
        "speedup_vs_serial": co_eps / serial_eps,
        "coalesce_flushes": flushes,
        "coalesce_batch_mean": (
            batched_rows / flushes if flushes else None
        ),
        # Steady-state gate: the vmapped warm loop must compile NOTHING
        # after its warm-up rounds (asserted in main on every backend).
        "warm_compile_count": warm_compiles,
        # Roster-locked probe (gated in main): the locked loop must
        # re-stack NOTHING and compile NOTHING, and its throughput must
        # hold >= the round-9 coalescer on the CPU ref (compute-bound;
        # the saved work is 3G row gathers + G buffer-tuple args per
        # flush) and >= 1.3x it on hardware, where the dispatch/transfer
        # overhead the fast path removes dominates the wave.
        "locked_epochs_per_s": locked_eps,
        "speedup_locked_vs_coalesced": locked_eps / co_eps,
        "locked_restack_dispatches": locked_restacks,
        "locked_roster_hits": locked_hits,
        "locked_warm_compile_count": locked_compiles,
        "single_stream_noop_p50_ms": float(np.percentile(noop_times, 50)),
        "single_stream_noop_epochs": noop_epochs,
        "target_speedup": 3.0,
    }


def config7_overload():
    """Overload stampede probe (ISSUE 6): 16 mixed-class tenants hitting
    a sidecar whose megabatch cap is 4 — a 4x-oversubscribed wave every
    round — with the overload detector tuned to engage.  What must hold
    (gated in main, every backend): critical-class p99 request latency
    stays within its configured 2 s deadline budget, ALL shedding lands
    on the lower classes first (critical is never shed; standard only
    sheds while best_effort sheds too), every served assignment is
    count-balanced, the measured waves compile NOTHING, and the
    ``recommend`` wire call returns a monotone consumer-count
    recommendation as one stream's lag trend steepens."""
    import concurrent.futures as cf

    from kafka_lag_based_assignor_tpu.service import (
        AssignorService,
        AssignorServiceClient,
    )
    from kafka_lag_based_assignor_tpu.utils import metrics as klba_metrics
    from kafka_lag_based_assignor_tpu.utils.observability import (
        compile_count,
        install_compile_counter,
    )

    install_compile_counter()
    P, C, ROUNDS = 2048, 8, 8
    CRITICAL_BUDGET_S = 2.0
    classes = (
        {f"crit-{i}": "critical" for i in range(4)}
        | {f"std-{i}": "standard" for i in range(4)}
        | {f"be-{i}": "best_effort" for i in range(8)}
    )
    members = [f"m{j}" for j in range(C)]
    rngs = {sid: np.random.default_rng(7000 + i)
            for i, sid in enumerate(sorted(classes))}
    lags_now = {
        sid: rng.integers(10**6, 10**8, P).astype(np.int64)
        for sid, rng in rngs.items()
    }

    def drift(sid):
        arr = lags_now[sid]
        bump = rngs[sid].integers(0, 10**6, P)
        lags_now[sid] = np.minimum(arr + bump, np.int64(2**31 - 2))
        return lags_now[sid]

    def rows(arr):
        return [[i, int(v)] for i, v in enumerate(arr)]

    from kafka_lag_based_assignor_tpu.testing import (
        assert_valid_assignment,
        shed_totals_by_class as shed_by_class,
    )
    from kafka_lag_based_assignor_tpu.utils.overload import ShedReject

    svc = AssignorService(
        port=0, solve_timeout_s=120.0,
        slo_classes=classes,
        slo_deadline_s={"critical": CRITICAL_BUDGET_S},
        overload_depth_high=6.0,
        coalesce_window_ms=2.0, coalesce_max_batch=4,
        # The stampede churns the stream set per wave (sheds drop rows);
        # keep the probe on the re-stack path — roster stability is
        # config6's concern.
        coalesce_lock_waves=1 << 30,
    ).start()
    svc._overload.eval_interval_s = 0.0
    pool = cf.ThreadPoolExecutor(max_workers=len(classes))
    clients = {
        sid: AssignorServiceClient(*svc.address, timeout_s=180.0)
        for sid in classes
    }
    lat = {"critical": [], "standard": [], "best_effort": []}
    served = {"critical": 0, "standard": 0, "best_effort": 0}
    rejected = {"critical": 0, "standard": 0, "best_effort": 0}
    errors = {"critical": 0, "standard": 0, "best_effort": 0}
    invalid = [0]

    def one(sid, override=None, record=True):
        klass = override or classes[sid]
        t0 = time.perf_counter()
        try:
            r = clients[sid].request("stream_assign", {
                "stream_id": sid, "topic": "t0",
                "lags": rows(drift(sid)), "members": members,
                **({"slo_class": override} if override else {}),
            })
        except ShedReject:
            # The ladder's structured rejection — the one outcome the
            # stampede is designed to produce for the lower classes.
            if record:
                rejected[klass] += 1
            return
        except (RuntimeError, ConnectionError):
            # Anything else is a genuine failure, not a shed: counted
            # apart so a partially-failing class cannot slip past the
            # p99/shed gates by vanishing from both.
            if record:
                errors[klass] += 1
            return
        if record:
            lat[klass].append(time.perf_counter() - t0)
            served[klass] += 1
            try:
                assert_valid_assignment(r["assignments"], P)
            except AssertionError:
                invalid[0] += 1

    try:
        # Warm phase: cold chains + fused executables, serially, every
        # stream overridden to "standard" so no cold compile races the
        # critical class's 2 s budget; then two full stampede waves to
        # compile the batch-4 megabatch executable off the record.
        for sid in sorted(classes):
            one(sid, override="standard", record=False)
        for _ in range(2):
            list(pool.map(lambda s: one(s, record=False),
                          sorted(classes)))
        shed_before = shed_by_class()
        compiles_before = compile_count()
        t0 = time.perf_counter()
        for _ in range(ROUNDS):
            list(pool.map(one, sorted(classes)))
        wall_s = time.perf_counter() - t0
        warm_compiles = compile_count() - compiles_before
        shed_delta = {
            k: v - shed_before.get(k, 0)
            for k, v in shed_by_class().items()
        }
        overload = clients["crit-0"].request("stats")["overload"]

        # Elasticity: steepen one stream's lag trend and require a
        # monotone non-decreasing consumer-count recommendation.
        recs = []
        for pct in (5, 15, 45):
            arr = lags_now["std-0"]
            lags_now["std-0"] = np.minimum(
                arr + arr // (100 // pct), np.int64(2**31 - 2)
            )
            one("std-0", record=False)
            rec = clients["std-0"].request(
                "recommend", {"stream_id": "std-0"}
            )["streams"]["std-0"]
            recs.append(rec["recommended_consumers"])
    finally:
        for cl in clients.values():
            cl.close()
        pool.shutdown(wait=True)
        svc.stop()

    def p99(key):
        return (
            float(np.percentile(lat[key], 99)) if lat[key] else None
        )

    return {
        "config": "overload_stampede",
        "streams": len(classes),
        "partitions": P,
        "consumers": C,
        "oversubscription": len(classes) / 4,
        "rounds": ROUNDS,
        "wall_s": wall_s,
        "served": served,
        "rejected": rejected,
        "request_errors": errors,
        "invalid_assignments": invalid[0],
        "critical_p99_s": p99("critical"),
        "critical_budget_s": CRITICAL_BUDGET_S,
        "standard_p99_s": p99("standard"),
        "shed_by_class": shed_delta,
        "overload_state": overload,
        "warm_compile_count": warm_compiles,
        "recommend_trajectory": recs,
        "recommend_monotone": recs == sorted(recs) and recs[-1] > C,
    }


def config9_delta():
    """Delta-drift probe (ISSUE 8): steady-state drift touching ~1.5%
    of the partitions per epoch (inside the probe's 1-5% churn band),
    served by a delta-epoch engine and by an always-dense twin over
    IDENTICAL seeded lag sequences.  What must hold (gated in main,
    every backend — the contract is correctness + upload bytes, not
    wall time): the first delta epoch and every subsequent epoch are
    BIT-IDENTICAL to the dense baseline, every drift epoch takes the
    delta path (klba_delta_epochs_total{outcome=applied}), zero fresh
    XLA compiles inside either measured loop (the K ladder warms via
    warmup), and the per-epoch H2D lag-payload bytes
    (klba_h2d_bytes_total{path=delta}) are >= 10x smaller than the
    dense twin's."""
    from kafka_lag_based_assignor_tpu.ops.streaming import (
        StreamingAssignor,
    )
    from kafka_lag_based_assignor_tpu.utils import metrics as klba_metrics
    from kafka_lag_based_assignor_tpu.utils.observability import (
        compile_count,
        install_compile_counter,
    )
    from kafka_lag_based_assignor_tpu.warmup import warmup

    install_compile_counter()
    P, C, epochs = 4096, 16, 12
    churn = max(1, int(0.015 * P))
    rng = np.random.default_rng(9)
    # int32-range lags: the payload dtype every epoch shares (a range
    # flip mid-loop would retrace the fused executable).
    base = rng.integers(10**5, 10**6, P).astype(np.int64)

    # Ladder + dense/cold executables off the measured path.
    warmup(max_partitions=P, consumers=[C], solvers=("stream",))

    dense_c = klba_metrics.REGISTRY.counter(
        "klba_h2d_bytes_total", {"path": "dense"}
    )
    delta_c = klba_metrics.REGISTRY.counter(
        "klba_h2d_bytes_total", {"path": "delta"}
    )
    applied_c = klba_metrics.REGISTRY.counter(
        "klba_delta_epochs_total", {"outcome": "applied"}
    )

    def drive(delta_enabled: bool):
        eng = StreamingAssignor(
            num_consumers=C, refine_iters=128, refine_threshold=None,
            delta_enabled=delta_enabled,
        )
        seq = np.random.default_rng(99)  # IDENTICAL drift both drives
        lags = base.copy()
        choices = [np.asarray(eng.rebalance(lags))]  # cold, unmeasured
        before = (
            dense_c.value, delta_c.value, applied_c.value,
            compile_count(),
        )
        times = []
        for _ in range(epochs):
            idx = seq.choice(P, size=churn, replace=False)
            lags = lags.copy()
            lags[idx] = seq.integers(10**5, 10**6, churn)
            t0 = time.perf_counter()
            choices.append(np.asarray(eng.rebalance(lags)))
            times.append((time.perf_counter() - t0) * 1000.0)
        after = (
            dense_c.value, delta_c.value, applied_c.value,
            compile_count(),
        )
        return choices, times, [a - b for a, b in zip(after, before)]

    dense_choices, dense_times, dense_delta_counts = drive(False)
    delta_choices, delta_times, delta_counts = drive(True)
    mismatched = sum(
        int(not np.array_equal(a, b))
        for a, b in zip(dense_choices, delta_choices)
    )
    dense_per_epoch = dense_delta_counts[0] / epochs
    delta_per_epoch = delta_counts[1] / epochs
    return {
        "config": "delta_drift",
        "partitions": P,
        "consumers": C,
        "epochs": epochs,
        "churn_fraction": churn / P,
        "dense_bytes_per_epoch": dense_per_epoch,
        "delta_bytes_per_epoch": delta_per_epoch,
        "upload_reduction_x": (
            dense_per_epoch / max(delta_per_epoch, 1e-9)
        ),
        "delta_applied": delta_counts[2],
        # Dense bytes charged DURING the delta engine's loop: any
        # nonzero value means an epoch fell back off the delta path.
        "delta_engine_dense_bytes": delta_counts[0],
        "mismatched_epochs": mismatched,
        "warm_compile_count": dense_delta_counts[3] + delta_counts[3],
        "dense_epoch_p50_ms": float(np.percentile(dense_times, 50)),
        "delta_epoch_p50_ms": float(np.percentile(delta_times, 50)),
        "reduction_target_x": 10.0,
    }


def config8_restart():
    """Restart-storm probe (ISSUE 7): N tenants on a snapshotting
    sidecar, a crash-equivalent stop (no drain — the periodic snapshot
    is all that survives), then a restart where EVERY tenant fires its
    next epoch at once.  What must hold (gated in main, every
    backend): every stream recovers from the snapshot, each recovered
    stream's first warm epoch is BIT-IDENTICAL to what an
    uninterrupted process would have produced from the same seeded
    choice, zero invalid assignments, zero warm-loop compiles after
    recovery (the recovered-shape warm-up runs off the serving path),
    and the storm's time-to-first-warm-epoch does not regress past
    10x the pre-crash warm-epoch baseline."""
    import concurrent.futures as cf
    import tempfile

    from kafka_lag_based_assignor_tpu.ops.streaming import (
        StreamingAssignor,
    )
    from kafka_lag_based_assignor_tpu.service import (
        AssignorService,
        AssignorServiceClient,
    )
    from kafka_lag_based_assignor_tpu.testing import (
        assert_valid_assignment,
    )
    from kafka_lag_based_assignor_tpu.utils.observability import (
        compile_count,
        install_compile_counter,
    )

    install_compile_counter()
    P, C, N = 2048, 8, 8
    streams = [f"s{i}" for i in range(N)]
    members = [f"m{j}" for j in range(C)]
    rngs = {sid: np.random.default_rng(8000 + i)
            for i, sid in enumerate(streams)}

    def fresh(sid):
        return rngs[sid].integers(0, 10**6, P).astype(np.int64)

    def rows(arr):
        return [[i, int(v)] for i, v in enumerate(arr)]

    snap_dir = tempfile.mkdtemp(prefix="klba-restart-")
    snap_path = f"{snap_dir}/snapshot.json"

    def decode(assignments):
        midx = {m: j for j, m in enumerate(members)}
        got = np.full(P, -1, np.int32)
        for m, tps in assignments.items():
            for _t, p in tps:
                got[p] = midx[m]
        return got

    # Phase A: serve warm epochs, snapshot, crash (stop without drain).
    svc = AssignorService(
        port=0, snapshot_path=snap_path, snapshot_interval_s=3600.0,
        coalesce_max_batch=N,
    ).start()
    pool = cf.ThreadPoolExecutor(max_workers=N)
    clients = {
        sid: AssignorServiceClient(*svc.address, timeout_s=300.0)
        for sid in streams
    }
    baseline_ms = []

    def epoch(sid, record=False):
        t0 = time.perf_counter()
        r = clients[sid].stream_assign(
            sid, "t0", rows(fresh(sid)), members
        )
        if record:
            baseline_ms.append((time.perf_counter() - t0) * 1000.0)
        return r

    try:
        for sid in streams:  # cold chains, serial
            epoch(sid)
        for _ in range(2):  # warm the megabatch path
            list(pool.map(epoch, streams))
        # The pre-crash warm-epoch baseline: one concurrent round.
        list(pool.map(lambda s: epoch(s, record=True), streams))
        assert svc.snapshot_now()["ok"]
        snap_choices = {
            sid: svc._streams[sid].engine.export_state()
            for sid in streams
        }
    finally:
        for cl in clients.values():
            cl.close()
        svc.stop()  # crash-equivalent: NO drain, NO final snapshot

    # The uninterrupted oracle: engines seeded with the same choices.
    next_lags = {sid: fresh(sid) for sid in streams}
    expected = {}
    for sid in streams:
        base = StreamingAssignor(
            num_consumers=C, imbalance_guardrail=1.25
        )
        base.seed_choice(snap_choices[sid])
        expected[sid] = np.asarray(base.rebalance(next_lags[sid]))

    # Phase B: restart + storm.  recovery_warmup covers the recovered
    # shapes (incl. megabatch buckets) off the serving path.
    svc2 = AssignorService(
        port=0, snapshot_path=snap_path, snapshot_interval_s=3600.0,
        coalesce_max_batch=N,
    ).start()
    recovery = dict(svc2._last_recovery or {})
    clients2 = {
        sid: AssignorServiceClient(*svc2.address, timeout_s=300.0)
        for sid in streams
    }
    storm_ms = {}
    mismatched = [0]
    invalid = [0]
    warm_restarts = [0]
    compiles_before = compile_count()

    def storm(sid):
        t0 = time.perf_counter()
        r = clients2[sid].stream_assign(
            sid, "t0", rows(next_lags[sid]), members
        )
        storm_ms[sid] = (time.perf_counter() - t0) * 1000.0
        if r["stream"]["warm_restart"]:
            warm_restarts[0] += 1
        try:
            assert_valid_assignment(r["assignments"], P)
        except AssertionError:
            invalid[0] += 1
        if not np.array_equal(decode(r["assignments"]), expected[sid]):
            mismatched[0] += 1

    try:
        t0 = time.perf_counter()
        list(pool.map(storm, streams))
        storm_wall_s = time.perf_counter() - t0
        post_compiles = compile_count() - compiles_before
    finally:
        for cl in clients2.values():
            cl.close()
        pool.shutdown(wait=True)
        svc2.stop()

    lat = sorted(storm_ms.values())

    # Phase C (ROADMAP lifecycle (b), bench-measured): the SAME
    # snapshot, rebooted with recovery_prestack=True — the boot
    # rebuilds each recovered engine's device-resident state (zero-lag
    # table build, off the serving path) so the storm's first epochs
    # skip the inline dense rebuild and coalesce.  Measured against
    # phase B's lazy-rebuild numbers; the verdict lands in BASELINE.md.
    svc3 = AssignorService(
        port=0, snapshot_path=snap_path, snapshot_interval_s=3600.0,
        coalesce_max_batch=N, recovery_prestack=True,
    ).start()
    recovery3 = dict(svc3._last_recovery or {})
    pre_choices = {
        sid: svc3._streams[sid].engine.export_state()
        for sid in streams if sid in svc3._streams
    }
    lags3 = {sid: fresh(sid) for sid in streams}
    expected3 = {}
    for sid, choice in pre_choices.items():
        base = StreamingAssignor(
            num_consumers=C, imbalance_guardrail=1.25
        )
        base.seed_choice(choice)
        expected3[sid] = np.asarray(base.rebalance(lags3[sid]))
    clients3 = {
        sid: AssignorServiceClient(*svc3.address, timeout_s=300.0)
        for sid in streams
    }
    pool3 = cf.ThreadPoolExecutor(max_workers=N)
    storm3_ms = {}
    mismatched3 = [0]

    def storm3(sid):
        t0 = time.perf_counter()
        r = clients3[sid].stream_assign(
            sid, "t0", rows(lags3[sid]), members
        )
        storm3_ms[sid] = (time.perf_counter() - t0) * 1000.0
        if not np.array_equal(
            decode(r["assignments"]), expected3[sid]
        ):
            mismatched3[0] += 1

    try:
        t0 = time.perf_counter()
        list(pool3.map(storm3, streams))
        prestack_wall_s = time.perf_counter() - t0
    finally:
        for cl in clients3.values():
            cl.close()
        pool3.shutdown(wait=True)
        svc3.stop()
    lat3 = sorted(storm3_ms.values())

    return {
        "config": "restart_storm",
        "streams": N,
        "partitions": P,
        "consumers": C,
        "streams_expected": N,
        "streams_recovered": recovery.get("streams_recovered", 0),
        "recovery_outcome": recovery.get("outcome"),
        "recovery_ms": recovery.get("duration_ms"),
        "warm_restart_epochs": warm_restarts[0],
        "baseline_epoch_p50_ms": float(np.percentile(baseline_ms, 50)),
        "first_epoch_p50_ms": float(np.percentile(lat, 50)),
        "first_epoch_max_ms": float(lat[-1]),
        "storm_wall_s": storm_wall_s,
        "mismatched_assignments": mismatched[0],
        "invalid_assignments": invalid[0],
        "post_recovery_compile_count": post_compiles,
        # Pre-stacked reboot (phase C) vs the lazy rebuild above —
        # the lifecycle (b) measurement.
        "prestack_streams": recovery3.get("streams_prestacked", 0),
        "prestack_first_epoch_p50_ms": float(
            np.percentile(lat3, 50)
        ) if lat3 else None,
        "prestack_first_epoch_max_ms": (
            float(lat3[-1]) if lat3 else None
        ),
        "prestack_storm_wall_s": prestack_wall_s,
        "prestack_mismatched_assignments": mismatched3[0],
    }


def config10_handoff():
    """Cross-host hand-off storm (ISSUE 9): TWO real service instances
    sharing one object-store-shaped snapshot backend (versioned CAS +
    epoch-fenced writer leases), driven through BOTH hand-off modes.
    Crash: instance A dies without a drain; replacement B waits out
    A's lease TTL, takes over with a bumped fencing token, rehydrates
    every tenant, and answers first warm epochs bit-identical to the
    uninterrupted baseline with zero compiles — while A's stale
    snapshot write is REJECTED by fencing (counted; the adopted state
    is never overwritten).  Drain: B releases the lease after its
    final snapshot and replacement C adopts without a TTL wait.
    Gated in main on all of the above."""
    import concurrent.futures as cf
    import tempfile

    from kafka_lag_based_assignor_tpu.ops.streaming import (
        StreamingAssignor,
    )
    from kafka_lag_based_assignor_tpu.service import (
        AssignorService,
        AssignorServiceClient,
    )
    from kafka_lag_based_assignor_tpu.testing import (
        assert_valid_assignment,
    )
    from kafka_lag_based_assignor_tpu.utils import (
        metrics as klba_metrics,
    )
    from kafka_lag_based_assignor_tpu.utils.observability import (
        compile_count,
        install_compile_counter,
    )

    install_compile_counter()
    P, C, N = 2048, 8, 8
    LEASE_TTL_S = 2.0
    streams = [f"h{i}" for i in range(N)]
    members = [f"m{j}" for j in range(C)]
    rngs = {sid: np.random.default_rng(9000 + i)
            for i, sid in enumerate(streams)}

    def fresh(sid):
        return rngs[sid].integers(0, 10**6, P).astype(np.int64)

    def rows(arr):
        return [[i, int(v)] for i, v in enumerate(arr)]

    def decode(assignments):
        midx = {m: j for j, m in enumerate(members)}
        got = np.full(P, -1, np.int32)
        for m, tps in assignments.items():
            for _t, p in tps:
                got[p] = midx[m]
        return got

    def fenced_count():
        return klba_metrics.REGISTRY.counter(
            "klba_snapshot_writes_total", {"outcome": "fenced"}
        ).value

    backend_dir = tempfile.mkdtemp(prefix="klba-handoff-")
    svc_kw = dict(
        snapshot_path=backend_dir, snapshot_backend="object",
        snapshot_lease_ttl_s=LEASE_TTL_S, snapshot_lease_wait_s=30.0,
        snapshot_interval_s=3600.0, coalesce_max_batch=N,
    )

    def oracle(choices, lag_map):
        out = {}
        for sid, choice in choices.items():
            base = StreamingAssignor(
                num_consumers=C, imbalance_guardrail=1.25
            )
            base.seed_choice(choice)
            out[sid] = np.asarray(base.rebalance(lag_map[sid]))
        return out

    def storm(svc, lag_map, expected, counters):
        """One concurrent first-epoch wave; fills counters dict."""
        clients = {
            sid: AssignorServiceClient(*svc.address, timeout_s=300.0)
            for sid in streams
        }
        lat_ms = {}

        def one(sid):
            t0 = time.perf_counter()
            r = clients[sid].stream_assign(
                sid, "t0", rows(lag_map[sid]), members
            )
            lat_ms[sid] = (time.perf_counter() - t0) * 1000.0
            try:
                assert_valid_assignment(r["assignments"], P)
            except AssertionError:
                counters["invalid"] += 1
            if not np.array_equal(
                decode(r["assignments"]), expected[sid]
            ):
                counters["mismatched"] += 1
            if r["stream"]["warm_restart"]:
                counters["warm_restarts"] += 1

        pool = cf.ThreadPoolExecutor(max_workers=N)
        compiles0 = compile_count()
        t0 = time.perf_counter()
        try:
            list(pool.map(one, streams))
        finally:
            counters["wall_s"] = time.perf_counter() - t0
            counters["compiles"] = compile_count() - compiles0
            for cl in clients.values():
                cl.close()
            pool.shutdown(wait=True)
        lat = sorted(lat_ms.values())
        counters["p50_ms"] = float(np.percentile(lat, 50))
        counters["max_ms"] = float(lat[-1])

    # -- Phase A: instance A serves warm traffic, snapshots, CRASHES.
    svc_a = AssignorService(port=0, **svc_kw).start()
    clients = {
        sid: AssignorServiceClient(*svc_a.address, timeout_s=300.0)
        for sid in streams
    }
    pool = cf.ThreadPoolExecutor(max_workers=N)
    try:
        for sid in streams:  # cold chains, serial
            clients[sid].stream_assign(
                sid, "t0", rows(fresh(sid)), members
            )
        for _ in range(2):  # warm the megabatch path
            list(pool.map(
                lambda s: clients[s].stream_assign(
                    s, "t0", rows(fresh(s)), members
                ),
                streams,
            ))
        assert svc_a.snapshot_now()["ok"]
        choices_a = {
            sid: svc_a._streams[sid].engine.export_state()
            for sid in streams
        }
    finally:
        for cl in clients.values():
            cl.close()
        pool.shutdown(wait=True)
        svc_a.stop()  # crash: the lease is NOT released

    # -- Phase B: replacement B waits out the TTL, adopts, storms.
    lags_b = {sid: fresh(sid) for sid in streams}
    expected_b = oracle(choices_a, lags_b)
    t_boot = time.perf_counter()
    svc_b = AssignorService(port=0, **svc_kw).start()
    boot_b_s = time.perf_counter() - t_boot
    handoff_b = dict(svc_b._last_handoff or {})
    recovery_b = dict(svc_b._last_recovery or {})
    crash = {
        "mismatched": 0, "invalid": 0, "warm_restarts": 0,
    }
    fenced0 = fenced_count()
    overwrites = 0
    try:
        storm(svc_b, lags_b, expected_b, crash)
        # The fenced-off predecessor tries a stale snapshot write:
        # rejected + counted, the adopted state version unmoved.
        version0 = svc_b._snapshot_store.backend.version()
        stale = svc_a.snapshot_now()
        if stale.get("ok"):
            overwrites += 1
        if svc_b._snapshot_store.backend.version() != version0:
            overwrites += 1
        # B then serves a second wave and DRAINS (releases the lease).
        lags_b2 = {sid: fresh(sid) for sid in streams}
        cl = {
            sid: AssignorServiceClient(*svc_b.address, timeout_s=300.0)
            for sid in streams
        }
        try:
            for sid in streams:
                cl[sid].stream_assign(
                    sid, "t0", rows(lags_b2[sid]), members
                )
        finally:
            for c in cl.values():
                c.close()
        choices_b = {
            sid: svc_b._streams[sid].engine.export_state()
            for sid in streams
        }
    finally:
        if not svc_b.begin_drain():
            svc_b.stop()
        svc_b.wait_stopped(60.0)
    fenced_stale_writes = fenced_count() - fenced0

    # -- Phase C: replacement C adopts INSTANTLY after the drain.
    lags_c = {sid: fresh(sid) for sid in streams}
    expected_c = oracle(choices_b, lags_c)
    t_boot = time.perf_counter()
    svc_c = AssignorService(port=0, **svc_kw).start()
    boot_c_s = time.perf_counter() - t_boot
    handoff_c = dict(svc_c._last_handoff or {})
    recovery_c = dict(svc_c._last_recovery or {})
    drain = {
        "mismatched": 0, "invalid": 0, "warm_restarts": 0,
    }
    try:
        storm(svc_c, lags_c, expected_c, drain)
    finally:
        svc_c.stop()

    return {
        "config": "handoff_storm",
        "streams": N,
        "partitions": P,
        "consumers": C,
        "backend": "object",
        "lease_ttl_s": LEASE_TTL_S,
        "crash_handoff_mode": handoff_b.get("mode"),
        "crash_lease_waited_ms": handoff_b.get("waited_ms"),
        "crash_boot_wall_s": boot_b_s,
        "crash_streams_recovered": recovery_b.get(
            "streams_recovered", 0
        ),
        "crash_warm_restart_epochs": crash["warm_restarts"],
        "crash_first_epoch_p50_ms": crash.get("p50_ms"),
        "crash_first_epoch_max_ms": crash.get("max_ms"),
        "crash_storm_wall_s": crash.get("wall_s"),
        "crash_mismatched_assignments": crash["mismatched"],
        "crash_invalid_assignments": crash["invalid"],
        "crash_post_takeover_compiles": crash.get("compiles", -1),
        "drain_handoff_mode": handoff_c.get("mode"),
        "drain_lease_waited_ms": handoff_c.get("waited_ms"),
        "drain_boot_wall_s": boot_c_s,
        "drain_streams_recovered": recovery_c.get(
            "streams_recovered", 0
        ),
        "drain_warm_restart_epochs": drain["warm_restarts"],
        "drain_first_epoch_p50_ms": drain.get("p50_ms"),
        "drain_first_epoch_max_ms": drain.get("max_ms"),
        "drain_storm_wall_s": drain.get("wall_s"),
        "drain_mismatched_assignments": drain["mismatched"],
        "drain_invalid_assignments": drain["invalid"],
        "drain_post_takeover_compiles": drain.get("compiles", -1),
        "fenced_stale_writes": fenced_stale_writes,
        "adopted_state_overwrites": overwrites,
    }


def config11_scrub():
    """Corruption-storm probe (ISSUE 11): seeded bit-flips into every
    resident buffer class (choice / counts / lags), on BOTH the
    single-stream inline path and a locked megabatch row, against a
    real sidecar.  What must hold (gated in main, every backend):
    every injected corruption is detected within one serving epoch
    (dispatch-input digest / delta conservation) or one scrub pass
    (idle-state audit), every quarantined stream heals BIT-EXACT vs an
    uncorrupted twin seeded from the same host truth, zero invalid
    (count-imbalanced) assignments are ever served, the measured storm
    round compiles nothing (the rehearsal round pays any first-touch
    compiles), and the per-epoch host-side digest verification costs
    < 1% of the warm no-op epoch."""
    import concurrent.futures as cf

    from kafka_lag_based_assignor_tpu.ops.streaming import (
        StreamingAssignor,
    )
    from kafka_lag_based_assignor_tpu.service import (
        AssignorService,
        AssignorServiceClient,
    )
    from kafka_lag_based_assignor_tpu.testing import (
        assert_valid_assignment,
    )
    from kafka_lag_based_assignor_tpu.utils import faults
    from kafka_lag_based_assignor_tpu.utils import metrics as m
    from kafka_lag_based_assignor_tpu.utils import scrub as scrub_mod
    from kafka_lag_based_assignor_tpu.utils.observability import (
        compile_count,
        install_compile_counter,
    )

    install_compile_counter()
    P, C, N = 2048, 8, 4
    members = [f"m{j}" for j in range(C)]
    # Deterministic detection: every epoch must DISPATCH the warm
    # resident path (the host no-op gate would defer detection to the
    # scrubber) and a guardrail trip would cold-resolve the corruption
    # away before the digest sees it.
    OPTS = {"guardrail": None, "refine_threshold": None}
    rng = np.random.default_rng(0x5C12B)
    BUFFERS = ("choice", "counts", "lags")

    def rows(arr):
        return [[i, int(v)] for i, v in enumerate(arr)]

    def fresh():
        return rng.integers(0, 10**6, P).astype(np.int64)

    def q_total(outcome):
        return sum(
            c.value
            for c in m.REGISTRY.series("klba_quarantine_total")
            if c.labels.get("outcome") == outcome
        )

    def decode(assignments):
        midx = {mm: j for j, mm in enumerate(members)}
        got = np.full(P, -1, np.int32)
        for mm, tps in assignments.items():
            for _t, p in tps:
                got[p] = midx[mm]
        return got

    injected = [0]
    detected = [0]
    invalid = [0]
    heal_mismatch = [0]
    late_detections = [0]
    seedseq = iter(range(100, 200))

    def twin_expect(prev, lags):
        twin = StreamingAssignor(
            num_consumers=C, refine_threshold=None,
        )
        twin.seed_choice(prev)
        return np.asarray(twin.rebalance(lags))

    # ---- Phase A: single-stream inline lanes ------------------------
    # Short breaker cooldown: the storm drives MANY corruption events
    # back-to-back on purpose, and escalation correctly trips the
    # stream breaker on un-forgiven strikes — each lane below also
    # serves FORGIVE_AFTER clean epochs so its strikes read as
    # isolated events, the scenario the per-lane gates score.
    svc_a = AssignorService(
        port=0, coalesce_max_batch=1, scrub_interval_ms=3600_000.0,
        breaker_cooldown_s=0.5,
    ).start()
    ca = AssignorServiceClient(*svc_a.address, timeout_s=300.0)

    def epoch_a(check=True):
        r = ca.stream_assign("a0", "t0", rows(fresh()), members,
                             options=OPTS)
        if check:
            try:
                assert_valid_assignment(r["assignments"], P)
            except AssertionError:
                invalid[0] += 1
        return r

    def storm_a(record=True):
        for buffer in BUFFERS:
            inj = faults.FaultInjector(seed=next(seedseq)).plan(
                f"device.corrupt.{buffer}", mode="raise", times=1
            )
            with faults.injected(inj):
                epoch_a()  # the corruption lands at this adopt
            if record:
                injected[0] += inj.fired(f"device.corrupt.{buffer}")
            engine = svc_a._streams["a0"].engine
            if buffer == "lags":
                # The resident lag buffer is consulted by delta
                # dispatches only — the SCRUBBER is the detection lane
                # for an idle stream: one pass must quarantine it.
                q0 = q_total("quarantined")
                svc_a._scrubber.scrub_once()
                if record:
                    if q_total("quarantined") - q0 >= 1:
                        detected[0] += 1
                    else:
                        late_detections[0] += 1
            else:
                # Dispatch-input digest: the FIRST epoch over the
                # corrupt buffer serves kept_previous (never the
                # corrupt state) — detection within one serving epoch.
                r = epoch_a()
                if record:
                    if r["stream"]["degraded_rung"] == "kept_previous":
                        detected[0] += 1
                    else:
                        late_detections[0] += 1
            # Heal: bit-exact vs a twin seeded from host truth.
            prev = np.array(engine._prev_choice, copy=True)
            heal_lags = fresh()
            r = ca.stream_assign("a0", "t0", rows(heal_lags), members,
                                 options=OPTS)
            try:
                assert_valid_assignment(r["assignments"], P)
            except AssertionError:
                invalid[0] += 1
            if record and not np.array_equal(
                decode(r["assignments"]), twin_expect(prev, heal_lags)
            ):
                heal_mismatch[0] += 1
            # Strike forgiveness (utils/scrub.FORGIVE_AFTER): the next
            # lane's corruption must read as an isolated event, not
            # the continuation of this one.
            epoch_a()
            epoch_a()

    epoch_a()  # cold chain
    epoch_a()  # warm resident
    # Rehearsal until compile-quiet: the quarantine/heal machinery has
    # one-time lazy paths (gather/convert utilities) whose first touch
    # depends on scheduling — the measured round starts only once a
    # whole rehearsal round compiled nothing new.
    for _ in range(3):
        c0 = compile_count()
        storm_a(record=False)
        if compile_count() == c0:
            break
    compiles_a0 = compile_count()
    storm_a()  # measured
    compiles_a = compile_count() - compiles_a0

    # Digest-overhead measurement: the per-epoch HOST cost of the
    # integrity gate (digest fetch + comparison) against the measured
    # warm no-op epoch — the device-side reductions are fused into a
    # dispatch that is upload/readback-bound (the <1%-of-noop gate).
    # Standalone state on purpose: the service engine's mirror is not
    # guaranteed here (a degraded-ladder epoch under extreme host load
    # legitimately leaves it unset), and the check's cost does not
    # depend on whose digest it is.
    from kafka_lag_based_assignor_tpu.ops.streaming import (
        _warm_fused_build,
    )
    from kafka_lag_based_assignor_tpu.ops.batched import stream_payload

    probe_lags = fresh()
    payload, _ = stream_payload(probe_lags)
    dig_out = _warm_fused_build(
        payload, (np.arange(P) % C).astype(np.int32), -1.0,
        num_consumers=C, iters=128, max_pairs=min(C // 2, 16),
        exchange_budget=128,
        bucket=StreamingAssignor(num_consumers=C)._bucket(P),
    )
    # The digest rides the narrow readback's device fetch (ONE
    # device_get for both — ops/streaming), so the marginal per-epoch
    # host cost is the comparison over the already-fetched int64[4].
    digest_host = np.asarray(dig_out[8])
    lag_sum = int(probe_lags.sum(dtype=np.int64))
    reps = 5000
    t0 = time.perf_counter()
    for _ in range(reps):
        scrub_mod.digest_failures(digest_host, P, lag_sum)
    digest_check_ms = (time.perf_counter() - t0) / reps * 1000.0
    # Warm no-op epoch at the NORTH-STAR scale (P=100k, C=1000, no-op
    # threshold path) — the same denominator the round-8 registry
    # budget (<1%, measured 0.75%) was written against, so the two
    # overhead bars read off one definition.
    noop_rng = np.random.default_rng(8)
    noop_lags_ns = noop_rng.integers(1, 10**6, size=100_000)
    eng_noop = StreamingAssignor(
        num_consumers=1000, refine_iters=64, refine_threshold=1000.0
    )
    eng_noop.rebalance(noop_lags_ns)
    eng_noop.rebalance(noop_lags_ns)
    noop_ms = []
    for _ in range(30):
        t0 = time.perf_counter()
        eng_noop.rebalance(noop_lags_ns)
        noop_ms.append((time.perf_counter() - t0) * 1000.0)
    noop_p50 = float(np.percentile(noop_ms, 50))
    ca.close()
    svc_a.stop()

    # ---- Phase B: locked megabatch rows -----------------------------
    # Generous admission window: the probe's determinism (one wave =
    # one locked flush) must not hinge on four client threads landing
    # within the serving default's 0.5 ms.
    svc_b = AssignorService(
        port=0, coalesce_max_batch=N, coalesce_window_ms=500.0,
        scrub_interval_ms=3600_000.0, breaker_cooldown_s=0.5,
    ).start()
    streams = [f"b{i}" for i in range(N)]
    clients = {
        sid: AssignorServiceClient(*svc_b.address, timeout_s=300.0)
        for sid in streams
    }
    pool = cf.ThreadPoolExecutor(max_workers=N)
    last = {sid: fresh() for sid in streams}
    eviction_deltas = []  # one entry per choice/counts locked-row event

    def wave(small_drift=False, check=True):
        for sid in streams:
            if small_drift:
                nxt = last[sid].copy()
                idx = np.random.default_rng(
                    7000 + int(sid[1:])
                ).choice(P, 16, replace=False)
                nxt[idx] += 13
            else:
                nxt = fresh()
            last[sid] = nxt

        def one(sid):
            r = clients[sid].stream_assign(
                sid, "t0", rows(last[sid]), members, options=OPTS
            )
            if check:
                try:
                    assert_valid_assignment(r["assignments"], P)
                except AssertionError:
                    invalid[0] += 1
            return sid, r

        return dict(pool.map(one, streams))

    def quarantined_sids():
        return [
            sid for sid in streams
            if svc_b._streams[sid].engine.quarantined
        ]

    def storm_b(record=True):
        for buffer in BUFFERS:
            inv0 = m.REGISTRY.counter(
                "klba_coalesce_roster_invalidations_total"
            ).value
            inj = faults.FaultInjector(seed=next(seedseq)).plan(
                f"device.corrupt.{buffer}", mode="raise", times=1
            )
            with faults.injected(inj):
                wave()  # locked wave; flip lands at its readback
            if record:
                injected[0] += inj.fired(f"device.corrupt.{buffer}")
            if buffer == "lags":
                # The stacked lag buffer is consumed by the locked
                # DELTA wave: the corrupt row diverges from its host
                # lag sum and re-syncs dense in-request (served, no
                # failure) — detection is the resync count.  If wave
                # scheduling broke the roster first, the wave re-stages
                # DENSE and the corruption is structurally replaced by
                # host truth the same epoch — verify that with a full
                # audit (detected-or-neutralized within one epoch
                # either way; a surviving divergence scores late).
                q0 = q_total("resynced")
                wave(small_drift=True)
                if record:
                    if q_total("resynced") - q0 >= 1:
                        detected[0] += 1
                    else:
                        clean = True
                        for sid in streams:
                            st = svc_b._streams[sid]
                            with st.lock:
                                _aud, fails = scrub_mod.audit_engine(
                                    st.engine
                                )
                            clean = clean and not fails
                        if clean:
                            detected[0] += 1
                        else:
                            late_detections[0] += 1
            else:
                results = wave()
                kept = [
                    sid for sid, r in results.items()
                    if r["stream"]["degraded_rung"] == "kept_previous"
                ]
                if record:
                    if len(kept) == 1:
                        detected[0] += 1
                    else:
                        late_detections[0] += 1
                    # Evicted exactly once per corruption event.
                    eviction_deltas.append(int(
                        m.REGISTRY.counter(
                            "klba_coalesce_roster_invalidations_total"
                        ).value - inv0
                    ))
                # Heal the quarantined row bit-exact before re-locking.
                bad = quarantined_sids()
                for sid in bad:
                    prev = np.array(
                        svc_b._streams[sid].engine._prev_choice,
                        copy=True,
                    )
                    heal_lags = fresh()
                    last[sid] = heal_lags
                    r = clients[sid].stream_assign(
                        sid, "t0", rows(heal_lags), members,
                        options=OPTS,
                    )
                    try:
                        assert_valid_assignment(r["assignments"], P)
                    except AssertionError:
                        invalid[0] += 1
                    if record and not np.array_equal(
                        decode(r["assignments"]),
                        twin_expect(prev, heal_lags),
                    ):
                        heal_mismatch[0] += 1
            wave()  # re-stack / settle
            wave()  # re-lock

    for sid in streams:  # cold chains, serial
        clients[sid].stream_assign(
            sid, "t0", rows(last[sid]), members, options=OPTS
        )
    wave()  # re-stack + lock
    wave()  # locked
    wave(small_drift=True)  # locked delta executable
    # Rehearsal until compile-quiet (see phase A).
    for _ in range(5):
        c0 = compile_count()
        storm_b(record=False)
        if compile_count() == c0:
            break
    compiles_b0 = compile_count()
    storm_b()  # measured
    compiles_b = compile_count() - compiles_b0

    for cl in clients.values():
        cl.close()
    pool.shutdown(wait=True)
    svc_b.stop()

    return {
        "config": "corruption_storm",
        "partitions": P,
        "consumers": C,
        "streams_locked": N,
        "injected": injected[0],
        "detected": detected[0],
        "late_detections": late_detections[0],
        "invalid_assignments": invalid[0],
        "heal_mismatches": heal_mismatch[0],
        "roster_eviction_events": len(eviction_deltas),
        "roster_eviction_max": max(eviction_deltas, default=0),
        "roster_eviction_min": min(eviction_deltas, default=0),
        "storm_compile_count": compiles_a + compiles_b,
        "digest_check_ms": digest_check_ms,
        "warm_noop_p50_ms": noop_p50,
        "digest_overhead_ratio": (
            digest_check_ms / noop_p50 if noop_p50 > 0 else None
        ),
        "quarantined_total": q_total("quarantined"),
        "healed_total": q_total("healed"),
        "resynced_total": q_total("resynced"),
    }


def config12_federated():
    """Federated-partition probe (ISSUE 12): three sidecars, each
    holding one shard of a global lag instance, converge a global
    assignment by exchanging only duals/marginals (federated/), then
    survive a full peer partition and heal.  What must hold (gated in
    main, every backend — the protocol is config, not hardware): the
    converged global assignment's quality is within 5% of the
    single-leader Sinkhorn solve on the concatenated instance; under a
    FULL partition every sidecar keeps serving valid (count-balanced)
    local assignments with zero request errors and zero warm-loop
    compiles; after heal, peers re-converge within the bounded round
    budget; an on-wire audit finds zero raw-lag byte windows in any
    ``peer_sync`` payload; and stale/fenced duals are rejected and
    counted, never blended."""
    import socket as socket_mod

    from kafka_lag_based_assignor_tpu.federated import wire
    from kafka_lag_based_assignor_tpu.models.sinkhorn import (
        assign_topic_sinkhorn,
    )
    from kafka_lag_based_assignor_tpu.ops import fedsolve
    from kafka_lag_based_assignor_tpu.ops.packing import pad_topic_rows
    from kafka_lag_based_assignor_tpu.service import (
        AssignorService,
        AssignorServiceClient,
    )
    from kafka_lag_based_assignor_tpu.utils import faults
    from kafka_lag_based_assignor_tpu.utils import metrics as m
    from kafka_lag_based_assignor_tpu.utils.observability import (
        compile_count,
        install_compile_counter,
    )

    install_compile_counter()
    P, C, N = 2048, 8, 3
    MAX_ROUNDS = 16
    members = [f"m{j}" for j in range(C)]
    rng = np.random.default_rng(0xFED12)
    shards = [
        rng.integers(0, 10**6, P).astype(np.int64) for _ in range(N)
    ]

    def rows(arr):
        return [[i, int(v)] for i, v in enumerate(arr)]

    def stale_total(reason):
        return sum(
            c.value
            for c in m.REGISTRY.series("klba_peer_stale_duals_total")
            if c.labels.get("reason") == reason
        )

    # Full-mesh topology on pre-allocated ports (the coordinator needs
    # every peer's address at construction).
    socks = [socket_mod.socket() for _ in range(N)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    ids = [f"dc{i}" for i in range(N)]
    svcs, clients = [], []
    for i in range(N):
        peer_spec = ",".join(
            f"{ids[j]}=127.0.0.1:{ports[j]}"
            for j in range(N) if j != i
        )
        svc = AssignorService(
            port=ports[i], coalesce_max_batch=1,
            scrub_interval_ms=0.0, breaker_cooldown_s=0.5,
            federation_self_id=ids[i], federation_peers=peer_spec,
            federation_rounds=MAX_ROUNDS,
            federation_sync_timeout_s=300.0,
        ).start()
        svcs.append(svc)
        clients.append(
            AssignorServiceClient(*svc.address, timeout_s=600.0)
        )

    def fed(i):
        return clients[i].federated_assign(
            "t0", rows(shards[i]), members
        )

    def decode_totals(resp, shard):
        midx = {mm: j for j, mm in enumerate(members)}
        got = np.full(P, -1, np.int32)
        for mm, tps in resp["assignments"].items():
            for _t, p in tps:
                got[p] = midx[mm]
        assert int(got.min()) >= 0
        counts = np.bincount(got, minlength=C)
        balanced = int(counts.max() - counts.min()) <= 1
        totals = np.bincount(
            got, weights=shard.astype(np.float64), minlength=C
        )
        return balanced, totals

    # ---- Rehearsal: registration + every ladder rung compiles here,
    # repeated until compile-quiet, so the measured phases below can
    # gate on ZERO fresh executables.
    for _ in range(2):
        for i in range(N):
            fed(i)
    with faults.injected(
        faults.FaultInjector(1).plan("peer.partition", times=0)
    ):
        fed(0)  # last_good_global rung
        svcs[0]._federation._last_good = None
        fed(0)  # local_only rung (stateless rounds solve)
    for svc in svcs:
        svc._watchdog.reset()
    for _ in range(4):
        quiet = compile_count()
        for i in range(N):
            fed(i)
        if compile_count() == quiet:
            break

    # ---- Phase A: converged global quality vs the single leader.
    errors_before = [svc.errors for svc in svcs]
    compiles_a = compile_count()
    responses = [fed(i) for i in range(N)]
    compiles_a = compile_count() - compiles_a
    global_rungs = [r["federation"]["rung"] for r in responses]
    converge_rounds = max(
        r["federation"]["rounds"] for r in responses
    )
    fed_totals = np.zeros(C)
    invalid = 0
    for resp, shard in zip(responses, shards):
        balanced, totals = decode_totals(resp, shard)
        invalid += 0 if balanced else 1
        fed_totals += totals
    fed_q = float(fed_totals.max() / fed_totals.mean())
    full = np.concatenate(shards)
    lags_p, pids_p, valid = pad_topic_rows(full)
    _, _, leader_totals = assign_topic_sinkhorn(
        lags_p, pids_p, valid, num_consumers=C
    )
    leader_totals = np.asarray(leader_totals, np.float64)
    leader_q = float(leader_totals.max() / leader_totals.mean())
    log(
        f"federated: global quality {fed_q:.5f} vs leader "
        f"{leader_q:.5f} in <= {converge_rounds} rounds"
    )

    # ---- Phase B: FULL peer partition — every sidecar keeps serving
    # valid local assignments, zero request errors, zero compiles.
    partition_rungs = []
    compiles_b = compile_count()
    with faults.injected(
        faults.FaultInjector(2).plan("peer.partition", times=0)
    ):
        for wave in range(3):
            for i in range(N):
                if wave == 1 and i == 0:
                    # One lane exercises the BOTTOM rung too: with the
                    # dual cache dropped, partition must degrade to
                    # exactly the single-cluster solve.
                    svcs[0]._federation._last_good = None
                r = fed(i)
                partition_rungs.append(r["federation"]["rung"])
                balanced, _ = decode_totals(r, shards[i])
                invalid += 0 if balanced else 1
    compiles_b = compile_count() - compiles_b
    partition_errors = sum(
        svc.errors - before
        for svc, before in zip(svcs, errors_before)
    )

    # ---- Phase C: heal — breakers close, peers re-converge within
    # the bounded round budget.
    for svc in svcs:
        svc._watchdog.reset()
    heal_rungs, heal_rounds = [], 0
    for i in range(N):
        r = fed(i)
        heal_rungs.append(r["federation"]["rung"])
        heal_rounds = max(heal_rounds, r["federation"]["rounds"])

    # ---- Phase D: on-wire audit — real protocol payloads (request
    # AND response, built by the audited serializer like every peer
    # byte) must contain no window of ANY shard's raw lag vector.
    fed_b = svcs[1]._federation
    total = sum(int(s.sum()) for s in shards)
    scale = max(float(total), 1.0) / C
    A, B = fedsolve.initial_duals(C)
    req = wire.sync_request(
        "bench-audit", 1, 1, C, scale=scale, duals_a=A, duals_b=B,
    )
    resp = fed_b.serve_sync(req)
    wire_leaks = 0
    for payload in (wire.encode(req), wire.encode(resp)):
        for shard in shards:
            try:
                wire.assert_lag_free(payload, shard)
            except AssertionError as exc:
                wire_leaks += 1
                log(f"federated: WIRE LEAK: {exc}")
    marginals_served = "marginals" in resp

    # ---- Phase E: stale + fenced duals rejected and counted.
    stale_before = stale_total("stale_epoch")
    fenced_before = stale_total("fenced")
    fed_b.serve_sync(wire.sync_request(
        "bench-stale", 9, 0, C, scale=1.0, phase="hello",
    ))
    stale_resp = fed_b.serve_sync(wire.sync_request(
        "bench-stale", 2, 0, C, scale=1.0, phase="hello",
    ))
    fed_b.serve_sync(wire.sync_request(
        "bench-fence", 1, 0, C, scale=1.0, phase="hello",
        fence_token=8,
    ))
    fenced_resp = fed_b.serve_sync(wire.sync_request(
        "bench-fence", 2, 0, C, scale=1.0, phase="hello",
        fence_token=3,
    ))
    stale_rejected = stale_total("stale_epoch") - stale_before
    fenced_rejected = stale_total("fenced") - fenced_before

    for c in clients:
        c.close()
    for svc in svcs:
        svc.stop()

    return {
        "config": "federated_partition",
        "sidecars": N,
        "partitions_per_shard": P,
        "consumers": C,
        "max_rounds": MAX_ROUNDS,
        "global_rungs": global_rungs,
        "converge_rounds": converge_rounds,
        "quality_global": round(fed_q, 5),
        "quality_leader": round(leader_q, 5),
        "quality_vs_leader": round(fed_q / leader_q, 5),
        "invalid_assignments": invalid,
        "partition_rungs": partition_rungs,
        "partition_errors": partition_errors,
        "partition_compile_count": compiles_b,
        "global_compile_count": compiles_a,
        "heal_rungs": heal_rungs,
        "heal_rounds": heal_rounds,
        "wire_leaks": wire_leaks,
        "wire_marginals_served": marginals_served,
        "stale_rejected": int(stale_rejected),
        "fenced_rejected": int(fenced_rejected),
        "stale_answer": stale_resp.get("rejected"),
        "fenced_answer": fenced_resp.get("rejected"),
    }


def config13_sharded():
    """Sharded-scale probe (ISSUE 13): the mesh-native backend on this
    host's device mesh — a P-sharded solve at a shape that exercises
    >= 4 devices, and the stream-sharded megabatch against a
    single-device twin.  What must hold (gated in main whenever a mesh
    is constructible — on CPU that needs
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8``, else the
    probe records ``skipped``): every sharded answer valid and
    count-balanced at quality <= 1.1x the bound, ZERO fresh compiles in
    the sharded warm loops, the stream-sharded megabatch within the
    CPU-ref no-regression bound of its single-device twin (2.5x — the
    virtual mesh timeshares ONE physical CPU, so collectives add pure
    overhead; the >= linear-scaling gate is reserved for hardware,
    where D devices actually exist), and a ``mesh.collective`` fault
    mid-wave serving every row valid through the single-device
    fallback with the manager degraded."""
    import threading
    import time as time_mod

    from kafka_lag_based_assignor_tpu.ops.coalesce import (
        MegabatchCoalescer,
    )
    from kafka_lag_based_assignor_tpu.ops.streaming import (
        StreamingAssignor,
    )
    from kafka_lag_based_assignor_tpu.sharded.mesh import MeshManager
    from kafka_lag_based_assignor_tpu.sharded.solve import solve_sharded
    from kafka_lag_based_assignor_tpu.utils import faults
    from kafka_lag_based_assignor_tpu.utils.observability import (
        compile_count,
        install_compile_counter,
    )

    install_compile_counter()
    import jax

    n_dev = len(jax.devices())
    out = {"config": "sharded_scale", "devices": n_dev}
    if n_dev < 4:
        out["skipped"] = (
            f"{n_dev} device(s) visible; the probe needs >= 4 (set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 for "
            "the virtual CPU mesh)"
        )
        log(json.dumps(out))
        return out
    D = 8 if n_dev >= 8 else 4
    mgr = MeshManager(devices=D, solve_min_rows=1024).configure()
    rng = np.random.default_rng(0x5A4D)

    # ---- Part A: P-sharded solve, >= 4 devices at this bucket.
    P, C = 32768, 64
    lags = zipf_lags(rng, P)

    def quality(choice, arr):
        totals = np.bincount(choice, weights=arr, minlength=C)
        return quality_ratio(
            imbalance(totals), imbalance_bound(arr, C)
        )

    single = StreamingAssignor(num_consumers=C)
    t0 = time_mod.perf_counter()
    single_choice = single.rebalance(lags)
    single_ms = (time_mod.perf_counter() - t0) * 1000.0
    solve_sharded(mgr.solve_mesh(), lags, C, refine_iters=64)  # compile
    c0 = compile_count()
    walls, worst_q, valid = [], 0.0, True
    for _ in range(5):
        fresh = zipf_lags(rng, P)
        t0 = time_mod.perf_counter()
        ch, cnt, _, _ = solve_sharded(
            mgr.solve_mesh(), fresh, C, refine_iters=64
        )
        walls.append((time_mod.perf_counter() - t0) * 1000.0)
        counts = np.bincount(ch, minlength=C)
        valid &= bool(
            ch.min() >= 0 and ch.max() < C
            and counts.max() - counts.min() <= 1
            and np.array_equal(cnt, counts)
        )
        worst_q = max(worst_q, quality(ch, fresh))
    out["solve"] = {
        "partitions": P,
        "consumers": C,
        "mesh_devices": D,
        "valid": valid,
        "warm_compile_count": compile_count() - c0,
        "sharded_p50_ms": round(float(np.median(walls)), 2),
        "single_cold_ms": round(single_ms, 2),
        "worst_quality_ratio": round(worst_q, 4),
        "single_quality_ratio": round(
            quality(np.asarray(single_choice), lags), 4
        ),
    }

    # ---- Part B: stream-sharded megabatch vs the single-device twin.
    N, P2, C2 = 8, 2048, 8

    def run_waves(mesh_manager, seed, waves=6):
        rng_w = np.random.default_rng(seed)
        engines = [
            StreamingAssignor(
                num_consumers=C2, refine_iters=64,
                refine_threshold=None,
            )
            for _ in range(N)
        ]
        for e in engines:
            e.rebalance(rng_w.integers(0, 1000, P2).astype(np.int64))
        coal = MegabatchCoalescer(
            window_s=2.0, max_batch=N, lock_waves=1,
            mesh_manager=mesh_manager,
        )
        all_valid, errors = True, 0

        def wave():
            nonlocal all_valid, errors
            arrs = [
                rng_w.integers(0, 1000, P2).astype(np.int64)
                for _ in range(N)
            ]
            outs = [None] * N

            def run(i):
                nonlocal errors
                try:
                    outs[i] = engines[i].submit_epoch(arrs[i], coal)
                except Exception:  # noqa: BLE001 — counted below
                    errors += 1

            threads = [
                threading.Thread(target=run, args=(i,))
                for i in range(N)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for o in outs:
                if o is None:
                    continue
                cc = np.bincount(np.asarray(o), minlength=C2)
                all_valid &= bool(cc.max() - cc.min() <= 1)

        try:
            wave()  # re-stack + lock
            wave()  # first locked wave (compiles once when sharded)
            cw0 = compile_count()
            t0 = time_mod.perf_counter()
            for _ in range(waves):
                wave()
            wall = (time_mod.perf_counter() - t0) * 1000.0
            compiles = compile_count() - cw0
            sharded_roster = coal.stats()["stream_sharded_rosters"]
        finally:
            coal.close()
        return wall, compiles, all_valid, errors, sharded_roster

    sh_wall, sh_compiles, sh_valid, sh_errors, sh_rosters = run_waves(
        mgr, 0xB1
    )
    si_wall, si_compiles, si_valid, si_errors, _ = run_waves(
        None, 0xB2
    )
    out["megabatch"] = {
        "streams": N,
        "partitions": P2,
        "consumers": C2,
        "stream_sharded_rosters": sh_rosters,
        "sharded_wall_ms": round(sh_wall, 2),
        "single_wall_ms": round(si_wall, 2),
        "wall_ratio_vs_single": round(sh_wall / max(si_wall, 1e-9), 3),
        "warm_compile_count": sh_compiles,
        "single_warm_compile_count": si_compiles,
        "all_valid": bool(sh_valid and si_valid),
        "errors": sh_errors + si_errors,
    }

    # ---- Part C: mesh.collective drill — one fault mid-wave must
    # serve every row valid through the single-device fallback and
    # degrade the manager (no invalid assignment, no request error).
    drill_mgr = MeshManager(devices=D, solve_min_rows=1024).configure()
    rng_d = np.random.default_rng(0xC3)
    engines = [
        StreamingAssignor(
            num_consumers=C2, refine_iters=64, refine_threshold=None
        )
        for _ in range(N)
    ]
    for e in engines:
        e.rebalance(rng_d.integers(0, 1000, P2).astype(np.int64))
    coal = MegabatchCoalescer(
        window_s=2.0, max_batch=N, lock_waves=1, mesh_manager=drill_mgr
    )
    drill_valid, drill_errors = True, 0
    try:

        def drill_wave():
            nonlocal drill_valid, drill_errors
            arrs = [
                rng_d.integers(0, 1000, P2).astype(np.int64)
                for _ in range(N)
            ]

            def run(i):
                nonlocal drill_valid, drill_errors
                try:
                    o = engines[i].submit_epoch(arrs[i], coal)
                    cc = np.bincount(np.asarray(o), minlength=C2)
                    drill_valid &= bool(cc.max() - cc.min() <= 1)
                except Exception:  # noqa: BLE001 — counted below
                    drill_errors += 1

            threads = [
                threading.Thread(target=run, args=(i,))
                for i in range(N)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        drill_wave()  # lock sharded
        inj = faults.FaultInjector(0xD).plan(
            "mesh.collective", times=1
        )
        with faults.injected(inj):
            drill_wave()  # faulted wave: single-stream fallback
        drill_wave()  # re-locked single-device
        out["collective_drill"] = {
            "fired": inj.fired("mesh.collective"),
            "degraded": not drill_mgr.active,
            "all_valid": drill_valid,
            "errors": drill_errors,
            "ok": bool(
                inj.fired("mesh.collective") == 1
                and not drill_mgr.active
                and drill_valid
                and drill_errors == 0
            ),
        }
    finally:
        coal.close()
    return out


def config14_linear():
    """Linear-OT quality-mode probe (ISSUE 14): the O(P + C) mirror-prox
    solve (ops/linear_ot) against the dense Sinkhorn path.  What must
    hold (gated in main):

    * **quality** — at the parity shape the linear mode's
      quality_ratio is <= 1.05x the dense Sinkhorn solve's;
    * **memory** — the linear solve's peak device memory does NOT
      scale with P*C: a live-buffer census + the module's analytic
      working-set estimate everywhere (XLA:CPU reports no allocator
      stats; a committed [P, C] plan would still surface as a live
      buffer), with ``jax.local_devices()[0].memory_stats()`` growth
      deltas folded in where the backend exposes them (the raw
      lifetime peak is process-wide and not attributable to one
      solve).  Gate: peak < 1/8 of the [P, C] f32 block at the large
      shape, and the large shape's peak grows sub-P*C from the small
      one's;
    * **zero warm compiles** — repeated linear solves at a warmed
      shape compile nothing;
    * **additive bound** — every solve's max consumer load holds
      ``<= total/C + max_lag`` (asserted inside ops/linear_ot; a
      violation raises and fails the probe).

    When >= 4 devices are visible, the P-sharded composition
    (sharded/solve.solve_linear_sharded) must return BIT-IDENTICAL
    assignments to the single-device path (else the part records
    skipped)."""
    import time as time_mod

    from kafka_lag_based_assignor_tpu.models.sinkhorn import (
        assign_topic_sinkhorn,
    )
    from kafka_lag_based_assignor_tpu.ops import dispatch as dispatch_mod
    from kafka_lag_based_assignor_tpu.ops import linear_ot
    from kafka_lag_based_assignor_tpu.ops.packing import pad_topic_rows
    from kafka_lag_based_assignor_tpu.utils.observability import (
        compile_count,
        install_compile_counter,
    )

    install_compile_counter()
    import jax

    out = {"config": "linear_ot_scale"}
    rng = np.random.default_rng(0x11EA)

    def solve_quality(totals, arr, C):
        totals = np.asarray(totals)
        return quality_ratio(
            imbalance(totals.astype(np.float64)),
            imbalance_bound(arr, C),
        )

    # ---- Part A: quality parity vs the dense Sinkhorn solve.
    P, C = 4096, 64
    lags = zipf_lags(rng, P)
    lp, pp, vp = pad_topic_rows(lags)
    with dispatch_mod.quality_scope("sinkhorn"):
        _, _, s_tot = assign_topic_sinkhorn(
            lp, pp, vp, num_consumers=C
        )
    with dispatch_mod.quality_scope("linear"):
        t0 = time_mod.perf_counter()
        _, _, l_tot = linear_ot.assign_topic_linear(
            lp, pp, vp, num_consumers=C
        )
        linear_ms = (time_mod.perf_counter() - t0) * 1000.0
    q_sink = solve_quality(s_tot, lags, C)
    q_lin = solve_quality(l_tot, lags, C)
    out["parity"] = {
        "partitions": P,
        "consumers": C,
        "quality_ratio_sinkhorn": round(q_sink, 5),
        "quality_ratio_linear": round(q_lin, 5),
        "linear_vs_sinkhorn": round(q_lin / max(q_sink, 1e-9), 5),
        "linear_cold_ms": round(linear_ms, 2),
    }

    # ---- Part B: memory scaling + zero-warm-compile gates.  Two
    # shapes a factor of 4 apart in P at fixed C: an O(P*C) peak
    # would quadruple; the linear peak is dominated by the O(P)
    # vectors + the fixed (tile, C) block.
    C2 = 128
    shapes = [16384, 65536]
    mem_rows = []
    dev = jax.local_devices()[0]
    for Pn in shapes:
        arr = zipf_lags(rng, Pn)
        lpn, ppn, vpn = pad_topic_rows(arr)
        with dispatch_mod.quality_scope("linear"):
            # Warm the executables, then measure the repeat solves.
            linear_ot.assign_topic_linear(lpn, ppn, vpn, num_consumers=C2)
            stats_fn = getattr(dev, "memory_stats", None)
            base_stats = stats_fn() if callable(stats_fn) else None
            c0 = compile_count()
            t0 = time_mod.perf_counter()
            _, _, tot_n = linear_ot.assign_topic_linear(
                lpn, ppn, vpn, num_consumers=C2
            )
            warm_ms = (time_mod.perf_counter() - t0) * 1000.0
            warm_compiles = compile_count() - c0
        info = linear_ot.last_solve_info() or {}
        pc_bytes = int(lpn.shape[0]) * C2 * 4
        # Live-buffer census (a materialized [P, C] plan would be a
        # committed buffer) + the module's analytic working-set
        # estimate: the attributable, backend-independent gate value.
        live = max(
            (int(np.prod(a.shape)) * a.dtype.itemsize
             for a in jax.live_arrays()),
            default=0,
        )
        peak = max(live, int(info.get("peak_bytes_estimate", 0)))
        peak_source = "live_buffers+estimate"
        if base_stats and "peak_bytes_in_use" in base_stats:
            # Allocator stats where the backend exposes them:
            # peak_bytes_in_use is a PROCESS-LIFETIME high-water mark
            # (configs 1-13 already pushed it), so only the growth
            # since the pre-solve snapshot is attributable to this
            # solve — fold that delta in, and report the raw peak for
            # the hardware follow-on (ROADMAP linear-space (a)).
            raw_peak = int(dev.memory_stats()["peak_bytes_in_use"])
            delta = raw_peak - int(base_stats["peak_bytes_in_use"])
            peak = max(peak, delta)
            peak_source = "memory_stats_delta+live_buffers+estimate"
        mem_rows.append({
            "partitions": int(lpn.shape[0]),
            "consumers": C2,
            "tiles": info.get("tiles"),
            "tile": info.get("tile"),
            "warm_ms": round(warm_ms, 2),
            "warm_compile_count": int(warm_compiles),
            "peak_bytes": int(peak),
            "peak_source": peak_source,
            "pc_bytes": pc_bytes,
            "peak_pc_fraction": round(peak / pc_bytes, 4),
            "quality_ratio": round(
                solve_quality(tot_n, arr, C2), 5
            ),
        })
    out["scale"] = {
        "rows": mem_rows,
        # Sub-P*C growth: with P x4 at fixed C, an O(P*C) peak grows
        # ~4x; the linear peak's growth is bounded by the O(P) terms.
        "peak_growth": round(
            mem_rows[1]["peak_bytes"] / max(mem_rows[0]["peak_bytes"], 1),
            3,
        ),
        "warm_compile_count": sum(
            r["warm_compile_count"] for r in mem_rows
        ),
    }

    # ---- Part C: sharded composition — bit-identical at mesh sizes.
    n_dev = len(jax.devices())
    if n_dev < 4:
        out["sharded"] = {"skipped": (
            f"{n_dev} device(s) visible; the parity part needs >= 4"
        )}
    else:
        from kafka_lag_based_assignor_tpu.sharded.mesh import MeshManager
        from kafka_lag_based_assignor_tpu.sharded.solve import (
            solve_linear_sharded,
        )

        D = 8 if n_dev >= 8 else 4
        Pq, Cq = 32768, 64
        arr = zipf_lags(rng, Pq)
        lpq, ppq, vpq = pad_topic_rows(arr)
        with dispatch_mod.quality_scope("linear"):
            single, _, tot_single = linear_ot.assign_topic_linear(
                lpq, ppq, vpq, num_consumers=Cq, refine_iters=64
            )
            mgr = MeshManager(devices=D, solve_min_rows=1024).configure()
            t0 = time_mod.perf_counter()
            sharded_ch, _, tot_sh, _ = solve_linear_sharded(
                mgr.solve_mesh(), arr, Cq, refine_iters=64
            )
            sharded_ms = (time_mod.perf_counter() - t0) * 1000.0
        out["sharded"] = {
            "partitions": Pq,
            "consumers": Cq,
            "mesh_devices": D,
            "bit_identical": bool(
                np.array_equal(
                    sharded_ch, np.asarray(single)[:Pq]
                )
                and np.array_equal(
                    np.asarray(tot_sh), np.asarray(tot_single)
                )
            ),
            "sharded_ms": round(sharded_ms, 2),
            "quality_ratio": round(
                solve_quality(tot_sh, arr, Cq), 5
            ),
        }
    return out


def config15_linear_kernel():
    """Linear-OT kernel plane probe (ISSUE 16): the fused Pallas
    mirror-prox step + digest epilogue (ops/linear_ot_pallas) against
    their XLA lowerings.  What must hold (gated in main):

    * **speed** — where the device probe enabled the duals kernel, the
      probe-shape race it recorded shows the kernel >= 1.0x the XLA
      tile scan (the admission condition, re-surfaced so the bench
      record carries the measured timings);
    * **zero warm compiles** — repeated solves at a warmed shape,
      through whichever lowering the gate elected, compile nothing;
    * **digest integrity 6/6** — a corruption storm over the resident
      state (range violations both directions, count drift both
      directions, lag tamper, an unaccounted reassignment) changes the
      digest in EVERY scenario, through the production seam
      (ops/refine.state_digest) AND the kernel trace (interpret mode
      covers it on CPU — the same trace Mosaic lowers on hardware);
    * **interpret parity** — the CPU-runnable bit-parity self-check
      passes for both planes."""
    import time as time_mod

    from kafka_lag_based_assignor_tpu.ops import dispatch as dispatch_mod
    from kafka_lag_based_assignor_tpu.ops import linear_ot, refine
    from kafka_lag_based_assignor_tpu.ops import linear_ot_pallas as lop
    from kafka_lag_based_assignor_tpu.ops.packing import pad_topic_rows
    from kafka_lag_based_assignor_tpu.utils.observability import (
        compile_count,
        install_compile_counter,
    )

    install_compile_counter()
    import jax
    import jax.numpy as jnp

    out = {"config": "linear_ot_kernel"}
    rng = np.random.default_rng(0x15E1)

    # ---- Part A: gate verdicts + the probe race.  The probe itself
    # ran in main, off every timed path; this reads the verdict.
    out["gate"] = {
        "backend": jax.default_backend(),
        "duals_kernel": bool(lop.linear_pallas_available(kind="duals")),
        "digest_kernel": bool(
            lop.linear_pallas_available(kind="digest")
        ),
        "race_ms": lop._LAST_RACE,
        "probe_shape": {
            "rows": lop.PROBE_ROWS,
            "consumers": lop.PROBE_CONSUMERS,
            "tile": lop.PROBE_TILE,
        },
    }

    # ---- Part B: zero warm compiles through the elected lowering
    # (kernel where the gate + admission elected it, XLA tile scan
    # otherwise — the dispatch seam itself is what is being warmed).
    P, C = 16384, 128
    arr = zipf_lags(rng, P)
    lpn, ppn, vpn = pad_topic_rows(arr)
    with dispatch_mod.quality_scope("linear"):
        linear_ot.assign_topic_linear(lpn, ppn, vpn, num_consumers=C)
        c0 = compile_count()
        t0 = time_mod.perf_counter()
        linear_ot.assign_topic_linear(lpn, ppn, vpn, num_consumers=C)
        warm_ms = (time_mod.perf_counter() - t0) * 1000.0
        warm_compiles = compile_count() - c0
    info = linear_ot.last_solve_info() or {}
    out["warm"] = {
        "partitions": P,
        "consumers": C,
        "warm_ms": round(warm_ms, 2),
        "warm_compile_count": int(warm_compiles),
        "duals_kernel_dispatched": bool(info.get("duals_kernel")),
    }

    # ---- Part C: digest corruption storm.  Every scenario must move
    # the digest — through the seam AND the kernel trace.
    Pd, Cd = 4096, 64
    lags = rng.integers(0, 10**9, size=Pd).astype(np.int64)
    choice = rng.integers(0, Cd, size=Pd).astype(np.int32)
    counts = np.bincount(choice, minlength=Cd).astype(np.int64)

    def seam_digest(lg, ch, ct):
        return np.asarray(refine.state_digest(
            jnp.asarray(lg), jnp.asarray(ch), jnp.asarray(ct), Cd
        ))

    def interp_digest(lg, ch, ct):
        return np.asarray(lop.state_digest_pallas(
            jnp.asarray(lg), jnp.asarray(ch), jnp.asarray(ct), Cd,
            interpret=True,
        ))

    clean = seam_digest(lags, choice, counts)
    if not (interp_digest(lags, choice, counts) == clean).all():
        raise AssertionError(
            "config15: kernel digest differs from the seam's on the "
            "CLEAN state"
        )

    def corrupted(name):
        lg, ch, ct = lags.copy(), choice.copy(), counts.copy()
        if name == "choice_high":
            ch[0] = Cd + 5
        elif name == "choice_negative":
            ch[1] = -9  # -1 is legitimate padding; below it is rot
        elif name == "count_inflate":
            ct[0] += 3
        elif name == "count_deflate":
            ct[Cd - 1] -= 1
        elif name == "lag_tamper":
            lg[7] += 1
        elif name == "row_reassign":
            # A row moved between consumers with counts left stale:
            # only the recount-vs-resident channel can see this.
            ch[2] = (int(ch[2]) + 1) % Cd
        return lg, ch, ct

    storm = {}
    for name in ("choice_high", "choice_negative", "count_inflate",
                 "count_deflate", "lag_tamper", "row_reassign"):
        lg, ch, ct = corrupted(name)
        seam_hit = not (seam_digest(lg, ch, ct) == clean).all()
        interp_hit = not (interp_digest(lg, ch, ct) == clean).all()
        storm[name] = bool(seam_hit and interp_hit)
    out["digest_storm"] = {
        "scenarios": storm,
        "detected": int(sum(storm.values())),
        "total": len(storm),
    }

    # ---- Part D: the CPU-runnable bit-parity self-check (also what
    # the kernel report artifact records).
    out["interpret_parity"] = lop.interpret_parity_check()
    return out


def config16_scenarios():
    """Adversarial scenario fleet (ISSUE 17): the full scenarios/
    corpus — seeded trace generators (hot-partition storms, flapping
    rosters, correlated lag waves, zipf tenant mixes, diurnal ramps,
    step loads) composed with fault-schedule planes and replayed
    wire-level against a real sidecar, each run gated by its
    declarative degradation envelope.  What must hold (gated in main):
    every scenario stays inside its envelope — zero invalid
    assignments, zero critical-class sheds, shed ordering respected,
    zero steady-state warm-loop compiles where gated, planted
    corruptions detected by the integrity plane, and the mid-trace
    crash/restart scenario bit-exact against its unfaulted twin.  The
    artifact lands in scenario_fleet.json (every row carries its
    reproduction command + seed; see DEPLOYMENT.md "Adversarial
    scenarios")."""
    from scenarios import run_fleet

    fleet = run_fleet(log=log)
    with open("scenario_fleet.json", "w") as f:
        json.dump(fleet, f, indent=2, default=str)
    rows = fleet["scenarios"]
    return {
        "config": "scenario_fleet",
        "scenarios": len(rows),
        "composed_fault_scenarios": sum(
            1 for r in rows
            if len(r["planes"]) >= 2 or (
                r["planes"] and r["crash_epoch"] is not None
            )
        ),
        "crash_restart_scenarios": sum(
            1 for r in rows if r["crash_epoch"] is not None
        ),
        "served": sum(r["served"] for r in rows),
        "sheds": sum(r["sheds"] for r in rows),
        "invalid": sum(r["invalid"] for r in rows),
        "quarantines": sum(r["quarantines"] for r in rows),
        "corruptions_planted": sum(
            r["corruptions_planted"] for r in rows
        ),
        "wall_s": round(sum(r["wall_s"] for r in rows), 3),
        "violations": fleet["violations"],
        "failed_scenarios": [
            {"scenario": r["scenario"], "violations": r["violations"],
             "reproduce": r["reproduce"]}
            for r in rows if r["violations"]
        ],
        "ok": fleet["ok"],
    }


def config17_tracing():
    """Causal-tracing probe (ISSUE 18): the trace plane end to end —
    a two-sidecar ``federated_assign`` degraded by an injected
    ``peer.partition`` AFTER the hello crossed (so the trace spans both
    processes AND descends the ladder), the coalescer's wave fan-in
    links, and the tracing plane's cost on the warm no-op epoch.  What
    must hold (gated in main, every backend — propagation and
    retention are host-side config): :func:`trace.join_trace` over the
    kept segments of the degraded request reconstructs ONE complete
    trace with >= 2 segments, kept as anomalous; every coalesced
    request trace is bidirectionally linked to its ``coalesce.wave``
    trace; the tracing plane's MARGINAL cost on the warm no-op epoch —
    traced scope vs the seed's flat request scope, order-cancelling
    paired estimator — stays < 1% of the epoch; and the traced loop
    compiles nothing."""
    import concurrent.futures as cf
    import socket as socket_mod

    from kafka_lag_based_assignor_tpu.ops.streaming import (
        StreamingAssignor,
    )
    from kafka_lag_based_assignor_tpu.service import (
        AssignorService,
        AssignorServiceClient,
    )
    from kafka_lag_based_assignor_tpu.utils import faults
    from kafka_lag_based_assignor_tpu.utils import metrics as m
    from kafka_lag_based_assignor_tpu.utils import trace as trace_mod
    from kafka_lag_based_assignor_tpu.utils.observability import (
        compile_count,
        install_compile_counter,
    )

    install_compile_counter()
    P, C = 2048, 8
    members = [f"m{j}" for j in range(C)]
    rng = np.random.default_rng(0x7AC17)

    def rows(arr):
        return [[i, int(v)] for i, v in enumerate(arr)]

    def fresh():
        return rng.integers(0, 10**6, P).astype(np.int64)

    coll = trace_mod.collector()
    prev_rate = coll.sample_rate

    def settled(trace_id, want=1, deadline_s=5.0):
        """Kept segments for ``trace_id`` — polled briefly: a wave's
        scope finishes on the reader thread a beat AFTER the request
        futures resolve, and the request scope closes as the response
        line is written."""
        t0 = time.perf_counter()
        while True:
            got = coll.traces(trace_id=trace_id)
            if len(got) >= want or time.perf_counter() - t0 > deadline_s:
                return got
            time.sleep(0.01)

    # ---- Phase C (run FIRST): overhead + compile gate ------------------
    # Measured before the sidecar/wave drills on purpose: minutes of
    # service churn fragment the heap and inflate the traced lane's
    # allocation cost ~8x, and a production sidecar's warm loop runs in
    # a process that never did any of that.  Priced at the documented
    # default sample rate — the fast-drop lane is the production case.
    coll.sample_rate = 0.01
    noop_rng = np.random.default_rng(8)
    noop_lags = noop_rng.integers(1, 10**6, size=100_000)
    eng = StreamingAssignor(
        num_consumers=1000, refine_iters=64, refine_threshold=1000.0
    )
    eng.rebalance(noop_lags)
    eng.rebalance(noop_lags)
    with m.request_scope(kind="client", root_name="client"):
        eng.rebalance(noop_lags)  # trace-path first-touch off the clock

    # The BASELINE is the seed's per-request scope, reproduced verbatim
    # (generator context manager over a trace-less _RequestCtx): the
    # service has wrapped every wire request in a scope since round 8,
    # so the 1% budget prices what the TRACING PLANE added to a warm
    # epoch, not the pre-existing timeline machinery.
    from contextlib import contextmanager

    @contextmanager
    def seed_scope():
        rid = m.mint_request_id()
        ctx = m._RequestCtx(rid, m.REGISTRY.clock())
        m._tls.ctx = ctx
        try:
            yield rid
        finally:
            m._tls.ctx = None
            m._teardown_ctx(ctx, finish=True)

    def run_seed():
        t0 = time.perf_counter()
        with seed_scope():
            eng.rebalance(noop_lags)
        return (time.perf_counter() - t0) * 1e6

    def run_traced():
        t0 = time.perf_counter()
        with m.request_scope(kind="client", root_name="client"):
            eng.rebalance(noop_lags)
        return (time.perf_counter() - t0) * 1e6

    def trimmed_mean(xs, frac=0.2):
        xs = np.sort(np.asarray(xs))
        k = int(len(xs) * frac)
        return float(xs[k: len(xs) - k].mean())

    def paired_delta(fa, fb, pairs):
        # Order-cancelling paired estimator: epoch noise on this host
        # (sigma ~10% of the epoch) dwarfs the ~10 us signal, and a
        # fixed a-then-b order carries a position bias of the same
        # magnitude as the bar.  Alternate the order, take the trimmed
        # mean per ordering (the trim also sheds the ~1% of traced
        # iterations that keep their trace and pay the full finish),
        # average the two — biases cancel, outliers drop.
        ab, ba = [], []
        for i in range(pairs):
            if i & 1:
                b = fb()
                a = fa()
                ba.append(b - a)
            else:
                a = fa()
                b = fb()
                ab.append(b - a)
        return (trimmed_mean(ab) + trimmed_mean(ba)) / 2

    compiles0 = compile_count()
    null_us = paired_delta(run_seed, run_seed, 600)
    marginal_us = paired_delta(run_seed, run_traced, 2400)
    warm_compiles = compile_count() - compiles0
    seed_p50_us = np.percentile([run_seed() for _ in range(200)], 50)
    plain_p50 = float(seed_p50_us) / 1000.0
    traced_p50 = plain_p50 + max(0.0, marginal_us) / 1000.0
    overhead = (
        max(0.0, marginal_us) / seed_p50_us if seed_p50_us > 0 else None
    )
    log(
        f"tracing: noop p50 {plain_p50:.3f}ms marginal "
        f"{marginal_us:.2f}us (estimator null {null_us:.2f}us) "
        f"overhead {overhead:.4%}"
    )


    # ---- Phase A: two-sidecar federated reconstruction -----------------
    # The documented per-process sampling limit (utils/trace module
    # docstring): cross-process reconstruction drills run at rate 1.0
    # so the HEALTHY remote segment of the locally-degraded trace is
    # kept by the same deterministic decision.
    coll.sample_rate = 1.0
    # Pre-allocated full-mesh ports (config12's construction pattern).
    socks = [socket_mod.socket() for _ in range(2)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    ids = ["tr0", "tr1"]
    shards = [fresh(), fresh()]
    svcs, clients = [], []
    for i in range(2):
        j = 1 - i
        svc = AssignorService(
            port=ports[i], coalesce_max_batch=1,
            scrub_interval_ms=0.0, breaker_cooldown_s=0.5,
            federation_self_id=ids[i],
            federation_peers=f"{ids[j]}=127.0.0.1:{ports[j]}",
            federation_rounds=8, federation_sync_timeout_s=300.0,
        ).start()
        svcs.append(svc)
        clients.append(
            AssignorServiceClient(*svc.address, timeout_s=600.0)
        )

    def fed(i):
        return clients[i].federated_assign(
            "t0", rows(shards[i]), members
        )

    # Rehearsal: register both shards, fill the last-good dual cache
    # (the rung the partition below must land on), compile-quiet.
    for _ in range(2):
        fed(0)
        fed(1)

    # The drill: call 1 at the transport fault point is the hello (it
    # must CROSS, carrying the traceparent, so the peer records its
    # joined segment) — ``after=1`` then partitions every exchange
    # round, abandoning the global attempt onto the cached rung.
    with faults.injected(
        faults.FaultInjector(17).plan("peer.partition", times=0, after=1)
    ):
        r = fed(0)
    fed_rung = r["federation"]["rung"]
    fed_tid = clients[0].last_trace_id
    t0 = time.perf_counter()
    while True:
        entries = settled(fed_tid, want=2)
        verdict = trace_mod.join_trace(entries)
        if verdict["complete"] or time.perf_counter() - t0 > 5.0:
            break
        time.sleep(0.01)
    origin = next(
        (
            e for e in entries
            if (e.get("root") or {}).get("parent_id") is None
        ),
        None,
    )
    remote_segments = sum(
        1 for e in entries
        if (e.get("root") or {}).get("parent_id") is not None
    )
    log(
        f"tracing: federated rung {fed_rung}, trace {fed_tid} "
        f"joined {verdict}"
    )
    for c in clients:
        c.close()
    for svc in svcs:
        svc.stop()

    # ---- Phase B: coalescer wave fan-in links --------------------------
    # Generous admission window (config11 Phase B's determinism note);
    # forced-dispatch options so the host no-op gate cannot absorb an
    # epoch before it reaches the coalescer.
    W = 4
    OPTS = {"guardrail": None, "refine_threshold": None}
    svc_w = AssignorService(
        port=0, coalesce_max_batch=W, coalesce_window_ms=500.0,
        scrub_interval_ms=3600_000.0, breaker_cooldown_s=0.5,
    ).start()
    streams = [f"w{i}" for i in range(W)]
    wave_clients = {
        sid: AssignorServiceClient(*svc_w.address, timeout_s=300.0)
        for sid in streams
    }
    pool = cf.ThreadPoolExecutor(max_workers=W)

    def wave_round():
        def one(sid):
            return wave_clients[sid].stream_assign(
                sid, "t0", rows(fresh()), members, options=OPTS
            )

        list(pool.map(one, streams))

    # Two warm rounds (cold solves may resolve singly off the wave
    # path), then the measured round whose links the gate reads.
    wave_round()
    wave_round()
    wave_round()
    wave_links_ok = True
    wave_ids = set()
    for sid in streams:
        tid = wave_clients[sid].last_trace_id
        req_entries = settled(tid)
        forward = [
            ln
            for e in req_entries
            for ln in e.get("links", [])
            if ln.get("relation") == "wave"
        ]
        if not forward:
            wave_links_ok = False
            log(f"tracing: stream {sid} trace {tid} has no wave link")
            continue
        wid = forward[-1]["trace_id"]
        wave_ids.add(wid)
        back = [
            ln
            for we in settled(wid)
            for ln in we.get("links", [])
            if ln.get("relation") == "request"
            and ln.get("trace_id") == tid
        ]
        if not back:
            wave_links_ok = False
            log(f"tracing: wave {wid} has no back-link to {tid}")
    pool.shutdown()
    for c in wave_clients.values():
        c.close()
    svc_w.stop()
    coll.sample_rate = prev_rate

    return {
        "config": "tracing",
        "sidecars": 2,
        "federated_rung": fed_rung,
        "federated_trace_id": fed_tid,
        "federated_join": verdict,
        "federated_outcome": (
            origin.get("outcome") if origin is not None else None
        ),
        "federated_anomalies": (
            origin.get("anomalies") if origin is not None else None
        ),
        "remote_segments": remote_segments,
        "wave_requests": W,
        "wave_traces": len(wave_ids),
        "wave_links_ok": wave_links_ok,
        "warm_noop_p50_ms": plain_p50,
        "traced_noop_p50_ms": traced_p50,
        "trace_marginal_us": float(marginal_us),
        "trace_estimator_null_us": float(null_us),
        "trace_overhead_ratio": overhead,
        "warm_compile_count": warm_compiles,
        "trace_stats": coll.stats(),
    }


def config18_delta_roundtrip():
    """Delta round-trip probe (ISSUE 19): the O(changed) READBACK half
    of the delta plane plus the async-gossip federated serve path, over
    a steady-state 1% churn drift.  What must hold (gated in main AND
    in the tier-1 workflow's probe step via
    :func:`delta_roundtrip_gates`, every backend — bytes and warm-cache
    routing are shape/config facts, not hardware ones): every epoch of
    the delta engine is BIT-IDENTICAL to an always-dense twin over the
    same seeded drift, every epoch takes the O(changed) readback
    (klba_rb_delta_epochs_total{outcome=applied}, zero dense d2h bytes
    charged to the delta engine), the per-epoch d2h bytes
    (klba_d2h_bytes_total{path=delta}) are >= 20x below the dense
    twin's, zero fresh XLA compiles in either measured loop (the
    compaction tail rides the resident refine executables' existing
    compile keys), and — with a warm gossip cache —
    ``federated_assign`` serves rung global from the cache in ONE
    local round (p50 warm_cache true, no synchronous peer RTT) at
    quality within 1.001x of the synchronous exchange on identical
    lags."""
    import socket as socket_mod

    from kafka_lag_based_assignor_tpu.ops.streaming import (
        StreamingAssignor,
    )
    from kafka_lag_based_assignor_tpu.service import (
        AssignorService,
        AssignorServiceClient,
    )
    from kafka_lag_based_assignor_tpu.utils import metrics as klba_metrics
    from kafka_lag_based_assignor_tpu.utils.observability import (
        compile_count,
        install_compile_counter,
    )
    from kafka_lag_based_assignor_tpu.warmup import warmup

    install_compile_counter()

    # ---- Phase A: O(changed) readback at 1% churn ----------------------
    # refine_iters=64 keeps the compaction width K = pow2(2*64) = 128
    # well under the 1%-churn dense narrow vector at this P, so the
    # >= 20x gate reads the plane's design margin, not a knife edge.
    P, C, epochs = 16384, 16, 12
    churn = max(1, int(0.01 * P))
    iters = 64
    rng = np.random.default_rng(18)
    base = rng.integers(10**5, 10**6, P).astype(np.int64)

    # Dense + delta executables (incl. the K-tailed readback variants)
    # off the measured path; must match refine_iters — the exchange
    # budget is a static compile key.
    warmup(
        max_partitions=P, consumers=[C], solvers=("stream",),
        stream_refine_iters=iters,
    )

    d2h_dense_c = klba_metrics.REGISTRY.counter(
        "klba_d2h_bytes_total", {"path": "dense"}
    )
    d2h_delta_c = klba_metrics.REGISTRY.counter(
        "klba_d2h_bytes_total", {"path": "delta"}
    )
    rb_applied_c = klba_metrics.REGISTRY.counter(
        "klba_rb_delta_epochs_total", {"outcome": "applied"}
    )

    def drive(delta_enabled: bool):
        eng = StreamingAssignor(
            num_consumers=C, refine_iters=iters, refine_threshold=None,
            delta_enabled=delta_enabled,
        )
        seq = np.random.default_rng(1899)  # IDENTICAL drift both drives
        lags = base.copy()
        choices = [np.asarray(eng.rebalance(lags))]  # cold, unmeasured
        before = (
            d2h_dense_c.value, d2h_delta_c.value, rb_applied_c.value,
            compile_count(),
        )
        for _ in range(epochs):
            idx = seq.choice(P, size=churn, replace=False)
            lags = lags.copy()
            lags[idx] = seq.integers(10**5, 10**6, churn)
            choices.append(np.asarray(eng.rebalance(lags)))
        after = (
            d2h_dense_c.value, d2h_delta_c.value, rb_applied_c.value,
            compile_count(),
        )
        return choices, [a - b for a, b in zip(after, before)]

    dense_choices, dense_counts = drive(False)
    delta_choices, delta_counts = drive(True)
    mismatched = sum(
        int(not np.array_equal(a, b))
        for a, b in zip(dense_choices, delta_choices)
    )
    dense_per_epoch = dense_counts[0] / epochs
    delta_per_epoch = delta_counts[1] / epochs
    log(
        f"delta_roundtrip: d2h dense {dense_per_epoch:.0f} B/epoch vs "
        f"delta {delta_per_epoch:.0f} B/epoch "
        f"({dense_per_epoch / max(delta_per_epoch, 1e-9):.1f}x), "
        f"rb applied {delta_counts[2]}/{epochs}"
    )

    # ---- Phase B: federated serve from the warm gossip cache -----------
    # Two sidecars, sidecar a with the gossip daemon on a 100 ms
    # jittered cadence (freshness window 2.5x that — comfortably wider
    # than a CPU-backend local round, so a loaded runner still serves
    # warm).  Fixed lags per side: the sync reference and the warm
    # serves then answer the SAME problem, so the quality ratio isolates
    # the cache (converged duals are identical -> ratio 1.0 by design).
    Pf, Cf = 2048, 8
    members = [f"m{j}" for j in range(Cf)]
    frng = np.random.default_rng(0x18F)
    shards = [
        frng.integers(0, 10**6, Pf).astype(np.int64) for _ in range(2)
    ]

    def rows(arr):
        return [[i, int(v)] for i, v in enumerate(arr)]

    def quality(assignments, lags):
        loads = [
            sum(int(lags[p]) for _t, p in tps)
            for tps in assignments.values()
        ]
        mean = sum(int(v) for v in lags) / Cf
        return max(loads) / mean if mean > 0 else 1.0

    socks = [socket_mod.socket() for _ in range(2)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    ids = ["dr0", "dr1"]
    svcs, clients = [], []
    for i in range(2):
        j = 1 - i
        svc = AssignorService(
            port=ports[i], coalesce_max_batch=1,
            scrub_interval_ms=0.0, breaker_cooldown_s=0.5,
            federation_self_id=ids[i],
            federation_peers=f"{ids[j]}=127.0.0.1:{ports[j]}",
            federation_rounds=8, federation_sync_timeout_s=300.0,
            federation_gossip_interval_s=0.1 if i == 0 else 0.0,
        ).start()
        svcs.append(svc)
        clients.append(
            AssignorServiceClient(*svc.address, timeout_s=600.0)
        )

    def fed(i):
        return clients[i].federated_assign(
            "t0", rows(shards[i]), members
        )

    try:
        # b registers its shard first (its serve path must hold a local
        # view before a's exchange can converge), then a's first call IS
        # the synchronous-exchange reference the warm serves compare to.
        fed(1)
        t0 = time.perf_counter()
        sync_r = fed(0)
        sync_ms = (time.perf_counter() - t0) * 1000.0
        sync_quality = quality(sync_r["assignments"], shards[0])
        sync_rung = sync_r["federation"]["rung"]
        # The daemon's first converged tick seeds the warm cache.
        gossip = svcs[0]._federation
        t0 = time.perf_counter()
        while (
            (gossip.last_gossip or {}).get("outcome") != "ok"
            and time.perf_counter() - t0 < 10.0
        ):
            time.sleep(0.01)
        fed(0)  # rehearsal: first warm-cache serve off the clock
        warm_ms, warm_flags, rungs, qualities = [], [], [], []
        for _ in range(9):
            t0 = time.perf_counter()
            r = fed(0)
            warm_ms.append((time.perf_counter() - t0) * 1000.0)
            warm_flags.append(
                bool(r["federation"].get("warm_cache", False))
            )
            rungs.append(r["federation"]["rung"])
            qualities.append(quality(r["assignments"], shards[0]))
    finally:
        for c in clients:
            c.close()
        for svc in svcs:
            svc.stop()

    warm_fraction = sum(warm_flags) / len(warm_flags)
    worst_quality_ratio = max(qualities) / sync_quality
    log(
        f"delta_roundtrip: federated warm fraction "
        f"{warm_fraction:.2f} (sync {sync_ms:.1f}ms rung {sync_rung}, "
        f"warm p50 {float(np.percentile(warm_ms, 50)):.1f}ms), "
        f"quality ratio {worst_quality_ratio:.6f}"
    )
    return {
        "config": "delta_roundtrip",
        "partitions": P,
        "consumers": C,
        "epochs": epochs,
        "churn_fraction": churn / P,
        "refine_iters": iters,
        "d2h_dense_bytes_per_epoch": dense_per_epoch,
        "d2h_delta_bytes_per_epoch": delta_per_epoch,
        "d2h_reduction_x": dense_per_epoch / max(delta_per_epoch, 1e-9),
        "rb_applied": delta_counts[2],
        # Dense d2h bytes charged DURING the delta engine's loop: any
        # nonzero value means an epoch fell off the O(changed) readback.
        "delta_engine_dense_d2h_bytes": delta_counts[0],
        "mismatched_epochs": mismatched,
        "warm_compile_count": dense_counts[3] + delta_counts[3],
        "reduction_target_x": 20.0,
        "fed_sync_rung": sync_rung,
        "fed_sync_ms": sync_ms,
        "fed_warm_p50_ms": float(np.percentile(warm_ms, 50)),
        "fed_warm_fraction": warm_fraction,
        "fed_rungs": sorted(set(rungs)),
        "fed_quality_ratio": worst_quality_ratio,
    }


def delta_roundtrip_gates(dr) -> list:
    """The delta_roundtrip regression gates, shared verbatim by
    bench main() and the tier-1 workflow's probe step (the config17
    precedent: one definition, two call sites)."""
    failures = []
    if dr.get("mismatched_epochs", 0) > 0:
        failures.append(
            f"delta_roundtrip produced {dr['mismatched_epochs']} "
            "epoch(s) differing from the dense-readback twin — the "
            "O(changed) readback is not bit-exact"
        )
    if dr.get("rb_applied", 0) < dr.get("epochs", 0):
        failures.append(
            f"delta_roundtrip applied only {dr.get('rb_applied')}"
            f"/{dr.get('epochs')} epochs via the O(changed) readback "
            f"(dense d2h bytes charged: "
            f"{dr.get('delta_engine_dense_d2h_bytes')})"
        )
    red = dr.get("d2h_reduction_x")
    if red is None or red < dr.get("reduction_target_x", 20.0):
        failures.append(
            f"delta_roundtrip d2h_reduction_x {red} < "
            f"{dr.get('reduction_target_x', 20.0)}x — the readback is "
            "not O(changed) at 1% churn"
        )
    if dr.get("warm_compile_count", 1) != 0:
        failures.append(
            f"delta_roundtrip warm_compile_count "
            f"{dr['warm_compile_count']} != 0 — the compaction tail "
            "minted fresh executables inside the steady-state loop"
        )
    if dr.get("fed_sync_rung") != "global":
        failures.append(
            f"delta_roundtrip federated sync reference served rung "
            f"{dr.get('fed_sync_rung')!r} — the exchange never "
            "converged, so the warm-cache gate read a degraded mesh"
        )
    if dr.get("fed_warm_fraction", 0.0) < 0.5:
        failures.append(
            f"delta_roundtrip fed_warm_fraction "
            f"{dr.get('fed_warm_fraction')} < 0.5 — federated_assign "
            "p50 is not serving from the warm gossip cache in one "
            "local round"
        )
    if dr.get("fed_rungs") != ["global"]:
        failures.append(
            f"delta_roundtrip federated serves hit rung(s) "
            f"{dr.get('fed_rungs')} != ['global'] — the warm-cache "
            "path degraded under a healthy mesh"
        )
    q = dr.get("fed_quality_ratio")
    if q is None or q > 1.001:
        failures.append(
            f"delta_roundtrip fed_quality_ratio {q} > 1.001 — the "
            "gossip-cached duals lost quality vs the synchronous "
            "exchange"
        )
    return failures


def _hlo_sort_dims(txt: str) -> list:
    """Sorted-dimension sizes of every stablehlo.sort in a lowered
    module (the operand tensor types follow the comparator region, so
    each sort op is paired with its closing type line)."""
    import re

    out = []
    lines = txt.splitlines()
    for i, line in enumerate(lines):
        if '"stablehlo.sort"' not in line:
            continue
        m = re.search(r"dimension = (\d+)", line)
        dim = int(m.group(1)) if m else -1
        for j in range(i + 1, min(i + 400, len(lines))):
            t = re.search(r"\}\) : \(tensor<([0-9x]+)x", lines[j])
            if t:
                shape = [int(d) for d in t.group(1).split("x")]
                out.append(
                    shape[dim] if 0 <= dim < len(shape) else max(shape)
                )
                break
    return out


def config19_mesh2d():
    """Cross-axis mesh probe (ISSUE 20): the 2-D ("streams", "p")
    composition against its 1-D twins.  What must hold (gated in main
    whenever >= 8 devices are visible — on CPU that needs
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8``, else the
    probe records ``skipped``): the P-sharded rounding tail
    bit-identical to the mesh-1 (single-device) tail with a lowering
    that contains NO full-P sort; every megabatch wave — cold, warm
    refine, delta, locked — bit-identical across single-device, 1-D
    streams, (2, 4), and (4, 2); ZERO fresh compiles in the 2-D
    steady state; and the 2-D steady wall within 1.05x the better 1-D
    twin (the virtual mesh timeshares one CPU, so the placements do
    identical compute and only placement overhead can differ)."""
    import threading
    import time as time_mod

    from kafka_lag_based_assignor_tpu.ops.coalesce import (
        MegabatchCoalescer,
    )
    from kafka_lag_based_assignor_tpu.ops.linear_ot import (
        assign_topic_linear,
    )
    from kafka_lag_based_assignor_tpu.ops.packing import pad_bucket
    from kafka_lag_based_assignor_tpu.ops.streaming import (
        StreamingAssignor,
        delta_k_ladder,
    )
    from kafka_lag_based_assignor_tpu.sharded import mesh as mesh_mod
    from kafka_lag_based_assignor_tpu.sharded import solve as ssolve
    from kafka_lag_based_assignor_tpu.sharded.mesh import (
        SOLVE_AXIS,
        MeshManager,
    )
    from kafka_lag_based_assignor_tpu.utils.observability import (
        compile_count,
        install_compile_counter,
    )

    install_compile_counter()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    n_dev = len(jax.devices())
    out = {"config": "mesh2d_scale", "devices": n_dev}
    if n_dev < 8:
        out["skipped"] = (
            f"{n_dev} device(s) visible; the 2-D probe needs 8 (set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 for "
            "the virtual CPU mesh)"
        )
        log(json.dumps(out))
        return out

    # ---- Part A: the P-sharded rounding tail vs the mesh-1 tail.
    # P pads past the scan-rounding ceiling, so the sharded lowering
    # elects the distributed winner-election + segmented-repair tail —
    # which must match the single-device linear solve bit for bit and
    # sort nothing P-sized.
    P, C = 6000, 16
    rng = np.random.default_rng(0x2D17)
    lags = zipf_lags(rng, P)
    pids = np.arange(P, dtype=np.int32)
    valid = np.ones(P, dtype=bool)
    choice1, _, _ = assign_topic_linear(
        lags, pids, valid, num_consumers=C, refine_iters=64
    )
    choice1 = np.asarray(choice1)
    mgr24 = MeshManager(
        devices=8, solve_min_rows=1024, shape="2x4"
    ).configure()
    mgr42 = MeshManager(
        devices=8, solve_min_rows=1024, shape="4x2"
    ).configure()
    mgr1p = MeshManager(devices=8, solve_min_rows=1024).configure()
    tail = {"partitions": P, "consumers": C}
    tail_walls = {}
    for name, mgr in (("2x4", mgr24), ("4x2", mgr42), ("1d_p", mgr1p)):
        ch, _, _, _ = ssolve.solve_linear_sharded(
            mgr.solve_mesh(), lags, C, refine_iters=64
        )
        tail[f"bit_identical_{name}"] = bool(
            np.array_equal(np.asarray(ch), choice1)
        )
        c0 = compile_count()
        walls = []
        for _ in range(3):
            t0 = time_mod.perf_counter()
            ssolve.solve_linear_sharded(
                mgr.solve_mesh(), lags, C, refine_iters=64
            )
            walls.append((time_mod.perf_counter() - t0) * 1000.0)
        tail[f"warm_compile_count_{name}"] = compile_count() - c0
        tail_walls[name] = float(np.median(walls))
        tail[f"p50_ms_{name}"] = round(tail_walls[name], 2)
    tail["bit_identical"] = bool(
        tail["bit_identical_2x4"]
        and tail["bit_identical_4x2"]
        and tail["bit_identical_1d_p"]
    )
    tail["warm_compile_count"] = (
        tail["warm_compile_count_2x4"] + tail["warm_compile_count_4x2"]
    )
    tail["wall_ratio_vs_1d"] = round(
        min(tail_walls["2x4"], tail_walls["4x2"])
        / max(tail_walls["1d_p"], 1e-9),
        3,
    )
    # The sharded lowering must keep every sort sub-P (the replicated
    # full-P2 sort is exactly what the distributed tail removes).
    P2 = pad_bucket(P)
    mesh = mgr24.solve_mesh()
    step = ssolve._linear_tail_executable(mesh, C, 64)
    sh_p = NamedSharding(mesh, PartitionSpec(SOLVE_AXIS))
    sh_r = NamedSharding(mesh, PartitionSpec())
    txt = step.lower(
        jax.device_put(np.ones(P2, np.int64), sh_p),
        jax.device_put(np.ones(P2, bool), sh_p),
        jax.device_put(np.zeros(C, np.float32), sh_r),
        jax.device_put(np.zeros(C, np.float32), sh_r),
    ).as_text()
    dims = _hlo_sort_dims(txt)
    tail["padded_rows"] = P2
    tail["max_sorted_dim"] = max(dims) if dims else 0
    tail["full_p_sorts"] = sum(1 for d in dims if d >= P2)
    out["tail"] = tail

    # ---- Part B: megabatch wave parity + steady wall across the
    # placements.  One shared wave script — cold, lock, warm dense,
    # delta (8-row perturbations), heavy churn — replayed under each
    # placement; every wave must be bit-identical to the single-device
    # run (the engines' cold solves stay single-device under the
    # 1<<20 row floor, so the runs differ ONLY in placement).
    N, P2b, C2 = 8, 2048, 8
    # Warm phase: re-stack+lock, first locked dense, first locked
    # DELTA (the delta executable is a separate compile — production
    # warms it via the coalesce warm-up jobs); measured phase: dense
    # and delta waves, compile-gated.
    WARM, MEASURED = 3, 6
    rng_w = np.random.default_rng(0xB2D)
    cold_arrs = [
        rng_w.integers(0, 1000, P2b).astype(np.int64) for _ in range(N)
    ]
    script = []
    for w in range(WARM + MEASURED):
        if w in (2, 4, 5):  # delta waves: small perturbation of the last
            prev = script[-1]
            arrs = []
            for a in prev:
                nxt = a.copy()
                nxt[:8] = nxt[:8] + 1 + (np.arange(8) % 7)
                arrs.append(nxt)
        else:
            arrs = [
                rng_w.integers(0, 1000, P2b).astype(np.int64)
                for _ in range(N)
            ]
        script.append(arrs)
    delta_k = delta_k_ladder(2)[-1]

    def run_script(shape):
        mgr = (
            MeshManager(
                devices=8, solve_min_rows=1 << 20, shape=shape
            ).configure()
            if shape is not None
            else None
        )
        ctx = mesh_mod.managed(mgr) if mgr is not None else None
        if ctx is not None:
            ctx.__enter__()
        try:
            engines = [
                StreamingAssignor(
                    num_consumers=C2,
                    refine_iters=64,
                    refine_threshold=None,
                    delta_max_fraction=1.0,
                    delta_buckets=2,
                )
                for _ in range(N)
            ]
            for e, a in zip(engines, cold_arrs):
                e.rebalance(a)
            coal = MegabatchCoalescer(
                window_s=2.0,
                max_batch=N,
                lock_waves=1,
                delta_k=delta_k,
                mesh_manager=mgr,
            )
            wave_outs, wave_walls, errs = [], [], []
            c0 = None
            try:
                for w, arrs in enumerate(script):
                    if w == WARM:
                        c0 = compile_count()
                    outs = [None] * N

                    def run(i):
                        try:
                            outs[i] = engines[i].submit_epoch(
                                arrs[i], coal
                            )
                        except Exception as exc:  # noqa: BLE001
                            errs.append((i, exc))

                    threads = [
                        threading.Thread(target=run, args=(i,))
                        for i in range(N)
                    ]
                    t0 = time_mod.perf_counter()
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join()
                    wave_walls.append(
                        (time_mod.perf_counter() - t0) * 1000.0
                    )
                    wave_outs.append([np.asarray(o) for o in outs])
                compiles = compile_count() - c0
                with coal._roster_lock:
                    batches = [
                        r.batch
                        for r in coal._rosters.values()
                        if r.batch is not None
                    ]
                batch_mesh = batches[0].mesh if batches else None
                locked_axes = (
                    dict(batch_mesh.shape) if batch_mesh is not None
                    else None
                )
            finally:
                coal.close()
            steady = float(np.median(wave_walls[WARM:]))
            return wave_outs, steady, compiles, locked_axes, len(errs)
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)

    runs = {}
    for shape in ("2x4", "4x2", "off", None):
        key = shape if shape is not None else "single"
        runs[key] = run_script(shape)

    base_outs = runs["single"][0]
    mb = {
        "streams": N,
        "partitions": P2b,
        "consumers": C2,
        "waves": WARM + MEASURED,
    }
    all_identical = True
    for key in ("2x4", "4x2", "off"):
        outs = runs[key][0]
        same = all(
            np.array_equal(outs[w][i], base_outs[w][i])
            for w in range(len(script))
            for i in range(N)
        )
        mb[f"bit_identical_{key}"] = bool(same)
        all_identical &= same
    mb["all_identical"] = bool(all_identical)
    mb["errors"] = sum(runs[k][4] for k in runs)
    mb["warm_compile_count"] = runs["2x4"][2] + runs["4x2"][2]
    mb["locked_axes_2x4"] = runs["2x4"][3]
    mb["locked_axes_4x2"] = runs["4x2"][3]
    mb["locked_2d"] = bool(
        (runs["2x4"][3] or {}).get(SOLVE_AXIS, 0) > 1
        and (runs["4x2"][3] or {}).get(SOLVE_AXIS, 0) > 1
    )
    mb["steady_p50_ms_2x4"] = round(runs["2x4"][1], 2)
    mb["steady_p50_ms_4x2"] = round(runs["4x2"][1], 2)
    mb["steady_p50_ms_1d_streams"] = round(runs["off"][1], 2)
    mb["steady_p50_ms_single"] = round(runs["single"][1], 2)
    mb["wall_ratio_vs_1d"] = round(
        min(runs["2x4"][1], runs["4x2"][1])
        / max(runs["off"][1], 1e-9),
        3,
    )
    out["megabatch"] = mb
    return out


def main():
    # A wedged accelerator tunnel must degrade the benchmark, not hang it
    # (the framework's own watchdog philosophy, SURVEY §5 failure row):
    # probe out-of-process first and fall back to the host CPU backend.
    device_fallback = not device_reachable()

    import jax

    if device_fallback:
        log("bench: accelerator unreachable within timeout - CPU fallback")
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    # Persist compiled executables across bench processes — first-ever run
    # pays the XLA compiles (~40 s/shape through this image's remote-compile
    # tunnel), subsequent runs start warm.
    jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    log(f"bench devices: {jax.devices()}")

    if not device_fallback:
        # Resolve the Pallas round-scan gate ONCE, off every timed path:
        # the probe bit-compares and races the kernel on the device
        # (several compiles); assign_stream then dispatches accordingly.
        try:
            from kafka_lag_based_assignor_tpu.ops.rounds_pallas import (
                rounds_pallas_available,
            )

            log(f"pallas round-scan enabled: "
                f"{rounds_pallas_available(run_probe=True)}")
        except Exception as exc:  # noqa: BLE001 — bench must not die
            log(f"pallas probe failed: {type(exc).__name__}: {exc}")
        # Same resolution for the linear-OT kernel plane: parity
        # bit-compare + speed race once, before any timed config.
        try:
            from kafka_lag_based_assignor_tpu.ops.linear_ot_pallas import (
                linear_pallas_available,
            )

            log(f"pallas linear-OT kernel enabled: "
                f"{linear_pallas_available(run_probe=True)}")
        except Exception as exc:  # noqa: BLE001 — bench must not die
            log(f"linear kernel probe failed: "
                f"{type(exc).__name__}: {exc}")

    results = {
        "harness": {
            "rtt_floor_ms": rtt_floor_ms(),
            "device_fallback": device_fallback,
        }
    }
    log(json.dumps(results["harness"]))
    # Registry snapshots bracket each config: the per-config BENCH record
    # embeds p50/p99 of every histogram series that moved (span
    # latencies, churn, solve durations) — the same registry the service
    # exports over the wire, so bench numbers and production telemetry
    # share one definition.
    from kafka_lag_based_assignor_tpu.utils import metrics as klba_metrics

    for fn in (config1_readme, config2_zipf, config3_vmap, config4_skew,
               config5_northstar, config6_multistream, config7_overload,
               config8_restart, config9_delta, config10_handoff,
               config11_scrub, config12_federated, config13_sharded,
               config14_linear, config15_linear_kernel,
               config16_scenarios, config17_tracing,
               config18_delta_roundtrip, config19_mesh2d):
        before = klba_metrics.REGISTRY.snapshot()
        r = fn()
        deltas = klba_metrics.histogram_deltas(
            before, klba_metrics.REGISTRY.snapshot()
        )
        if deltas:
            r["registry_histograms"] = deltas
        results[r["config"]] = r
        log(json.dumps(r))

    with open("BENCH_DETAILS.json", "w") as f:
        json.dump(results, f, indent=2, sort_keys=True)

    # Persist the kernel-plane report where CI's artifact step and
    # `dump_metrics --summary` expect it (gate verdicts, race timings,
    # interpret parity) — the probe already ran, so this is cheap.
    try:
        from kafka_lag_based_assignor_tpu.ops.linear_ot_pallas import (
            write_kernel_report,
        )

        log(f"kernel report: {write_kernel_report()}")
    except Exception as exc:  # noqa: BLE001 — diagnostics only
        log(f"kernel report failed: {type(exc).__name__}: {exc}")

    ns = results["northstar_100k_1kc"]
    line = {
        "metric": "assign_wall_ms_100k_partitions_1k_consumers",
        "value": round(ns["assign_ms"], 3),
        "unit": "ms",
        "vs_baseline": round(ns["speedup_vs_baseline"], 1),
        # Quality normalized to the input-driven bound (see quality_ratio):
        # the <=1.05 target reads against this, not the raw imbalance.
        "quality_ratio": round(ns["quality_ratio"], 4),
        # Solve cost above the measured zero-work transport floor for the
        # identical I/O contract on this harness (see transport_floor_ms).
        "above_floor_ms": round(ns["above_floor_ms"], 3),
    }
    if device_fallback:
        line["device_fallback"] = True  # accelerator was unreachable
    print(json.dumps(line))

    # Regression gates (nonzero rc so CI fails LOUDLY, after the one-line
    # contract output above is already printed):
    #   * a warm refine epoch costing more than a cold solve is the exact
    #     r5 inversion this harness exists to prevent;
    #   * a fresh XLA compile inside the steady-state warm loop means the
    #     warm-up no longer covers the production executables.
    failures = []
    wr = ns.get("warm_refine_p50_ms")
    # The cold reference is the from-scratch solve measured INSIDE the
    # same drift loop (streaming_p50_ms: stream_once runs assign_stream
    # every epoch, temporally interleaved with the warm epochs) — the
    # headline assign_ms is measured minutes earlier, and this host's
    # session noise (observed >50% swings between phases) would fail the
    # gate on drift rather than regression.  Same pairing rationale as
    # interleaved_floor.
    cold_ref = ns.get("streaming_p50_ms", ns["assign_ms"])
    if wr is not None and wr > cold_ref:
        failures.append(
            f"warm_refine_p50_ms {wr:.1f} exceeds the same-loop cold "
            f"solve p50 {cold_ref:.1f} — warm epoch costlier than cold"
        )
    if ns.get("warm_compile_count", 0) > 0:
        failures.append(
            f"warm_compile_count {ns['warm_compile_count']} != 0 — fresh "
            "XLA compiles inside the steady-state warm loop"
        )
    msg_cfg = results.get("multistream_32g", {})
    if msg_cfg.get("warm_compile_count", 0) > 0:
        failures.append(
            f"multistream_32g warm_compile_count "
            f"{msg_cfg['warm_compile_count']} != 0 — fresh XLA compiles "
            "inside the steady-state coalesced warm loop"
        )
    # The >= 3x aggregate-throughput gate measures DISPATCH amortization
    # and only binds where serialized device round-trips are the cost —
    # on the CPU fallback, compute dominates and the ratio is recorded
    # but not gated (same policy as the device-named phase fields).
    spd = msg_cfg.get("speedup_vs_serial")
    if not device_fallback and spd is not None and spd < 3.0:
        failures.append(
            f"multistream_32g speedup_vs_serial {spd:.2f} < 3.0x — the "
            "megabatch coalescer is not amortizing device dispatch"
        )
    # Roster-locked steady-state gates (every backend): once the roster
    # locks, the host path must stop re-stacking and stop compiling.
    if msg_cfg.get("locked_restack_dispatches", 0) > 0:
        failures.append(
            f"multistream_32g locked loop performed "
            f"{msg_cfg['locked_restack_dispatches']} re-stack "
            "dispatch(es) — the roster fast path is not engaging"
        )
    if msg_cfg.get("locked_warm_compile_count", 0) > 0:
        failures.append(
            f"multistream_32g locked_warm_compile_count "
            f"{msg_cfg['locked_warm_compile_count']} != 0 — fresh XLA "
            "compiles inside the roster-locked steady state"
        )
    lspd = msg_cfg.get("speedup_locked_vs_coalesced")
    # CPU ref is compute-bound: the gate is no-regression vs the same
    # run's re-stack loop (0.97 absorbs the timer's noise floor);
    # hardware, where dispatch overhead dominates, must gain >= 1.3x.
    locked_floor = 0.97 if device_fallback else 1.3
    if lspd is not None and lspd < locked_floor:
        failures.append(
            f"multistream_32g speedup_locked_vs_coalesced {lspd:.2f} < "
            f"{locked_floor}x — the roster-stable fast path is not "
            "paying for itself"
        )
    # Overload-stampede gates (every backend — the budgets are config
    # this probe sets, not hardware-dependent): the critical class must
    # hold its deadline while the lower classes shed, sheds must walk
    # the ladder bottom-up, no served assignment may be invalid, the
    # measured waves must compile nothing, and the elasticity loop must
    # scale monotonically with a steepening lag trend.
    ov = results.get("overload_stampede", {})
    crit_p99 = ov.get("critical_p99_s")
    if crit_p99 is None:
        failures.append(
            "overload_stampede served no critical requests — the probe "
            "is not exercising the priority path"
        )
    elif crit_p99 > ov["critical_budget_s"]:
        failures.append(
            f"overload_stampede critical_p99_s {crit_p99:.3f} exceeds "
            f"the {ov['critical_budget_s']}s class deadline budget"
        )
    crit_errors = ov.get("request_errors", {}).get("critical", 0)
    if crit_errors > 0:
        failures.append(
            f"overload_stampede saw {crit_errors} non-shed critical "
            "request error(s) — critical traffic failed outright rather "
            "than being served or shed"
        )
    shed = ov.get("shed_by_class", {})
    if shed.get("critical", 0) > 0:
        failures.append(
            f"overload_stampede shed {shed['critical']} critical "
            "request(s) — the ladder must never shed the top class"
        )
    if shed.get("standard", 0) > 0 and shed.get("best_effort", 0) == 0:
        failures.append(
            "overload_stampede shed standard without shedding "
            "best_effort — shedding must land on the lowest class first"
        )
    if ov.get("invalid_assignments", 0) > 0:
        failures.append(
            f"overload_stampede produced {ov['invalid_assignments']} "
            "invalid (count-imbalanced) assignment(s) under overload"
        )
    if ov.get("warm_compile_count", 0) > 0:
        failures.append(
            f"overload_stampede warm_compile_count "
            f"{ov['warm_compile_count']} != 0 — fresh XLA compiles "
            "inside the stampede's measured waves"
        )
    if ov and not ov.get("recommend_monotone", False):
        failures.append(
            f"overload_stampede recommend trajectory "
            f"{ov.get('recommend_trajectory')} is not a monotone "
            "scale-up under a rising lag trend"
        )
    # Restart-storm gates (every backend — crash-safety is config, not
    # hardware): every stream must recover, first warm epochs must be
    # bit-identical to the uninterrupted baseline, valid, compile-free,
    # and not regress time-to-first-warm-epoch past 10x the pre-crash
    # warm baseline (the recovered-shape warm-up's whole point).
    rs = results.get("restart_storm", {})
    if rs:
        if rs.get("streams_recovered", 0) < rs.get("streams_expected", 0):
            failures.append(
                f"restart_storm recovered {rs.get('streams_recovered')}"
                f"/{rs.get('streams_expected')} streams — snapshot "
                "recovery is dropping warm state"
            )
        if rs.get("mismatched_assignments", 0) > 0:
            failures.append(
                f"restart_storm produced {rs['mismatched_assignments']} "
                "first-epoch assignment(s) differing from the "
                "uninterrupted baseline — recovery is not bit-exact"
            )
        if rs.get("invalid_assignments", 0) > 0:
            failures.append(
                f"restart_storm produced {rs['invalid_assignments']} "
                "invalid (count-imbalanced) assignment(s) post-recovery"
            )
        if rs.get("post_recovery_compile_count", 0) > 0:
            failures.append(
                f"restart_storm post_recovery_compile_count "
                f"{rs['post_recovery_compile_count']} != 0 — fresh XLA "
                "compiles inside the restart storm's first warm epochs"
            )
        base_ms = rs.get("baseline_epoch_p50_ms") or 0.0
        first_ms = rs.get("first_epoch_p50_ms")
        if base_ms and first_ms is not None and first_ms > 10.0 * base_ms:
            failures.append(
                f"restart_storm first_epoch_p50_ms {first_ms:.1f} > "
                f"10x the pre-crash baseline {base_ms:.1f} — "
                "time-to-first-warm-epoch regressed"
            )
        # The pre-stacked reboot (lifecycle (b) measurement) is timed,
        # not latency-gated — but it must stay CORRECT.
        if rs.get("prestack_mismatched_assignments", 0) > 0:
            failures.append(
                f"restart_storm prestacked reboot produced "
                f"{rs['prestack_mismatched_assignments']} first-epoch "
                "assignment(s) differing from its seeded baseline — "
                "pre-stacking broke bit-exact recovery"
            )
    # Cross-host hand-off gates (every backend — fencing and recovery
    # are protocol facts, not hardware ones): BOTH hand-off modes must
    # adopt every stream with bit-identical, compile-free first warm
    # epochs, every fenced stale write from the predecessor must be
    # rejected and counted, and the adopted state must never be
    # overwritten.
    ho = results.get("handoff_storm", {})
    if ho:
        for mode in ("crash", "drain"):
            if ho.get(f"{mode}_streams_recovered", 0) < ho.get(
                "streams", 0
            ):
                failures.append(
                    f"handoff_storm {mode} hand-off adopted "
                    f"{ho.get(f'{mode}_streams_recovered')}/"
                    f"{ho.get('streams')} streams — the replacement "
                    "is not adopting the warm state"
                )
            if ho.get(f"{mode}_mismatched_assignments", 0) > 0:
                failures.append(
                    f"handoff_storm {mode} hand-off produced "
                    f"{ho[f'{mode}_mismatched_assignments']} first-"
                    "epoch assignment(s) differing from the "
                    "uninterrupted baseline — takeover is not bit-exact"
                )
            if ho.get(f"{mode}_invalid_assignments", 0) > 0:
                failures.append(
                    f"handoff_storm {mode} hand-off produced "
                    f"{ho[f'{mode}_invalid_assignments']} invalid "
                    "assignment(s)"
                )
            if ho.get(f"{mode}_post_takeover_compiles", 0) != 0:
                failures.append(
                    f"handoff_storm {mode} hand-off compiled "
                    f"{ho.get(f'{mode}_post_takeover_compiles')} "
                    "executable(s) inside the first warm epochs — the "
                    "recovered-shape warm-up is not covering takeover"
                )
        if ho.get("crash_handoff_mode") != "takeover_crash":
            failures.append(
                f"handoff_storm crash hand-off reported mode "
                f"{ho.get('crash_handoff_mode')!r} — the lease "
                "takeover did not see the expired predecessor"
            )
        if ho.get("drain_handoff_mode") != "takeover_drain":
            failures.append(
                f"handoff_storm drain hand-off reported mode "
                f"{ho.get('drain_handoff_mode')!r} — the released "
                "lease was not adopted as a drain hand-off"
            )
        if ho.get("fenced_stale_writes", 0) < 1:
            failures.append(
                "handoff_storm recorded no fenced stale write — the "
                "predecessor's clobber attempt was not exercised or "
                "not counted"
            )
        if ho.get("adopted_state_overwrites", 0) != 0:
            failures.append(
                f"handoff_storm adopted_state_overwrites "
                f"{ho['adopted_state_overwrites']} != 0 — a fenced-off "
                "instance overwrote the replacement's adopted state"
            )
    # Delta-drift gates (every backend — correctness and upload bytes
    # are config/shape facts, not hardware ones): every epoch must be
    # bit-identical to the dense twin, every drift epoch must take the
    # delta path, the measured loops must compile nothing, and the
    # per-epoch upload bytes must shrink >= 10x at the probe's churn.
    dd = results.get("delta_drift", {})
    if dd:
        if dd.get("mismatched_epochs", 0) > 0:
            failures.append(
                f"delta_drift produced {dd['mismatched_epochs']} "
                "epoch(s) differing from the dense baseline — the "
                "delta path is not bit-exact"
            )
        if dd.get("delta_applied", 0) < dd.get("epochs", 0):
            failures.append(
                f"delta_drift applied only {dd.get('delta_applied')}"
                f"/{dd.get('epochs')} epochs via the delta path "
                f"(dense bytes charged: "
                f"{dd.get('delta_engine_dense_bytes')})"
            )
        if dd.get("warm_compile_count", 0) > 0:
            failures.append(
                f"delta_drift warm_compile_count "
                f"{dd['warm_compile_count']} != 0 — fresh XLA compiles "
                "inside the measured drift loops (the K ladder warm-up "
                "is not covering the serving path)"
            )
        red = dd.get("upload_reduction_x")
        if red is not None and red < dd.get("reduction_target_x", 10.0):
            failures.append(
                f"delta_drift upload_reduction_x {red:.1f} < "
                f"{dd.get('reduction_target_x', 10.0)}x — the delta "
                "path is not cutting per-epoch H2D bytes"
            )
    # Corruption-storm gates (every backend — state integrity is
    # config, not hardware): every injected corruption detected within
    # one serving epoch / one scrub pass, bit-exact heals, zero
    # invalid served assignments, zero measured-round compiles, and a
    # per-epoch digest-verification cost under 1% of the warm no-op
    # epoch (the round-8 instrumentation budget's definition).
    cs = results.get("corruption_storm", {})
    if cs:
        if cs.get("injected", 0) != 6:
            failures.append(
                f"corruption_storm measured round injected "
                f"{cs.get('injected')} corruption(s) != 6 — the drill "
                "did not land every buffer class on both paths"
            )
        if cs.get("detected", 0) != cs.get("injected", 0) or cs.get(
            "late_detections", 0
        ) > 0:
            failures.append(
                f"corruption_storm detected {cs.get('detected')}/"
                f"{cs.get('injected')} injected corruption(s) within "
                f"one epoch/scrub pass ({cs.get('late_detections')} "
                "late) — the integrity plane is missing corruption"
            )
        if cs.get("heal_mismatches", 0) > 0:
            failures.append(
                f"corruption_storm produced {cs['heal_mismatches']} "
                "healed stream(s) differing from the uncorrupted twin "
                "— quarantine healing is not bit-exact"
            )
        if cs.get("invalid_assignments", 0) > 0:
            failures.append(
                f"corruption_storm served {cs['invalid_assignments']} "
                "invalid (count-imbalanced) assignment(s) while "
                "corruption was active"
            )
        if cs.get("storm_compile_count", 0) != 0:
            failures.append(
                f"corruption_storm compiled "
                f"{cs['storm_compile_count']} executable(s) in the "
                "measured round — the rehearsal/warm-up is not "
                "covering the quarantine/heal paths"
            )
        # Every locked-row choice/counts event must evict the roster
        # exactly once (no event may skip the eviction or double it).
        if (
            cs.get("roster_eviction_events", 0) != 2
            or cs.get("roster_eviction_max", 0) != 1
            or cs.get("roster_eviction_min", 0) != 1
        ):
            failures.append(
                f"corruption_storm locked-row evictions min/max "
                f"{cs.get('roster_eviction_min')}/"
                f"{cs.get('roster_eviction_max')} over "
                f"{cs.get('roster_eviction_events')} event(s) — a "
                "locked-row quarantine is not evict-and-relock-"
                "exactly-once"
            )
        ratio = cs.get("digest_overhead_ratio")
        if ratio is not None and ratio >= 0.01:
            failures.append(
                f"corruption_storm digest_overhead_ratio {ratio:.3%} "
                ">= 1% of the warm no-op epoch"
            )
    # Federated-partition gates (every backend — the exchange protocol
    # and its ladder are config facts, not hardware ones): converged
    # global quality within 5% of the single leader, valid local
    # assignments with zero errors and zero compiles through a FULL
    # partition, bounded re-convergence after heal, zero raw-lag bytes
    # on the peer wire, and stale/fenced duals rejected + counted.
    fp = results.get("federated_partition", {})
    if fp:
        if any(r != "global" for r in fp.get("global_rungs", ["x"])):
            failures.append(
                f"federated_partition rungs {fp.get('global_rungs')} "
                "— not every sidecar converged a global assignment "
                "with all peers reachable"
            )
        q = fp.get("quality_vs_leader")
        if q is None or q > 1.05:
            failures.append(
                f"federated_partition quality_vs_leader {q} > 1.05 — "
                "the dual-exchange assignment lost more than 5% to "
                "the single-leader solve"
            )
        if fp.get("invalid_assignments", 0) > 0:
            failures.append(
                f"federated_partition served "
                f"{fp['invalid_assignments']} invalid (count-"
                "imbalanced) local assignment(s)"
            )
        if fp.get("partition_errors", 0) > 0:
            failures.append(
                f"federated_partition saw {fp['partition_errors']} "
                "request error(s) during the full peer partition — "
                "the ladder is not failing open"
            )
        if fp.get("partition_compile_count", 0) != 0:
            failures.append(
                f"federated_partition compiled "
                f"{fp['partition_compile_count']} executable(s) "
                "during the partition — a degradation rung is not "
                "covered by the rehearsal/warm-up"
            )
        bad_rungs = [
            r for r in fp.get("partition_rungs", [])
            if r not in ("last_good_global", "local_only")
        ]
        if bad_rungs:
            failures.append(
                f"federated_partition served rung(s) {bad_rungs} "
                "during the full partition — a partitioned peer set "
                "must degrade, not claim convergence"
            )
        if any(r != "global" for r in fp.get("heal_rungs", ["x"])):
            failures.append(
                f"federated_partition heal rungs "
                f"{fp.get('heal_rungs')} — peers did not re-converge "
                "after the partition healed"
            )
        if fp.get("heal_rounds", 99) > fp.get("max_rounds", 16):
            failures.append(
                f"federated_partition re-converged in "
                f"{fp.get('heal_rounds')} rounds > the "
                f"{fp.get('max_rounds')}-round budget"
            )
        if fp.get("wire_leaks", 1) != 0:
            failures.append(
                f"federated_partition found {fp.get('wire_leaks')} "
                "raw-lag byte window(s) in peer_sync payloads — the "
                "privacy contract is broken"
            )
        if not fp.get("wire_marginals_served", False):
            failures.append(
                "federated_partition wire audit got no marginals — "
                "the audited exchange response was not exercised"
            )
        if fp.get("stale_rejected", 0) < 1 or fp.get(
            "fenced_rejected", 0
        ) < 1:
            failures.append(
                f"federated_partition stale/fenced rejections "
                f"{fp.get('stale_rejected')}/"
                f"{fp.get('fenced_rejected')} — regressed or fenced "
                "duals are not being rejected and counted"
            )
    # Sharded-scale gates (whenever a >= 4-device mesh was
    # constructible — virtual CPU or hardware): validity + quality on
    # every sharded answer, zero compiles in both sharded warm loops,
    # the CPU-ref no-regression bound on the stream-sharded megabatch
    # (2.5x of the single-device twin — the virtual mesh timeshares
    # one physical CPU; the >= linear-scaling gate is reserved for
    # hardware), and the mesh.collective drill serving valid through
    # the single-device fallback.
    sh = results.get("sharded_scale", {})
    if sh and not sh.get("skipped"):
        sv = sh.get("solve", {})
        if not sv.get("valid", False):
            failures.append(
                "sharded_scale solve produced an invalid (count-"
                "imbalanced or out-of-range) assignment"
            )
        if sv.get("warm_compile_count", 1) != 0:
            failures.append(
                f"sharded_scale solve compiled "
                f"{sv.get('warm_compile_count')} executable(s) in the "
                "warm loop — the sharded program cache is not holding"
            )
        if sv.get("worst_quality_ratio", 99) > 1.1:
            failures.append(
                f"sharded_scale solve worst_quality_ratio "
                f"{sv.get('worst_quality_ratio')} > 1.1"
            )
        mb = sh.get("megabatch", {})
        if not mb.get("all_valid", False) or mb.get("errors", 1):
            failures.append(
                "sharded_scale megabatch served invalid rows or "
                f"errors ({mb.get('errors')})"
            )
        if mb.get("warm_compile_count", 1) != 0:
            failures.append(
                f"sharded_scale megabatch compiled "
                f"{mb.get('warm_compile_count')} executable(s) in the "
                "locked sharded steady state"
            )
        if mb.get("stream_sharded_rosters", 0) < 1:
            failures.append(
                "sharded_scale megabatch never locked a stream-"
                "sharded roster — the placement path did not engage"
            )
        ratio = mb.get("wall_ratio_vs_single")
        if ratio is not None and ratio > 2.5:
            failures.append(
                f"sharded_scale megabatch wall_ratio_vs_single "
                f"{ratio} > 2.5 — the sharded placement regressed "
                "past the virtual-mesh overhead bound"
            )
        if not sh.get("collective_drill", {}).get("ok", False):
            failures.append(
                f"sharded_scale collective drill failed: "
                f"{sh.get('collective_drill')} — a mesh fault must "
                "serve valid through the single-device fallback and "
                "degrade the manager"
            )

    # mesh2d_scale gates (ISSUE 20): the P-sharded rounding tail must
    # be bit-identical to the mesh-1 tail with no full-P sort in its
    # lowering; every wave bit-identical across single / 1-D streams /
    # (2,4) / (4,2); zero 2-D steady-state compiles; the 2-D roster
    # actually locked cross-axis; and the 2-D steady wall within 1.05x
    # the better 1-D twin.
    m2 = results.get("mesh2d_scale", {})
    if m2 and not m2.get("skipped"):
        tl = m2.get("tail", {})
        if not tl.get("bit_identical", False):
            failures.append(
                "mesh2d_scale P-sharded rounding tail is not "
                "bit-identical to the single-device linear tail"
            )
        if tl.get("full_p_sorts", 1) != 0:
            failures.append(
                f"mesh2d_scale tail lowering contains "
                f"{tl.get('full_p_sorts')} full-P sort(s) "
                f"(max sorted dim {tl.get('max_sorted_dim')} vs "
                f"P2 {tl.get('padded_rows')}) — the rounding tail is "
                "not running P-sharded"
            )
        if tl.get("warm_compile_count", 1) != 0:
            failures.append(
                f"mesh2d_scale tail compiled "
                f"{tl.get('warm_compile_count')} executable(s) in the "
                "warm loop — the sharded tail program cache is not "
                "holding"
            )
        mb2 = m2.get("megabatch", {})
        if not mb2.get("all_identical", False):
            failures.append(
                "mesh2d_scale megabatch waves are not bit-identical "
                "across the single / 1-D streams / (2,4) / (4,2) "
                "placements"
            )
        if mb2.get("errors", 1) != 0:
            failures.append(
                f"mesh2d_scale megabatch saw {mb2.get('errors')} "
                "submit error(s)"
            )
        if mb2.get("warm_compile_count", 1) != 0:
            failures.append(
                f"mesh2d_scale megabatch compiled "
                f"{mb2.get('warm_compile_count')} executable(s) in the "
                "2-D steady state — the cross-axis warm-up is not "
                "covering the locked executables"
            )
        if not mb2.get("locked_2d", False):
            failures.append(
                f"mesh2d_scale megabatch never locked a cross-axis "
                f"roster (locked axes 2x4={mb2.get('locked_axes_2x4')} "
                f"4x2={mb2.get('locked_axes_4x2')}) — the 2-D "
                "placement path did not engage"
            )
        ratio = mb2.get("wall_ratio_vs_1d")
        if ratio is not None and ratio > 1.05:
            failures.append(
                f"mesh2d_scale megabatch wall_ratio_vs_1d {ratio} > "
                "1.05 — the 2-D placement regressed past the 1-D "
                "streams twin"
            )

    # linear_ot_scale gates (ISSUE 14): quality parity with the dense
    # Sinkhorn solve, peak device memory NOT scaling with P*C, zero
    # warm-loop compiles, and — when a mesh was constructible —
    # bit-identical sharded composition.
    lo = results.get("linear_ot_scale", {})
    if lo:
        pa = lo.get("parity", {})
        if pa.get("linear_vs_sinkhorn", 99) > 1.05:
            failures.append(
                f"linear_ot_scale quality_ratio_linear is "
                f"{pa.get('linear_vs_sinkhorn')}x the dense Sinkhorn "
                "solve's (> 1.05x) at the parity shape"
            )
        sc = lo.get("scale", {})
        if sc.get("warm_compile_count", 1) != 0:
            failures.append(
                f"linear_ot_scale compiled "
                f"{sc.get('warm_compile_count')} executable(s) in the "
                "warm loop — the linear quality mode's jit cache is "
                "not holding"
            )
        # The absolute fraction gate reads the LARGEST shape: at small
        # P the constant O(tile*C) term legitimately dominates the
        # tiny [P, C] block; what must never happen is the big shape's
        # peak tracking P*C.
        rows = sc.get("rows", [])
        if rows and rows[-1].get("peak_pc_fraction", 99) > 0.125:
            row = rows[-1]
            failures.append(
                f"linear_ot_scale peak memory at "
                f"{row.get('partitions')}x{row.get('consumers')} "
                f"is {row.get('peak_pc_fraction')} of the [P, C] "
                "f32 block (> 1/8) — the linear mode's peak is "
                "scaling with P*C"
            )
        # P x4 at fixed C: an O(P*C)-proportional peak quadruples;
        # allow the O(P) terms to quadruple plus slack, but fail the
        # gate before a full P*C-shaped blow-up reappears.
        if sc.get("peak_growth", 99) > 4.5:
            failures.append(
                f"linear_ot_scale peak_growth {sc.get('peak_growth')} "
                "> 4.5 across a 4x P step — super-linear memory"
            )
        lsh = lo.get("sharded", {})
        if lsh and not lsh.get("skipped"):
            if not lsh.get("bit_identical", False):
                failures.append(
                    "linear_ot_scale sharded composition is not "
                    "bit-identical to the single-device linear solve"
                )
    # linear_ot_kernel gates (ISSUE 16): where the device probe
    # enabled the duals kernel it must have WON its race (>= 1.0x the
    # XLA tile scan on the probe shape); the elected lowering must
    # compile nothing warm; the digest must move under every
    # corruption scenario; and the interpret-mode bit-parity
    # self-check must pass on every backend.
    lk = results.get("linear_ot_kernel", {})
    if lk:
        gate = lk.get("gate", {})
        race = gate.get("race_ms") or {}
        if gate.get("duals_kernel") and race.get("xla_ms"):
            if race.get("pallas_ms", 0) > race["xla_ms"]:
                failures.append(
                    f"linear_ot_kernel race has the kernel at "
                    f"{race.get('pallas_ms')}ms vs XLA "
                    f"{race.get('xla_ms')}ms on the probe shape — "
                    "the admission race admitted a slower kernel"
                )
        if lk.get("warm", {}).get("warm_compile_count", 1) != 0:
            failures.append(
                f"linear_ot_kernel compiled "
                f"{lk.get('warm', {}).get('warm_compile_count')} "
                "executable(s) in the warm loop — the kernel-plane "
                "dispatch seam is re-minting executables"
            )
        ds = lk.get("digest_storm", {})
        if ds.get("detected") != ds.get("total", 6):
            failures.append(
                f"linear_ot_kernel digest storm detected "
                f"{ds.get('detected')}/{ds.get('total')} corruption "
                f"scenario(s) ({ds.get('scenarios')}) — the integrity "
                "digest has a blind channel"
            )
        ip = lk.get("interpret_parity", {})
        if not (ip.get("duals") and ip.get("digest")):
            failures.append(
                f"linear_ot_kernel interpret parity {ip} — the kernel "
                "trace diverged bitwise from the XLA lowering"
            )
    # The adversarial fleet's verdict: every scenario inside its
    # declarative envelope, with the corpus-shape floors that make the
    # gate meaningful (a corpus edit silently dropping the composed-
    # fault or crash/restart scenarios must fail here, not pass
    # vacuously).
    sf = results.get("scenario_fleet", {})
    if sf:
        if sf.get("scenarios", 0) < 8:
            failures.append(
                f"scenario_fleet ran {sf.get('scenarios')} scenario(s) "
                "< 8 — the corpus lost coverage"
            )
        if sf.get("composed_fault_scenarios", 0) < 3:
            failures.append(
                f"scenario_fleet has {sf.get('composed_fault_scenarios')} "
                "composed-fault scenario(s) < 3"
            )
        if sf.get("crash_restart_scenarios", 0) < 1:
            failures.append(
                "scenario_fleet has no mid-trace crash/restart scenario"
            )
        if not sf.get("ok", False):
            for row in sf.get("failed_scenarios", []):
                failures.append(
                    f"scenario_fleet {row['scenario']} violated its "
                    f"envelope: {'; '.join(row['violations'])} "
                    f"(reproduce: {row['reproduce']})"
                )
    # Causal-tracing gates (ISSUE 18, every backend — propagation and
    # tail retention are host-side config, not hardware): the degraded
    # two-sidecar federated_assign must reconstruct as ONE complete
    # cross-process trace, kept by the anomaly bias; every coalesced
    # request must be bidirectionally linked to its wave trace; and the
    # tracing plane must stay under 1% of the warm no-op epoch without
    # minting a single warm-loop executable.
    tr = results.get("tracing", {})
    if tr:
        join = tr.get("federated_join", {})
        if not join.get("complete", False) or join.get(
            "segments", 0
        ) < 2:
            failures.append(
                f"tracing federated join {join} — the two-sidecar "
                "degraded federated_assign did not reconstruct as ONE "
                "complete cross-process trace"
            )
        if tr.get("federated_rung") not in (
            "last_good_global", "local_only"
        ):
            failures.append(
                f"tracing federated rung {tr.get('federated_rung')!r} "
                "— the partition drill did not degrade the ladder, so "
                "the reconstruction gate read a healthy trace"
            )
        if tr.get("federated_outcome") != "kept_anomalous":
            failures.append(
                f"tracing degraded trace retention outcome "
                f"{tr.get('federated_outcome')!r} (anomalies "
                f"{tr.get('federated_anomalies')}) != kept_anomalous — "
                "the tail sampler is not always-keeping ladder traces"
            )
        if not tr.get("wave_links_ok", False):
            failures.append(
                "tracing coalesced request traces are not "
                "bidirectionally linked to their coalesce.wave trace"
            )
        ratio = tr.get("trace_overhead_ratio")
        if ratio is None or ratio >= 0.01:
            failures.append(
                f"tracing trace_overhead_ratio {ratio} >= 1% of the "
                "warm no-op epoch — the tracing plane is over the "
                "instrumentation budget"
            )
        if tr.get("warm_compile_count", 1) != 0:
            failures.append(
                f"tracing warm_compile_count "
                f"{tr.get('warm_compile_count')} != 0 — fresh XLA "
                "compiles inside the traced warm no-op loop"
            )
    # Delta round-trip gates (ISSUE 19): shared with the tier-1
    # workflow's probe step — see delta_roundtrip_gates.
    dr = results.get("delta_roundtrip", {})
    if dr:
        failures.extend(delta_roundtrip_gates(dr))
    for msg in failures:
        log(f"bench: REGRESSION GATE FAILED: {msg}")
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
