#!/bin/bash
# Poll the tunneled accelerator (killable subprocess probes, the only safe
# way — a wedged tunnel blocks even jax.devices() forever) and run the
# one-shot revalidation the moment it answers.  Writes a status heartbeat
# to tools/poll_status.txt and the revalidation log to
# tools/revalidate_r05.log.  Exits after one successful revalidation.
set -u
cd "$(dirname "$0")/.."
STATUS=tools/poll_status.txt
LOG=tools/revalidate_r05.log
for i in $(seq 1 200); do
  echo "$(date -u +%H:%M:%S) probe $i" >> "$STATUS"
  if timeout 120 python - <<'EOF' > /dev/null 2>&1
import jax, numpy as np
x = jax.device_put(np.arange(8, dtype=np.int32))
assert int(jax.jit(lambda v: (v + 1).sum())(x)) == 36
EOF
  then
    echo "$(date -u +%H:%M:%S) DEVICE UP - revalidating" >> "$STATUS"
    bash tools/device_revalidate.sh > "$LOG" 2>&1
    echo "$(date -u +%H:%M:%S) revalidate done rc=$?" >> "$STATUS"
    exit 0
  fi
  sleep 240
done
echo "$(date -u +%H:%M:%S) gave up" >> "$STATUS"
exit 1
