"""Probe 3: separate device compute from transport via chained kernels."""

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import sys

sys.path.insert(0, "/root/repo")
from kafka_lag_based_assignor_tpu.ops.rounds_kernel import assign_topic_rounds
from kafka_lag_based_assignor_tpu.ops.scan_kernel import pack_shift_for
from kafka_lag_based_assignor_tpu.ops.packing import pad_bucket

print("devices:", jax.devices())


def med(f, iters=8):
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        f()
        ts.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(ts)), float(np.min(ts))


rng = np.random.default_rng(5)
P, C = 100_000, 1000
ranks = rng.permutation(P) + 1
lags = (1000 * (P / ranks) ** (1.0 / 1.1)).astype(np.int64)
shift = pack_shift_for(int(lags.max()), pad_bucket(P) - 1)


@functools.partial(jax.jit, static_argnames=("reps",))
def chained(lags, reps: int):
    P = lags.shape[0]
    P_pad = pad_bucket(P)
    pids = jnp.arange(P_pad, dtype=jnp.int32)
    valid = pids < P

    def body(i, carry):
        lg, acc = carry
        lags_p = jnp.pad(lg, (0, P_pad - P))
        choice, _, _ = assign_topic_rounds(
            lags_p, pids, valid, num_consumers=C, pack_shift=shift
        )
        c = choice[:P]
        # dependency so iterations can't be collapsed
        return lg + (c[0] - c[0]).astype(lg.dtype), acc + c

    _, acc = jax.lax.fori_loop(
        0, reps, body, (lags, jnp.zeros((P,), jnp.int32))
    )
    return acc.astype(jnp.int16)


for reps in (1, 4):
    f = lambda reps=reps: np.asarray(chained(lags, reps=reps))
    f()
    m, mn = med(f)
    print(f"chained x{reps}: median {m:.2f} min {mn:.2f} ms")


# trivial kernel, identical I/O shapes (int64[100k] in, int16[100k] out)
@jax.jit
def trivial(lags):
    return (lags % 997).astype(jnp.int16)


f = lambda: np.asarray(trivial(lags))
f()
m, mn = med(f)
print(f"trivial same-IO e2e: median {m:.2f} min {mn:.2f} ms")


# scalar-out trivial (transport floor with real input upload)
@jax.jit
def trivial_scalar(lags):
    return lags.sum()


f = lambda: float(trivial_scalar(lags))
f()
m, mn = med(f)
print(f"trivial scalar-out e2e: median {m:.2f} min {mn:.2f} ms")

# tiny-in tiny-out (pure dispatch floor, re-measured now)
x = np.arange(1024, dtype=np.int32)
g = jax.jit(lambda v: (v * 2 + 1).sum())
float(g(x))
m, mn = med(lambda: float(g(x)))
print(f"tiny dispatch floor now: median {m:.2f} min {mn:.2f} ms")
