"""Round-body cost sweep on real hardware (VERDICT r4 item 7).

The north-star solve's device budget is ~100 sequential rounds at ~90 us
of tiny-op overhead each (tools/probe_round5d.py).  This probe measures,
with the fetch-synchronized amortized method (the ONLY valid clock on the
tunneled platform — block_until_ready returns at dispatch):

  1. the production kernel at scan unroll factors 2/4/8/16 (bit-identical
     lowering variants, static arg `scan_unroll`);
  2. an EXPERIMENTAL pow2-padded-consumer round body (C=1000 padded to
     1024 with sentinel keys that sort last and receive zero gain —
     possibly a friendlier sort-network shape), bit-parity-checked here
     against the production kernel before timing.

Run after the tunnel recovers; pick the winning unroll as the new default
(and productize the pow2 body only if it wins).
"""

import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import functools  # noqa: E402

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from kafka_lag_based_assignor_tpu.ops.batched import (  # noqa: E402
    stream_payload,
    totals_rank_bits_for,
)
from kafka_lag_based_assignor_tpu.ops.packing import pad_bucket  # noqa: E402
from kafka_lag_based_assignor_tpu.ops.rounds_kernel import (  # noqa: E402
    assign_topic_rounds,
)
from kafka_lag_based_assignor_tpu.ops.scan_kernel import (  # noqa: E402
    sort_partitions_with,
)

P, C = 100_000, 1000
N_HI = 8


def zipf_lags(rng, n, a=1.1, scale=1000):
    ranks = rng.permutation(n) + 1
    return (scale * (n / ranks) ** (1.0 / a)).astype(np.int64)


rng = np.random.default_rng(5)
lags0 = zipf_lags(rng, P)
payload, shift = stream_payload(lags0)
rb = totals_rank_bits_for(payload, C)
B = pad_bucket(P)


def solve_variant(v, unroll):
    lags_p = jnp.pad(v.astype(jnp.int64), (0, B - P))
    pids = jnp.arange(B, dtype=jnp.int32)
    valid = pids < P
    choice, _, _ = assign_topic_rounds(
        lags_p, pids, valid, num_consumers=C, pack_shift=shift,
        n_valid=P, totals_rank_bits=rb, scan_unroll=unroll,
    )
    return choice[:P].astype(jnp.int32).sum()


# --- experimental pow2-padded-consumer packed body ---------------------
C_PAD = 1024
RANK_BITS_PAD = 10  # 1024 ids
SENTINEL = np.int64(int(lags0.sum()) + 1)  # > any achievable total


def solve_pow2c(v, unroll):
    lags_p = jnp.pad(v.astype(jnp.int64), (0, B - P))
    pids = jnp.arange(B, dtype=jnp.int32)
    valid = pids < P
    perm, sorted_lags, sorted_valid = sort_partitions_with(
        lags_p, pids, valid, shift
    )
    L = P
    R = -(-L // C)
    head = R * C
    lags_h = sorted_lags[:head].reshape(R, C)
    valid_h = sorted_valid[:head].reshape(R, C)
    # Pad each round's partition row C -> C_PAD with zero-gain invalid
    # rows, and the consumer carry with sentinel totals: sentinel keys
    # sort last, so pad consumers can never occupy a real partition's
    # position.
    lags_r = jnp.pad(lags_h, ((0, 0), (0, C_PAD - C)))
    valid_r = jnp.pad(valid_h, ((0, 0), (0, C_PAD - C)))
    totals0 = jnp.concatenate([
        jnp.zeros((C,), jnp.int64),
        jnp.full((C_PAD - C,), SENTINEL, jnp.int64),
    ])
    ids0 = jnp.arange(C_PAD, dtype=jnp.int32)

    def body(carry, xs):
        totals_s, ids_s = carry
        round_lags, round_valid = xs
        key = (totals_s << RANK_BITS_PAD) | ids_s.astype(jnp.int64)
        skey = lax.sort(key)
        ids_new = (skey & (C_PAD - 1)).astype(jnp.int32)
        gain = jnp.where(round_valid, round_lags, 0)
        totals_new = (skey >> RANK_BITS_PAD) + gain
        choice = jnp.where(round_valid, ids_new, -1)
        return (totals_new, ids_new), choice

    (_, _), round_choice = lax.scan(
        body, (totals0, ids0), (lags_r, valid_r), unroll=unroll
    )
    sorted_choice = round_choice[:, :C].reshape(head)
    flat = jnp.concatenate(
        [sorted_choice, jnp.full((B - head,), -1, jnp.int32)]
    )
    from kafka_lag_based_assignor_tpu.ops.sortops import unsort

    choice = unsort(perm, flat)
    return choice[:P]


def solve_pallas(v, unroll):
    """Full solve with the Pallas round-scan kernel replacing the XLA
    scan: device sort -> in-VMEM bitonic rounds -> unsort (the _stream
    contract; unroll is ignored — the kernel loops in-VMEM)."""
    from kafka_lag_based_assignor_tpu.ops.rounds_pallas import (
        assign_sorted_rounds_pallas,
    )
    from kafka_lag_based_assignor_tpu.ops.sortops import unsort

    lags_p = jnp.pad(v.astype(jnp.int64), (0, B - P))
    pids = jnp.arange(B, dtype=jnp.int32)
    valid = pids < P
    perm, sorted_lags, sorted_valid = sort_partitions_with(
        lags_p, pids, valid, shift
    )
    _, flat = assign_sorted_rounds_pallas(
        sorted_lags, sorted_valid, num_consumers=C, n_valid=P,
        total_lag_bound=int(lags0.sum()),
    )
    return unsort(perm, flat)[:P]


def amortized_ms(make_fn, unroll, label, src=None):
    src = payload if src is None else src
    batch = jax.device_put(
        np.stack([np.roll(src, 7919 * i) for i in range(N_HI)])
    )

    @functools.partial(jax.jit, static_argnames=("n",))
    def many(b, n):
        return lax.map(lambda v: make_fn(v, unroll), b[:n]).sum()

    def timed(n, iters=8):
        int(many(batch, n=n))  # warm-up/compile
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            int(many(batch, n=n))
            ts.append((time.perf_counter() - t0) * 1000.0)
        return float(np.median(ts))

    t_lo, t_hi = timed(1), timed(N_HI)
    per = max(0.0, (t_hi - t_lo) / (N_HI - 1))
    print(f"{label}: amortized {per:.2f} ms/solve "
          f"(t1={t_lo:.1f} t{N_HI}={t_hi:.1f})", flush=True)
    return per


def main():
    print(f"devices: {jax.devices()}", flush=True)

    # Bit-parity of the experimental body BEFORE timing it.
    base = np.asarray(
        jax.jit(
            lambda v: assign_topic_rounds(
                jnp.pad(v.astype(jnp.int64), (0, B - P)),
                jnp.arange(B, dtype=jnp.int32),
                jnp.arange(B, dtype=jnp.int32) < P,
                num_consumers=C, pack_shift=shift, n_valid=P,
                totals_rank_bits=rb,
            )[0][:P]
        )(payload)
    )
    exp = np.asarray(jax.jit(lambda v: solve_pow2c(v, 4))(payload))
    assert (base == exp).all(), "pow2-C body is NOT bit-identical"
    print("pow2-C body: bit-parity OK", flush=True)

    results = {}
    for unroll in (2, 4, 8, 16):
        results[f"unroll{unroll}"] = amortized_ms(
            lambda v, u: solve_variant(v, u), unroll, f"unroll={unroll}"
        )
    results["pow2c_u4"] = amortized_ms(
        lambda v, u: solve_pow2c(v, u).astype(jnp.int32).sum(),
        4, "pow2-C unroll=4",
    )
    results["pow2c_u8"] = amortized_ms(
        lambda v, u: solve_pow2c(v, u).astype(jnp.int32).sum(),
        8, "pow2-C unroll=8",
    )
    # Pallas in-VMEM round scan (experimental): parity-check on the real
    # lowering first, then time it.  Any Mosaic legalization failure is
    # reported and skipped — the XLA variants above still report.
    try:
        pal = np.asarray(jax.jit(lambda v: solve_pallas(v, 0))(payload))
        assert (base == pal).all(), "pallas body NOT bit-identical on HW"
        print("pallas round-scan: bit-parity OK on device", flush=True)
        results["pallas"] = amortized_ms(
            lambda v, u: solve_pallas(v, u).astype(jnp.int32).sum(),
            0, "pallas round-scan",
        )
    except Exception as exc:  # noqa: BLE001 — probe must finish
        print(f"pallas round-scan unavailable: {type(exc).__name__}: "
              f"{exc}", flush=True)
    # WIDE (two-plane totals) variant at the same scale: scale the lags
    # so the total crosses the int32 gate while each lag fits 31 bits,
    # parity-check the wide lowering, then time it.
    try:
        from kafka_lag_based_assignor_tpu.ops.rounds_pallas import (
            assign_sorted_rounds_pallas,
            pallas_mode_for,
        )
        from kafka_lag_based_assignor_tpu.ops.rounds_kernel import (
            assign_topic_rounds as _atr,
        )
        from kafka_lag_based_assignor_tpu.ops.sortops import unsort
        from kafka_lag_based_assignor_tpu.ops.scan_kernel import (
            sort_partitions_with as _spw,
        )

        wide_lags = (lags0 * 32).astype(np.int64)
        assert pallas_mode_for(wide_lags, C, -(-P // C)) == "wide"
        w_total = int(wide_lags.sum())

        def solve_wide(v, _u):
            lags_p = jnp.pad(v.astype(jnp.int64), (0, B - P))
            pids = jnp.arange(B, dtype=jnp.int32)
            valid = pids < P
            perm, sl, sv = _spw(lags_p, pids, valid, 0)
            _, flat = assign_sorted_rounds_pallas(
                sl, sv, num_consumers=C, n_valid=P,
                total_lag_bound=w_total,
                max_lag_bound=int(wide_lags.max()),
            )
            return unsort(perm, flat)[:P]

        w_base = np.asarray(jax.jit(
            lambda v: _atr(
                jnp.pad(v.astype(jnp.int64), (0, B - P)),
                jnp.arange(B, dtype=jnp.int32),
                jnp.arange(B, dtype=jnp.int32) < P,
                num_consumers=C, n_valid=P,
            )[0][:P]
        )(wide_lags))
        w_pal = np.asarray(jax.jit(lambda v: solve_wide(v, 0))(wide_lags))
        assert (w_base == w_pal).all(), "WIDE body NOT bit-identical"
        print("pallas WIDE: bit-parity OK on device", flush=True)
        results["pallas_wide"] = amortized_ms(
            lambda v, u: solve_wide(v, u).astype(jnp.int32).sum(),
            0, "pallas WIDE round-scan", src=wide_lags,
        )
    except Exception as exc:  # noqa: BLE001 — probe must finish
        print(f"pallas WIDE unavailable: {type(exc).__name__}: {exc}",
              flush=True)
    best = min(results, key=results.get)
    print(f"BEST: {best} at {results[best]:.2f} ms", flush=True)


if __name__ == "__main__":
    main()
