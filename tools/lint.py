"""Self-contained lint gate — compatibility shim over tools/analyze.

The 1,048-line monolith this file used to be lives on as the engine's
legacy ruleset: L001-L021 are registered in
tools/analyze/rules_style.py and tools/analyze/rules_invariants.py,
behavior-identical (pinned by tests/test_lint.py and byte-for-byte by
the parity test in tests/test_analyze.py against the frozen monolith
copy in tests/fixtures/legacy_lint_monolith.py).  The rule catalog
itself is documented in DEPLOYMENT.md "Static analysis".

``python tools/lint.py`` and every existing CI invocation keep
working unchanged and still run EXACTLY the L001-L021 set.  The full
analyzer — deep whole-program rules A001-A003, W001 unused-waiver
accounting, SARIF output, the incremental cache — is
``python -m tools.analyze`` / ``klba-analyze``.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import Iterator, List

_ROOT = Path(__file__).resolve().parent.parent
if str(_ROOT) not in sys.path:
    sys.path.insert(0, str(_ROOT))

from tools.analyze import core as _core
from tools.analyze.core import LEGACY_CODES, Finding

MAX_LINE = _core.MAX_LINE


def lint_source(path: Path, source: str) -> List[Finding]:
    """The monolith's per-file entry point: run the L001-L021 ruleset
    over one source blob (noqa suppression applied, no waiver
    accounting — that is the analyzer's job)."""
    return _core.analyze_source(path, source, codes=LEGACY_CODES).findings


def lint_paths(paths: Iterator[Path]) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        findings.extend(
            lint_source(path, path.read_text(encoding="utf-8"))
        )
    return findings


def repo_python_files(root: Path) -> List[Path]:
    return _core.repo_python_files(root)


def main() -> int:
    root = Path(__file__).resolve().parent.parent
    findings = lint_paths(iter(repo_python_files(root)))
    for f in findings:
        print(f)
    print(f"{len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
