#!/bin/bash
# One-shot revalidation after the tunnel recovers: kernel probes first
# (fetch-synchronized, tools/probe_round5f.py), then the full benchmark
# (writes BENCH_DETAILS.json).  Run from the repo root:
#   bash tools/device_revalidate.sh
set -u
cd "$(dirname "$0")/.."
echo "== device probe =="
timeout 150 python -c "
import jax, numpy as np
x = jax.device_put(np.arange(8, dtype=np.int32))
assert int(jax.jit(lambda v: (v+1).sum())(x)) == 36
print('device alive:', jax.devices())" || { echo "device unreachable"; exit 1; }
# Bench FIRST: it is the driver-relevant artifact, and the tunnel has
# re-wedged mid-session before — secure BENCH_DETAILS while the window
# is open, then spend remaining time on the engineering probes.
echo "== full bench =="
timeout 3600 python bench.py
echo "== BENCH_DETAILS.json updated =="
echo "== round-body sweep (probe_round6) =="
timeout 2400 python tools/probe_round6.py 2>&1 | grep -vE "WARN|INFO|warning"
echo "== kernel probe (probe_round5f) =="
timeout 2400 python tools/probe_round5f.py 2>&1 | grep -vE "WARN|INFO|warning"
echo "== done =="
