"""Device-compute breakdown of the headline solve (round-5 probe).

The tunnel's per-call round-trip (~40-70 ms, drifting) swamps single-call
timings, so each stage is timed as a lax.map over N independent inputs
inside ONE jit call: (e2e_N - e2e_1) / (N - 1) ~= per-solve device time
with the RTT amortized out.

Run on the real chip:  python tools/probe_round5.py
"""

import sys
import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

sys.path.insert(0, "/root/repo")

import functools  # noqa: E402

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from kafka_lag_based_assignor_tpu.ops.packing import pad_bucket  # noqa: E402
from kafka_lag_based_assignor_tpu.ops.refine import (  # noqa: E402
    refine_assignment,
)
from kafka_lag_based_assignor_tpu.ops.rounds_kernel import (  # noqa: E402
    _rounds_scan,
    _unsort_choice,
)
from kafka_lag_based_assignor_tpu.ops.scan_kernel import (  # noqa: E402
    pack_shift_for,
    sort_partitions_with,
)

print("devices:", jax.devices(), flush=True)

P, C, N = 100_000, 1000, 8
B = pad_bucket(P)
rng = np.random.default_rng(0)
ranks = rng.permutation(P) + 1
lags1 = (1000.0 * (P / ranks) ** (1 / 1.1)).astype(np.int64)
shift = pack_shift_for(int(lags1.max()), B - 1)
batch = np.stack(
    [np.roll(lags1, 17 * i).astype(np.int32) for i in range(N)]
)


def full_solve(lags32):
    lags_p = jnp.pad(lags32.astype(jnp.int64), (0, B - P))
    pids = jnp.arange(B, dtype=jnp.int32)
    valid = pids < P
    perm, sl, sv = sort_partitions_with(lags_p, pids, valid, shift)
    totals0 = jnp.zeros((C,), dtype=jnp.int64)
    totals, sc = _rounds_scan(sl, sv, totals0, C)
    choice, _ = _unsort_choice(perm, sc, B, C)
    return choice[:P].astype(jnp.int16)


def sort_only(lags32):
    lags_p = jnp.pad(lags32.astype(jnp.int64), (0, B - P))
    pids = jnp.arange(B, dtype=jnp.int32)
    valid = pids < P
    perm, sl, sv = sort_partitions_with(lags_p, pids, valid, shift)
    return (perm.sum() + sl.sum()).astype(jnp.int32)


def sort_scan(lags32):
    lags_p = jnp.pad(lags32.astype(jnp.int64), (0, B - P))
    pids = jnp.arange(B, dtype=jnp.int32)
    valid = pids < P
    perm, sl, sv = sort_partitions_with(lags_p, pids, valid, shift)
    totals, sc = _rounds_scan(sl, sv, jnp.zeros((C,), jnp.int64), C)
    return (totals.sum() + sc.sum().astype(jnp.int64)).astype(jnp.int32)


def refine1(lags32):
    lags_p = jnp.pad(lags32.astype(jnp.int64), (0, B - P))
    valid = jnp.arange(B, dtype=jnp.int32) < P
    choice = jnp.where(valid, jnp.arange(B, dtype=jnp.int32) % C, -1)
    refined, _, _ = refine_assignment(
        lags_p, valid, choice, num_consumers=C, iters=1, max_pairs=C // 2
    )
    return refined[:P].astype(jnp.int16)


def timed(name, fn, reduce_out=True):
    @functools.partial(jax.jit, static_argnames=("n",))
    def many(b, n):
        out = lax.map(fn, b[:n])
        return out.sum(axis=0) if reduce_out else out

    for n in (1, N):
        many(batch, n=n).block_until_ready()
    ts = {1: [], N: []}
    for _ in range(8):
        for n in (1, N):
            t0 = time.perf_counter()
            many(batch, n=n).block_until_ready()
            ts[n].append((time.perf_counter() - t0) * 1000.0)
    t1, tn = np.median(ts[1]), np.median(ts[N])
    per = (tn - t1) / (N - 1)
    print(
        f"{name:12s} e2e1={t1:7.2f}ms e2e{N}={tn:7.2f}ms "
        f"per-solve~{per:6.2f}ms",
        flush=True,
    )
    return per


timed("full", full_solve)
timed("sort_only", sort_only)
timed("sort+scan", sort_scan)
timed("refine1", refine1)
