"""Device-compute isolation with REAL synchronization.

On this tunneled platform ``block_until_ready`` returns at dispatch, not
completion (probe_round5b recorded 0.04 ms for 64 refine rounds), so the
only trustworthy clock is a host fetch of freshly computed data.  A fetch
costs one RTT (~40-70 ms, drifting), so each stage is measured at two
in-executable repetition counts and differenced:

    per_unit = (t[n_hi] - t[n_lo]) / (n_hi - n_lo)

which cancels the RTT and the constant dispatch overhead.
"""

import sys
import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

sys.path.insert(0, "/root/repo")

import functools  # noqa: E402

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from kafka_lag_based_assignor_tpu.models.sinkhorn import (  # noqa: E402
    _dedup_weights,
    _sinkhorn_duals_jit,
)
from kafka_lag_based_assignor_tpu.ops.packing import pad_bucket  # noqa: E402
from kafka_lag_based_assignor_tpu.ops.refine import (  # noqa: E402
    refine_assignment,
)
from kafka_lag_based_assignor_tpu.ops.rounds_kernel import (  # noqa: E402
    _rounds_scan,
    _unsort_choice,
)
from kafka_lag_based_assignor_tpu.ops.scan_kernel import (  # noqa: E402
    pack_shift_for,
    sort_partitions_with,
)

print("devices:", jax.devices(), flush=True)

P, C = 100_000, 1000
B = pad_bucket(P)
rng = np.random.default_rng(0)
ranks = rng.permutation(P) + 1
lags1 = (1000.0 * (P / ranks) ** (1 / 1.1)).astype(np.int64)
shift = pack_shift_for(int(lags1.max()), B - 1)
N_HI = 8
batch = jax.device_put(
    np.stack([np.roll(lags1, 17 * i).astype(np.int32) for i in range(N_HI)])
)


def fetch_med(f, iters=10):
    f()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        f()
        ts.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(ts))


def report(name, unit, t_lo, t_hi, n_lo, n_hi):
    per = (t_hi - t_lo) / (n_hi - n_lo)
    print(
        f"{name:14s} t[{n_lo}]={t_lo:7.2f}ms t[{n_hi}]={t_hi:7.2f}ms "
        f"-> {per:6.3f} ms/{unit}",
        flush=True,
    )


def full_solve(lags32):
    lags_p = jnp.pad(lags32.astype(jnp.int64), (0, B - P))
    pids = jnp.arange(B, dtype=jnp.int32)
    valid = pids < P
    perm, sl, sv = sort_partitions_with(lags_p, pids, valid, shift)
    totals, sc = _rounds_scan(sl, sv, jnp.zeros((C,), jnp.int64), C)
    choice, _ = _unsort_choice(perm, sc, B, C)
    return choice[:P].astype(jnp.int32).sum()


@functools.partial(jax.jit, static_argnames=("n",))
def solve_many(b, n):
    return lax.map(full_solve, b[:n]).sum()


ts = {}
for n in (1, N_HI):
    ts[n] = fetch_med(lambda n=n: int(solve_many(batch, n=n)))
report("full_solve", "solve", ts[1], ts[N_HI], 1, N_HI)

# Refine: chained rounds inside one executable (patience disabled so the
# round count is exactly `iters`).
lags_p = np.zeros(B, np.int64)
lags_p[:P] = lags1
valid_np = np.zeros(B, bool)
valid_np[:P] = True
choice_np = np.full(B, -1, np.int32)
choice_np[:P] = rng.permutation(P) % C
d_lags = jax.device_put(lags_p)
d_valid = jax.device_put(valid_np)
d_choice = jax.device_put(choice_np)


def refine_n(iters):
    r, _, _ = refine_assignment(
        d_lags, d_valid, d_choice, num_consumers=C, iters=iters,
        max_pairs=C // 2, patience=10**6,
    )
    return int(np.asarray(r[:1])[0])


t1 = fetch_med(lambda: refine_n(1))
t65 = fetch_med(lambda: refine_n(65))
report("refine_round", "round", t1, t65, 1, 65)

# Sinkhorn duals iteration (zipf: dedup collapses ~3x at this draw).
ws_u, count_u, wsum_u = _dedup_weights(lags_p, valid_np, C)
print(f"dedup U_pad={ws_u.shape[0]}", flush=True)


def duals_n(iters):
    A, _Bd = _sinkhorn_duals_jit(
        ws_u, count_u, wsum_u, num_consumers=C, iters=iters
    )
    return float(np.asarray(A[:1])[0])


t1 = fetch_med(lambda: duals_n(1), 6)
t97 = fetch_med(lambda: duals_n(97), 6)
report("duals_iter", "iter", t1, t97, 1, 97)
