"""Legacy invariant rules L011-L021, ported from the tools/lint.py
monolith onto the engine's shared scope walker (behavior-identical;
pinned by tests/test_lint.py and the tests/test_analyze.py parity
test).  See DEPLOYMENT.md "Static analysis" for the rule catalog; every
rule here is waivable with ``# noqa: <code>`` stating a reason.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from .core import FileContext, Finding, rule, walk_with_scope


def _call_name(node: ast.Call) -> str:
    """Terminal name of the called object: ``deque`` for both
    ``deque(...)`` and ``collections.deque(...)``."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


# --- L011 silent except Exception ----------------------------------------


def _catches_exception(handler: ast.ExceptHandler) -> bool:
    """True when the handler type names bare ``Exception`` (directly or
    in a tuple)."""
    node = handler.type
    types = node.elts if isinstance(node, ast.Tuple) else [node]
    return any(
        isinstance(t, ast.Name) and t.id == "Exception" for t in types
    )


def _handler_is_loud(handler: ast.ExceptHandler) -> bool:
    """True when the body re-raises or logs the traceback: a ``raise``
    statement, any call with an ``exc_info`` keyword, or a
    ``logger.exception(...)`` call."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            if any(kw.arg == "exc_info" for kw in node.keywords):
                return True
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "exception"
            ):
                return True
    return False


@rule(
    "L011",
    "silent `except Exception` in package code",
    waivable=True,
    applies=lambda ctx: ctx.is_package,
)
def check_silent_except(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.ExceptHandler)
            and node.type is not None
            and _catches_exception(node)
            and not _handler_is_loud(node)
        ):
            yield Finding(
                ctx.rel,
                node.lineno,
                "L011",
                "silent `except Exception`: re-raise, log with "
                "exc_info, or waive with `# noqa: L011`",
            )


# --- L012 direct clock calls ---------------------------------------------


def _is_banned_clock_call(node: ast.Call, from_time_names: set) -> bool:
    """True for ``time.time(...)`` / ``time.perf_counter(...)`` and for
    bare calls of those names when imported via ``from time import``."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return (
            func.attr in ("time", "perf_counter")
            and isinstance(func.value, ast.Name)
            and func.value.id == "time"
        )
    if isinstance(func, ast.Name):
        return func.id in from_time_names
    return False


@rule(
    "L012",
    "direct time.time()/perf_counter() in package code",
    waivable=True,
    applies=lambda ctx: ctx.is_package
    and ctx.name not in ("metrics.py", "observability.py"),
)
def check_direct_clock(ctx: FileContext) -> Iterator[Finding]:
    banned_from_time = {
        alias.asname or alias.name
        for node in ast.walk(ctx.tree)
        if isinstance(node, ast.ImportFrom) and node.module == "time"
        for alias in node.names
        if alias.name in ("time", "perf_counter")
    }
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _is_banned_clock_call(
            node, banned_from_time
        ):
            yield Finding(
                ctx.rel,
                node.lineno,
                "L012",
                "direct time.time()/time.perf_counter() call: use "
                "stopwatch/metrics.span or an injectable clock "
                "(waive with `# noqa: L012`)",
            )


# --- L013 blocking device sync in the coalescer --------------------------


def _is_blocking_sync_call(node: ast.Call, from_jax_names: set) -> bool:
    """True for ``jax.device_get(...)`` / ``jax.block_until_ready(...)``,
    any ``x.block_until_ready()`` method call, and bare calls of those
    names when imported via ``from jax import ...``."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr in ("device_get", "block_until_ready")
    if isinstance(func, ast.Name):
        return func.id in from_jax_names
    return False


@rule(
    "L013",
    "blocking device sync on the coalescer dispatch path",
    waivable=True,
    applies=lambda ctx: ctx.is_package and ctx.name == "coalesce.py",
)
def check_blocking_sync(ctx: FileContext) -> Iterator[Finding]:
    from_jax = {
        alias.asname or alias.name
        for node in ast.walk(ctx.tree)
        if isinstance(node, ast.ImportFrom) and node.module == "jax"
        for alias in node.names
        if alias.name in ("device_get", "block_until_ready")
    }
    for node, in_readback in walk_with_scope(
        ctx.tree, lambda name: "readback" in name
    ):
        if (
            isinstance(node, ast.Call)
            and not in_readback
            and _is_blocking_sync_call(node, from_jax)
        ):
            yield Finding(
                ctx.rel,
                node.lineno,
                "L013",
                "blocking device sync on the coalescer's "
                "admission/dispatch path: move it to the "
                "readback stage (or waive with `# noqa: L013`)",
            )


# --- L014 unbounded buffers ----------------------------------------------

_UNBOUNDED_QUEUE_TYPES = ("Queue", "LifoQueue", "PriorityQueue")


def _is_unbounded_buffer_ctor(node: ast.Call) -> Optional[str]:
    """Returns the offending type name for a ``deque`` without a
    (non-None) ``maxlen`` or a queue.Queue family call without a
    positive ``maxsize``; None when bounded/unrelated."""
    name = _call_name(node)
    if name == "deque":
        for kw in node.keywords:
            if kw.arg == "maxlen" and not (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is None
            ):
                return None
        if len(node.args) >= 2:  # deque(iterable, maxlen) positional
            return None
        return "deque"
    if name in _UNBOUNDED_QUEUE_TYPES:
        bound = None
        if node.args:
            bound = node.args[0]
        for kw in node.keywords:
            if kw.arg == "maxsize":
                bound = kw.value
        if bound is None:
            return name
        # A literal bound must be positive (maxsize=0 means unbounded);
        # a computed bound is taken on faith — the rule targets the
        # default-unbounded constructors, not arithmetic.
        if isinstance(bound, ast.Constant) and (
            not isinstance(bound.value, int) or bound.value <= 0
        ):
            return name
        return None
    return None


@rule(
    "L014",
    "unbounded buffer in package code",
    waivable=True,
    applies=lambda ctx: ctx.is_package,
)
def check_unbounded_buffers(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        unbounded = _is_unbounded_buffer_ctor(node)
        if unbounded is not None:
            yield Finding(
                ctx.rel,
                node.lineno,
                "L014",
                f"unbounded {unbounded} buffer: "
                "pass maxlen/maxsize (or waive with `# noqa: L014` "
                "stating the bound)",
            )
    # Instance-attribute list buffers: within one class, an attribute
    # assigned an empty list literal AND ``.append``-ed, with no
    # visible trim (``del self.x[...]`` or a ``self.x = self.x[...]``
    # re-slice), must carry an explicit waiver stating its bound.
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        assigns: dict = {}  # attr -> first empty-list assignment node
        appended: set = set()
        trimmed: set = set()

        def self_attr(node) -> Optional[str]:
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                return node.attr
            return None

        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = node.value
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for target in targets:
                    attr = self_attr(target)
                    if attr is None:
                        continue
                    if isinstance(value, ast.List) and not value.elts:
                        assigns.setdefault(attr, node)
                    elif isinstance(value, ast.Subscript):
                        inner = self_attr(value.value)
                        if inner == attr:
                            trimmed.add(attr)  # self.x = self.x[...]
            elif isinstance(node, ast.Delete):
                for target in node.targets:
                    if isinstance(target, ast.Subscript):
                        attr = self_attr(target.value)
                        if attr is not None:
                            trimmed.add(attr)  # del self.x[...]
            elif isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Attribute) and func.attr in (
                    "append", "extend", "insert",
                ):
                    attr = self_attr(func.value)
                    if attr is not None:
                        appended.add(attr)
        for attr, node in assigns.items():
            if attr not in appended or attr in trimmed:
                continue
            yield Finding(
                ctx.rel,
                node.lineno,
                "L014",
                f"unbounded list buffer self.{attr} (assigned [] and "
                "appended, no visible trim): add an explicit bound "
                "or waive with `# noqa: L014` stating the bound",
            )


# --- L015 bare write-mode open -------------------------------------------


def _open_write_mode(node: ast.Call) -> bool:
    """True for ``open(...)`` / ``io.open(...)`` calls whose mode is a
    string CONSTANT selecting a write/append/create/update mode.  A
    missing mode is a read; a computed mode is taken on faith (the rule
    targets the literal ``open(p, "w")`` idiom)."""
    if _call_name(node) != "open":
        return False
    mode = node.args[1] if len(node.args) > 1 else None
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not isinstance(mode, ast.Constant) or not isinstance(
        mode.value, str
    ):
        return False
    return any(ch in mode.value for ch in "wax+")


@rule(
    "L015",
    "bare write-mode open() in package code",
    waivable=True,
    applies=lambda ctx: ctx.is_package,
)
def check_bare_write_open(ctx: FileContext) -> Iterator[Finding]:
    for node, in_helper in walk_with_scope(
        ctx.tree, lambda name: "atomic_write" in name
    ):
        if (
            isinstance(node, ast.Call)
            and not in_helper
            and _open_write_mode(node)
        ):
            yield Finding(
                ctx.rel,
                node.lineno,
                "L015",
                "bare write-mode open() in package code: go "
                "through utils/snapshot.atomic_write_bytes "
                "(or waive with `# noqa: L015`)",
            )


# --- L016 raw H2D uploads in the warm-path modules -----------------------

#: The counted upload sites — the only functions in the warm-path
#: modules allowed to start a host->device transfer explicitly.
_L016_UPLOAD_SITES = (
    "_stage_upload", "_stage_delta_upload", "_cold_solve_inner",
)


def _is_upload_call(node: ast.Call) -> bool:
    """True for ``jax.device_put(...)`` (any base) and
    ``jnp.asarray(...)`` / ``jax.numpy.asarray(...)`` — the explicit
    H2D entry points.  ``np.asarray`` (a D2H materialization in this
    codebase) is deliberately not matched."""
    func = node.func
    if not isinstance(func, ast.Attribute):
        return False
    if func.attr == "device_put":
        return True
    if func.attr != "asarray":
        return False
    base = func.value
    if isinstance(base, ast.Name):
        return base.id == "jnp"
    return (
        isinstance(base, ast.Attribute)
        and base.attr == "numpy"
        and isinstance(base.value, ast.Name)
        and base.value.id == "jax"
    )


@rule(
    "L016",
    "raw host->device upload outside the counted helpers",
    waivable=True,
    applies=lambda ctx: ctx.is_package
    and ctx.name in ("coalesce.py", "streaming.py"),
)
def check_raw_upload(ctx: FileContext) -> Iterator[Finding]:
    for node, in_site in walk_with_scope(
        ctx.tree,
        lambda name: any(site in name for site in _L016_UPLOAD_SITES),
    ):
        if (
            isinstance(node, ast.Call)
            and not in_site
            and _is_upload_call(node)
        ):
            yield Finding(
                ctx.rel,
                node.lineno,
                "L016",
                "raw host->device upload outside the counted "
                "dense-upload helpers: route it through "
                "_stage_upload/_stage_delta_upload/"
                "_cold_solve_inner so "
                "klba_h2d_bytes_total stays honest (or waive "
                "with `# noqa: L016`)",
            )


# --- L017 snapshot persistence outside the backend layer -----------------


def _is_atomic_write_call(node: ast.Call) -> bool:
    """True for ``atomic_write_bytes(...)`` however addressed
    (bare name or any dotted base)."""
    return _call_name(node) == "atomic_write_bytes"


@rule(
    "L017",
    "snapshot persistence outside the backend layer",
    waivable=True,
    applies=lambda ctx: ctx.is_package and ctx.name != "snapshot.py",
)
def check_snapshot_outside_backend(ctx: FileContext) -> Iterator[Finding]:
    for node, in_backend in walk_with_scope(
        ctx.tree, lambda name: "snapshot_backend" in name
    ):
        if (
            isinstance(node, ast.Call)
            and not in_backend
            and _is_atomic_write_call(node)
        ):
            yield Finding(
                ctx.rel,
                node.lineno,
                "L017",
                "snapshot persistence outside the backend "
                "layer: go through the SnapshotBackend "
                "interface (utils/snapshot) so CAS + writer "
                "fencing police the write (or waive with "
                "`# noqa: L017`)",
            )


# --- L018 resident-buffer assignment outside audited helpers -------------

#: Resident-state fields whose assignment must stay inside audited
#: helpers.  Engine-side fields apply to both warm-path modules; the
#: batch-member names only to the coalescer (where the stacked
#: _ResidentBatch lives — "lags" etc. are too generic to police in
#: streaming.py, whose engine keeps them inside _resident).
_L018_ENGINE_FIELDS = frozenset({"_resident", "_lag_mirror"})
_L018_BATCH_FIELDS = frozenset({"choice", "row_tab", "counts", "lags"})


def _assign_targets(node) -> list:
    if isinstance(node, ast.Assign):
        raw = node.targets
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        raw = [node.target]
    else:
        return []
    # Flatten tuple/list unpacking: `a.choice, a.lags = c, l` must
    # not be an unpoliced route around the invariant.
    flat: list = []
    for target in raw:
        if isinstance(target, (ast.Tuple, ast.List)):
            flat.extend(target.elts)
        else:
            flat.append(target)
    return flat


@rule(
    "L018",
    "resident-buffer assignment outside an audited helper",
    waivable=True,
    applies=lambda ctx: ctx.is_package
    and ctx.name in ("coalesce.py", "streaming.py"),
)
def check_resident_assignment(ctx: FileContext) -> Iterator[Finding]:
    fields = set(_L018_ENGINE_FIELDS)
    if ctx.name == "coalesce.py":
        fields |= _L018_BATCH_FIELDS
    for node, in_helper in walk_with_scope(
        ctx.tree,
        lambda name: "resident" in name or name == "__init__",
    ):
        if in_helper:
            continue
        for target in _assign_targets(node):
            if (
                isinstance(target, ast.Attribute)
                and target.attr in fields
            ):
                yield Finding(
                    ctx.rel,
                    node.lineno,
                    "L018",
                    f"resident-buffer field .{target.attr} "
                    "assigned outside an audited helper: "
                    "route it through an *resident* helper "
                    "so the scrubber's host-mirror truth "
                    "cannot drift from the device (or "
                    "waive with `# noqa: L018`)",
                )


# --- L019 peer payloads outside the audited serializer -------------------

#: The payload-envelope keys whose dict-literal construction is
#: confined to the audited serializer.
_L019_PAYLOAD_KEYS = frozenset({"duals", "marginals"})


@rule(
    "L019",
    "peer-bound federation payload outside federated/wire.py",
    waivable=True,
    applies=lambda ctx: ctx.is_package
    and not (ctx.in_federated and ctx.name == "wire.py"),
)
def check_peer_payload(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Dict):
            keys = {
                k.value for k in node.keys
                if isinstance(k, ast.Constant)
                and isinstance(k.value, str)
            }
            if keys & _L019_PAYLOAD_KEYS:
                yield Finding(
                    ctx.rel,
                    node.lineno,
                    "L019",
                    "peer payload envelope (duals/marginals dict) "
                    "built outside federated/wire.py: use the "
                    "audited serializer so the no-raw-lags "
                    "contract stays enforceable (or waive with "
                    "`# noqa: L019`)",
                )
        elif ctx.in_federated and isinstance(node, ast.Call):
            func = node.func
            is_dumps = (
                isinstance(func, ast.Attribute) and func.attr == "dumps"
                and isinstance(func.value, ast.Name)
                and func.value.id == "json"
            )
            if is_dumps:
                yield Finding(
                    ctx.rel,
                    node.lineno,
                    "L019",
                    "raw json.dumps in the federated package: "
                    "peer-bound bytes must go through "
                    "federated/wire.encode (or waive with "
                    "`# noqa: L019`)",
                )


# --- L020 mesh construction outside sharded/ -----------------------------

#: The mesh-construction entry points confined to sharded/.
_L020_MESH_CTORS = frozenset(
    {"Mesh", "NamedSharding", "shard_map", "make_mesh"}
)


@rule(
    "L020",
    "mesh/shard_map construction outside the sharded subsystem",
    waivable=True,
    applies=lambda ctx: ctx.is_package and "sharded" not in ctx.parts,
)
def check_mesh_outside_sharded(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if _call_name(node) not in _L020_MESH_CTORS:
            continue
        yield Finding(
            ctx.rel,
            node.lineno,
            "L020",
            f"mesh construction ({_call_name(node)}) outside the "
            "sharded/ subsystem: topology decisions live in "
            "kafka_lag_based_assignor_tpu/sharded (selected via "
            "ops/dispatch) — or waive with `# noqa: L020`",
        )


# --- L021 dense [P, C] materialization -----------------------------------

#: BinOp node types whose complementary axis-expanded operands
#: materialize a dense rank-2 block.
_L021_OPS = (ast.Mult, ast.Add, ast.Sub, ast.Div, ast.Mod)


def _axis_expanded(node, none_last: bool) -> bool:
    """True for a Subscript whose index tuple carries ``None`` in the
    trailing (``a[:, None]``; ``none_last``) or leading
    (``b[None, :]``) position — numpy/jax's rank-expansion idiom.  A
    leading ``-`` (UnaryOp) is transparent."""
    if isinstance(node, ast.UnaryOp):
        node = node.operand
    if not isinstance(node, ast.Subscript):
        return False
    idx = node.slice
    if not isinstance(idx, ast.Tuple) or len(idx.elts) < 2:
        return False
    elt = idx.elts[-1] if none_last else idx.elts[0]
    return isinstance(elt, ast.Constant) and elt.value is None


def _is_dense_outer_binop(node: ast.BinOp) -> bool:
    """True when the BinOp's direct operands are complementary
    axis-expanded rank-1s: ``x[:, None] <op> y[None, :]`` (either
    order) — the construction of a dense (rows, consumers) block."""
    if not isinstance(node.op, _L021_OPS):
        return False
    left, right = node.left, node.right
    return (
        _axis_expanded(left, True) and _axis_expanded(right, False)
    ) or (
        _axis_expanded(left, False) and _axis_expanded(right, True)
    )


@rule(
    "L021",
    "[P, C]-proportional dense materialization outside a tile body",
    waivable=True,
    applies=lambda ctx: ctx.is_package and ctx.name != "sinkhorn.py",
)
def check_dense_materialization(ctx: FileContext) -> Iterator[Finding]:
    for node, in_tile_body in walk_with_scope(
        ctx.tree, lambda name: "tile" in name
    ):
        if (
            isinstance(node, ast.BinOp)
            and not in_tile_body
            and _is_dense_outer_binop(node)
        ):
            yield Finding(
                ctx.rel,
                node.lineno,
                "L021",
                "[P, C]-proportional dense broadcast outside a "
                "tile body: stream it in fixed-size tiles "
                "(ops/linear_ot pattern) or waive with "
                "`# noqa: L021` stating why the block is not "
                "[P, C]-proportional",
            )
