"""Pluggable whole-program static-analysis engine (stdlib-only).

The framework behind ``klba-analyze`` and the ``tools/lint.py``
compatibility shim.  It provides:

- per-rule registration (:func:`rule` / :func:`deep_rule`) with code,
  severity, waivability, and an ``applies(ctx)`` scope predicate;
- a shared :class:`FileContext` (parsed tree, raw lines, path-derived
  scoping flags) handed to every rule, plus :func:`walk_with_scope` —
  the enclosing-function-context traversal the legacy monolith
  re-implemented per rule;
- centralized ``# noqa: <CODE>`` suppression with accounting: waiver
  comments are scanned with ``tokenize`` (string literals never count)
  and any waiver that suppresses nothing is itself a finding (W001);
- whole-program rules: per-file ``collect(ctx)`` produces
  JSON-serializable facts (cacheable by tools/analyze/cache.py) and a
  project-level ``finalize(facts_by_file)`` emits findings over the
  merged set — how A001/A002/A003 (rules_deep) see across modules.

Legacy rules L001-L021 are registered by rules_style / rules_invariants
and are behavior-identical to the retired tools/lint.py monolith
(pinned by tests/test_lint.py and the parity test in
tests/test_analyze.py against tests/fixtures/legacy_lint_monolith.py).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    NamedTuple,
    Optional,
    Sequence,
    Tuple,
)

MAX_LINE = 100

#: The ruleset the tools/lint.py shim runs (the monolith's catalog).
LEGACY_CODES = tuple(f"L{i:03d}" for i in range(1, 22))

#: Engine-level accounting code: an unused ``# noqa`` waiver.
UNUSED_WAIVER_CODE = "W001"


class Finding(NamedTuple):
    """One diagnostic.  ``str()`` matches the monolith's line format so
    existing tooling (and the parity test) see identical bytes."""

    path: str
    line: int
    code: str
    message: str
    severity: str = "error"

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


class FileContext:
    """Everything a per-file rule needs: the parsed tree, raw lines,
    and the path-derived scoping flags the monolith computed inline."""

    def __init__(self, path: Path, source: str, tree: ast.Module) -> None:
        self.path = path
        self.rel = str(path)
        self.source = source
        self.lines: List[str] = source.splitlines()
        self.tree = tree
        self.name = path.name
        self.parts = path.parts
        self.is_init = path.name == "__init__.py"
        self.is_package = "kafka_lag_based_assignor_tpu" in path.parts
        self.in_federated = self.is_package and "federated" in path.parts
        self.in_sharded = "sharded" in path.parts
        #: scratch space rules may use to share one-pass computations
        #: (e.g. A001/A003 share the dispatch-site scan).
        self.scratch: Dict[str, Any] = {}


def walk_with_scope(
    tree: ast.AST, marker: Callable[[str], bool]
) -> Iterator[Tuple[ast.AST, bool]]:
    """Yield ``(node, in_marked_scope)`` for every node: the scope flag
    is True when ANY enclosing function's name satisfies ``marker`` —
    the enclosing-function-context walk every L013-pattern rule (and
    the deep analyses) share instead of re-implementing."""

    def visit(node: ast.AST, flag: bool) -> Iterator[Tuple[ast.AST, bool]]:
        for child in ast.iter_child_nodes(node):
            child_flag = flag
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_flag = flag or marker(child.name)
            yield child, flag
            yield from visit(child, child_flag)

    return visit(tree, False)


def _always(ctx: FileContext) -> bool:
    return True


@dataclass(frozen=True)
class Rule:
    """One registered analysis.  Per-file rules set ``check``;
    whole-program rules set ``collect`` + ``finalize`` (facts must be
    JSON-serializable so the incremental cache can hold them)."""

    code: str
    summary: str
    severity: str = "error"
    waivable: bool = False
    applies: Callable[[FileContext], bool] = _always
    check: Optional[Callable[[FileContext], Iterable[Finding]]] = None
    collect: Optional[Callable[[FileContext], Any]] = None
    finalize: Optional[
        Callable[[Dict[str, Any]], Iterable[Finding]]
    ] = None


REGISTRY: Dict[str, Rule] = {}


def register(r: Rule) -> Rule:
    if r.code in REGISTRY:
        raise ValueError(f"duplicate rule code {r.code!r}")
    REGISTRY[r.code] = r
    return r


def rule(
    code: str,
    summary: str,
    *,
    severity: str = "error",
    waivable: bool = False,
    applies: Callable[[FileContext], bool] = _always,
) -> Callable:
    """Decorator registering a per-file rule: the function receives a
    :class:`FileContext` and yields :class:`Finding`s (suppression is
    the engine's job — rules never look at ``noqa`` themselves)."""

    def deco(fn: Callable[[FileContext], Iterable[Finding]]) -> Callable:
        register(
            Rule(
                code=code,
                summary=summary,
                severity=severity,
                waivable=waivable,
                applies=applies,
                check=fn,
            )
        )
        return fn

    return deco


def deep_rule(
    code: str,
    summary: str,
    *,
    finalize: Callable[[Dict[str, Any]], Iterable[Finding]],
    severity: str = "error",
    applies: Callable[[FileContext], bool] = _always,
) -> Callable:
    """Decorator registering a whole-program rule's ``collect`` phase;
    ``finalize`` runs once over the merged per-file facts."""

    def deco(fn: Callable[[FileContext], Any]) -> Callable:
        register(
            Rule(
                code=code,
                summary=summary,
                severity=severity,
                waivable=True,
                applies=applies,
                collect=fn,
                finalize=finalize,
            )
        )
        return fn

    return deco


# --- waiver scanning ------------------------------------------------------

_NOQA_COMMENT = re.compile(
    r"#\s*noqa:\s*([A-Z]+[0-9]+(?:\s*,\s*[A-Z]+[0-9]+)*)"
)


def scan_waivers(source: str) -> List[Tuple[int, Tuple[str, ...]]]:
    """``(line, codes)`` for every real ``# noqa: X123[, Y456]`` COMMENT
    on a line that carries code.  Tokenize-based, so noqa text inside
    string literals (rule docs, test fixtures) never counts, and a
    comment-only line (the ``# noqa: L014 below — ...`` justification
    idiom) is prose, not a waiver."""
    out: List[Tuple[int, Tuple[str, ...]]] = []
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            if not tok.line[: tok.start[1]].strip():
                continue
            m = _NOQA_COMMENT.search(tok.string)
            if m:
                codes = tuple(
                    c.strip() for c in m.group(1).split(",")
                )
                out.append((tok.start[0], codes))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return []
    return out


# --- per-file analysis ----------------------------------------------------


@dataclass
class FileResult:
    """One file's analysis: post-suppression findings, the suppressions
    that fired, the waiver comments present, and whole-program facts."""

    rel: str
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Tuple[int, str]] = field(default_factory=list)
    waivers: List[Tuple[int, Tuple[str, ...]]] = field(
        default_factory=list
    )
    facts: Dict[str, Any] = field(default_factory=dict)
    parse_failed: bool = False


def _selected(codes: Optional[Sequence[str]]) -> List[Rule]:
    if codes is None:
        return [REGISTRY[c] for c in sorted(REGISTRY)]
    return [REGISTRY[c] for c in codes if c in REGISTRY]


def analyze_source(
    path: Path,
    source: str,
    codes: Optional[Sequence[str]] = None,
    with_facts: bool = False,
) -> FileResult:
    """Run the selected per-file rules (default: all registered) over
    one source blob; optionally run the selected deep rules' collect
    phase.  Suppression (``noqa: <code>`` on the finding's line, the
    monolith's substring semantics) is applied here for per-file rules;
    deep-rule findings are suppressed at finalize time from the waiver
    records."""
    rules = _selected(codes)
    rel = str(path)
    result = FileResult(rel=rel)
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as exc:
        result.parse_failed = True
        if any(r.code == "L001" for r in rules):
            result.findings.append(
                Finding(
                    rel, exc.lineno or 0, "L001",
                    f"syntax error: {exc.msg}",
                )
            )
        return result
    ctx = FileContext(path, source, tree)
    for r in rules:
        if r.check is None or not r.applies(ctx):
            continue
        for f in r.check(ctx):
            if (
                r.waivable
                and 0 < f.line <= len(ctx.lines)
                and f"noqa: {r.code}" in ctx.lines[f.line - 1]
            ):
                result.suppressed.append((f.line, r.code))
            else:
                result.findings.append(f)
    result.waivers = scan_waivers(source)
    if with_facts:
        for r in rules:
            if r.collect is not None and r.applies(ctx):
                result.facts[r.code] = r.collect(ctx)
    return result


# --- project-level analysis -----------------------------------------------


@dataclass
class ProjectReport:
    findings: List[Finding]
    stats: Dict[str, Any]
    results: Dict[str, FileResult]


def _finish(
    results: Dict[str, FileResult],
    codes: Optional[Sequence[str]],
    waiver_accounting: bool = True,
) -> ProjectReport:
    """Deep-rule finalize + waiver accounting over per-file results.
    ``waiver_accounting=False`` skips W001 — on a SUBSET run a deep
    waiver can look stale merely because the facts that make it fire
    (a donor in another module) are outside the analyzed set."""
    rules = _selected(codes)
    findings: List[Finding] = []
    used: Dict[str, set] = {}
    for rel, res in results.items():
        findings.extend(res.findings)
        used[rel] = set(res.suppressed)

    for r in rules:
        if r.finalize is None:
            continue
        facts = {
            rel: res.facts[r.code]
            for rel, res in results.items()
            if r.code in res.facts
        }
        for f in r.finalize(facts):
            res = results.get(f.path)
            waived = False
            if res is not None:
                for line, wcodes in res.waivers:
                    if line == f.line and r.code in wcodes:
                        used[f.path].add((line, r.code))
                        waived = True
                        break
            if not waived:
                findings.append(f)

    run_unused = waiver_accounting and (
        codes is None or UNUSED_WAIVER_CODE in codes
    )
    unused = 0
    if run_unused:
        ran = {r.code for r in rules}
        for rel, res in results.items():
            if res.parse_failed:
                continue
            for line, wcodes in res.waivers:
                for code in wcodes:
                    r = REGISTRY.get(code)
                    if r is None or not r.waivable or code not in ran:
                        continue
                    if (line, code) in used[rel]:
                        continue
                    unused += 1
                    findings.append(
                        Finding(
                            rel, line, UNUSED_WAIVER_CODE,
                            f"unused suppression `# noqa: {code}`: no "
                            f"{code} finding is suppressed on this "
                            "line — delete the stale waiver",
                            "warning",
                        )
                    )

    findings.sort(key=lambda f: (f.path, f.line, f.code, f.message))
    by_code: Dict[str, int] = {}
    for f in findings:
        by_code[f.code] = by_code.get(f.code, 0) + 1
    stats = {
        "files": len(results),
        "findings": len(findings),
        "by_code": by_code,
        "suppressed": sum(len(u) for u in used.values()),
        "unused_waivers": unused,
    }
    return ProjectReport(findings=findings, stats=stats, results=results)


def analyze_sources(
    sources: Dict[str, str], codes: Optional[Sequence[str]] = None
) -> ProjectReport:
    """Analyze an in-memory {relpath: source} tree — the fixture-test
    entry point (exercises per-file rules AND deep finalize)."""
    results = {
        rel: analyze_source(Path(rel), src, codes=codes, with_facts=True)
        for rel, src in sources.items()
    }
    return _finish(results, codes)


def analyze_paths(
    paths: Iterable[Path],
    codes: Optional[Sequence[str]] = None,
    cache: Optional[Any] = None,
    waiver_accounting: bool = True,
) -> ProjectReport:
    """Analyze files on disk; ``cache`` (tools/analyze/cache.py) makes
    repeat runs incremental — unchanged files reuse their findings,
    suppressions, waivers, and deep-rule facts.  Pass
    ``waiver_accounting=False`` for subset runs (see :func:`_finish`)."""
    results: Dict[str, FileResult] = {}
    for path in paths:
        rel = str(path)
        cached = cache.lookup(path) if cache is not None else None
        if cached is not None:
            results[rel] = cached
            continue
        res = analyze_source(
            path, path.read_text(encoding="utf-8"), codes=codes,
            with_facts=True,
        )
        results[rel] = res
        if cache is not None:
            cache.store(path, res)
    if cache is not None:
        cache.save()
    return _finish(results, codes, waiver_accounting=waiver_accounting)


def repo_python_files(root: Path) -> List[Path]:
    """Every python file the gate scans (the monolith's list, plus the
    analyzer package itself via the recursive tools glob)."""
    files = [root / "bench.py", root / "__graft_entry__.py"]
    files += sorted((root / "kafka_lag_based_assignor_tpu").rglob("*.py"))
    files += sorted((root / "tests").glob("*.py"))
    files += sorted((root / "tools").rglob("*.py"))
    return [
        f for f in files if f.exists() and "__pycache__" not in f.parts
    ]
