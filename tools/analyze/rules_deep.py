"""Deep whole-program analyses A001-A005 — the invariants the bench
gates and chaos soaks only catch at runtime, proven at review time.

  A001  donation safety: a value passed at a ``donate_argnums`` /
        ``donate_argnames`` position of a jitted dispatch is INVALID
        after the dispatch (XLA reuses its buffer).  Any read of that
        binding on a path after the dispatch — including the next
        iteration of an enclosing warm loop, and including reads
        through a SECOND name bound to the same buffer before the
        dispatch (``snapshot = choice``) — is the silent-corruption
        class the resident-state scrubber only detects after the fact.
  A002  lock-order / held-lock discipline: builds the project-wide
        lock-acquisition graph (``with <lock>:`` nesting plus one level
        of interprocedural resolution through calls made under a held
        lock), flags cycles and non-reentrant self-acquisition, and
        flags registry / flight-dump / device-sync calls made while a
        breaker or stream lock is held — the round-8 bug class
        (``note_breaker_trip`` under the watchdog lock stalled every
        thread's fail-fast admission during an incident).
  A003  recompile hazard: a call site of a jitted function whose
        STATIC argument derives from an unbucketed runtime value
        (``len(...)`` / ``.shape``) mints one executable per distinct
        value — the compile-storm class the ``compile_count`` bench
        gates only catch at runtime.  Static args must be constants or
        flow through the pow2 ladder helpers (``pad_bucket`` /
        ``delta_bucket`` / ``table_rows`` / ``pad_chunk`` / ladders).
  A004  wire-method span coverage: every method named in the service's
        ``_KNOWN_METHODS`` wire surface must have its dispatch wrapped
        in ``metrics.span`` — either a literal ``span("wire.X")`` or
        the label-clamped f-string form (``span(f"wire.{label}")``
        guarded by a ``_KNOWN_METHODS`` membership test); and every
        ``method == "X"`` dispatch branch must be IN ``_KNOWN_METHODS``
        — a branch outside it serves under the span/metric label
        "unknown", making its latency unattributable.
  A005  span-name catalog: every literal ``span("...")`` name in
        package code must be registered in ``utils/trace.py``'s
        ``SPAN_CATALOG`` — an unregistered name fragments the trace
        vocabulary (dashboards, the trace wire view, and the span
        histograms key on these strings), and a typo'd name silently
        mints a new series instead of failing.  ``wire.*`` names are
        A004's surface; f-string spans are dynamic by design.

All of these collect JSON-serializable per-file facts (cacheable) and
finalize over the merged set, so a donor defined in ops/streaming.py is
matched at its coalescer call sites.  Waivable with ``# noqa: A00x``
stating a reason.  Known limits (deliberate — reviewer aid, not a
verifier): bindings are tracked syntactically at the dispatch site
(plain-name aliases — ``alias = buf`` still standing at the dispatch
line — are followed to a fixpoint; aliases smuggled through containers,
calls, or attributes of OTHER bases are not), a kill inside one branch
of a conditional counts for all paths, and lock identity is name-based
(per-instance locks of one class share a node).
"""

from __future__ import annotations

import ast
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .core import FileContext, Finding, deep_rule

# --- shared helpers -------------------------------------------------------


def _expr_terminal(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _short(node: ast.AST) -> str:
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on 3.10
        return "<expr>"
    return text if len(text) <= 40 else text[:37] + "..."


def _int_seq_kw(call: ast.Call, name: str) -> Optional[List[int]]:
    for kw in call.keywords:
        if kw.arg != name:
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return [v.value]
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if not (
                    isinstance(e, ast.Constant)
                    and isinstance(e.value, int)
                ):
                    return None
                out.append(e.value)
            return out
        return None
    return None


def _str_seq_kw(call: ast.Call, name: str) -> Optional[List[str]]:
    for kw in call.keywords:
        if kw.arg != name:
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return [v.value]
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if not (
                    isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                ):
                    return None
                out.append(e.value)
            return out
        return None
    return None


def _jit_call_info(call: ast.Call) -> Optional[Dict[str, Any]]:
    """Recognize ``jax.jit(...)`` and ``functools.partial(jax.jit,
    ...)`` and extract the donation/static configuration; None when the
    call is neither or carries none of the four keywords."""
    fname = _expr_terminal(call.func)
    if fname == "partial":
        if not (call.args and _expr_terminal(call.args[0]) == "jit"):
            return None
    elif fname != "jit":
        return None
    info = {
        "donate": _int_seq_kw(call, "donate_argnums"),
        "donate_names": _str_seq_kw(call, "donate_argnames"),
        "static_nums": _int_seq_kw(call, "static_argnums"),
        "static_names": _str_seq_kw(call, "static_argnames"),
    }
    if all(v is None for v in info.values()):
        return None
    return info


def _fn_params(fn: ast.AST) -> List[str]:
    args = fn.args
    return [a.arg for a in list(args.posonlyargs) + list(args.args)]


# --- use-after-donation machinery (A001) ----------------------------------


def _child_blocks(stmt: ast.stmt) -> Iterator[List[ast.stmt]]:
    for name in ("body", "orelse", "finalbody"):
        blk = getattr(stmt, name, None)
        if blk and isinstance(blk, list):
            yield blk
    for handler in getattr(stmt, "handlers", []) or []:
        if handler.body:
            yield handler.body


def _find_chain(
    body: List[ast.stmt], call: ast.Call
) -> Optional[List[Tuple[List[ast.stmt], int]]]:
    """Ancestor chain [(block, index), ...] from the given block down
    to the innermost statement containing ``call``; nested function /
    class bodies are not descended (they do not execute here)."""
    for i, stmt in enumerate(body):
        if not any(n is call for n in ast.walk(stmt)):
            continue
        if not isinstance(
            stmt,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
        ):
            for blk in _child_blocks(stmt):
                sub = _find_chain(blk, call)
                if sub is not None:
                    return [(body, i)] + sub
        return [(body, i)]
    return None


def _emit_events(node: ast.AST, out: List[Tuple[str, tuple, int]]) -> None:
    """Append (kind, key, line) binding events for one statement or
    expression in approximate execution order.  Keys: ``("n", name)``
    for plain names, ``("a", base, attr)`` for ``base.attr``."""
    if isinstance(
        node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
    ):
        return  # a nested def's body does not execute here
    if isinstance(node, ast.Assign):
        _emit_events(node.value, out)
        for t in node.targets:
            _emit_events(t, out)
        return
    if isinstance(node, ast.AnnAssign):
        if node.value is not None:
            _emit_events(node.value, out)
        _emit_events(node.target, out)
        return
    if isinstance(node, ast.AugAssign):
        _emit_events(node.value, out)
        # x += v both reads and rebinds x
        key = _event_key(node.target)
        if key is not None:
            out.append(("load", key, node.target.lineno))
            out.append(("store", key, node.target.lineno))
        else:
            _emit_events(node.target, out)
        return
    if isinstance(node, ast.Name):
        key = ("n", node.id)
        kind = "load" if isinstance(node.ctx, ast.Load) else "store"
        out.append((kind, key, node.lineno))
        return
    if isinstance(node, ast.Attribute) and isinstance(
        node.value, ast.Name
    ):
        key = ("a", node.value.id, node.attr)
        if isinstance(node.ctx, ast.Load):
            out.append(("load", key, node.lineno))
            out.append(("load", ("n", node.value.id), node.lineno))
        else:
            out.append(("store", key, node.lineno))
        return
    if isinstance(node, ast.Call):
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and any(
                m in func.attr for m in ("resident", "adopt", "drop")
            )
        ):
            # an audited swap helper re-installs the base's buffers:
            # evaluate its arguments, then treat the base as refreshed
            for a in node.args:
                _emit_events(a, out)
            for kw in node.keywords:
                _emit_events(kw.value, out)
            out.append(("killbase", ("n", func.value.id), node.lineno))
            return
    for child in ast.iter_child_nodes(node):
        _emit_events(child, out)


def _event_key(node: ast.AST) -> Optional[tuple]:
    if isinstance(node, ast.Name):
        return ("n", node.id)
    if isinstance(node, ast.Attribute) and isinstance(
        node.value, ast.Name
    ):
        return ("a", node.value.id, node.attr)
    return None


def _track_key(expr: ast.AST) -> Optional[tuple]:
    """The binding a donated argument expression reads: a plain name, a
    ``container[i]`` element (tracked as the container name), or a
    ``base.attr`` field."""
    if isinstance(expr, ast.Name):
        return ("n", expr.id)
    if isinstance(expr, ast.Subscript) and isinstance(
        expr.value, ast.Name
    ):
        return ("n", expr.value.id)
    if isinstance(expr, ast.Attribute) and isinstance(
        expr.value, ast.Name
    ):
        return ("a", expr.value.id, expr.attr)
    return None


def _alias_keys(
    fn: ast.AST, key: tuple, call_line: int
) -> List[tuple]:
    """``key`` plus every plain name whose binding still standing at
    the dispatch line reads the same buffer (``alias = buf``,
    ``alias = resident[i]``, ``alias = base.attr`` — transitively, to
    a fixpoint).  A donated buffer stays reachable through every such
    second binding, so the use-after-donation scan must follow all of
    them; a name rebound to something else before the dispatch no
    longer aliases it."""
    last_rhs: Dict[str, ast.AST] = {}
    best_line: Dict[str, int] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if node.lineno >= call_line:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and node.lineno > best_line.get(
                t.id, -1
            ):
                best_line[t.id] = node.lineno
                last_rhs[t.id] = value
    keys = [key]
    resolved = {key}
    changed = True
    while changed:
        changed = False
        for name, rhs in sorted(last_rhs.items()):
            nk = ("n", name)
            if nk in resolved:
                continue
            rk = _track_key(rhs)
            if rk is not None and rk in resolved:
                resolved.add(nk)
                keys.append(nk)
                changed = True
    return keys


def _scan_events(
    events: List[Tuple[str, tuple, int]], live: set
) -> Tuple[Optional[int], Optional[tuple]]:
    """First read of any binding still in ``live``; mutates ``live``,
    discarding bindings as stores/killbases rebind them (an attr
    binding also dies when its base name is rebound).  Returns
    ``(line, key)`` of the first live read, or ``(None, None)``."""
    for kind, k, line in events:
        if not live:
            break
        if kind in ("store", "killbase"):
            live.discard(k)
            if k[0] == "n":
                for ak in [
                    x for x in live if x[0] == "a" and x[1] == k[1]
                ]:
                    live.discard(ak)
        elif kind == "load" and k in live:
            return line, k
    return None, None


def _use_after_call(
    fn_body: List[ast.stmt], call: ast.Call, keys: List[tuple]
) -> Tuple[Optional[int], Optional[tuple]]:
    """``(line, key)`` of the first read of any of ``keys`` (the
    donated binding plus its aliases) after the statement containing
    ``call`` and before that binding's rebind, following the tail of
    every enclosing block and the back edge of the innermost enclosing
    loop; ``(None, None)`` when every binding is rebound first or
    never read again."""
    chain = _find_chain(fn_body, call)
    if chain is None:
        return None, None
    events: List[Tuple[str, tuple, int]] = []
    block, idx = chain[-1]
    stmt = block[idx]
    # the dispatch statement's own targets rebind AFTER the call runs
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            _emit_events(t, events)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        _emit_events(stmt.target, events)
    for blk, i in reversed(chain):
        for later in blk[i + 1:]:
            _emit_events(later, events)
    live = set(keys)
    line, used = _scan_events(events, live)
    if line is not None:
        return line, used
    if not live:
        return None, None  # every binding rebound before any read
    # back edge: the innermost enclosing loop replays its body, so the
    # dispatch's own argument loads become next-iteration reads
    for blk, i in reversed(chain[:-1]):
        s = blk[i]
        if isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
            loop_events: List[Tuple[str, tuple, int]] = []
            for body_stmt in s.body:
                _emit_events(body_stmt, loop_events)
            return _scan_events(loop_events, live)
    return None, None


# --- A003 raw-runtime detection -------------------------------------------

_BUCKET_MARKERS = ("bucket", "pad_chunk", "table_rows", "ladder", "pow2")


def _is_bucketing_call(call: ast.Call) -> bool:
    name = _expr_terminal(call.func)
    return any(m in name for m in _BUCKET_MARKERS)


def _expr_is_raw(expr: ast.AST) -> bool:
    """True when the expression derives from ``len(...)`` or ``.shape``
    WITHOUT flowing through a sanctioned bucketing helper."""
    stack = [expr]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Call):
            if _is_bucketing_call(n):
                continue  # sanctioned: do not descend
            if (
                isinstance(n.func, ast.Name) and n.func.id == "len"
            ):
                return True
            stack.extend(ast.iter_child_nodes(n))
            continue
        if isinstance(n, ast.Attribute) and n.attr == "shape":
            return True
        stack.extend(ast.iter_child_nodes(n))
    return False


def _last_assign_rhs(
    fn: Optional[ast.AST], name: str, before_line: int
) -> Optional[ast.AST]:
    if fn is None:
        return None
    best: Optional[ast.AST] = None
    best_line = -1
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        if node.lineno >= before_line or node.lineno <= best_line:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == name:
                best, best_line = value, node.lineno
    return best


def _arg_is_raw(
    expr: ast.AST, fn: Optional[ast.AST], call_line: int
) -> bool:
    seen: set = set()
    e = expr
    for _ in range(4):
        if not isinstance(e, ast.Name):
            break
        if e.id in seen:
            return False
        seen.add(e.id)
        rhs = _last_assign_rhs(fn, e.id, call_line)
        if rhs is None:
            return False  # parameter / attribute state: taken on faith
        e = rhs
    return _expr_is_raw(e)


# --- shared dispatch-site scan (A001 + A003) ------------------------------

_PKG = "kafka_lag_based_assignor_tpu"


def _dispatch_scan(ctx: FileContext) -> Dict[str, Any]:
    """One pass shared by A001 and A003: the file's jit registry
    (donation + static config) and, for every call site of a local or
    package-imported jitted candidate, per-argument facts — the first
    use-after-dispatch line of the binding it reads, and whether it is
    an unbucketed runtime derivation."""
    if "dispatch" in ctx.scratch:
        return ctx.scratch["dispatch"]

    module_fns = {
        n.name: n
        for n in ctx.tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    jits: Dict[str, Dict[str, Any]] = {}
    jit_wrapped: set = set()  # ANY jit decoration, kwargs or not
    imported: set = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, (ast.Name, ast.Attribute)):
                    if _expr_terminal(dec) == "jit":
                        jit_wrapped.add(node.name)  # bare @jax.jit
                    continue
                if not isinstance(dec, ast.Call):
                    continue
                dec_name = _expr_terminal(dec.func)
                if dec_name == "jit" or (
                    dec_name == "partial"
                    and dec.args
                    and _expr_terminal(dec.args[0]) == "jit"
                ):
                    jit_wrapped.add(node.name)
                info = _jit_call_info(dec)
                if info is not None:
                    info["params"] = _fn_params(node)
                    jits[node.name] = info
        elif isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            info = _jit_call_info(node.value)
            if info is not None and len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                inner = (
                    node.value.args[0] if node.value.args else None
                )
                params = None
                if isinstance(inner, ast.Name) and inner.id in module_fns:
                    params = _fn_params(module_fns[inner.id])
                info["params"] = params
                jits[node.targets[0].id] = info
        elif isinstance(node, ast.Import):
            # only package-origin imports can name a project jit —
            # np/jnp/jax library calls are never donors/static sites,
            # and scanning them would dominate the cold run + cache
            for alias in node.names:
                if alias.name.startswith(_PKG):
                    imported.add(
                        alias.asname or alias.name.split(".")[0]
                    )
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0 and not (
                node.module or ""
            ).startswith(_PKG):
                continue  # absolute import of a foreign library
            for alias in node.names:
                if alias.name != "*":
                    imported.add(alias.asname or alias.name)

    candidates = set(jits) | imported
    calls: List[Dict[str, Any]] = []

    def arg_fact(
        expr: ast.AST, fn: Optional[ast.AST], call: ast.Call
    ) -> Dict[str, Any]:
        fact: Dict[str, Any] = {
            "desc": _short(expr),
            "line": expr.lineno,
        }
        key = _track_key(expr)
        if key is not None and fn is not None:
            keys = _alias_keys(fn, key, call.lineno)
            use, used_key = _use_after_call(fn.body, call, keys)
            fact["use"] = use
            if (
                used_key is not None
                and used_key != key
                and used_key[0] == "n"
            ):
                fact["via"] = used_key[1]
        else:
            fact["use"] = None
        fact["raw"] = _arg_is_raw(expr, fn, call.lineno)
        return fact

    def visit(node: ast.AST, fn: Optional[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            child_fn = fn
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                child_fn = child
            if isinstance(child, ast.Call):
                tname = _expr_terminal(child.func)
                dotted = (
                    isinstance(child.func, ast.Attribute)
                    and isinstance(child.func.value, ast.Name)
                    and child.func.value.id in imported
                )
                if tname in candidates or dotted:
                    calls.append(
                        {
                            "callee": tname,
                            "line": child.lineno,
                            "in_jit": fn is not None
                            and (
                                fn.name in jits
                                or fn.name in jit_wrapped
                            ),
                            "args": [
                                arg_fact(a, fn, child)
                                for a in child.args
                            ],
                            "kwargs": {
                                kw.arg: arg_fact(kw.value, fn, child)
                                for kw in child.keywords
                                if kw.arg is not None
                            },
                        }
                    )
            visit(child, child_fn)

    visit(ctx.tree, None)
    scan = {"jits": jits, "calls": calls}
    ctx.scratch["dispatch"] = scan
    return scan


# --- A001 donation safety -------------------------------------------------


def _finalize_a001(facts: Dict[str, Any]) -> Iterator[Finding]:
    donors: Dict[str, Dict[str, Any]] = {}
    for f in facts.values():
        for name, spec in f.get("jits", {}).items():
            if spec.get("donate") or spec.get("donate_names"):
                donors.setdefault(name, spec)
    for f in facts.values():
        rel = f["rel"]
        for call in f.get("calls", []):
            spec = donors.get(call["callee"])
            if spec is None:
                continue
            params = spec.get("params")
            donated_names = set(spec.get("donate_names") or [])
            positions = list(spec.get("donate") or [])
            for p in positions:
                if params and p < len(params):
                    donated_names.add(params[p])
            hits: List[Tuple[Dict[str, Any], int]] = []
            for p in positions:
                if p < len(call["args"]):
                    hits.append((call["args"][p], p))
            for name, fact in call.get("kwargs", {}).items():
                if name in donated_names:
                    hits.append((fact, -1))
            for fact, _pos in hits:
                use = fact.get("use")
                if use is None:
                    continue
                via = fact.get("via")
                reach = (
                    f" through its alias `{via}`" if via else ""
                )
                yield Finding(
                    rel,
                    use,
                    "A001",
                    f"use after donation: `{fact['desc']}` was "
                    f"donated to {call['callee']}() (dispatch at "
                    f"line {call['line']}) and is read "
                    f"afterwards{reach} — XLA reuses donated "
                    "buffers, so this read sees corrupt data; "
                    "rebind the dispatch result (or waive with "
                    "`# noqa: A001`)",
                )


@deep_rule(
    "A001",
    "use of a donated buffer after its jit dispatch",
    finalize=_finalize_a001,
    applies=lambda ctx: ctx.is_package,
)
def collect_a001(ctx: FileContext) -> Dict[str, Any]:
    scan = _dispatch_scan(ctx)
    return {"rel": ctx.rel, "jits": scan["jits"], "calls": scan["calls"]}


# --- A003 recompile hazard ------------------------------------------------


def _finalize_a003(facts: Dict[str, Any]) -> Iterator[Finding]:
    jits: Dict[str, Dict[str, Any]] = {}
    for f in facts.values():
        for name, spec in f.get("jits", {}).items():
            if spec.get("static_nums") or spec.get("static_names"):
                jits.setdefault(name, spec)
    for f in facts.values():
        rel = f["rel"]
        for call in f.get("calls", []):
            spec = jits.get(call["callee"])
            if spec is None:
                continue
            if call.get("in_jit"):
                # inside an enclosing jit trace the inner call inlines
                # — .shape is a trace-time static, bucketed by the
                # OUTER executable's signature, not a fresh compile
                continue
            params = spec.get("params")
            static_names = set(spec.get("static_names") or [])
            positions = list(spec.get("static_nums") or [])
            for name in static_names:
                if params and name in params:
                    positions.append(params.index(name))
            hits: List[Dict[str, Any]] = []
            for p in set(positions):
                if p < len(call["args"]):
                    hits.append(call["args"][p])
            for name, fact in call.get("kwargs", {}).items():
                if name in static_names:
                    hits.append(fact)
            for fact in hits:
                if not fact.get("raw"):
                    continue
                yield Finding(
                    rel,
                    fact.get("line") or call["line"],
                    "A003",
                    f"recompile hazard: static argument "
                    f"`{fact['desc']}` to jitted {call['callee']}() "
                    "derives from an unbucketed runtime value "
                    "(len()/.shape) — every distinct value mints an "
                    "executable; route it through the pow2 ladder "
                    "(pad_bucket/delta_bucket/table_rows) or waive "
                    "with `# noqa: A003`",
                )


@deep_rule(
    "A003",
    "jit static argument from an unbucketed runtime value",
    finalize=_finalize_a003,
    applies=lambda ctx: ctx.is_package,
)
def collect_a003(ctx: FileContext) -> Dict[str, Any]:
    scan = _dispatch_scan(ctx)
    return {"rel": ctx.rel, "jits": scan["jits"], "calls": scan["calls"]}


# --- A002 lock order / held-lock discipline -------------------------------

#: Calls that must never run under a breaker or stream lock: registry
#: access, flight-recorder dumps (JSON build + file write), and
#: blocking device syncs — each can stall every other thread's
#: fail-fast admission exactly during an incident.
_A002_BANNED = frozenset(
    {
        "note_breaker_trip",
        "flight_recorder",
        "dump_flight",
        "registry",
        "get_registry",
        "device_get",
        "block_until_ready",
    }
)


def _lock_ref(
    expr: ast.AST, cls: Optional[str]
) -> Optional[Dict[str, Any]]:
    """A name-based reference to an acquired lock, or None when the
    with-item is not lock-shaped (only attrs/names containing 'lock'
    count)."""
    if isinstance(expr, ast.Attribute) and isinstance(
        expr.value, ast.Name
    ):
        if "lock" not in expr.attr.lower():
            return None
        base = expr.value.id
        return {
            "base": base,
            "attr": expr.attr,
            "cls": cls if base == "self" else None,
        }
    if isinstance(expr, ast.Name) and "lock" in expr.id.lower():
        return {"base": None, "attr": expr.id, "cls": None}
    return None


def collect_a002_facts(ctx: FileContext) -> Dict[str, Any]:
    locks: List[Dict[str, Any]] = []
    edges: List[Dict[str, Any]] = []
    calls: List[Dict[str, Any]] = []
    fn_locks: Dict[str, List[Dict[str, Any]]] = {}

    def record_lock_def(node: ast.Assign, cls: Optional[str]) -> None:
        value = node.value
        if not (
            isinstance(value, ast.Call)
            and _expr_terminal(value.func) in ("Lock", "RLock")
        ):
            return
        kind = _expr_terminal(value.func)
        for t in node.targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                locks.append(
                    {
                        "cls": cls,
                        "name": t.attr,
                        "kind": kind,
                        "line": node.lineno,
                    }
                )
            elif isinstance(t, ast.Name):
                locks.append(
                    {
                        "cls": None,
                        "name": t.id,
                        "kind": kind,
                        "line": node.lineno,
                    }
                )

    def visit(
        node: ast.AST,
        cls: Optional[str],
        fn: Optional[str],
        held: List[Dict[str, Any]],
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name, None, [])
                continue
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                visit(child, cls, child.name, [])
                continue
            if isinstance(child, ast.Assign):
                record_lock_def(child, cls)
            if isinstance(child, (ast.With, ast.AsyncWith)):
                refs = []
                for item in child.items:
                    ref = _lock_ref(item.context_expr, cls)
                    if ref is not None:
                        refs.append(ref)
                for ref in refs:
                    for outer in held:
                        edges.append(
                            {
                                "outer": outer,
                                "inner": ref,
                                "line": child.lineno,
                                "fn": fn,
                            }
                        )
                    if fn is not None:
                        fn_locks.setdefault(fn, []).append(ref)
                visit(child, cls, fn, held + refs)
                continue
            if isinstance(child, ast.Call) and held:
                calls.append(
                    {
                        "locks": list(held),
                        "callee": _expr_terminal(child.func),
                        "line": child.lineno,
                    }
                )
            visit(child, cls, fn, held)

    visit(ctx.tree, None, None, [])
    return {
        "rel": ctx.rel,
        "locks": locks,
        "edges": edges,
        "calls": calls,
        "fn_locks": fn_locks,
    }


def _a002_resolver(facts: Dict[str, Any]):
    """Build a lock-reference resolver over every file's lock defs:
    returns (resolve(ref, rel) -> (lock_id, kind, def_rel), ...)."""
    by_cls_attr: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    by_attr: Dict[str, List[Tuple[str, str, Optional[str]]]] = {}
    by_global: Dict[Tuple[str, str], str] = {}
    for rel, f in facts.items():
        for d in f.get("locks", []):
            if d["cls"] is not None:
                by_cls_attr.setdefault((d["cls"], d["name"]), []).append(
                    (rel, d["kind"])
                )
            else:
                by_global[(rel, d["name"])] = d["kind"]
            by_attr.setdefault(d["name"], []).append(
                (rel, d["kind"], d["cls"])
            )

    def shortmod(rel: str) -> str:
        parts = rel.replace("\\", "/").split("/")
        return "/".join(parts[-2:])

    def resolve(
        ref: Dict[str, Any], rel: str
    ) -> Tuple[str, Optional[str], Optional[str]]:
        cls = ref.get("cls")
        attr = ref["attr"]
        if cls is not None:
            defs = by_cls_attr.get((cls, attr), [])
            if defs:
                drel, kind = defs[0]
                return f"{shortmod(drel)}::{cls}.{attr}", kind, drel
            return f"{shortmod(rel)}::{cls}.{attr}", None, rel
        if ref.get("base") is None:
            kind = by_global.get((rel, attr))
            if kind is not None:
                return f"{shortmod(rel)}::{attr}", kind, rel
            return f"?::{attr}", None, None
        # Per-instance identity: a lock reached through a NON-self base
        # (``peer._cache_lock``) is a different lock object per
        # instance, so its id carries the instance expression —
        # collapsing it onto the class attribute would alias every
        # instance's lock into one node and invent cycles/self-
        # deadlocks between code that orders two instances correctly
        # (e.g. a gossip thread touching its own coordinator next to a
        # drill touching a twin's).
        inst = f"@{ref['base']}"
        defs = by_attr.get(attr, [])
        if len(defs) == 1:
            drel, kind, dcls = defs[0]
            owner = f"{dcls}." if dcls else ""
            return f"{shortmod(drel)}::{owner}{attr}{inst}", kind, drel
        return f"?::{attr}{inst}", None, None

    return resolve


def _finalize_a002(facts: Dict[str, Any]) -> Iterator[Finding]:
    resolve = _a002_resolver(facts)

    # one-level interprocedural: a function's directly-acquired locks,
    # usable only when its terminal name is project-unique
    fn_index: Dict[str, List[Tuple[str, List[str]]]] = {}
    for rel, f in facts.items():
        for fname, refs in f.get("fn_locks", {}).items():
            ids = sorted({resolve(r, rel)[0] for r in refs})
            fn_index.setdefault(fname, []).append((rel, ids))

    graph: Dict[str, set] = {}
    sites: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add_edge(a: str, b: str, rel: str, line: int, how: str) -> None:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
        sites.setdefault((a, b), (rel, line, how))

    emitted: set = set()
    out: List[Finding] = []

    for rel, f in facts.items():
        for e in f.get("edges", []):
            outer_id, outer_kind, _ = resolve(e["outer"], rel)
            inner_id, _, _ = resolve(e["inner"], rel)
            if outer_id == inner_id:
                # Same id + same base expression = the SAME lock
                # object (self-through-self, or the same non-self
                # instance variable re-acquired) — with per-instance
                # ids two different instances of one class never reach
                # here, so this branch is exactly the guaranteed
                # self-deadlock.
                same_inst = (
                    e["outer"].get("base") is not None
                    and e["outer"].get("base") == e["inner"].get("base")
                )
                if same_inst and outer_kind == "Lock":
                    key = (rel, e["line"], outer_id)
                    if key not in emitted:
                        emitted.add(key)
                        out.append(
                            Finding(
                                rel,
                                e["line"],
                                "A002",
                                f"nested acquisition of {outer_id} "
                                "(a non-reentrant threading.Lock) "
                                "while already held: guaranteed "
                                "self-deadlock (or waive with "
                                "`# noqa: A002`)",
                            )
                        )
                continue
            add_edge(outer_id, inner_id, rel, e["line"], "nested with")

    for rel, f in facts.items():
        for c in f.get("calls", []):
            entries = fn_index.get(c["callee"], [])
            if len(entries) != 1:
                continue
            callee_rel, callee_ids = entries[0]
            held_ids = {resolve(r, rel)[0] for r in c["locks"]}
            for held in held_ids:
                for inner in callee_ids:
                    if inner == held:
                        continue
                    add_edge(
                        held, inner, rel, c["line"],
                        f"via {c['callee']}()",
                    )

    # cycle detection (iterative Tarjan SCC)
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstack: Dict[str, bool] = {}
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack[v] = True
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack[w] = True
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if onstack.get(w):
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack[w] = False
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    for comp in sccs:
        if len(comp) < 2:
            continue
        members = set(comp)
        cycle_sites = sorted(
            (site, pair)
            for pair, site in sites.items()
            if pair[0] in members and pair[1] in members
        )
        if not cycle_sites:
            continue
        (rel, line, how), _pair = cycle_sites[0]
        order = " -> ".join(sorted(members))
        out.append(
            Finding(
                rel,
                line,
                "A002",
                f"lock-order cycle: {order} — these locks are "
                f"acquired in conflicting orders ({how} here); pick "
                "one global order or waive with `# noqa: A002`",
            )
        )

    # held-lock discipline: registry / flight-dump / device-sync work
    # under a breaker or stream lock
    for rel, f in facts.items():
        for c in f.get("calls", []):
            if c["callee"] not in _A002_BANNED:
                continue
            for ref in c["locks"]:
                lock_id, _kind, def_rel = resolve(ref, rel)
                breaker = def_rel is not None and def_rel.endswith(
                    "watchdog.py"
                )
                stream = "stream" in ref["attr"].lower()
                if not (breaker or stream):
                    continue
                key = (rel, c["line"], c["callee"])
                if key in emitted:
                    continue
                emitted.add(key)
                out.append(
                    Finding(
                        rel,
                        c["line"],
                        "A002",
                        f"{c['callee']}() called while holding "
                        f"{lock_id}: registry/flight-dump/device-"
                        "sync work under a breaker or stream lock "
                        "stalls every thread's fail-fast admission "
                        "during an incident — move it outside the "
                        "lock (or waive with `# noqa: A002`)",
                    )
                )
                break
    return iter(out)


@deep_rule(
    "A002",
    "lock-order cycle or banned call under a breaker/stream lock",
    finalize=_finalize_a002,
    applies=lambda ctx: ctx.is_package,
)
def collect_a002(ctx: FileContext) -> Dict[str, Any]:
    return collect_a002_facts(ctx)


# --- A004 wire-method span coverage ---------------------------------------


def _a004_known_methods(tree: ast.Module) -> Optional[Dict[str, Any]]:
    """The file's ``_KNOWN_METHODS = frozenset({...})`` definition, as
    ``{"names": [...], "line": n}`` — the wire surface whose coverage
    A004 proves."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "_KNOWN_METHODS"
            for t in node.targets
        ):
            continue
        call = node.value
        if not (
            isinstance(call, ast.Call)
            and _expr_terminal(call.func) == "frozenset"
            and call.args
        ):
            continue
        elts = getattr(call.args[0], "elts", None)
        if elts is None:
            continue
        names = [
            e.value
            for e in elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
        if names:
            return {"names": sorted(names), "line": node.lineno}
    return None


def _a004_span_arg(call: ast.Call) -> Optional[Tuple[str, str]]:
    """Classify a ``metrics.span(...)`` first argument: ``("literal",
    name)`` for ``span("wire.X")``, ``("dynamic", "")`` for an f-string
    beginning with the ``wire.`` prefix, None otherwise."""
    if _expr_terminal(call.func) != "span" or not call.args:
        return None
    a = call.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        if a.value.startswith("wire."):
            return ("literal", a.value[len("wire."):])
        return None
    if isinstance(a, ast.JoinedStr) and a.values:
        head = a.values[0]
        if (
            isinstance(head, ast.Constant)
            and isinstance(head.value, str)
            and head.value.startswith("wire.")
        ):
            return ("dynamic", "")
    return None


def _a004_has_known_guard(fn: ast.AST) -> bool:
    """True when ``fn`` contains a membership test against
    ``_KNOWN_METHODS`` (the label-clamping guard that makes a dynamic
    ``span(f"wire.{label}")`` cover every known method)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        if not any(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
            continue
        for comp in node.comparators:
            if _expr_terminal(comp) == "_KNOWN_METHODS":
                return True
    return False


def _finalize_a004(facts: Dict[str, Any]) -> Iterator[Finding]:
    known: Optional[Dict[str, Any]] = None
    known_rel = ""
    for f in facts.values():
        if f.get("known"):
            known = f["known"]
            known_rel = f["rel"]
            break
    if known is None:
        return  # no wire surface in the analyzed set: nothing to prove
    known_names = set(known["names"])
    literal: Dict[str, Tuple[str, int]] = {}
    dynamic = False
    for f in facts.values():
        for name, line in f.get("literal_spans", []):
            literal.setdefault(name, (f["rel"], line))
        dynamic = dynamic or f.get("dynamic_span", False)
    for name in sorted(known_names):
        if dynamic or name in literal:
            continue
        yield Finding(
            known_rel,
            known["line"],
            "A004",
            f"wire method `{name}` is in _KNOWN_METHODS but no "
            "metrics.span wraps its dispatch — its latency is "
            "invisible to klba_span_duration_ms and the flight "
            "recorder; wrap the dispatch in `metrics.span(\"wire."
            f"{name}\")` (or the guarded f-string form) or waive "
            "with `# noqa: A004`",
        )
    for f in facts.values():
        for name, line in f.get("dispatch_eq", []):
            if name in known_names:
                continue
            yield Finding(
                f["rel"],
                line,
                "A004",
                f"dispatch branch for wire method `{name}` is absent "
                "from _KNOWN_METHODS — it is served under the span/"
                "metric label \"unknown\", so its latency and request "
                "counts are unattributable; add it to _KNOWN_METHODS "
                "(or waive with `# noqa: A004`)",
            )


@deep_rule(
    "A004",
    "wire method without metrics.span coverage",
    finalize=_finalize_a004,
    applies=lambda ctx: ctx.is_package,
)
def collect_a004(ctx: FileContext) -> Dict[str, Any]:
    known = _a004_known_methods(ctx.tree)
    literal_spans: List[Tuple[str, int]] = []
    dynamic = False
    for node in ast.walk(ctx.tree):
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            continue
        guarded = _a004_has_known_guard(node)
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            kind = _a004_span_arg(sub)
            if kind is None:
                continue
            if kind[0] == "literal":
                literal_spans.append((kind[1], sub.lineno))
            elif guarded:
                # span(f"wire.{label}") under a _KNOWN_METHODS
                # membership clamp covers the whole known surface.
                dynamic = True
    dispatch_eq: List[Tuple[str, int]] = []
    if known is not None:
        # Dispatch branches live with the surface definition: string
        # equality against the `method` binding.
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Compare):
                continue
            if not (
                isinstance(node.left, ast.Name)
                and node.left.id == "method"
                and len(node.ops) == 1
                and isinstance(node.ops[0], ast.Eq)
                and len(node.comparators) == 1
            ):
                continue
            comp = node.comparators[0]
            if isinstance(comp, ast.Constant) and isinstance(
                comp.value, str
            ):
                dispatch_eq.append((comp.value, node.lineno))
    return {
        "rel": ctx.rel,
        "known": known,
        "literal_spans": literal_spans,
        "dynamic_span": dynamic,
        "dispatch_eq": dispatch_eq,
    }


# --- A005 span-name catalog ------------------------------------------------


def _a005_span_catalog(tree: ast.Module) -> Optional[Dict[str, Any]]:
    """The file's ``SPAN_CATALOG = frozenset({...})`` definition, as
    ``{"names": [...], "line": n}`` — the registered span vocabulary
    A005 checks literal ``span("...")`` names against."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "SPAN_CATALOG"
            for t in node.targets
        ):
            continue
        call = node.value
        if not (
            isinstance(call, ast.Call)
            and _expr_terminal(call.func) == "frozenset"
            and call.args
        ):
            continue
        elts = getattr(call.args[0], "elts", None)
        if elts is None:
            continue
        names = [
            e.value
            for e in elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
        if names:
            return {"names": sorted(names), "line": node.lineno}
    return None


def _finalize_a005(facts: Dict[str, Any]) -> Iterator[Finding]:
    catalog: Optional[Dict[str, Any]] = None
    for f in facts.values():
        if f.get("catalog"):
            catalog = f["catalog"]
            break
    if catalog is None:
        return  # no span catalog in the analyzed set: nothing to prove
    registered = set(catalog["names"])
    for f in facts.values():
        for name, line in f.get("span_literals", []):
            if name in registered:
                continue
            yield Finding(
                f["rel"],
                line,
                "A005",
                f"span name `{name}` is not registered in utils/"
                "trace.py SPAN_CATALOG — an unregistered literal "
                "fragments the trace vocabulary (and a typo mints a "
                "new series instead of failing); add it to "
                "SPAN_CATALOG or waive with `# noqa: A005`",
            )


@deep_rule(
    "A005",
    "literal span name outside the registered catalog",
    finalize=_finalize_a005,
    applies=lambda ctx: ctx.is_package,
)
def collect_a005(ctx: FileContext) -> Dict[str, Any]:
    span_literals: List[Tuple[str, int]] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if _expr_terminal(node.func) != "span" or not node.args:
            continue
        a = node.args[0]
        if not (isinstance(a, ast.Constant) and isinstance(a.value, str)):
            continue  # f-string / computed names are dynamic by design
        if a.value.startswith("wire."):
            continue  # the wire surface is A004's contract
        span_literals.append((a.value, node.lineno))
    return {
        "rel": ctx.rel,
        "catalog": _a005_span_catalog(ctx.tree),
        "span_literals": span_literals,
    }
