"""Pluggable whole-program static analysis (``klba-analyze``).

Importing the package registers the full rule catalog: the L001-L021
legacy rules (behavior-identical to the retired tools/lint.py
monolith), the deep invariant analyses A001-A004, and the engine's
W001 unused-waiver accounting.  See DEPLOYMENT.md "Static analysis"
for the catalog, the waiver policy, and how to add a rule."""

from . import rules_deep, rules_invariants, rules_style  # noqa: F401
from .core import (
    LEGACY_CODES,
    REGISTRY,
    FileContext,
    FileResult,
    Finding,
    ProjectReport,
    Rule,
    analyze_paths,
    analyze_source,
    analyze_sources,
    repo_python_files,
    rule,
)

__all__ = [
    "LEGACY_CODES",
    "REGISTRY",
    "FileContext",
    "FileResult",
    "Finding",
    "ProjectReport",
    "Rule",
    "analyze_paths",
    "analyze_source",
    "analyze_sources",
    "repo_python_files",
    "rule",
]
