"""The ``klba-analyze`` command line (also ``python -m tools.analyze``).

Default run: every repo python file through the full ruleset
(L001-L021 legacy + A001-A005 deep + W001 waiver accounting), text
report to stdout, exit 1 on any finding.  ``--changed`` analyzes only
the files git reports as changed (working tree + commits past the
merge base, :func:`git_changed_files`) — a pre-commit hook touches a
handful of files, not the 100+-file stat sweep the mtime cache still
walks; when the tree is not a git checkout the flag degrades to that
cache-backed full sweep.  ``--sarif PATH`` writes the CI artifact
next to whatever ``--format`` goes to stdout."""

from __future__ import annotations

import argparse
import subprocess
import sys
import time
from pathlib import Path
from typing import List, Optional

from .cache import CACHE_BASENAME, AnalysisCache
from .core import LEGACY_CODES, analyze_paths, repo_python_files
from .reporters import RENDERERS, render_sarif


def _repo_root() -> Path:
    root = Path(__file__).resolve().parent.parent.parent
    if (root / "kafka_lag_based_assignor_tpu").is_dir():
        return root
    # installed console script (site-packages): analyze the checkout
    # the operator is standing in
    return Path.cwd()


def _git_lines(root: Path, *args: str) -> Optional[List[str]]:
    try:
        out = subprocess.run(
            ["git", *args], cwd=root, capture_output=True,
            text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    return out.stdout.splitlines()


def git_changed_files(root: Path) -> Optional[List[Path]]:
    """Python files git considers changed, or ``None`` off a checkout.

    The changed set is the union of the working-tree delta
    (``git status --porcelain``: staged, unstaged, and untracked) and
    the commits past the upstream merge base (``git diff --name-only
    <base>...HEAD``) so a CI run on a feature branch sees the same
    set a pre-commit hook saw locally.  Deleted files are dropped —
    there is nothing left to parse.  ``None`` (as opposed to an empty
    list, which means "a checkout with nothing changed") tells the
    caller git itself is unavailable and the mtime sweep must run.
    """
    status = _git_lines(root, "status", "--porcelain")
    if status is None:
        return None
    rel: set = set()
    for line in status:
        if len(line) < 4:
            continue
        path = line[3:]
        # a rename line is "R  old -> new"; only the new side exists
        if " -> " in path:
            path = path.split(" -> ", 1)[1]
        rel.add(path.strip().strip('"'))
    base = _git_lines(root, "merge-base", "HEAD", "@{upstream}")
    if not base:
        base = _git_lines(root, "merge-base", "HEAD", "origin/HEAD")
    if base and base[0].strip():
        diffed = _git_lines(root, "diff", "--name-only",
                            base[0].strip(), "HEAD")
        if diffed:
            rel.update(p.strip() for p in diffed if p.strip())
    files = []
    for p in sorted(rel):
        if not p.endswith(".py") or "__pycache__" in p:
            continue
        full = root / p
        if full.is_file():
            files.append(full)
    return files


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="klba-analyze",
        description=(
            "whole-program static analysis for the TPU lag assignor "
            "(rule catalog: DEPLOYMENT.md 'Static analysis')"
        ),
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files to analyze (default: the whole repo)",
    )
    parser.add_argument(
        "--format", choices=sorted(RENDERERS), default="text",
        help="stdout report format (default: text)",
    )
    parser.add_argument(
        "--sarif", type=Path, metavar="FILE",
        help="also write a SARIF 2.1.0 artifact to FILE",
    )
    parser.add_argument(
        "--changed", action="store_true",
        help=(
            "analyze only the files git reports as changed (working "
            "tree + commits past the merge base); degrades to the "
            "mtime-cached full sweep outside a git checkout"
        ),
    )
    parser.add_argument(
        "--no-cache", action="store_true",
        help="ignore and do not write the analysis cache",
    )
    parser.add_argument(
        "--cache-file", type=Path,
        help=f"cache location (default: <repo>/{CACHE_BASENAME})",
    )
    parser.add_argument(
        "--legacy-only", action="store_true",
        help="run only the L001-L021 ruleset (the tools/lint.py gate)",
    )
    parser.add_argument(
        "--stats", action="store_true",
        help="print timing + cache stats to stderr",
    )
    args = parser.parse_args(argv)

    root = _repo_root()
    if args.changed and not args.paths:
        changed = git_changed_files(root)
        if changed is not None:
            if not changed:
                print("klba-analyze: no changed python files",
                      file=sys.stderr)
                return 0
            try:
                args.paths = [
                    p.relative_to(Path.cwd()) for p in changed
                ]
            except ValueError:
                args.paths = changed
    if args.paths:
        files = []
        missing = []
        for p in args.paths:
            if p.is_dir():
                files.extend(
                    sorted(
                        f for f in p.rglob("*.py")
                        if "__pycache__" not in f.parts
                    )
                )
            elif p.exists():
                files.append(p)
            else:
                missing.append(p)
        if missing:
            # a typo'd path must never let the gate pass green
            for p in missing:
                print(f"klba-analyze: no such file: {p}", file=sys.stderr)
            return 2
        if not files:
            print("klba-analyze: no python files to analyze",
                  file=sys.stderr)
            return 2
    else:
        try:
            files = [
                p.relative_to(Path.cwd()) for p in repo_python_files(root)
            ]
        except ValueError:
            files = repo_python_files(root)
        if not files:
            # an installed script run outside a checkout must never
            # report a green gate over zero files
            print(
                f"klba-analyze: no python files found under {root} — "
                "run from a repo checkout or pass explicit paths",
                file=sys.stderr,
            )
            return 2

    codes = list(LEGACY_CODES) if args.legacy_only else None
    cache = None
    if not args.no_cache and (args.changed or not args.paths):
        cache_path = args.cache_file or (root / CACHE_BASENAME)
        cache = AnalysisCache(cache_path, codes=codes)
    started = time.monotonic()
    # explicit paths = a subset run: W001 waiver accounting is skipped
    # (a deep waiver can look stale only because its cross-file facts
    # are outside the analyzed set)
    report = analyze_paths(
        files, codes=codes, cache=cache,
        waiver_accounting=not args.paths,
    )
    elapsed = time.monotonic() - started

    print(RENDERERS[args.format](report.findings, report.stats))
    if args.sarif is not None:
        args.sarif.write_text(
            render_sarif(report.findings, report.stats),
            encoding="utf-8",
        )
    if args.stats:
        hits = cache.hits if cache is not None else 0
        misses = cache.misses if cache is not None else len(files)
        print(
            f"analyzed {len(files)} file(s) in {elapsed:.2f}s "
            f"(cache: {hits} hit(s), {misses} miss(es))",
            file=sys.stderr,
        )
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
