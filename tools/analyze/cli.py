"""The ``klba-analyze`` command line (also ``python -m tools.analyze``).

Default run: every repo python file through the full ruleset
(L001-L021 legacy + A001-A004 deep + W001 waiver accounting), text
report to stdout, exit 1 on any finding.  ``--changed`` keeps the
hot-loop invocation incremental via the mtime-keyed cache (unchanged
files are never re-parsed); ``--sarif PATH`` writes the CI artifact
next to whatever ``--format`` goes to stdout."""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path
from typing import List, Optional

from .cache import CACHE_BASENAME, AnalysisCache
from .core import LEGACY_CODES, analyze_paths, repo_python_files
from .reporters import RENDERERS, render_sarif


def _repo_root() -> Path:
    root = Path(__file__).resolve().parent.parent.parent
    if (root / "kafka_lag_based_assignor_tpu").is_dir():
        return root
    # installed console script (site-packages): analyze the checkout
    # the operator is standing in
    return Path.cwd()


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="klba-analyze",
        description=(
            "whole-program static analysis for the TPU lag assignor "
            "(rule catalog: DEPLOYMENT.md 'Static analysis')"
        ),
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files to analyze (default: the whole repo)",
    )
    parser.add_argument(
        "--format", choices=sorted(RENDERERS), default="text",
        help="stdout report format (default: text)",
    )
    parser.add_argument(
        "--sarif", type=Path, metavar="FILE",
        help="also write a SARIF 2.1.0 artifact to FILE",
    )
    parser.add_argument(
        "--changed", action="store_true",
        help=(
            "incremental mode: reuse the mtime-keyed cache so only "
            "files changed since the last run are re-analyzed"
        ),
    )
    parser.add_argument(
        "--no-cache", action="store_true",
        help="ignore and do not write the analysis cache",
    )
    parser.add_argument(
        "--cache-file", type=Path,
        help=f"cache location (default: <repo>/{CACHE_BASENAME})",
    )
    parser.add_argument(
        "--legacy-only", action="store_true",
        help="run only the L001-L021 ruleset (the tools/lint.py gate)",
    )
    parser.add_argument(
        "--stats", action="store_true",
        help="print timing + cache stats to stderr",
    )
    args = parser.parse_args(argv)

    root = _repo_root()
    if args.paths:
        files = []
        missing = []
        for p in args.paths:
            if p.is_dir():
                files.extend(
                    sorted(
                        f for f in p.rglob("*.py")
                        if "__pycache__" not in f.parts
                    )
                )
            elif p.exists():
                files.append(p)
            else:
                missing.append(p)
        if missing:
            # a typo'd path must never let the gate pass green
            for p in missing:
                print(f"klba-analyze: no such file: {p}", file=sys.stderr)
            return 2
        if not files:
            print("klba-analyze: no python files to analyze",
                  file=sys.stderr)
            return 2
    else:
        try:
            files = [
                p.relative_to(Path.cwd()) for p in repo_python_files(root)
            ]
        except ValueError:
            files = repo_python_files(root)
        if not files:
            # an installed script run outside a checkout must never
            # report a green gate over zero files
            print(
                f"klba-analyze: no python files found under {root} — "
                "run from a repo checkout or pass explicit paths",
                file=sys.stderr,
            )
            return 2

    codes = list(LEGACY_CODES) if args.legacy_only else None
    cache = None
    if not args.no_cache and (args.changed or not args.paths):
        cache_path = args.cache_file or (root / CACHE_BASENAME)
        cache = AnalysisCache(cache_path, codes=codes)
    started = time.monotonic()
    # explicit paths = a subset run: W001 waiver accounting is skipped
    # (a deep waiver can look stale only because its cross-file facts
    # are outside the analyzed set)
    report = analyze_paths(
        files, codes=codes, cache=cache,
        waiver_accounting=not args.paths,
    )
    elapsed = time.monotonic() - started

    print(RENDERERS[args.format](report.findings, report.stats))
    if args.sarif is not None:
        args.sarif.write_text(
            render_sarif(report.findings, report.stats),
            encoding="utf-8",
        )
    if args.stats:
        hits = cache.hits if cache is not None else 0
        misses = cache.misses if cache is not None else len(files)
        print(
            f"analyzed {len(files)} file(s) in {elapsed:.2f}s "
            f"(cache: {hits} hit(s), {misses} miss(es))",
            file=sys.stderr,
        )
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
