"""Mtime-keyed incremental cache for the analyzer.

One JSON file (default ``.analyze_cache.json`` at the repo root,
gitignored) maps each analyzed file to its (mtime_ns, size) stamp plus
the full per-file result: post-suppression findings, fired
suppressions, waiver comments, and whole-program facts.  Unchanged
files skip parsing entirely — the deep rules' finalize still runs
every time over the (cached) facts, so cross-file findings stay
correct.  The cache key includes a digest of the analyzer's own
sources: editing any rule invalidates everything."""

from __future__ import annotations

import hashlib
import json
import sys
from pathlib import Path
from typing import Any, Dict, Optional

from .core import FileResult, Finding

CACHE_BASENAME = ".analyze_cache.json"


def _engine_digest(codes: Optional[Any] = None) -> str:
    """Digest of the analyzer package's own sources plus the selected
    ruleset — the rules ARE the cache schema, so any edit to them (or a
    different --legacy-only selection) must invalidate."""
    h = hashlib.sha1()
    h.update(f"py{sys.version_info[0]}.{sys.version_info[1]}".encode())
    h.update(repr(sorted(codes) if codes is not None else None).encode())
    pkg = Path(__file__).resolve().parent
    for f in sorted(pkg.glob("*.py")):
        h.update(f.name.encode())
        h.update(f.read_bytes())
    return h.hexdigest()


def _encode(res: FileResult) -> Dict[str, Any]:
    return {
        "rel": res.rel,
        "findings": [list(f) for f in res.findings],
        "suppressed": [list(s) for s in res.suppressed],
        "waivers": [[line, list(codes)] for line, codes in res.waivers],
        "facts": res.facts,
        "parse_failed": res.parse_failed,
    }


def _decode(payload: Dict[str, Any]) -> FileResult:
    return FileResult(
        rel=payload["rel"],
        findings=[Finding(*f) for f in payload["findings"]],
        suppressed=[tuple(s) for s in payload["suppressed"]],
        waivers=[
            (line, tuple(codes)) for line, codes in payload["waivers"]
        ],
        facts=payload["facts"],
        parse_failed=payload["parse_failed"],
    )


class AnalysisCache:
    """Load/lookup/store/save; a version or digest mismatch drops the
    whole cache (never a partial mix of rule revisions)."""

    def __init__(self, path: Path, codes: Optional[Any] = None) -> None:
        self.path = path
        self.digest = _engine_digest(codes)
        self.hits = 0
        self.misses = 0
        self._dirty = False
        self._entries: Dict[str, Dict[str, Any]] = {}
        try:
            payload = json.loads(path.read_text(encoding="utf-8"))
            if payload.get("digest") == self.digest:
                self._entries = payload.get("files", {})
        except (OSError, ValueError):
            self._entries = {}

    def _stamp(self, path: Path) -> Optional[Dict[str, int]]:
        try:
            st = path.stat()
        except OSError:
            return None
        return {"mtime_ns": st.st_mtime_ns, "size": st.st_size}

    def lookup(self, path: Path) -> Optional[FileResult]:
        entry = self._entries.get(str(path))
        if entry is None:
            self.misses += 1
            return None
        stamp = self._stamp(path)
        if stamp is None or stamp != entry.get("stamp"):
            self.misses += 1
            return None
        self.hits += 1
        try:
            return _decode(entry["result"])
        except (KeyError, TypeError):
            self.misses += 1
            return None

    def store(self, path: Path, res: FileResult) -> None:
        stamp = self._stamp(path)
        if stamp is None:
            return
        self._entries[str(path)] = {
            "stamp": stamp,
            "result": _encode(res),
        }
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        payload = {"digest": self.digest, "files": self._entries}
        try:
            self.path.write_text(
                json.dumps(payload), encoding="utf-8"
            )
        except OSError:
            pass  # a cold next run is the only cost
        self._dirty = False
