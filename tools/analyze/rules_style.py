"""Legacy style rules L001-L010, ported from the tools/lint.py
monolith onto the engine (behavior-identical; pinned by
tests/test_lint.py and the tests/test_analyze.py parity test).

  L001  syntax error (file does not parse) — engine-raised
  L002  star import (``from x import *``)
  L003  unused import (exempt: ``__init__.py`` re-export surfaces)
  L004  mutable default argument (list/dict/set literal)
  L005  bare ``except:``
  L006  comparison to None with ``==`` / ``!=``
  L007  line longer than 100 characters
  L008  trailing whitespace
  L009  duplicate top-level definition name
  L010  f-string without placeholders
"""

from __future__ import annotations

import ast
from typing import Iterator

from .core import MAX_LINE, FileContext, Finding, Rule, register, rule

# L001 has no per-file checker: the engine raises it when ast.parse
# fails (there is no tree for a checker to walk).
register(
    Rule(
        code="L001",
        summary="syntax error (file does not parse)",
        severity="error",
    )
)


@rule("L002", "star import", severity="warning")
def check_star_import(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) and any(
            a.name == "*" for a in node.names
        ):
            yield Finding(ctx.rel, node.lineno, "L002", "star import")


def _imported_names(node: ast.AST) -> Iterator[tuple]:
    for child in ast.walk(node):
        if isinstance(child, ast.Import):
            for alias in child.names:
                name = alias.asname or alias.name.split(".")[0]
                yield name, child.lineno
        elif isinstance(child, ast.ImportFrom):
            if child.module == "__future__":
                continue
            for alias in child.names:
                if alias.name == "*":
                    continue
                yield (alias.asname or alias.name), child.lineno


def _used_names(tree: ast.AST) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # the root of a dotted access counts as a use of the import
            root = node
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                used.add(root.id)
    # `__all__` strings are re-export uses
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id == "__all__":
                    for elt in ast.walk(node.value):
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str
                        ):
                            used.add(elt.value)
    return used


@rule(
    "L003",
    "unused import",
    severity="warning",
    applies=lambda ctx: not ctx.is_init,
)
def check_unused_import(ctx: FileContext) -> Iterator[Finding]:
    used = _used_names(ctx.tree)
    for name, lineno in _imported_names(ctx.tree):
        if name not in used:
            yield Finding(
                ctx.rel, lineno, "L003", f"unused import {name!r}"
            )


@rule("L004", "mutable default argument", severity="warning")
def check_mutable_default(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        defaults = list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]
        for d in defaults:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                yield Finding(
                    ctx.rel,
                    d.lineno,
                    "L004",
                    f"mutable default argument in {node.name}()",
                )


@rule("L005", "bare except", severity="error")
def check_bare_except(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            yield Finding(ctx.rel, node.lineno, "L005", "bare except")


@rule("L006", "comparison to None with ==/!=", severity="warning")
def check_none_compare(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Compare):
            continue
        for op, comparator in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)) and (
                (
                    isinstance(comparator, ast.Constant)
                    and comparator.value is None
                )
                or (
                    isinstance(node.left, ast.Constant)
                    and node.left.value is None
                )
            ):
                yield Finding(
                    ctx.rel,
                    node.lineno,
                    "L006",
                    "comparison to None with ==/!= (use is/is not)",
                )


@rule("L007", "line too long", severity="warning")
def check_line_length(ctx: FileContext) -> Iterator[Finding]:
    for i, line in enumerate(ctx.source.splitlines(), start=1):
        if len(line) > MAX_LINE:
            yield Finding(
                ctx.rel, i, "L007",
                f"line too long ({len(line)} > {MAX_LINE})",
            )


@rule("L008", "trailing whitespace", severity="warning")
def check_trailing_whitespace(ctx: FileContext) -> Iterator[Finding]:
    for i, line in enumerate(ctx.source.splitlines(), start=1):
        if line != line.rstrip():
            yield Finding(ctx.rel, i, "L008", "trailing whitespace")


@rule("L009", "duplicate top-level definition", severity="error")
def check_duplicate_toplevel(ctx: FileContext) -> Iterator[Finding]:
    seen: dict = {}
    for node in ctx.tree.body:
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            if node.name in seen:
                yield Finding(
                    ctx.rel,
                    node.lineno,
                    "L009",
                    f"duplicate top-level definition {node.name!r} "
                    f"(first at line {seen[node.name]})",
                )
            else:
                seen[node.name] = node.lineno


@rule("L010", "f-string without placeholders", severity="warning")
def check_placeholderless_fstring(ctx: FileContext) -> Iterator[Finding]:
    # A format spec (the ":02d" in f"{j:02d}") parses as a nested
    # JoinedStr of constants — not a placeholder-less f-string.
    format_specs = {
        id(node.format_spec)
        for node in ast.walk(ctx.tree)
        if isinstance(node, ast.FormattedValue)
        and node.format_spec is not None
    }
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.JoinedStr):
            if id(node) not in format_specs and not any(
                isinstance(v, ast.FormattedValue) for v in node.values
            ):
                yield Finding(
                    ctx.rel, node.lineno, "L010",
                    "f-string without placeholders",
                )
