"""Output formats for the analyzer: text (the monolith's line format),
JSON, and SARIF 2.1.0 (the CI artifact format — uploaded by tier1.yml
and consumed by tools/dump_metrics.py --summary)."""

from __future__ import annotations

import json
from typing import Any, Dict, List

from .core import REGISTRY, Finding

SARIF_SCHEMA_URI = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)
SARIF_VERSION = "2.1.0"
TOOL_NAME = "klba-analyze"
TOOL_VERSION = "1.0.0"


def render_text(findings: List[Finding], stats: Dict[str, Any]) -> str:
    lines = [str(f) for f in findings]
    lines.append(
        f"{stats['findings']} finding(s), {stats['suppressed']} "
        f"suppressed, {stats['unused_waivers']} unused waiver(s), "
        f"{stats['files']} file(s)"
    )
    return "\n".join(lines)


def render_json(findings: List[Finding], stats: Dict[str, Any]) -> str:
    return json.dumps(
        {
            "findings": [
                {
                    "path": f.path,
                    "line": f.line,
                    "code": f.code,
                    "message": f.message,
                    "severity": f.severity,
                }
                for f in findings
            ],
            "stats": stats,
        },
        indent=2,
        sort_keys=True,
    )


def _sarif_level(severity: str) -> str:
    return severity if severity in ("error", "warning", "note") else "none"


def build_sarif(
    findings: List[Finding], stats: Dict[str, Any]
) -> Dict[str, Any]:
    """A minimal-but-valid SARIF 2.1.0 document: tool.driver rule
    metadata from the registry, one result per finding, and the run
    stats stashed in ``runs[0].properties`` (dump_metrics reads
    them)."""
    rules = []
    for code in sorted(REGISTRY):
        r = REGISTRY[code]
        rules.append(
            {
                "id": r.code,
                "shortDescription": {"text": r.summary},
                "defaultConfiguration": {
                    "level": _sarif_level(r.severity)
                },
                "properties": {"waivable": r.waivable},
            }
        )
    rules.append(
        {
            "id": "W001",
            "shortDescription": {"text": "unused `# noqa` waiver"},
            "defaultConfiguration": {"level": "warning"},
            "properties": {"waivable": False},
        }
    )
    results = []
    for f in findings:
        uri = f.path.replace("\\", "/").lstrip("/")
        results.append(
            {
                "ruleId": f.code,
                "level": _sarif_level(f.severity),
                "message": {"text": f.message},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {"uri": uri},
                            "region": {"startLine": max(f.line, 1)},
                        }
                    }
                ],
            }
        )
    return {
        "$schema": SARIF_SCHEMA_URI,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": TOOL_NAME,
                        "version": TOOL_VERSION,
                        "informationUri": (
                            "https://github.com/grantneale/"
                            "kafka-lag-based-assignor"
                        ),
                        "rules": rules,
                    }
                },
                "results": results,
                "properties": dict(stats),
            }
        ],
    }


def render_sarif(findings: List[Finding], stats: Dict[str, Any]) -> str:
    return json.dumps(build_sarif(findings, stats), indent=2)


RENDERERS = {
    "text": render_text,
    "json": render_json,
    "sarif": render_sarif,
}
