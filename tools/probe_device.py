"""One-off probe of the tunneled TPU transport: RTT floor, transfer cost,
compute cost, readback scaling.  Not part of the package; diagnostic only."""

import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

print("devices:", jax.devices())


def med(f, iters=10):
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        f()
        ts.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(ts)), float(np.min(ts))


# 1. RTT floor: fetch freshly computed 4-byte scalar.
x = jax.device_put(np.arange(1024, dtype=np.int32))
f = jax.jit(lambda x: (x * 2 + 1).sum())
float(f(x))
m, mn = med(lambda: float(f(x)))
print(f"scalar compute+readback: median {m:.2f} min {mn:.2f} ms")

# 1b. readback of an ALREADY-computed scalar (no dispatch).
r = f(x)
r.block_until_ready()
m, mn = med(lambda: float(r))
print(f"resident scalar readback: median {m:.2f} min {mn:.2f} ms")

# 2. block_until_ready without readback (dispatch + sync only).
m, mn = med(lambda: f(x).block_until_ready())
print(f"dispatch+sync no readback: median {m:.2f} min {mn:.2f} ms")

# 3. host->device transfer of 100k int64 (the north-star lag vector).
lags = np.random.randint(0, 1 << 40, size=100_000).astype(np.int64)
m, mn = med(lambda: jax.device_put(lags).block_until_ready())
print(f"h2d 800KB int64: median {m:.2f} min {mn:.2f} ms")

# 4. d2h of int16[100k] (choice vector readback).
g = jax.jit(lambda v: (v % 7).astype(np.int16))
y = g(jax.device_put(lags))
y.block_until_ready()
m, mn = med(lambda: np.asarray(y))
print(f"d2h 200KB int16 resident: median {m:.2f} min {mn:.2f} ms")

# 5. empty dispatch round-trip: tiny jit, readback scalar, repeatedly.
h = jax.jit(lambda s: s + 1)
s = jax.device_put(np.int32(0))
s = h(s)
float(s)
m, mn = med(lambda: float(h(s)))
print(f"tiny dispatch+scalar readback: median {m:.2f} min {mn:.2f} ms")

# 6. two back-to-back readbacks (does RTT pipeline?)
r1, r2 = f(x), g(jax.device_put(lags))
r1.block_until_ready(); r2.block_until_ready()
def two():
    a = f(x)
    b = h(s)
    float(a); float(b)
m, mn = med(two)
print(f"two dispatch+2 readbacks: median {m:.2f} min {mn:.2f} ms")
