"""Probe: refine round cost anatomy on device."""

import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import sys

sys.path.insert(0, "/root/repo")
from kafka_lag_based_assignor_tpu.ops.refine import refine_assignment

print("devices:", jax.devices())


def med(f, iters=6):
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        f()
        ts.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(ts)), float(np.min(ts))


rng = np.random.default_rng(0)
P, C = 131072, 1000
lags = rng.integers(0, 1 << 30, size=P).astype(np.int64)
valid = np.ones(P, bool)
choice = rng.integers(0, C, size=P).astype(np.int32)
dl = jax.device_put(lags)
dv = jax.device_put(valid)
dc = jax.device_put(choice)
jax.block_until_ready((dl, dv, dc))

for it in (1, 2, 4, 8):
    f = lambda it=it: jax.block_until_ready(
        refine_assignment(dl, dv, dc, num_consumers=C, iters=it,
                          patience=10_000)
    )
    f()
    m, mn = med(f)
    print(f"refine iters={it}: median {m:.2f} min {mn:.2f} ms")


# micro: argsort int32[P], searchsorted scan vs sort, segment scatter-min
keys = rng.integers(0, 1 << 31, size=P).astype(np.int32)
dkeys = jax.device_put(keys)
q = jax.device_put(rng.integers(0, 1 << 31, size=P).astype(np.int32))
jax.block_until_ready((dkeys, q))

f = jax.jit(lambda k: jnp.argsort(k))
jax.block_until_ready(f(dkeys))
m, mn = med(lambda: jax.block_until_ready(f(dkeys)))
print(f"argsort int32[{P}]: median {m:.2f} min {mn:.2f} ms")

sk = jax.block_until_ready(jax.jit(jnp.sort)(dkeys))
for method in ("scan", "sort", "compare_all"):
    try:
        g = jax.jit(
            lambda a, v, method=method: jnp.searchsorted(a, v, method=method)
        )
        jax.block_until_ready(g(sk, q))
        m, mn = med(lambda: jax.block_until_ready(g(sk, q)))
        print(f"searchsorted[{method}]: median {m:.2f} min {mn:.2f} ms")
    except Exception as e:
        print(f"searchsorted[{method}]: failed {type(e).__name__}")

seg = jax.device_put(rng.integers(0, 501, size=P).astype(np.int32))
score = jax.device_put(rng.integers(0, 1 << 60, size=P).astype(np.int64))
jax.block_until_ready((seg, score))


@jax.jit
def segmin(score, seg):
    minv = jnp.full((501 + 1,), jnp.iinfo(score.dtype).max,
                    score.dtype).at[seg].min(score)
    hit = (score == minv[seg]) & (seg < 501)
    idx_cand = jnp.where(hit, jnp.arange(P, dtype=jnp.int32), P)
    idx = jnp.full((501 + 1,), P, jnp.int32).at[seg].min(idx_cand)
    return minv, idx


jax.block_until_ready(segmin(score, seg))
m, mn = med(lambda: jax.block_until_ready(segmin(score, seg)))
print(f"segment argmin x1: median {m:.2f} min {mn:.2f} ms")


# scatter set at[P-sized idx].set
idx = jax.device_put(rng.permutation(P).astype(np.int32))
vals = jax.device_put(rng.integers(0, C, size=P).astype(np.int32))
jax.block_until_ready((idx, vals))
h = jax.jit(lambda c, i, v: c.at[i].set(v, mode="drop"))
jax.block_until_ready(h(dc, idx, vals))
m, mn = med(lambda: jax.block_until_ready(h(dc, idx, vals)))
print(f"scatter set [P]: median {m:.2f} min {mn:.2f} ms")

# gather [P]
g2 = jax.jit(lambda a, i: a[i])
jax.block_until_ready(g2(dl, idx))
m, mn = med(lambda: jax.block_until_ready(g2(dl, idx)))
print(f"gather int64[P]: median {m:.2f} min {mn:.2f} ms")
