"""Component breakdown of the full solve and one refine round, with
fetch-synchronized amortized timing (see probe_round5c.py header)."""

import sys
import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

sys.path.insert(0, "/root/repo")

import functools  # noqa: E402

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from kafka_lag_based_assignor_tpu.ops.packing import pad_bucket  # noqa: E402
from kafka_lag_based_assignor_tpu.ops.rounds_kernel import (  # noqa: E402
    _rounds_scan,
)
from kafka_lag_based_assignor_tpu.ops.scan_kernel import (  # noqa: E402
    pack_shift_for,
    sort_partitions_with,
)
from kafka_lag_based_assignor_tpu.ops.sortops import unsort  # noqa: E402

print("devices:", jax.devices(), flush=True)

P, C = 100_000, 1000
B = pad_bucket(P)
rng = np.random.default_rng(0)
ranks = rng.permutation(P) + 1
lags1 = (1000.0 * (P / ranks) ** (1 / 1.1)).astype(np.int64)
shift = pack_shift_for(int(lags1.max()), B - 1)
N_HI = 8
batch = jax.device_put(
    np.stack([np.roll(lags1, 17 * i).astype(np.int32) for i in range(N_HI)])
)


def fetch_med(f, iters=8):
    f()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        f()
        ts.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(ts))


def measure(name, body):
    @functools.partial(jax.jit, static_argnames=("n",))
    def many(b, n):
        return lax.map(body, b[:n]).sum()

    t1 = fetch_med(lambda: int(many(batch, n=1)))
    t8 = fetch_med(lambda: int(many(batch, n=N_HI)))
    print(f"{name:18s} {(t8 - t1) / (N_HI - 1):7.3f} ms", flush=True)


def prep(lags32):
    lags_p = jnp.pad(lags32.astype(jnp.int64), (0, B - P))
    pids = jnp.arange(B, dtype=jnp.int32)
    return lags_p, pids, pids < P


def body_sort(lags32):
    lags_p, pids, valid = prep(lags32)
    perm, sl, sv = sort_partitions_with(lags_p, pids, valid, shift)
    return perm.sum() + sl.sum().astype(jnp.int32)


def body_sort_scan(lags32):
    lags_p, pids, valid = prep(lags32)
    perm, sl, sv = sort_partitions_with(lags_p, pids, valid, shift)
    totals, sc = _rounds_scan(sl, sv, jnp.zeros((C,), jnp.int64), C)
    return totals.sum().astype(jnp.int32) + sc.sum()


def body_full(lags32):
    lags_p, pids, valid = prep(lags32)
    perm, sl, sv = sort_partitions_with(lags_p, pids, valid, shift)
    totals, sc = _rounds_scan(sl, sv, jnp.zeros((C,), jnp.int64), C)
    choice = unsort(perm, sc)
    return choice.sum() + totals.sum().astype(jnp.int32)


def body_raw_sort64(lags32):
    lags_p, _, _ = prep(lags32)
    return jnp.sort(lags_p).sum().astype(jnp.int32)


def body_raw_sort32(lags32):
    s = jnp.sort(jnp.pad(lags32, (0, B - P)))
    return s.sum()


measure("raw_sort_int64", body_raw_sort64)
measure("raw_sort_int32", body_raw_sort32)
measure("pack_sort", body_sort)
measure("pack_sort+scan", body_sort_scan)
measure("full(+unsort)", body_full)
