"""Probe: per-op device cost via chained fori_loop, e2e numpy in/out."""

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

print("devices:", jax.devices())

P, C, K = 131072, 1000, 501


def e2e(f, *args, iters=5):
    f(*args)
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        np.asarray(f(*args))
        ts.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(ts))


def slope(make, *args):
    f1 = make(1)
    f32 = make(33)
    a = e2e(f1, *args)
    b = e2e(f32, *args)
    return (b - a) / 32.0, a


rng = np.random.default_rng(0)
keys32 = rng.integers(0, 1 << 31, size=P).astype(np.int32)
vals64 = rng.integers(0, 1 << 60, size=P).astype(np.int64)
seg = rng.integers(0, K + 1, size=P).astype(np.int32)
idx = rng.permutation(P).astype(np.int32)


def mk_argsort(n):
    @jax.jit
    def f(k):
        def body(i, acc):
            p = jnp.argsort(k + acc[0])
            return p.astype(jnp.int32)

        return lax.fori_loop(0, n, body, k * 0)[:1]

    return f


s, base = slope(mk_argsort, keys32)
print(f"argsort int32[{P}]: {s:.2f} ms/op (base {base:.1f})")


def mk_sort64(n):
    @jax.jit
    def f(v):
        def body(i, acc):
            return jnp.sort(v + acc[0]).astype(v.dtype)

        return lax.fori_loop(0, n, body, v * 0)[:1]

    return f


s, base = slope(mk_sort64, vals64)
print(f"sort int64[{P}]: {s:.2f} ms/op (base {base:.1f})")


def mk_scatter_min(n):
    @jax.jit
    def f(v, seg):
        def body(i, acc):
            m = jnp.full((K + 1,), jnp.iinfo(v.dtype).max, v.dtype).at[
                seg
            ].min(v + acc[0])
            return m

        return lax.fori_loop(0, n, body, jnp.zeros(K + 1, vals64.dtype))[:1]

    return f


s, base = slope(mk_scatter_min, vals64, seg)
print(f"scatter-min int64[{P}]->[{K+1}]: {s:.2f} ms/op (base {base:.1f})")


def mk_scatter_set(n):
    @jax.jit
    def f(v, i32):
        def body(i, acc):
            return acc.at[i32].set(v + acc[0], mode="drop")

        return lax.fori_loop(0, n, body, v * 0)[:1]

    return f


s, base = slope(mk_scatter_set, vals64, idx)
print(f"scatter-set int64[{P}]: {s:.2f} ms/op (base {base:.1f})")


def mk_gather(n):
    @jax.jit
    def f(v, i32):
        def body(i, acc):
            return (v + acc[0])[i32]

        return lax.fori_loop(0, n, body, v * 0)[:1]

    return f


s, base = slope(mk_gather, vals64, idx)
print(f"gather int64[{P}]: {s:.2f} ms/op (base {base:.1f})")


def mk_searchsorted(method):
    def mk(n):
        @jax.jit
        def f(k, q):
            sk = jnp.sort(k)

            def body(i, acc):
                return jnp.searchsorted(
                    sk, q + acc[0], method=method
                ).astype(jnp.int32)

            return lax.fori_loop(0, n, body, q * 0)[:1]

        return f

    return mk


for method in ("scan", "sort"):
    s, base = slope(mk_searchsorted(method), keys32, keys32)
    print(f"searchsorted[{method}] [{P}]: {s:.2f} ms/op (base {base:.1f})")


def mk_segmin_sortbased(n):
    # segment argmin via ONE extra sort instead of scatter-min
    @jax.jit
    def f(v, seg):
        def body(i, acc):
            key = (seg.astype(jnp.int64) << 50) | ((v + acc[0]) >> 14)
            sk = jnp.sort(key)
            bound = jnp.searchsorted(
                sk, jnp.arange(K + 1, dtype=jnp.int64) << 50, method="scan"
            )
            return bound.astype(jnp.int64)

        return lax.fori_loop(0, n, body, jnp.zeros(K + 1, jnp.int64))[:1]

    return f


s, base = slope(mk_segmin_sortbased, vals64, seg)
print(f"segmin via sort+searchsorted: {s:.2f} ms/op (base {base:.1f})")
