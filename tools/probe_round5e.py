"""Primitive device costs with fetch-synchronized amortized timing:
gathers, scatters, cummax, searchsorted, segment argmin — the refine
round's building blocks (probe_round5d showed P-sorts are ~0.4 ms and the
rounds scan ~90 us/round; this locates the refine round's 10 ms)."""

import sys
import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

sys.path.insert(0, "/root/repo")

import functools  # noqa: E402

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from kafka_lag_based_assignor_tpu.ops.sortops import (  # noqa: E402
    segment_argmin_first,
)

print("devices:", jax.devices(), flush=True)

B = 131072
K = 500
N_HI = 8
rng = np.random.default_rng(0)
batch = jax.device_put(
    np.stack(
        [rng.permutation(B).astype(np.int32) for _ in range(N_HI)]
    )
)
vals64 = jax.device_put(rng.integers(0, 1 << 40, B).astype(np.int64))
vals32 = jax.device_put(rng.integers(0, 1 << 30, B).astype(np.int32))
sorted64 = jax.device_put(np.sort(rng.integers(0, 1 << 40, B)).astype(np.int64))
seg = jax.device_put(rng.integers(0, K + 1, B).astype(np.int32))


def fetch_med(f, iters=8):
    f()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        f()
        ts.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(ts))


def measure(name, body):
    @functools.partial(jax.jit, static_argnames=("n",))
    def many(b, n):
        return lax.map(body, b[:n]).sum()

    t1 = fetch_med(lambda: int(many(batch, n=1)))
    t8 = fetch_med(lambda: int(many(batch, n=N_HI)))
    print(f"{name:22s} {(t8 - t1) / (N_HI - 1):7.3f} ms", flush=True)


measure("gather64[P]", lambda idx: vals64[idx].sum().astype(jnp.int32))
measure("gather32[P]", lambda idx: vals32[idx].sum())
measure(
    "gather64[P]x3",
    lambda idx: (
        vals64[idx].sum() + vals64[(idx + 1) % B].sum()
        + vals64[(idx * 3) % B].sum()
    ).astype(jnp.int32),
)
measure(
    "scatter_set[P]",
    lambda idx: jnp.zeros((B,), jnp.int32).at[idx].set(idx).sum(),
)
measure(
    "cummax[P]",
    lambda idx: lax.cummax(idx).sum() + lax.cummax(idx, reverse=True).sum(),
)
measure(
    "searchsorted_sort",
    lambda idx: jnp.searchsorted(
        sorted64, vals64[idx], method="sort"
    ).sum(),
)
measure(
    "seg_argmin[P]",
    lambda idx: sum(
        segment_argmin_first(vals64 + idx[0], seg, K, B)[1].sum()
        for _ in range(1)
    ),
)
measure(
    "small_gather[K->P]",
    lambda idx: jnp.arange(K + 1, dtype=jnp.int32)[
        jnp.clip(idx, 0, K)
    ].sum(),
)
