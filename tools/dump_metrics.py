"""Operator CLI for a live sidecar's metrics: ``{"method": "metrics"}``.

Usage::

    python tools/dump_metrics.py [host] [port]            # JSON snapshot
    python tools/dump_metrics.py [host] [port] --prom     # Prometheus text
    python tools/dump_metrics.py [host] [port] --flight   # last flight dump
    python tools/dump_metrics.py [host] [port] --summary  # p50/p99 table

Defaults match the service's (127.0.0.1:7531).  ``--prom`` output is the
standard text exposition — pipe it wherever a scrape would go.  The
``--summary`` view prints one line per histogram series (count, p50,
p99) and every counter — the quick "what is this sidecar doing" look.
See DEPLOYMENT.md "Observability" for the metric catalog.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def analyzer_summary_line(sarif_path) -> str:
    """One ``analyze:`` row from a klba-analyze SARIF artifact, or ""
    when the file is absent/unreadable (the summary must never fail
    because no analyzer run happened on this host)."""
    try:
        doc = json.loads(Path(sarif_path).read_text(encoding="utf-8"))
        run = doc["runs"][0]
        results = run.get("results", [])
        props = run.get("properties", {})
        by_level: dict = {}
        for r in results:
            lvl = r.get("level", "none")
            by_level[lvl] = by_level.get(lvl, 0) + 1
    except (OSError, ValueError, KeyError, IndexError, TypeError,
            AttributeError):
        return ""
    levels = ", ".join(
        f"{k}={v}" for k, v in sorted(by_level.items())
    ) or "clean"
    return (
        f"analyze: {len(results)} finding(s) ({levels}), "
        f"{props.get('suppressed', 0)} suppressed, "
        f"{props.get('unused_waivers', 0)} unused waiver(s) "
        f"over {props.get('files', '?')} file(s)"
    )


def kernel_summary_line(report_path) -> str:
    """One ``kernel:`` row from a kernel-plane report artifact
    (ops/linear_ot_pallas.write_kernel_report — the bench writes one,
    CI uploads it), or "" when the file is absent/unreadable (the
    summary must never fail because no probe ran on this host)."""
    try:
        doc = json.loads(Path(report_path).read_text(encoding="utf-8"))
        duals = bool(doc["duals_kernel"])
        digest = bool(doc["digest_kernel"])
        parity = doc.get("interpret_parity") or {}
        race = doc.get("race_ms") or {}
    except (OSError, ValueError, KeyError, TypeError):
        return ""
    parity_txt = ",".join(
        f"{k}={'ok' if v else 'FAIL'}" for k, v in sorted(parity.items())
    ) or "unchecked"
    race_txt = (
        f", race xla={race.get('xla_ms')}ms "
        f"pallas={race.get('pallas_ms')}ms"
        if race else ""
    )
    probed = "probed" if doc.get("probed") else "unprobed"
    return (
        f"kernel: duals={'on' if duals else 'off'} "
        f"digest={'on' if digest else 'off'} ({probed}, backend "
        f"{doc.get('backend')}), interpret parity "
        f"{parity_txt}{race_txt}"
    )


def scenario_summary_line(report_path) -> str:
    """One ``scenarios:`` row from a scenario-fleet artifact
    (``python -m scenarios --json`` / bench config16 — CI uploads
    scenario_fleet.json), or "" when the file is absent/unreadable
    (the summary must never fail because no fleet ran on this host)."""
    try:
        doc = json.loads(Path(report_path).read_text(encoding="utf-8"))
        rows = doc["scenarios"]
        failed = [r["scenario"] for r in rows if r.get("violations")]
        quarantines = sum(r.get("quarantines", 0) for r in rows)
        planted = sum(r.get("corruptions_planted", 0) for r in rows)
        sheds = sum(r.get("sheds", 0) for r in rows)
    except (OSError, ValueError, KeyError, TypeError):
        return ""
    verdict = (
        "all inside envelopes" if not failed
        else f"FAILED: {', '.join(failed)}"
    )
    return (
        f"scenarios: {len(rows)} run(s), "
        f"{doc.get('violations', 0)} violation(s) ({verdict}), "
        f"sheds={sheds}, corruption detect {quarantines}/{planted}"
    )


def main() -> int:
    parser = argparse.ArgumentParser(
        prog="dump_metrics",
        description="Dump a running assignor sidecar's metrics registry",
    )
    parser.add_argument("host", nargs="?", default="127.0.0.1")
    parser.add_argument("port", nargs="?", type=int, default=7531)
    mode = parser.add_mutually_exclusive_group()
    mode.add_argument(
        "--prom", action="store_true",
        help="print the Prometheus text exposition",
    )
    mode.add_argument(
        "--flight", action="store_true",
        help="print the last flight-recorder dump (if any)",
    )
    mode.add_argument(
        "--summary", action="store_true",
        help="print a one-line-per-series p50/p99 summary",
    )
    parser.add_argument(
        "--timeout", type=float, default=10.0,
        help="socket timeout in seconds (default 10)",
    )
    parser.add_argument(
        "--analyze-sarif", type=Path, default=None,
        help=(
            "SARIF artifact for the --summary static-analysis row "
            "(default: $KLBA_ANALYZE_SARIF or <repo>/analyze.sarif)"
        ),
    )
    parser.add_argument(
        "--kernel-report", type=Path, default=None,
        help=(
            "kernel-plane report for the --summary kernel row "
            "(default: $KLBA_KERNEL_REPORT or <repo>/kernel_report.json)"
        ),
    )
    parser.add_argument(
        "--scenario-report", type=Path, default=None,
        help=(
            "scenario-fleet artifact for the --summary scenarios row "
            "(default: $KLBA_SCENARIO_REPORT or "
            "<repo>/scenario_fleet.json)"
        ),
    )
    args = parser.parse_args()

    from kafka_lag_based_assignor_tpu.service import AssignorServiceClient

    # Fetch only the view being printed — a scrape loop should not pull
    # the JSON snapshot AND the exposition AND the last dump per poll.
    view = (
        "prometheus" if args.prom
        else "flight" if args.flight
        else "json"
    )
    try:
        with AssignorServiceClient(
            args.host, args.port, timeout_s=args.timeout
        ) as client:
            result = client.request("metrics", {"view": view})
            # The summary's lifecycle + scrub rows (state, snapshot
            # age, last recovery, scrub coverage) come from the stats
            # surface, not the registry.
            stats = client.request("stats") if args.summary else {}
            trace_view = None
            if args.summary:
                try:
                    trace_view = client.request("trace", {"limit": 1})
                except Exception:  # noqa: BLE001 — older sidecar
                    trace_view = None
            lifecycle = stats.get("lifecycle")
            scrub = stats.get("scrub")
            federation = stats.get("federation")
            mesh = stats.get("mesh")
            quality = stats.get("quality")
    except OSError as exc:
        print(
            f"cannot reach sidecar at {args.host}:{args.port}: {exc}",
            file=sys.stderr,
        )
        return 1

    if args.prom:
        sys.stdout.write(result["prometheus"])
        return 0
    if args.flight:
        flight = result["flight"]
        if flight["dumps"] == 0:
            print("no flight-recorder dumps (no incident triggers yet)")
            print(f"ring holds {flight['records']} records")
            return 0
        print(
            f"dumps: {flight['dumps']} "
            f"(last reason: {flight['last_dump_reason']}); "
            f"ring holds {flight['records']} records",
            file=sys.stderr,
        )
        print(json.dumps(flight["last_dump"], indent=2, sort_keys=True))
        return 0
    if args.summary:
        js = result["json"]
        for name, entry in sorted(js.items()):
            for s in entry["series"]:
                labels = ",".join(
                    f"{k}={v}" for k, v in sorted(s["labels"].items())
                )
                sig = f"{name}{{{labels}}}" if labels else name
                if entry["type"] == "histogram":
                    print(
                        f"{sig} count={s['count']} p50={s['p50']} "
                        f"p99={s['p99']}"
                    )
                else:
                    print(f"{sig} {s['value']}")

        # Derived coalescer view: roster hit-rate (locked fast-path
        # flushes over all multi-row flushes) and the per-stage
        # pipeline latencies — the "is the fast path engaging, and
        # where does a flush spend its time" look.
        def counter_total(name: str) -> float:
            return sum(
                s["value"] for s in js.get(name, {}).get("series", [])
            )

        hits = counter_total("klba_coalesce_roster_hits_total")
        restacks = counter_total("klba_coalesce_restack_total")
        if hits + restacks:
            rate = hits / (hits + restacks)
            print(
                f"coalesce roster hit-rate {rate:.3f} "
                f"({int(hits)} locked / {int(restacks)} re-stack)"
            )

        # Delta-epoch view (DEPLOYMENT.md "Delta epochs"): cumulative
        # H2D lag-payload bytes by path and the delta hit-rate — the
        # "is the sparse-upload fast path engaging, and what is it
        # saving" look, next to the roster line above.
        def by_label(name: str, label: str):
            return {
                s["labels"].get(label, ""): s["value"]
                for s in js.get(name, {}).get("series", [])
            }

        h2d = by_label("klba_h2d_bytes_total", "path")
        if h2d:
            dense_b = int(h2d.get("dense", 0))
            delta_b = int(h2d.get("delta", 0))
            print(f"h2d bytes: dense {dense_b} / delta {delta_b}")
        # D2H mirror (DEPLOYMENT.md "Delta responses"): readback bytes
        # by path plus the O(changed)-readback hit-rate — the response
        # direction of the same sparse-path question.
        d2h = by_label("klba_d2h_bytes_total", "path")
        if d2h:
            dense_b = int(d2h.get("dense", 0))
            delta_b = int(d2h.get("delta", 0))
            print(f"d2h bytes: dense {dense_b} / delta {delta_b}")
        rb = by_label("klba_rb_delta_epochs_total", "outcome")
        rb_total = sum(rb.values())
        if rb_total:
            applied = rb.get("applied", 0)
            print(
                f"readback delta hit-rate {applied / rb_total:.3f} "
                f"({int(applied)} applied / "
                f"{int(rb.get('fallback', 0))} fallback / "
                f"{int(rb.get('overflow', 0))} overflow)"
            )
        outcomes = by_label("klba_delta_epochs_total", "outcome")
        total = sum(outcomes.values())
        if total:
            applied = outcomes.get("applied", 0)
            print(
                f"delta epoch hit-rate {applied / total:.3f} "
                f"({int(applied)} applied / "
                f"{int(outcomes.get('fallback', 0))} fallback / "
                f"{int(outcomes.get('resync', 0))} resync)"
            )
        # Adaptive-delta view (ROADMAP delta follow-on (b)): the
        # last effective delta/dense cutoff any stream applied — a
        # value pinned to the configured max.fraction means the
        # adaptive window has not diverged from the global knob.
        eff = js.get("klba_delta_effective_fraction", {}).get(
            "series", []
        )
        if eff:
            print(
                f"delta effective max.fraction {eff[0]['value']:.4f} "
                "(adaptive per-stream cutoff, last writer)"
            )

        # Multi-device mesh view (DEPLOYMENT.md "Multi-device
        # sharding"): topology, health, and sharded-dispatch volume —
        # the "is the sharded backend actually serving" look.
        if mesh:
            state = (
                "ACTIVE" if mesh.get("active")
                else f"degraded ({mesh.get('degraded')})"
                if mesh.get("degraded") else "inactive"
            )
            print(
                f"mesh: {state}, {mesh.get('devices', 0)} device(s) "
                f"(spec {mesh.get('spec')}, solve floor "
                f"{mesh.get('solve_min_rows')} rows)"
            )
            # Cross-axis composition view (DEPLOYMENT.md "Cross-axis
            # mesh"): the active (streams, p) factorization and the
            # degrade-ladder rung, plus any observed ladder
            # transitions — the "is the fleet still on the 2-D
            # placement, and if not, how did it come down" look.
            shape = mesh.get("shape")
            rung = mesh.get("rung")
            if shape is not None or rung not in (None, "single"):
                print(
                    f"mesh composition: shape "
                    f"{shape if shape is not None else '1-D'}, "
                    f"rung {rung}"
                )
            degrades = {
                (
                    s["labels"].get("from", "?"),
                    s["labels"].get("to", "?"),
                ): s["value"]
                for s in js.get(
                    "klba_mesh_degrade_total", {}
                ).get("series", [])
            }
            if degrades:
                rows = ", ".join(
                    f"{frm}->{to}={int(v)}"
                    for (frm, to), v in sorted(degrades.items())
                )
                print(f"mesh ladder transitions: {rows}")
            sharded = by_label("klba_sharded_dispatch_total", "path")
            if sharded:
                rows = ", ".join(
                    f"{k}={int(v)}" for k, v in sorted(sharded.items())
                )
                print(f"sharded dispatches: {rows}")

        # Quality-mode view (DEPLOYMENT.md "Quality modes"): the
        # routing knobs, per-mode solve counts, and the last linear
        # solve's tile geometry + peak-memory estimate — the "which
        # quality path is serving, and in how much memory" look, next
        # to the mesh rows above.
        if quality:
            print(
                f"quality mode: {quality.get('mode')} "
                f"(tile {quality.get('tile')} rows, auto floor "
                f"{quality.get('auto_min_rows')} rows)"
            )
            last = quality.get("last_linear_solve")
            if last:
                peak = last.get("peak_bytes_estimate", 0)
                print(
                    f"last linear solve: {last.get('rows')} rows x "
                    f"{last.get('consumers')} consumers on "
                    f"{last.get('backend')}, {last.get('tiles')} "
                    f"tiles, {last.get('duals_rounds')} duals rounds, "
                    f"peak-mem est {peak / (1024.0 * 1024.0):.1f} MiB"
                )
        solves = by_label("klba_quality_solve_total", "mode")
        if solves:
            rows = ", ".join(
                f"{k}={int(v)}" for k, v in sorted(solves.items())
            )
            print(f"quality solves: {rows}")
        for s in js.get("klba_span_duration_ms", {}).get("series", []):
            span = s["labels"].get("span", "")
            if span.startswith("coalesce.") and span != "coalesce.window":
                stage = span.split(".", 1)[1]
                print(
                    f"coalesce stage {stage}: count={s['count']} "
                    f"p50={s['p50']} p99={s['p99']}"
                )

        # Overload view: the shed ladder's position and who is being
        # shed — the "is this sidecar protecting its critical tenants"
        # look (DEPLOYMENT.md "Overload and SLOs").
        rung_series = js.get("klba_overload_rung", {}).get("series", [])
        if rung_series:
            from kafka_lag_based_assignor_tpu.utils.overload import RUNGS

            idx = int(rung_series[0]["value"])
            name = RUNGS[idx] if 0 <= idx < len(RUNGS) else str(idx)
            pressure = ""
            for s in js.get("klba_overload_pressure", {}).get("series", []):
                pressure = f" pressure={s['value']:.2f}"
            print(f"overload state: rung {idx} ({name}){pressure}")
        shed_rows = js.get("klba_shed_total", {}).get("series", [])
        if shed_rows:
            total = 0
            for s in shed_rows:
                total += s["value"]
                print(
                    f"shed class={s['labels'].get('class')} "
                    f"rung={s['labels'].get('rung')}: {s['value']}"
                )
            print(f"shed total: {int(total)}")

        # Tracing view (DEPLOYMENT.md "Distributed tracing"): the tail
        # sampler's retention split and the last anomalous trace id —
        # the "is anything degrading, and which trace explains it"
        # look.  Sourced from the {"method": "trace"} wire view.
        if trace_view:
            ts = trace_view.get("stats") or {}
            last = ts.get("last_anomalous_trace_id")
            print(
                f"trace: kept_anomalous={ts.get('kept_anomalous', 0)} "
                f"kept_sampled={ts.get('kept_sampled', 0)} "
                f"dropped={ts.get('dropped', 0)} "
                f"(rate {ts.get('sample_rate')}), "
                f"last anomalous {last or '<none>'}"
            )

        # Lifecycle view: serving/draining state, snapshot freshness,
        # and the last recovery's outcome — the "would a restart be a
        # non-event right now" look (DEPLOYMENT.md "Restarts and
        # recovery").
        if lifecycle:
            print(f"lifecycle state: {lifecycle.get('state')}")
            snap = lifecycle.get("snapshot")
            if snap:
                age = snap.get("age_s")
                age_txt = (
                    f"{age:.1f}s old" if age is not None
                    else "never written"
                )
                print(
                    f"snapshot: {age_txt} ({snap.get('writes', 0)} "
                    f"writes, {snap.get('write_errors', 0)} errors, "
                    f"path {snap.get('path')})"
                )
            else:
                print("snapshot: disabled (no snapshot path configured)")
            rec = lifecycle.get("recovery")
            if rec:
                print(
                    f"last recovery: outcome={rec.get('outcome')} "
                    f"streams_recovered={rec.get('streams_recovered')} "
                    f"discarded={rec.get('streams_discarded')} "
                    f"in {rec.get('duration_ms', 0):.1f} ms"
                )
            # Cross-host hand-off view: who holds the fenced writer
            # lease (and for how long), the last hand-off's outcome,
            # and whether any fenced/paced events fired — the "is this
            # instance the legitimate owner of the warm state" look
            # (DEPLOYMENT.md "Cross-host hand-off").
            lease = lifecycle.get("lease")
            if lease and lease.get("enabled"):
                holder = lease.get("holder")
                if holder is None:
                    print("lease: no current holder")
                else:
                    age = lease.get("holder_age_s")
                    age_txt = (
                        f"{age:.1f}s" if age is not None else "?"
                    )
                    print(
                        f"lease: holder={holder} "
                        f"token={lease.get('holder_token')} "
                        f"age={age_txt} held_by_me="
                        f"{lease.get('held')}"
                    )
            handoff = lifecycle.get("handoff")
            if handoff:
                print(
                    f"last hand-off: mode={handoff.get('mode')} "
                    f"acquired={handoff.get('acquired')} "
                    f"waited={handoff.get('waited_ms', 0):.0f} ms "
                    f"from={handoff.get('previous_holder')}"
                )
            writes = by_label("klba_snapshot_writes_total", "outcome")
            fenced = int(writes.get("fenced", 0))
            denied = int(writes.get("no_lease", 0))
            if fenced or denied:
                print(
                    f"fenced snapshot writes: {fenced} rejected, "
                    f"{denied} denied without lease"
                )
            paced = counter_total("klba_resync_paced_total")
            if paced:
                print(f"resync epochs paced: {int(paced)}")

        # State-integrity view (DEPLOYMENT.md "State integrity"):
        # scrub coverage (streams audited / pass interval), last-scrub
        # age, and the per-buffer quarantine totals — the "is the
        # long-lived device state being watched, and has anything
        # rotted" look, next to the lifecycle rows above.
        if scrub:
            age = scrub.get("last_pass_age_s")
            age_txt = (
                f"{age:.1f}s ago" if age is not None else "never"
            )
            print(
                f"scrub: {int(scrub.get('streams_audited', 0))} "
                f"audits over {int(scrub.get('passes', 0))} passes "
                f"(every {scrub.get('interval_ms', 0) / 1000.0:.0f}s), "
                f"last pass {age_txt}, "
                f"{int(scrub.get('quarantined_streams', 0))} stream(s) "
                "quarantined now"
            )
            # Scrub-coverage SLO (ROADMAP state-integrity (b)): a
            # wedged scrubber is flagged by PRESENCE — audit progress
            # stalled while streams are live — not by counters that
            # quietly stopped moving.
            if scrub.get("wedged"):
                print(
                    "scrub WEDGED: no audit progress for > 3 "
                    "intervals while streams are live "
                    "(klba_scrub_streams_audited_total stalled)"
                )
        elif lifecycle:
            print("scrub: disabled (tpu.assignor.scrub.interval.ms=0)")
        quarantines = js.get("klba_quarantine_total", {}).get(
            "series", []
        )
        if quarantines:
            total = 0
            for s in quarantines:
                total += s["value"]
                print(
                    f"quarantine buffer={s['labels'].get('buffer')} "
                    f"outcome={s['labels'].get('outcome')}: "
                    f"{int(s['value'])}"
                )
            print(f"quarantine total: {int(total)}")

        # Federation view (DEPLOYMENT.md "Federated assignment"):
        # degradation rung, per-peer link/breaker state, dual-cache
        # age, and the stale/fenced rejection totals — the "is this
        # sidecar converging with its peers, and who is partitioned"
        # look.
        if federation:
            rung = federation.get("rung") or "never ran"
            cache = federation.get("last_good")
            cache_txt = (
                f"last-good duals {cache['age_s']:.1f}s old "
                f"({cache['rounds']} rounds)"
                if cache else "no last-good duals"
            )
            print(
                f"federation: rung={rung} epoch="
                f"{federation.get('epoch')} "
                f"last_rounds={federation.get('last_rounds')}, "
                f"{cache_txt}"
            )
            for pid, peer in sorted(
                (federation.get("peers") or {}).items()
            ):
                print(
                    f"peer {pid} ({peer.get('address')}): "
                    f"breaker={peer.get('breaker')} "
                    f"last={peer.get('last_outcome')} "
                    f"epoch_seen={peer.get('epoch_seen')}"
                )
            stale = by_label("klba_peer_stale_duals_total", "reason")
            if stale:
                rows = ", ".join(
                    f"{k}={int(v)}" for k, v in sorted(stale.items())
                )
                print(f"stale/fenced duals rejected: {rows}")

        # Static-analysis view (DEPLOYMENT.md "Static analysis"): the
        # last analyzer run's finding/suppression counts, sourced from
        # the SARIF artifact when one is present (CI uploads
        # analyze.sarif; operators can `klba-analyze --sarif`) — the
        # "did anything merge past the invariant gate" look, next to
        # the lint line the tier-1 gate prints.
        line = analyzer_summary_line(
            args.analyze_sarif
            or os.environ.get("KLBA_ANALYZE_SARIF")
            or Path(__file__).resolve().parent.parent / "analyze.sarif"
        )
        if line:
            print(line)

        # Kernel-plane view (DEPLOYMENT.md "Kernel plane"): gate
        # verdicts, probe race, and interpret parity from the last
        # kernel report (bench writes one; CI uploads it) — the "is
        # the Pallas plane serving, and did it earn it" look.  The
        # per-phase device timings themselves print above as
        # klba_device_phase_ms{phase=...} histogram rows.
        line = kernel_summary_line(
            args.kernel_report
            or os.environ.get("KLBA_KERNEL_REPORT")
            or Path(__file__).resolve().parent.parent
            / "kernel_report.json"
        )
        if line:
            print(line)

        # Adversarial-fleet view (DEPLOYMENT.md "Adversarial
        # scenarios"): the last fleet run's envelope verdicts from its
        # artifact (CI uploads scenario_fleet.json; bench config16 and
        # `python -m scenarios --json` both write one) — the "did the
        # service degrade inside its envelopes" look.
        line = scenario_summary_line(
            args.scenario_report
            or os.environ.get("KLBA_SCENARIO_REPORT")
            or Path(__file__).resolve().parent.parent
            / "scenario_fleet.json"
        )
        if line:
            print(line)
        return 0
    print(json.dumps(result["json"], indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
