"""Repo tooling namespace (makes tools/ importable for tools.analyze)."""
