"""One-shot real-TPU validation of the round-4 latency work.

Run when the tunnel is reachable:  python tools/probe_round4.py
Measures: headline e2e, warm streaming p50, sinkhorn skew/zipf wall time
(post start-selection), transport floor, and an exact-shape (P=100000)
vs pow2-padded (131072) sort comparison for the headline kernel.
"""

import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import sys

sys.path.insert(0, "/root/repo")

print("devices:", jax.devices(), flush=True)


def med(f, iters=10):
    f()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        f()
        ts.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(ts)), float(np.min(ts))


def zipf(seed, P, a=1.1, scale=1000):
    rng = np.random.default_rng(seed)
    ranks = rng.permutation(P) + 1
    return (scale * (P / ranks) ** (1.0 / a)).astype(np.int64)


from kafka_lag_based_assignor_tpu.ops.batched import assign_stream
from kafka_lag_based_assignor_tpu.models.sinkhorn import assign_topic_sinkhorn
from kafka_lag_based_assignor_tpu.ops.packing import pad_topic_rows
from kafka_lag_based_assignor_tpu.ops.streaming import StreamingAssignor
import bench as bench_mod

P, C = 100_000, 1000
lags = zipf(5, P)

m, mn = med(lambda: np.asarray(assign_stream(lags, num_consumers=C)), 20)
print(f"headline e2e: median {m:.2f} min {mn:.2f} ms", flush=True)

floor_once = bench_mod.make_transport_floor(lags, C)
fm, _ = bench_mod.timed_solve(floor_once, iters=12)
fmn = bench_mod.timed_solve.last_min_ms
print(f"transport floor: median {fm:.2f} min {fmn:.2f} ms "
      f"(above-floor {m - fm:.2f})", flush=True)

# Warm streaming
eng = StreamingAssignor(num_consumers=C, refine_iters=128,
                        imbalance_guardrail=1.25)
eng.rebalance(lags)
eng.rebalance(lags)
rng = np.random.default_rng(99)
lf = lags.astype(np.float64)
warm = []
for _ in range(8):
    lf = lf * rng.lognormal(0, 0.2, P) + rng.integers(0, 1000, P)
    arr = lf.astype(np.int64)
    t0 = time.perf_counter()
    eng.rebalance(arr)
    warm.append((time.perf_counter() - t0) * 1000.0)
print(f"warm p50: {np.percentile(warm, 50):.2f} min {min(warm):.2f} ms",
      flush=True)

# Sinkhorn skew (start selection should pick greedy & stop fast)
rng = np.random.default_rng(4)
P2, C2 = 10_000, 512
sl = np.zeros(P2, dtype=np.int64)
hot = rng.choice(P2, size=P2 // 10, replace=False)
sl[hot] = rng.integers(10**5, 10**7, size=hot.size)
lp, pp, vp = pad_topic_rows(sl)


def sk():
    _, _, t = assign_topic_sinkhorn(lp, pp, vp, num_consumers=C2)
    return np.asarray(t)


m, mn = med(sk, 5)
tot = sk()
print(f"sinkhorn skew: median {m:.2f} min {mn:.2f} ms "
      f"imb {float(tot.max()/tot.mean()):.4f}", flush=True)

# Sinkhorn zipf
zl = zipf(2, 1000)
lp2, pp2, vp2 = pad_topic_rows(zl)


def sk2():
    _, _, t = assign_topic_sinkhorn(lp2, pp2, vp2, num_consumers=16)
    return np.asarray(t)


m, mn = med(sk2, 8)
tot = sk2()
print(f"sinkhorn zipf: median {m:.2f} min {mn:.2f} ms "
      f"ratio {float(tot.max()/tot.mean())/1.755907398403936:.5f}",
      flush=True)

# Sinkhorn northstar quality (single shot)
lp3, pp3, vp3 = pad_topic_rows(lags)
t0 = time.perf_counter()
_, _, t = assign_topic_sinkhorn(lp3, pp3, vp3, num_consumers=C)
t = np.asarray(t)
print(f"sinkhorn northstar: {1000*(time.perf_counter()-t0):.0f} ms "
      f"(first call) imb {float(t.max()/t.mean()):.5f}", flush=True)

# Exact-shape (non-pow2) sort experiment: is padding to 131072 worth it?
import functools
import jax.numpy as jnp
from kafka_lag_based_assignor_tpu.ops.rounds_kernel import assign_topic_rounds
from kafka_lag_based_assignor_tpu.ops.scan_kernel import pack_shift_for


@functools.partial(jax.jit, static_argnames=("num_consumers", "pack_shift"))
def stream_exact(lags, num_consumers: int, pack_shift: int = 0):
    P = lags.shape[0]
    pids = jnp.arange(P, dtype=jnp.int32)
    valid = jnp.ones((P,), bool)
    choice, _, _ = assign_topic_rounds(
        lags.astype(jnp.int64), pids, valid, num_consumers=num_consumers,
        pack_shift=pack_shift,
    )
    return choice.astype(jnp.int16)


shift = pack_shift_for(int(lags.max()), P - 1)
t0 = time.perf_counter()
np.asarray(stream_exact(lags.astype(np.int32), num_consumers=C,
                        pack_shift=shift))
print(f"exact-shape compile+first: {time.perf_counter()-t0:.1f}s",
      flush=True)
m, mn = med(lambda: np.asarray(
    stream_exact(lags.astype(np.int32), num_consumers=C, pack_shift=shift)
), 20)
print(f"exact-shape e2e: median {m:.2f} min {mn:.2f} ms", flush=True)
