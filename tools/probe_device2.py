"""Probe 2: transfer latency vs size, overlap behavior, north-star anatomy."""

import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

print("devices:", jax.devices())


def med(f, iters=8):
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        f()
        ts.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(ts)), float(np.min(ts))


# Transfer latency vs size
for n in (1, 1024, 10_240, 102_400, 1_024_000):
    a = np.arange(n, dtype=np.int64)
    m, mn = med(lambda a=a: jax.device_put(a).block_until_ready())
    print(f"h2d int64[{n}] ({n*8/1024:.0f} KB): median {m:.2f} min {mn:.2f} ms")

# d2h fresh (uncached) readback vs size: compute on device then fetch
for n in (1024, 102_400, 1_024_000):
    a = jax.device_put(np.arange(n, dtype=np.int64)).block_until_ready()
    g = jax.jit(lambda v: v + 1)

    def once(a=a, g=g):
        r = g(a)
        return np.asarray(r)

    once()
    m, mn = med(once)
    print(f"dispatch+d2h int64[{n}]: median {m:.2f} min {mn:.2f} ms")

# North-star anatomy with assign_stream
import sys

sys.path.insert(0, "/root/repo")
from kafka_lag_based_assignor_tpu.ops.batched import (
    _stream_device,
    assign_stream,
)
from kafka_lag_based_assignor_tpu.ops.scan_kernel import pack_shift_for
from kafka_lag_based_assignor_tpu.ops.packing import pad_bucket

rng = np.random.default_rng(5)
P, C = 100_000, 1000
ranks = rng.permutation(P) + 1
lags = (1000 * (P / ranks) ** (1.0 / 1.1)).astype(np.int64)
shift = pack_shift_for(int(lags.max()), pad_bucket(P) - 1)

# full path (numpy in, numpy out)
m, mn = med(lambda: np.asarray(assign_stream(lags, num_consumers=C)))
print(f"assign_stream e2e: median {m:.2f} min {mn:.2f} ms")

# device-resident input, sync only (pure dispatch+compute, no h2d/d2h)
dl = jax.device_put(lags).block_until_ready()
m, mn = med(
    lambda: _stream_device(
        dl, num_consumers=C, pack_shift=shift
    ).block_until_ready()
)
print(f"stream resident dispatch+sync: median {m:.2f} min {mn:.2f} ms")

# resident input, with d2h readback
def res_read():
    r = _stream_device(dl, num_consumers=C, pack_shift=shift)
    return np.asarray(r)

res_read()
m, mn = med(res_read)
print(f"stream resident + readback: median {m:.2f} min {mn:.2f} ms")

# h2d put followed by dispatch referencing it (two transport ops queued)
def put_then_dispatch():
    d = jax.device_put(lags)
    r = _stream_device(d, num_consumers=C, pack_shift=shift)
    return np.asarray(r)

put_then_dispatch()
m, mn = med(put_then_dispatch)
print(f"explicit put + dispatch + readback: median {m:.2f} min {mn:.2f} ms")

# pipelined steady state: issue epoch N+1 before reading epoch N
def pipelined(iters=8):
    res = []
    r_prev = _stream_device(dl, num_consumers=C, pack_shift=shift)
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        r = _stream_device(dl, num_consumers=C, pack_shift=shift)
        np.asarray(r_prev)
        r_prev = r
        ts.append((time.perf_counter() - t0) * 1000.0)
    print(f"pipelined per-epoch (resident): median {np.median(ts):.2f} "
          f"min {np.min(ts):.2f} ms")

pipelined()


# pipelined with fresh numpy input each epoch (the real streaming shape)
def pipelined_np(iters=8):
    r_prev = assign_stream(lags, num_consumers=C)
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        r = assign_stream(lags, num_consumers=C)
        np.asarray(r_prev)
        r_prev = r
        ts.append((time.perf_counter() - t0) * 1000.0)
    print(f"pipelined per-epoch (numpy in): median {np.median(ts):.2f} "
          f"min {np.min(ts):.2f} ms")

pipelined_np()
