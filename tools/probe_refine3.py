"""Probe: e2e refine cost vs iters (numpy in, numpy out — the real pattern)."""

import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import sys

sys.path.insert(0, "/root/repo")
from kafka_lag_based_assignor_tpu.ops.refine import refine_assignment

print("devices:", jax.devices())


def med(f, iters=6):
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        f()
        ts.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(ts)), float(np.min(ts))


rng = np.random.default_rng(0)

for P, C in ((131072, 1000), (16384, 512)):
    lags = rng.integers(0, 1 << 30, size=P).astype(np.int64)
    valid = np.ones(P, bool)
    # count-balanced start
    choice = (rng.permutation(P) % C).astype(np.int32)
    for it in (1, 16, 64):
        def f(it=it):
            c, _, t = refine_assignment(
                lags, valid, choice, num_consumers=C, iters=it,
                patience=10_000
            )
            return np.asarray(c), np.asarray(t)

        f()
        m, mn = med(f)
        print(f"P={P} C={C} e2e refine iters={it}: "
              f"median {m:.2f} min {mn:.2f} ms")
