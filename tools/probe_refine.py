"""Probe: warm-path refine + sinkhorn wall time on the real chip."""

import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import sys

sys.path.insert(0, "/root/repo")

from kafka_lag_based_assignor_tpu.ops.streaming import StreamingAssignor
from kafka_lag_based_assignor_tpu.models.sinkhorn import assign_topic_sinkhorn
from kafka_lag_based_assignor_tpu.ops.packing import pad_topic_rows

print("devices:", jax.devices())


def zipf_lags(rng, P, a=1.1, scale=1000):
    ranks = rng.permutation(P) + 1
    return (scale * (P / ranks) ** (1.0 / a)).astype(np.int64)


# Warm streaming path, north-star shape
rng = np.random.default_rng(5)
P, C = 100_000, 1000
lags0 = zipf_lags(rng, P)
engine = StreamingAssignor(num_consumers=C, refine_iters=128,
                          imbalance_guardrail=1.25)
engine.rebalance(lags0)
engine.rebalance(lags0)  # compile warm path
lags = lags0.astype(np.float64)
warm = []
for _ in range(8):
    drift = rng.lognormal(0.0, 0.2, size=P)
    lags = lags * drift + rng.integers(0, 1000, size=P)
    arr = lags.astype(np.int64)
    t0 = time.perf_counter()
    engine.rebalance(arr)
    warm.append((time.perf_counter() - t0) * 1000.0)
print(f"warm p50: {np.percentile(warm, 50):.2f} ms  min {min(warm):.2f}")

# Sinkhorn skew config
rng = np.random.default_rng(4)
P, C = 10_000, 512
slags = np.zeros(P, dtype=np.int64)
hot = rng.choice(P, size=P // 10, replace=False)
slags[hot] = rng.integers(10**5, 10**7, size=hot.size)
lags_p, pids, valid = pad_topic_rows(slags)


def sink_once():
    _, _, t = assign_topic_sinkhorn(lags_p, pids, valid, num_consumers=C)
    return np.asarray(t)


sink_once()
ts = []
for _ in range(5):
    t0 = time.perf_counter()
    tot = sink_once()
    ts.append((time.perf_counter() - t0) * 1000.0)
imb = float(tot.max() / tot.mean())
print(f"sinkhorn skew: median {np.median(ts):.2f} min {min(ts):.2f} ms "
      f"imb {imb:.4f}")

# Sinkhorn zipf config
rng = np.random.default_rng(2)
P, C = 1000, 16
zl = zipf_lags(rng, P)
lags_p, pids, valid = pad_topic_rows(zl)


def sink2():
    _, _, t = assign_topic_sinkhorn(lags_p, pids, valid, num_consumers=C)
    return np.asarray(t)


sink2()
ts = []
for _ in range(8):
    t0 = time.perf_counter()
    tot = sink2()
    ts.append((time.perf_counter() - t0) * 1000.0)
imb = float(tot.max() / tot.mean())
bound = float(zl.max() / (zl.sum() / C))
print(f"sinkhorn zipf: median {np.median(ts):.2f} min {min(ts):.2f} ms "
      f"imb {imb:.4f} bound {bound:.4f} ratio {imb/max(bound,1):.4f}")
