"""Refine-round and sinkhorn-stage device costs (block-only timings on
device-resident inputs: no host<->device payload, so the tunnel RTT term
is the same small constant for every row — deltas are device compute)."""

import sys
import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

sys.path.insert(0, "/root/repo")

from kafka_lag_based_assignor_tpu.models.sinkhorn import (  # noqa: E402
    _dedup_weights,
    _sinkhorn_duals_jit,
)
from kafka_lag_based_assignor_tpu.ops.packing import pad_bucket  # noqa: E402
from kafka_lag_based_assignor_tpu.ops.refine import (  # noqa: E402
    refine_assignment,
)

print("devices:", jax.devices(), flush=True)


def med(f, iters=10):
    f()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        f()
        ts.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(ts))


P, C = 100_000, 1000
B = pad_bucket(P)
rng = np.random.default_rng(0)
ranks = rng.permutation(P) + 1
lags1 = (1000.0 * (P / ranks) ** (1 / 1.1)).astype(np.int64)
lags_p = np.zeros(B, np.int64)
lags_p[:P] = lags1
valid = np.zeros(B, bool)
valid[:P] = True
choice = np.full(B, -1, np.int32)
choice[:P] = rng.permutation(P) % C

d_lags = jax.device_put(lags_p)
d_valid = jax.device_put(valid)
d_choice = jax.device_put(choice)

base = None
for it in (1, 2, 4, 16, 64):
    def f(it=it):
        r, _, _ = refine_assignment(
            d_lags, d_valid, d_choice, num_consumers=C, iters=it,
            max_pairs=C // 2,
        )
        r.block_until_ready()

    m = med(f)
    extra = "" if base is None else f"  (+{(m - base) / max(it - 1, 1):.2f}ms/round)"
    if base is None:
        base = m
    print(f"refine iters={it:3d}: {m:7.2f}ms{extra}", flush=True)

# Sinkhorn duals iteration at the north-star shape (zipf: U ~= P).
ws_u, count_u, wsum_u = _dedup_weights(lags_p, valid, C)
print(f"dedup U_pad={ws_u.shape[0]}", flush=True)
for iters in (1, 24):
    def g(iters=iters):
        A, _B = _sinkhorn_duals_jit(
            ws_u, count_u, wsum_u, num_consumers=C, iters=iters
        )
        A.block_until_ready()

    print(f"duals iters={iters:3d}: {med(g, 5):7.2f}ms", flush=True)
