"""Post-rewrite device validation: new full-solve and refine-round costs
(fetch-synchronized, see probe_round5c.py) plus an end-to-end bench-style
interleaved floor/solve measurement."""

import sys
import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

sys.path.insert(0, "/root/repo")

import functools  # noqa: E402

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from kafka_lag_based_assignor_tpu.ops.batched import (  # noqa: E402
    _stream_device,
    assign_stream,
    stream_payload,
    totals_rank_bits_for,
)
from kafka_lag_based_assignor_tpu.ops.packing import pad_bucket  # noqa: E402
from kafka_lag_based_assignor_tpu.ops.refine import (  # noqa: E402
    refine_assignment,
)

print("devices:", jax.devices(), flush=True)

P, C, N_HI = 100_000, 1000, 8
B = pad_bucket(P)
rng = np.random.default_rng(0)
ranks = rng.permutation(P) + 1
lags1 = (1000.0 * (P / ranks) ** (1 / 1.1)).astype(np.int64)
payload, shift = stream_payload(lags1)
rb = totals_rank_bits_for(payload, C)
print(f"shift={shift} rank_bits={rb} dtype={payload.dtype}", flush=True)
batch = jax.device_put(
    np.stack([np.roll(payload, 17 * i) for i in range(N_HI)])
)


def fetch_med(f, iters=10):
    f()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        f()
        ts.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(ts))


@functools.partial(jax.jit, static_argnames=("n",))
def solve_many(b, n):
    f = lambda v: _stream_device(  # noqa: E731
        v, num_consumers=C, pack_shift=shift, totals_rank_bits=rb
    ).astype(jnp.int32).sum()
    return lax.map(f, b[:n]).sum()


t1 = fetch_med(lambda: int(solve_many(batch, n=1)))
t8 = fetch_med(lambda: int(solve_many(batch, n=N_HI)))
print(
    f"full_solve_v2   t[1]={t1:7.2f} t[8]={t8:7.2f} "
    f"-> {(t8 - t1) / (N_HI - 1):6.3f} ms/solve",
    flush=True,
)

lags_p = np.zeros(B, np.int64)
lags_p[:P] = lags1
valid_np = np.zeros(B, bool)
valid_np[:P] = True
choice_np = np.full(B, -1, np.int32)
choice_np[:P] = rng.permutation(P) % C
d_lags = jax.device_put(lags_p)
d_valid = jax.device_put(valid_np)
d_choice = jax.device_put(choice_np)


def refine_n(iters):
    r, _, _ = refine_assignment(
        d_lags, d_valid, d_choice, num_consumers=C, iters=iters,
        max_pairs=C // 2, patience=10**6,
    )
    return int(np.asarray(r[:1])[0])


t1 = fetch_med(lambda: refine_n(1))
t65 = fetch_med(lambda: refine_n(65))
print(
    f"refine_round_v2 t[1]={t1:7.2f} t[65]={t65:7.2f} "
    f"-> {(t65 - t1) / 64:6.3f} ms/round",
    flush=True,
)

# End-to-end interleaved floor vs solve (the bench's headline method).
import bench as bench_mod  # noqa: E402

floor_once = bench_mod.make_transport_floor(lags1, C)
flr, _ = bench_mod.interleaved_floor(
    lambda: np.asarray(assign_stream(lags1, num_consumers=C)), floor_once
)
print({k: round(v, 2) for k, v in flr.items()}, flush=True)
