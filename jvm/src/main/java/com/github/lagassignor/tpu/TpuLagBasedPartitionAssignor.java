package com.github.lagassignor.tpu;

import java.io.BufferedReader;
import java.io.BufferedWriter;
import java.io.IOException;
import java.io.InputStreamReader;
import java.io.OutputStreamWriter;
import java.net.InetSocketAddress;
import java.net.Socket;
import java.nio.charset.StandardCharsets;
import java.util.ArrayList;
import java.util.Collections;
import java.util.HashMap;
import java.util.HashSet;
import java.util.List;
import java.util.Map;
import java.util.Optional;
import java.util.PriorityQueue;
import java.util.Properties;
import java.util.Set;
import java.util.TreeMap;
import java.util.TreeSet;

import org.apache.kafka.clients.consumer.Consumer;
import org.apache.kafka.clients.consumer.ConsumerConfig;
import org.apache.kafka.clients.consumer.ConsumerPartitionAssignor;
import org.apache.kafka.clients.consumer.KafkaConsumer;
import org.apache.kafka.clients.consumer.OffsetAndMetadata;
import org.apache.kafka.common.Cluster;
import org.apache.kafka.common.Configurable;
import org.apache.kafka.common.PartitionInfo;
import org.apache.kafka.common.TopicPartition;
import org.apache.kafka.common.serialization.ByteArrayDeserializer;
import org.slf4j.Logger;
import org.slf4j.LoggerFactory;

/**
 * JVM-side shim for the TPU lag-balanced partition assignor.
 *
 * <p>This class is the {@code partition.assignment.strategy} entry point the
 * north star keeps on the JVM: Kafka instantiates it by reflection on every
 * consumer, and on the elected group leader calls {@link #assign(Cluster,
 * GroupSubscription)} during a rebalance.  It keeps the host-side
 * responsibilities of the reference assignor — group bookkeeping and the
 * offset/lag broker RPCs (reference LagBasedPartitionAssignor.java:317-365) —
 * and marshals only the pure combinatorial core across a process boundary to
 * the co-located TPU sidecar ({@code python -m
 * kafka_lag_based_assignor_tpu.service}), which runs the batched JAX solve
 * and returns the member→partitions map.
 *
 * <p>Wire protocol: newline-delimited JSON over TCP, one request per line —
 * see the sidecar module docstring (service.py) and the golden conformance
 * fixtures in {@code tests/fixtures/wire_conformance.jsonl}, which pin the
 * exact request/response byte shapes this class must produce/consume (they
 * are exercised against the Python service by tests/test_service.py, so the
 * protocol cannot drift without a test failing).
 *
 * <p>Failure model: if the sidecar is unreachable, times out, or answers
 * with an error, the shim falls back to a local greedy solve with identical
 * semantics (count-primary, lag-secondary, member-id tiebreak — reference
 * :246-259), so a rebalance never fails because of the accelerator.  This
 * mirrors the Python framework's watchdog + host-fallback design
 * (utils/watchdog.py, SURVEY §5 failure row).
 *
 * <p>Configuration (all via the consumer config map, reference-compatible):
 * <ul>
 *   <li>{@code group.id} — required (reference :107-113 fails fast).</li>
 *   <li>{@code auto.offset.reset} — no-committed-offset fallback mode
 *       (reference :346-347; default {@code latest}).</li>
 *   <li>{@code tpu.assignor.sidecar.host} / {@code .port} — sidecar address
 *       (default 127.0.0.1:7531).</li>
 *   <li>{@code tpu.assignor.sidecar.timeout.ms} — socket/solve timeout
 *       (default 120000, covering a cold first-compile).</li>
 *   <li>{@code tpu.assignor.solver} — {@code rounds} (default), {@code scan},
 *       {@code global}, {@code sinkhorn}, or {@code host}.</li>
 * </ul>
 */
public class TpuLagBasedPartitionAssignor
        implements ConsumerPartitionAssignor, Configurable {

    private static final Logger LOG =
            LoggerFactory.getLogger(TpuLagBasedPartitionAssignor.class);

    public static final String PROTOCOL_NAME = "lag";
    public static final String SIDECAR_HOST_CONFIG =
            "tpu.assignor.sidecar.host";
    public static final String SIDECAR_PORT_CONFIG =
            "tpu.assignor.sidecar.port";
    public static final String SIDECAR_TIMEOUT_MS_CONFIG =
            "tpu.assignor.sidecar.timeout.ms";
    public static final String SOLVER_CONFIG = "tpu.assignor.solver";
    /** One-shot quality mode: exchange-refinement rounds chained into
     *  the sidecar solve (same key as the Python plugin; NOT bit-parity
     *  with the reference, so unset keeps strict parity).  Only
     *  marshaled when set; rejected by the sidecar for the 'global'
     *  solver. */
    public static final String REFINE_ITERS_CONFIG =
            "tpu.assignor.refine.iters";

    private Properties consumerGroupProps;
    private Properties metadataConsumerProps;
    private Consumer<byte[], byte[]> metadataConsumer;

    private String sidecarHost = "127.0.0.1";
    private int sidecarPort = 7531;
    private int sidecarTimeoutMs = 120_000;
    private String solver = "rounds";
    private Long refineIters;  // null = strict parity (no option sent)
    private long requestId = 0;

    // ------------------------------------------------------------------
    // Configurable
    // ------------------------------------------------------------------

    @Override
    public void configure(Map<String, ?> configs) {
        consumerGroupProps = new Properties();
        for (Map.Entry<String, ?> e : configs.entrySet()) {
            if (e.getValue() != null) {
                consumerGroupProps.put(e.getKey(), e.getValue().toString());
            }
        }
        String groupId =
                consumerGroupProps.getProperty(ConsumerConfig.GROUP_ID_CONFIG);
        if (groupId == null || groupId.isEmpty()) {
            // Reference :107-113: the assignor is useless without the group
            // whose committed offsets define lag.
            throw new IllegalArgumentException(
                    PROTOCOL_NAME + " assignor requires " +
                    ConsumerConfig.GROUP_ID_CONFIG + " to be configured");
        }
        // Derived metadata-consumer config (reference :116-120): never
        // auto-commit on the probe consumer, and tag its client.id.
        metadataConsumerProps = new Properties();
        metadataConsumerProps.putAll(consumerGroupProps);
        metadataConsumerProps.put(
                ConsumerConfig.ENABLE_AUTO_COMMIT_CONFIG, "false");
        metadataConsumerProps.put(
                ConsumerConfig.CLIENT_ID_CONFIG, groupId + ".assignor");

        sidecarHost = consumerGroupProps.getProperty(
                SIDECAR_HOST_CONFIG, sidecarHost);
        sidecarPort = Integer.parseInt(consumerGroupProps.getProperty(
                SIDECAR_PORT_CONFIG, Integer.toString(sidecarPort)));
        sidecarTimeoutMs = Integer.parseInt(consumerGroupProps.getProperty(
                SIDECAR_TIMEOUT_MS_CONFIG,
                Integer.toString(sidecarTimeoutMs)));
        solver = consumerGroupProps.getProperty(SOLVER_CONFIG, solver);
        String refine = consumerGroupProps.getProperty(REFINE_ITERS_CONFIG);
        refineIters = (refine == null || refine.isEmpty()
                || "auto".equals(refine)) ? null : Long.valueOf(refine);
        LOG.debug("configured {} assignor: sidecar {}:{} solver {}",
                PROTOCOL_NAME, sidecarHost, sidecarPort, solver);
    }

    // ------------------------------------------------------------------
    // ConsumerPartitionAssignor
    // ------------------------------------------------------------------

    @Override
    public String name() {
        return PROTOCOL_NAME;  // the JoinGroup protocol name (reference :132)
    }

    @Override
    public GroupAssignment assign(Cluster metadata,
                                  GroupSubscription groupSubscription) {
        Map<String, Subscription> subscriptions =
                groupSubscription.groupSubscription();

        // member -> subscribed topics; union of all topics (reference
        // :140-146).  TreeMap/TreeSet for deterministic JSON ordering.
        Map<String, List<String>> memberTopics = new TreeMap<>();
        Set<String> allTopics = new TreeSet<>();
        for (Map.Entry<String, Subscription> e : subscriptions.entrySet()) {
            List<String> topics = new ArrayList<>(new TreeSet<>(
                    e.getValue().topics()));
            memberTopics.put(e.getKey(), topics);
            allTopics.addAll(topics);
        }

        Map<String, List<long[]>> topicLags =
                readTopicPartitionLags(metadata, allTopics);

        Map<String, List<TopicPartition>> assignment;
        try {
            assignment = sidecarAssign(topicLags, memberTopics);
        } catch (Exception ex) {
            LOG.warn("TPU sidecar assign failed; falling back to local "
                    + "greedy", ex);
            assignment = localGreedyAssign(topicLags, memberTopics);
        }

        Map<String, Assignment> result = new HashMap<>();
        for (String member : subscriptions.keySet()) {
            // Every member appears in the result, possibly empty
            // (reference :171-174).
            result.put(member, new Assignment(assignment.getOrDefault(
                    member, Collections.emptyList())));
        }
        return new GroupAssignment(result);
    }

    // ------------------------------------------------------------------
    // Lag acquisition (stays JVM-side; reference :317-404 semantics)
    // ------------------------------------------------------------------

    /** topic -> [[partition, lag], ...] using three batch RPCs per topic. */
    private Map<String, List<long[]>> readTopicPartitionLags(
            Cluster metadata, Set<String> topics) {
        if (metadataConsumer == null) {
            // Lazy shared probe consumer, never closed (reference :322-324).
            metadataConsumer = createMetadataConsumer();
        }
        String resetMode = consumerGroupProps.getProperty(
                ConsumerConfig.AUTO_OFFSET_RESET_CONFIG, "latest");
        Map<String, List<long[]>> out = new TreeMap<>();
        for (String topic : topics) {
            List<PartitionInfo> infos = metadata.partitionsForTopic(topic);
            if (infos == null || infos.isEmpty()) {
                // Tolerated fault: warn and skip (reference :358-360).
                LOG.warn("skipping topic {}: no partition metadata", topic);
                continue;
            }
            List<TopicPartition> tps = new ArrayList<>(infos.size());
            for (PartitionInfo info : infos) {
                tps.add(new TopicPartition(topic, info.partition()));
            }
            // The network boundary (reference :339-342).  No try/catch: an
            // RPC failure aborts the rebalance and Kafka retries
            // (reference behavior, SURVEY §2.4.9).
            Map<TopicPartition, Long> begin =
                    metadataConsumer.beginningOffsets(tps);
            Map<TopicPartition, Long> end = metadataConsumer.endOffsets(tps);
            Map<TopicPartition, OffsetAndMetadata> committed =
                    metadataConsumer.committed(new HashSet<>(tps));
            List<long[]> rows = new ArrayList<>(tps.size());
            for (TopicPartition tp : tps) {
                long lag = computePartitionLag(
                        Optional.ofNullable(committed.get(tp))
                                .map(OffsetAndMetadata::offset),
                        begin.getOrDefault(tp, 0L),
                        end.getOrDefault(tp, 0L),
                        resetMode);
                rows.add(new long[] {tp.partition(), lag});
            }
            rows.sort((a, b) -> Long.compare(a[0], b[0]));
            out.put(topic, rows);
        }
        return out;
    }

    /**
     * The exact lag formula (reference :376-404): committed offset if
     * present; otherwise {@code latest} ⇒ end offset (lag 0), any other
     * reset mode ⇒ beginning offset (full backlog); clamped at 0 to guard
     * failed end-offset reads.
     */
    static long computePartitionLag(Optional<Long> committed, long begin,
                                    long end, String resetMode) {
        long next = committed.orElseGet(
                () -> "latest".equals(resetMode) ? end : begin);
        return Math.max(end - next, 0L);
    }

    protected Consumer<byte[], byte[]> createMetadataConsumer() {
        return new KafkaConsumer<>(metadataConsumerProps,
                new ByteArrayDeserializer(), new ByteArrayDeserializer());
    }

    // ------------------------------------------------------------------
    // Sidecar wire protocol (pinned by tests/fixtures/wire_conformance.jsonl)
    // ------------------------------------------------------------------

    private Map<String, List<TopicPartition>> sidecarAssign(
            Map<String, List<long[]>> topicLags,
            Map<String, List<String>> memberTopics) throws IOException {
        String request = buildAssignRequest(
                ++requestId, topicLags, memberTopics, solver, refineIters);
        return parseAssignResponse(roundTrip(request));
    }

    /**
     * Marshal one {@code assign} request line (byte shape pinned by the
     * {@code assign_*} entries of tests/fixtures/wire_conformance.jsonl).
     * Static and socket-free so the Java tests can assert the exact bytes.
     * {@code refineIters} null sends no options (strict parity).
     */
    static String buildAssignRequest(long id,
            Map<String, List<long[]>> topicLags,
            Map<String, List<String>> memberTopics, String solver,
            Long refineIters) {
        StringBuilder sb = new StringBuilder(1 << 16);
        sb.append("{\"id\": ").append(id)
          .append(", \"method\": \"assign\", \"params\": {\"topics\": {");
        boolean firstTopic = true;
        for (Map.Entry<String, List<long[]>> e : topicLags.entrySet()) {
            if (!firstTopic) sb.append(", ");
            firstTopic = false;
            Json.writeString(sb, e.getKey());
            sb.append(": [");
            for (int i = 0; i < e.getValue().size(); i++) {
                long[] row = e.getValue().get(i);
                if (i > 0) sb.append(", ");
                sb.append('[').append(row[0]).append(", ").append(row[1])
                  .append(']');
            }
            sb.append(']');
        }
        sb.append("}, \"subscriptions\": {");
        boolean firstMember = true;
        for (Map.Entry<String, List<String>> e : memberTopics.entrySet()) {
            if (!firstMember) sb.append(", ");
            firstMember = false;
            Json.writeString(sb, e.getKey());
            sb.append(": [");
            for (int i = 0; i < e.getValue().size(); i++) {
                if (i > 0) sb.append(", ");
                Json.writeString(sb, e.getValue().get(i));
            }
            sb.append(']');
        }
        sb.append("}, \"solver\": ");
        Json.writeString(sb, solver);
        if (refineIters != null) {
            sb.append(", \"options\": {\"refine_iters\": ")
              .append(refineIters.longValue()).append('}');
        }
        sb.append("}}");
        return sb.toString();
    }

    /** Unmarshal one {@code assign} response line. */
    static Map<String, List<TopicPartition>> parseAssignResponse(
            String responseLine) throws IOException {
        Map<?, ?> response = (Map<?, ?>) Json.parse(responseLine);
        raiseOnError(response);
        Map<?, ?> result = (Map<?, ?>) response.get("result");
        return parseAssignmentsMap((Map<?, ?>) result.get("assignments"));
    }

    private static void raiseOnError(Map<?, ?> response) throws IOException {
        Object error = response.get("error");
        if (error != null) {
            throw new IOException("sidecar error: "
                    + ((Map<?, ?>) error).get("message"));
        }
    }

    private static Map<String, List<TopicPartition>> parseAssignmentsMap(
            Map<?, ?> assignments) {
        Map<String, List<TopicPartition>> out = new HashMap<>();
        for (Map.Entry<?, ?> e : assignments.entrySet()) {
            List<TopicPartition> tps = new ArrayList<>();
            for (Object pair : (List<?>) e.getValue()) {
                List<?> tp = (List<?>) pair;
                tps.add(new TopicPartition((String) tp.get(0),
                        ((Number) tp.get(1)).intValue()));
            }
            out.put((String) e.getKey(), tps);
        }
        return out;
    }

    // ------------------------------------------------------------------
    // Streaming client (sidecar stream_assign / stream_reset; wire shapes
    // pinned by the stream_assign_* fixtures).  Groups that rebalance one
    // topic on a timer call streamAssign each epoch: the sidecar keeps the
    // previous assignment warm per stream_id, makes still-balanced epochs
    // no-ops, bounds churn via the exchange refinement, and carries state
    // across member joins/leaves by NAME.  NOTE: streaming responses list
    // partitions in ascending partition-id order, not processing order.
    // ------------------------------------------------------------------

    /** One streaming epoch's result: the assignment plus the engine's
     *  per-epoch stats (sidecar service.py, stream_assign response). */
    public static final class StreamResult {
        public final Map<String, List<TopicPartition>> assignments;
        public final boolean coldStart;
        public final boolean refined;
        public final boolean guardrailTripped;
        public final long churn;

        StreamResult(Map<String, List<TopicPartition>> assignments,
                boolean coldStart, boolean refined,
                boolean guardrailTripped, long churn) {
            this.assignments = assignments;
            this.coldStart = coldStart;
            this.refined = refined;
            this.guardrailTripped = guardrailTripped;
            this.churn = churn;
        }
    }

    /**
     * One streaming rebalance epoch for {@code streamId}.
     *
     * @param lags    {@code [partition, lag]} rows (lags must be >= 0; the
     *                sidecar rejects negative values).
     * @param members group member ids; a changed set triggers the
     *                sidecar's by-name warm-state remap.
     * @param options optional per-epoch knobs ({@code refine_iters},
     *                {@code guardrail}, {@code refine_threshold}); null or
     *                empty sends none.  The sidecar may quantize values
     *                and echoes the effective ones.
     */
    public StreamResult streamAssign(String streamId, String topic,
            List<long[]> lags, List<String> members,
            Map<String, Object> options) throws IOException {
        String request = buildStreamAssignRequest(
                ++requestId, streamId, topic, lags, members, options);
        return parseStreamAssignResponse(roundTrip(request));
    }

    /** Drop a stream's warm state; returns whether it existed. */
    public boolean streamReset(String streamId) throws IOException {
        String line = roundTrip(buildStreamResetRequest(
                ++requestId, streamId));
        Map<?, ?> response = (Map<?, ?>) Json.parse(line);
        raiseOnError(response);
        Map<?, ?> result = (Map<?, ?>) response.get("result");
        return Boolean.TRUE.equals(result.get("dropped"));
    }

    static String buildStreamAssignRequest(long id, String streamId,
            String topic, List<long[]> lags, List<String> members,
            Map<String, Object> options) {
        StringBuilder sb = new StringBuilder(1 << 12);
        sb.append("{\"id\": ").append(id)
          .append(", \"method\": \"stream_assign\", ")
          .append("\"params\": {\"stream_id\": ");
        Json.writeString(sb, streamId);
        sb.append(", \"topic\": ");
        Json.writeString(sb, topic);
        sb.append(", \"lags\": [");
        for (int i = 0; i < lags.size(); i++) {
            long[] row = lags.get(i);
            if (i > 0) sb.append(", ");
            sb.append('[').append(row[0]).append(", ").append(row[1])
              .append(']');
        }
        sb.append("], \"members\": [");
        for (int i = 0; i < members.size(); i++) {
            if (i > 0) sb.append(", ");
            Json.writeString(sb, members.get(i));
        }
        sb.append(']');
        if (options != null && !options.isEmpty()) {
            // TreeMap: deterministic key order, like every other map the
            // shim marshals.
            sb.append(", \"options\": {");
            boolean first = true;
            for (Map.Entry<String, Object> e
                    : new TreeMap<>(options).entrySet()) {
                if (!first) sb.append(", ");
                first = false;
                Json.writeString(sb, e.getKey());
                sb.append(": ");
                Json.writeValue(sb, e.getValue());
            }
            sb.append('}');
        }
        sb.append("}}");
        return sb.toString();
    }

    static String buildStreamResetRequest(long id, String streamId) {
        StringBuilder sb = new StringBuilder(128);
        sb.append("{\"id\": ").append(id)
          .append(", \"method\": \"stream_reset\", ")
          .append("\"params\": {\"stream_id\": ");
        Json.writeString(sb, streamId);
        sb.append("}}");
        return sb.toString();
    }

    static StreamResult parseStreamAssignResponse(String responseLine)
            throws IOException {
        Map<?, ?> response = (Map<?, ?>) Json.parse(responseLine);
        raiseOnError(response);
        Map<?, ?> result = (Map<?, ?>) response.get("result");
        Map<String, List<TopicPartition>> out = parseAssignmentsMap(
                (Map<?, ?>) result.get("assignments"));
        Map<?, ?> stream = (Map<?, ?>) result.get("stream");
        return new StreamResult(out,
                Boolean.TRUE.equals(stream.get("cold_start")),
                Boolean.TRUE.equals(stream.get("refined")),
                Boolean.TRUE.equals(stream.get("guardrail_tripped")),
                ((Number) stream.get("churn")).longValue());
    }

    private String roundTrip(String requestLine) throws IOException {
        try (Socket socket = new Socket()) {
            socket.connect(new InetSocketAddress(sidecarHost, sidecarPort),
                    sidecarTimeoutMs);
            socket.setSoTimeout(sidecarTimeoutMs);
            BufferedWriter writer = new BufferedWriter(new OutputStreamWriter(
                    socket.getOutputStream(), StandardCharsets.UTF_8));
            BufferedReader reader = new BufferedReader(new InputStreamReader(
                    socket.getInputStream(), StandardCharsets.UTF_8));
            writer.write(requestLine);
            writer.write('\n');
            writer.flush();
            String line = reader.readLine();
            if (line == null) {
                throw new IOException("sidecar closed the connection");
            }
            return line;
        }
    }

    // ------------------------------------------------------------------
    // Local greedy fallback — identical semantics to the sidecar's host
    // solver (count primary, total lag secondary, member id tiebreak;
    // reference :227-266) as an O(P log C) heap loop.
    // ------------------------------------------------------------------

    static Map<String, List<TopicPartition>> localGreedyAssign(
            Map<String, List<long[]>> topicLags,
            Map<String, List<String>> memberTopics) {
        Map<String, List<TopicPartition>> out = new HashMap<>();
        for (String member : memberTopics.keySet()) {
            out.put(member, new ArrayList<>());
        }
        // topic -> subscribed members, sorted for the id tiebreak.
        Map<String, List<String>> consumersPerTopic = new TreeMap<>();
        for (Map.Entry<String, List<String>> e : memberTopics.entrySet()) {
            for (String topic : e.getValue()) {
                consumersPerTopic
                        .computeIfAbsent(topic, t -> new ArrayList<>())
                        .add(e.getKey());
            }
        }
        for (Map.Entry<String, List<String>> e
                : consumersPerTopic.entrySet()) {
            String topic = e.getKey();
            List<long[]> rows = topicLags.get(topic);
            List<String> members = e.getValue();
            if (rows == null || rows.isEmpty() || members.isEmpty()) {
                continue;
            }
            Collections.sort(members);
            // Partitions in descending lag, ties ascending partition id
            // (reference :228-235).
            List<long[]> sorted = new ArrayList<>(rows);
            sorted.sort((a, b) -> a[1] != b[1]
                    ? Long.compare(b[1], a[1]) : Long.compare(a[0], b[0]));
            // Heap entries: {count, totalLag, memberRank}.  Pop-min /
            // push-back reproduces the reference's linear min scan
            // (:240-263) at O(P log C).
            PriorityQueue<long[]> heap = new PriorityQueue<>((a, b) -> {
                if (a[0] != b[0]) return Long.compare(a[0], b[0]);
                if (a[1] != b[1]) return Long.compare(a[1], b[1]);
                return Long.compare(a[2], b[2]);
            });
            for (int rank = 0; rank < members.size(); rank++) {
                heap.add(new long[] {0, 0, rank});
            }
            for (long[] row : sorted) {
                long[] top = heap.poll();
                out.get(members.get((int) top[2]))
                        .add(new TopicPartition(topic, (int) row[0]));
                top[0] += 1;
                top[1] += row[1];
                heap.add(top);
            }
        }
        return out;
    }

    // ------------------------------------------------------------------
    // Minimal dependency-free JSON: a writer for strings and a
    // recursive-descent parser covering exactly the protocol's value set
    // (objects, arrays, strings, numbers, booleans, null).
    // ------------------------------------------------------------------

    static final class Json {
        private final String s;
        private int pos;

        private Json(String s) {
            this.s = s;
        }

        static void writeString(StringBuilder sb, String value) {
            sb.append('"');
            for (int i = 0; i < value.length(); i++) {
                char c = value.charAt(i);
                switch (c) {
                    case '"': sb.append("\\\""); break;
                    case '\\': sb.append("\\\\"); break;
                    case '\n': sb.append("\\n"); break;
                    case '\r': sb.append("\\r"); break;
                    case '\t': sb.append("\\t"); break;
                    default:
                        if (c < 0x20) {
                            sb.append(String.format("\\u%04x", (int) c));
                        } else {
                            sb.append(c);
                        }
                }
            }
            sb.append('"');
        }

        /** Write a protocol value: null, String, Boolean, integral or
         *  floating Number — exactly the option-value set the sidecar
         *  accepts. */
        static void writeValue(StringBuilder sb, Object value) {
            if (value == null) {
                sb.append("null");
            } else if (value instanceof String) {
                writeString(sb, (String) value);
            } else if (value instanceof Boolean) {
                sb.append(value);
            } else if (value instanceof Double || value instanceof Float) {
                sb.append(((Number) value).doubleValue());
            } else if (value instanceof Number) {
                sb.append(((Number) value).longValue());
            } else {
                throw new IllegalArgumentException(
                        "unsupported JSON value type: " + value.getClass());
            }
        }

        static Object parse(String text) {
            Json p = new Json(text);
            Object value = p.parseValue();
            p.skipWhitespace();
            if (p.pos != text.length()) {
                throw new IllegalArgumentException(
                        "trailing JSON content at " + p.pos);
            }
            return value;
        }

        private Object parseValue() {
            skipWhitespace();
            char c = peek();
            if (c == '{') return parseObject();
            if (c == '[') return parseArray();
            if (c == '"') return parseString();
            if (c == 't' || c == 'f') return parseBoolean();
            if (c == 'n') { expect("null"); return null; }
            return parseNumber();
        }

        private Map<String, Object> parseObject() {
            Map<String, Object> out = new HashMap<>();
            expectChar('{');
            skipWhitespace();
            if (peek() == '}') { pos++; return out; }
            while (true) {
                skipWhitespace();
                String key = parseString();
                skipWhitespace();
                expectChar(':');
                out.put(key, parseValue());
                skipWhitespace();
                char c = next();
                if (c == '}') return out;
                if (c != ',') {
                    throw new IllegalArgumentException(
                            "expected ',' or '}' at " + (pos - 1));
                }
            }
        }

        private List<Object> parseArray() {
            List<Object> out = new ArrayList<>();
            expectChar('[');
            skipWhitespace();
            if (peek() == ']') { pos++; return out; }
            while (true) {
                out.add(parseValue());
                skipWhitespace();
                char c = next();
                if (c == ']') return out;
                if (c != ',') {
                    throw new IllegalArgumentException(
                            "expected ',' or ']' at " + (pos - 1));
                }
            }
        }

        private String parseString() {
            expectChar('"');
            StringBuilder sb = new StringBuilder();
            while (true) {
                char c = next();
                if (c == '"') return sb.toString();
                if (c == '\\') {
                    char esc = next();
                    switch (esc) {
                        case '"': sb.append('"'); break;
                        case '\\': sb.append('\\'); break;
                        case '/': sb.append('/'); break;
                        case 'n': sb.append('\n'); break;
                        case 'r': sb.append('\r'); break;
                        case 't': sb.append('\t'); break;
                        case 'b': sb.append('\b'); break;
                        case 'f': sb.append('\f'); break;
                        case 'u':
                            sb.append((char) Integer.parseInt(
                                    s.substring(pos, pos + 4), 16));
                            pos += 4;
                            break;
                        default:
                            throw new IllegalArgumentException(
                                    "bad escape \\" + esc);
                    }
                } else {
                    sb.append(c);
                }
            }
        }

        private Object parseNumber() {
            int start = pos;
            while (pos < s.length()
                    && "+-0123456789.eE".indexOf(s.charAt(pos)) >= 0) {
                pos++;
            }
            String token = s.substring(start, pos);
            if (token.indexOf('.') >= 0 || token.indexOf('e') >= 0
                    || token.indexOf('E') >= 0) {
                return Double.parseDouble(token);
            }
            return Long.parseLong(token);
        }

        private Boolean parseBoolean() {
            if (peek() == 't') { expect("true"); return Boolean.TRUE; }
            expect("false");
            return Boolean.FALSE;
        }

        private void expect(String literal) {
            if (!s.startsWith(literal, pos)) {
                throw new IllegalArgumentException(
                        "expected '" + literal + "' at " + pos);
            }
            pos += literal.length();
        }

        private void expectChar(char c) {
            if (next() != c) {
                throw new IllegalArgumentException(
                        "expected '" + c + "' at " + (pos - 1));
            }
        }

        private void skipWhitespace() {
            while (pos < s.length()
                    && Character.isWhitespace(s.charAt(pos))) {
                pos++;
            }
        }

        private char peek() {
            if (pos >= s.length()) {
                throw new IllegalArgumentException("unexpected end of JSON");
            }
            return s.charAt(pos);
        }

        private char next() {
            char c = peek();
            pos++;
            return c;
        }
    }

}
