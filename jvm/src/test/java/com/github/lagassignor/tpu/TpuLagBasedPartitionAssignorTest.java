package com.github.lagassignor.tpu;

import static org.junit.Assert.assertEquals;
import static org.junit.Assert.assertFalse;
import static org.junit.Assert.assertNull;
import static org.junit.Assert.assertTrue;

import java.io.BufferedReader;
import java.io.BufferedWriter;
import java.io.InputStreamReader;
import java.io.OutputStreamWriter;
import java.net.ServerSocket;
import java.net.Socket;
import java.nio.charset.StandardCharsets;
import java.util.ArrayList;
import java.util.Arrays;
import java.util.Collections;
import java.util.HashMap;
import java.util.List;
import java.util.Map;
import java.util.Optional;
import java.util.TreeMap;

import org.apache.kafka.common.TopicPartition;
import org.junit.Test;

/**
 * Java-side tests for the shim, mirroring the coverage of the reference's
 * test class (reference LagBasedPartitionAssignorTest.java:21-228: the four
 * lag-formula cases, the golden two-topic assignment, the zero-lag and
 * heavily-skewed count-invariant cases) against THIS shim's API — plus the
 * JSON codec and the wire byte shapes, which are pinned cross-language by
 * tests/fixtures/wire_conformance.jsonl in the parent repo (the Python
 * service replays the same fixtures in tests/test_service.py).
 */
public class TpuLagBasedPartitionAssignorTest {

    // ------------------------------------------------------------------
    // computePartitionLag — the exact reference formula (:376-404).
    // ------------------------------------------------------------------

    @Test
    public void computesLagFromCommittedOffset() {
        // reference testComputePartitionLag: committed 5555, end 9999.
        assertEquals(4444L, TpuLagBasedPartitionAssignor.computePartitionLag(
                Optional.of(5555L), 1111, 9999, "none"));
    }

    @Test
    public void clampsNegativeLagWhenEndOffsetLookupFailed() {
        // reference testComputePartitionLagNoEndOffset: begin=end=0 with a
        // committed offset would give a negative difference; clamp to 0.
        assertEquals(0L, TpuLagBasedPartitionAssignor.computePartitionLag(
                Optional.of(5555L), 0, 0, "none"));
    }

    @Test
    public void noCommittedOffsetLatestMeansZeroLag() {
        // reference testComputePartitionLagNoCommittedOffsetResetModeLatest.
        assertEquals(0L, TpuLagBasedPartitionAssignor.computePartitionLag(
                Optional.empty(), 1111, 9999, "latest"));
    }

    @Test
    public void noCommittedOffsetEarliestMeansFullBacklog() {
        // reference ...ResetModeEarliest: lag = end - begin.
        assertEquals(8888L, TpuLagBasedPartitionAssignor.computePartitionLag(
                Optional.empty(), 1111, 9999, "earliest"));
    }

    @Test
    public void committedOffsetBeatsResetMode() {
        // The reset mode only matters when no offset is committed.
        assertEquals(100L, TpuLagBasedPartitionAssignor.computePartitionLag(
                Optional.of(900L), 0, 1000, "latest"));
    }

    // ------------------------------------------------------------------
    // localGreedyAssign — the sidecar-down fallback; identical semantics
    // to the reference's static assign core (:166-308).
    // ------------------------------------------------------------------

    private static Map<String, List<long[]>> goldenTopicLags() {
        Map<String, List<long[]>> topicLags = new TreeMap<>();
        topicLags.put("topic1", Arrays.asList(
                new long[] {0, 100000}, new long[] {1, 100000},
                new long[] {2, 500}, new long[] {3, 1}));
        topicLags.put("topic2", Arrays.asList(
                new long[] {0, 900000}, new long[] {1, 100000}));
        return topicLags;
    }

    @Test
    public void goldenTwoTopicAssignment() {
        // reference testAssign: consumer-1 subscribes both topics,
        // consumer-2 only topic1; expected map including processing order.
        Map<String, List<String>> subs = new TreeMap<>();
        subs.put("consumer-1", Arrays.asList("topic1", "topic2"));
        subs.put("consumer-2", Collections.singletonList("topic1"));

        Map<String, List<TopicPartition>> expected = new HashMap<>();
        expected.put("consumer-1", Arrays.asList(
                new TopicPartition("topic1", 0),
                new TopicPartition("topic1", 2),
                new TopicPartition("topic2", 0),
                new TopicPartition("topic2", 1)));
        expected.put("consumer-2", Arrays.asList(
                new TopicPartition("topic1", 1),
                new TopicPartition("topic1", 3)));

        assertEquals(expected, TpuLagBasedPartitionAssignor
                .localGreedyAssign(goldenTopicLags(), subs));
    }

    private static int spread(Map<String, List<TopicPartition>> assignment) {
        int max = Integer.MIN_VALUE;
        int min = Integer.MAX_VALUE;
        for (List<TopicPartition> tps : assignment.values()) {
            max = Math.max(max, tps.size());
            min = Math.min(min, tps.size());
        }
        return max - min;
    }

    @Test
    public void zeroLagsDistributeEvenly() {
        // reference testAssignWithZeroLags: 7 partitions, 2 consumers.
        Map<String, List<long[]>> topicLags = new TreeMap<>();
        List<long[]> rows = new ArrayList<>();
        for (int p = 0; p < 7; p++) {
            rows.add(new long[] {p, 0});
        }
        topicLags.put("topic1", rows);
        Map<String, List<String>> subs = new TreeMap<>();
        subs.put("consumer-1", Collections.singletonList("topic1"));
        subs.put("consumer-2", Collections.singletonList("topic1"));

        assertTrue("count spread must be <= 1", spread(
                TpuLagBasedPartitionAssignor.localGreedyAssign(
                        topicLags, subs)) <= 1);
    }

    @Test
    public void heavilySkewedLagsKeepCountInvariant() {
        // reference testAssignWithHeavilySkewedLags: 10 partitions (not
        // divisible by 3 consumers), two of them hot.
        long[] lags = {360, 359, 230, 118, 444, 122, 65, 111, 455000, 424000};
        Map<String, List<long[]>> topicLags = new TreeMap<>();
        List<long[]> rows = new ArrayList<>();
        for (int p = 0; p < lags.length; p++) {
            rows.add(new long[] {p, lags[p]});
        }
        topicLags.put("topic1", rows);
        Map<String, List<String>> subs = new TreeMap<>();
        for (int c = 1; c <= 3; c++) {
            subs.put("consumer-" + c,
                    Collections.singletonList("topic1"));
        }

        Map<String, List<TopicPartition>> assignment =
                TpuLagBasedPartitionAssignor.localGreedyAssign(
                        topicLags, subs);
        assertTrue("count spread must be <= 1", spread(assignment) <= 1);

        // The reference's own TODO (its test file, line 226), resolved
        // here: the consumer carrying the most lag must hold the FEWEST
        // partitions — count-primary greedy steers the extra partition
        // (10 = 3*3+1) away from the hot consumers.
        String hottest = null;
        long hottestLag = -1;
        int minCount = Integer.MAX_VALUE;
        for (Map.Entry<String, List<TopicPartition>> e
                : assignment.entrySet()) {
            long total = 0;
            for (TopicPartition tp : e.getValue()) {
                total += lags[tp.partition()];
            }
            if (total > hottestLag) {
                hottestLag = total;
                hottest = e.getKey();
            }
            minCount = Math.min(minCount, e.getValue().size());
        }
        assertEquals("hottest consumer must hold the fewest partitions",
                minCount, assignment.get(hottest).size());
    }

    @Test
    public void emptyTopicsYieldEmptyLists() {
        // Members with no solvable topics still appear with empty lists
        // (reference :171-174 — every member gets an Assignment).
        Map<String, List<String>> subs = new TreeMap<>();
        subs.put("consumer-1", Collections.singletonList("missing"));
        Map<String, List<TopicPartition>> assignment =
                TpuLagBasedPartitionAssignor.localGreedyAssign(
                        new TreeMap<>(), subs);
        assertTrue(assignment.get("consumer-1").isEmpty());
    }

    // ------------------------------------------------------------------
    // JSON codec — the dependency-free parser/writer the wire relies on.
    // ------------------------------------------------------------------

    @Test
    @SuppressWarnings("unchecked")
    public void jsonParsesProtocolResponseShapes() {
        Map<String, Object> parsed = (Map<String, Object>)
                TpuLagBasedPartitionAssignor.Json.parse(
                        "{\"id\": 3, \"result\": {\"assignments\": "
                        + "{\"C0\": [[\"t0\", 0]]}, \"stats\": "
                        + "{\"wall_ms\": 1.5, \"fallback\": false, "
                        + "\"note\": null}}}");
        assertEquals(3L, parsed.get("id"));
        Map<String, Object> result =
                (Map<String, Object>) parsed.get("result");
        Map<String, Object> stats = (Map<String, Object>)
                result.get("stats");
        assertEquals(1.5, (Double) stats.get("wall_ms"), 1e-12);
        assertEquals(Boolean.FALSE, stats.get("fallback"));
        assertNull(stats.get("note"));
        List<Object> pair = (List<Object>) ((List<Object>)
                ((Map<String, Object>) result.get("assignments"))
                        .get("C0")).get(0);
        assertEquals("t0", pair.get(0));
        assertEquals(0L, pair.get(1));
    }

    @Test
    public void jsonStringEscapingRoundTrips() {
        String tricky = "a\"b\\c\nd\tef";
        StringBuilder sb = new StringBuilder();
        TpuLagBasedPartitionAssignor.Json.writeString(sb, tricky);
        assertEquals(tricky,
                TpuLagBasedPartitionAssignor.Json.parse(sb.toString()));
    }

    @Test
    public void jsonParsesLongsBeyondIntRange() {
        // Kafka offsets are longs; 2^53-scale lags must survive.
        assertEquals(9007199254740993L,
                TpuLagBasedPartitionAssignor.Json.parse(
                        "9007199254740993"));
    }

    @Test
    public void jsonWriteValueCoversOptionTypes() {
        StringBuilder sb = new StringBuilder();
        TpuLagBasedPartitionAssignor.Json.writeValue(sb, null);
        sb.append('|');
        TpuLagBasedPartitionAssignor.Json.writeValue(sb, 128L);
        sb.append('|');
        TpuLagBasedPartitionAssignor.Json.writeValue(sb, 1.5);
        sb.append('|');
        TpuLagBasedPartitionAssignor.Json.writeValue(sb, Boolean.TRUE);
        assertEquals("null|128|1.5|true", sb.toString());
    }

    // ------------------------------------------------------------------
    // Wire byte shapes — must match tests/fixtures/wire_conformance.jsonl
    // exactly (the Python service replays those fixtures, so both sides
    // are pinned to the same bytes).
    // ------------------------------------------------------------------

    @Test
    public void assignRequestMatchesPinnedWireShape() {
        assertEquals(
                "{\"id\": 1, \"method\": \"assign\", \"params\": "
                + "{\"topics\": {\"t0\": [[0, 100000], [1, 50000], "
                + "[2, 60000]]}, \"subscriptions\": {\"C0\": [\"t0\"], "
                + "\"C1\": [\"t0\"]}, \"solver\": \"rounds\"}}",
                TpuLagBasedPartitionAssignor.buildAssignRequest(
                        1,
                        new TreeMap<>(Collections.singletonMap(
                                "t0", Arrays.asList(
                                        new long[] {0, 100000},
                                        new long[] {1, 50000},
                                        new long[] {2, 60000}))),
                        readmeSubscriptions(),
                        "rounds",
                        null));
    }

    @Test
    public void assignRequestWithRefineOptionMatchesPinnedFixture() {
        // Byte-for-byte the "assign_rounds_refined_option" fixture line.
        assertEquals(
                "{\"id\": 24, \"method\": \"assign\", \"params\": "
                + "{\"topics\": {\"t0\": [[0, 100000], [1, 50000], "
                + "[2, 60000]]}, \"subscriptions\": {\"C0\": [\"t0\"], "
                + "\"C1\": [\"t0\"]}, \"solver\": \"rounds\", "
                + "\"options\": {\"refine_iters\": 16}}}",
                TpuLagBasedPartitionAssignor.buildAssignRequest(
                        24,
                        new TreeMap<>(Collections.singletonMap(
                                "t0", Arrays.asList(
                                        new long[] {0, 100000},
                                        new long[] {1, 50000},
                                        new long[] {2, 60000}))),
                        readmeSubscriptions(),
                        "rounds",
                        Long.valueOf(16)));
    }

    private static Map<String, List<String>> readmeSubscriptions() {
        Map<String, List<String>> subs = new TreeMap<>();
        subs.put("C0", Collections.singletonList("t0"));
        subs.put("C1", Collections.singletonList("t0"));
        return subs;
    }

    @Test
    public void streamAssignRequestMatchesPinnedFixture() {
        // Byte-for-byte the "stream_assign_cold" fixture request line.
        assertEquals(
                "{\"id\": 20, \"method\": \"stream_assign\", \"params\": "
                + "{\"stream_id\": \"wire-s1\", \"topic\": \"t0\", "
                + "\"lags\": [[0, 100000], [1, 50000], [2, 60000]], "
                + "\"members\": [\"C1\", \"C0\"]}}",
                TpuLagBasedPartitionAssignor.buildStreamAssignRequest(
                        20, "wire-s1", "t0",
                        Arrays.asList(new long[] {0, 100000},
                                new long[] {1, 50000},
                                new long[] {2, 60000}),
                        Arrays.asList("C1", "C0"),
                        null));
    }

    @Test
    public void streamAssignRequestWithOptionsMatchesPinnedFixture() {
        // The "stream_assign_options_echoed" fixture's option set —
        // TreeMap ordering puts guardrail < refine_iters <
        // refine_threshold, matching the fixture line.
        Map<String, Object> options = new TreeMap<>();
        options.put("refine_iters", 100L);
        options.put("guardrail", null);
        options.put("refine_threshold", 1.5);
        assertEquals(
                "{\"id\": 21, \"method\": \"stream_assign\", \"params\": "
                + "{\"stream_id\": \"wire-s2\", \"topic\": \"t0\", "
                + "\"lags\": [[0, 7], [1, 5]], \"members\": [\"C0\"], "
                + "\"options\": {\"guardrail\": null, "
                + "\"refine_iters\": 100, \"refine_threshold\": 1.5}}",
                TpuLagBasedPartitionAssignor.buildStreamAssignRequest(
                        21, "wire-s2", "t0",
                        Arrays.asList(new long[] {0, 7}, new long[] {1, 5}),
                        Collections.singletonList("C0"),
                        options));
    }

    @Test
    public void streamResetRequestShape() {
        assertEquals(
                "{\"id\": 23, \"method\": \"stream_reset\", \"params\": "
                + "{\"stream_id\": \"never-created\"}}",
                TpuLagBasedPartitionAssignor.buildStreamResetRequest(
                        23, "never-created"));
    }

    @Test
    public void parsesStreamAssignResponse() throws Exception {
        TpuLagBasedPartitionAssignor.StreamResult r =
                TpuLagBasedPartitionAssignor.parseStreamAssignResponse(
                        "{\"id\": 20, \"result\": {\"assignments\": "
                        + "{\"C0\": [[\"t0\", 0]], \"C1\": [[\"t0\", 1], "
                        + "[\"t0\", 2]]}, \"stream\": {\"cold_start\": "
                        + "true, \"refined\": false, \"guardrail_tripped\":"
                        + " false, \"churn\": 0, \"repaired_rows\": 0}}}");
        assertTrue(r.coldStart);
        assertFalse(r.refined);
        assertFalse(r.guardrailTripped);
        assertEquals(0L, r.churn);
        assertEquals(Collections.singletonList(new TopicPartition("t0", 0)),
                r.assignments.get("C0"));
        assertEquals(Arrays.asList(new TopicPartition("t0", 1),
                new TopicPartition("t0", 2)), r.assignments.get("C1"));
    }

    @Test(expected = java.io.IOException.class)
    public void errorResponsesRaise() throws Exception {
        TpuLagBasedPartitionAssignor.parseAssignResponse(
                "{\"id\": 9, \"error\": {\"message\": \"boom\"}}");
    }

    // ------------------------------------------------------------------
    // Socket round-trip against an in-process fake sidecar: the streaming
    // client's full path (marshal -> TCP -> unmarshal) without Python.
    // ------------------------------------------------------------------

    @Test
    public void streamClientRoundTripsOverSocket() throws Exception {
        final String canned =
                "{\"id\": 1, \"result\": {\"assignments\": {\"C0\": "
                + "[[\"t0\", 0], [\"t0\", 1]]}, \"stream\": "
                + "{\"cold_start\": true, \"refined\": false, "
                + "\"guardrail_tripped\": false, \"churn\": 0}}}";
        final List<String> received =
                Collections.synchronizedList(new ArrayList<String>());
        try (ServerSocket server = new ServerSocket(0)) {
            Thread sidecar = new Thread(() -> {
                try (Socket sock = server.accept()) {
                    BufferedReader in = new BufferedReader(
                            new InputStreamReader(sock.getInputStream(),
                                    StandardCharsets.UTF_8));
                    BufferedWriter out = new BufferedWriter(
                            new OutputStreamWriter(sock.getOutputStream(),
                                    StandardCharsets.UTF_8));
                    received.add(in.readLine());
                    out.write(canned);
                    out.write('\n');
                    out.flush();
                } catch (Exception e) {
                    throw new RuntimeException(e);
                }
            });
            sidecar.start();

            TpuLagBasedPartitionAssignor assignor =
                    new TpuLagBasedPartitionAssignor();
            Map<String, Object> configs = new HashMap<>();
            configs.put("group.id", "test-group");
            configs.put(TpuLagBasedPartitionAssignor.SIDECAR_PORT_CONFIG,
                    Integer.toString(server.getLocalPort()));
            assignor.configure(configs);

            TpuLagBasedPartitionAssignor.StreamResult r =
                    assignor.streamAssign("s1", "t0",
                            Arrays.asList(new long[] {0, 10},
                                    new long[] {1, 5}),
                            Collections.singletonList("C0"), null);
            sidecar.join(5000);

            assertEquals(1, received.size());
            assertEquals(
                    "{\"id\": 1, \"method\": \"stream_assign\", "
                    + "\"params\": {\"stream_id\": \"s1\", \"topic\": "
                    + "\"t0\", \"lags\": [[0, 10], [1, 5]], \"members\": "
                    + "[\"C0\"]}}",
                    received.get(0));
            assertTrue(r.coldStart);
            assertEquals(Arrays.asList(new TopicPartition("t0", 0),
                    new TopicPartition("t0", 1)),
                    r.assignments.get("C0"));
        }
    }

    @Test
    public void requiresGroupId() {
        TpuLagBasedPartitionAssignor assignor =
                new TpuLagBasedPartitionAssignor();
        try {
            assignor.configure(new HashMap<String, Object>());
            throw new AssertionError("configure() must require group.id");
        } catch (IllegalArgumentException expected) {
            assertTrue(expected.getMessage().contains("group.id"));
        }
    }
}
