"""Compile-cache warm-up for latency-critical rebalances.

A rebalance is on the consumer group's critical path, but the FIRST solve
at a new padded shape pays an XLA compile — tens of seconds through a
remote-compile transport (this image: ~20-70 s/shape).  The shapes are
predictable, though: every kernel input is padded to power-of-two buckets
(:func:`.ops.packing.pad_bucket`), so a deployment can pre-compile every
shape it will ever see at startup, populating both the in-process jit
cache and (when ``jax_compilation_cache_dir`` is set) the persistent
on-disk cache shared across processes.

Usage (at consumer startup or image build, NOT inside a rebalance)::

    from kafka_lag_based_assignor_tpu.warmup import warmup
    shapes = warmup(max_partitions=100_000, consumers=[1000], topics=[1])

The warm-up runs each bucketed shape through the same jitted entry points
the rebalance path uses (batched rounds kernel, transfer-lean stream path,
and optionally the quality solvers), on synthetic data.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .ops.packing import pad_bucket
from .utils.observability import stopwatch

LOGGER = logging.getLogger(__name__)


def bucket_range(max_value: int, minimum: int = 8) -> List[int]:
    """All power-of-two buckets that inputs in [1, max_value] pad to."""
    buckets = []
    b = minimum
    while True:
        buckets.append(b)
        if b >= max_value:
            break
        b *= 2
    return buckets


def warmup(
    max_partitions: int,
    consumers: Sequence[int],
    topics: Sequence[int] = (1,),
    solvers: Sequence[str] = ("rounds", "stream"),
    all_partition_buckets: bool = False,
    sinkhorn_iters: int = 24,
    refine_iters: Optional[int] = None,
    stream_refine_iters: int = 128,
    coalesce_max_batch: int = 1,
    delta_buckets: int = 6,
    mesh_manager=None,
) -> List[Tuple[str, int, int, int, float]]:
    """Pre-compile kernels for every shape the deployment will see.

    Args:
      max_partitions: largest per-topic partition count expected.
      consumers: exact consumer-group sizes to warm (C is not bucketed —
        it is a static kernel parameter).
      topics: topic-batch sizes to warm for the batched kernels (bucketed).
      solvers: subset of {"rounds", "scan", "global", "stream",
        "sinkhorn", "linear"}.  The quality plane warms PER MODE
        (ops/dispatch quality routing): "sinkhorn" compiles the dense
        implicit-plan executables under a pinned scope, and the
        linear-space O(P + C) executables additionally warm when
        requested explicitly OR when ``resolve_quality_mode(P, C)``
        would route the shape to them (including the P-sharded duals
        program when ``mesh_manager`` is active — recorded as
        ``("sharded_linear", D, P, C, s)`` rows).
      all_partition_buckets: warm every bucket up to the max (True) or only
        the single bucket ``max_partitions`` pads to (default — smaller
        shapes still trigger one compile each on first sight).
      sinkhorn_iters / refine_iters: must match the production config
        (they are static jit parameters; different values = new compile).
        The defaults mirror the production defaults (utils/config.py):
        iters=24, refine_iters=None = the per-path auto budget — the
        warm-up goes through the same public solver wrapper that resolves
        the auto rule, so default warm-up compiles exactly the executables
        a default-config rebalance uses.  For the parity solvers
        ("rounds"/"scan"), an explicit refine_iters warms the REFINED
        executable variant (the one-shot quality mode is a different
        static-arg compile than plain parity) — exactly what a
        ``tpu.assignor.refine.iters`` deployment dispatches.
      stream_refine_iters: the StreamingAssignor exchange budget to warm —
        the "stream" warm-up drives a cold + warm + repair-invalidated
        rebalance sequence so the cold :func:`..ops.batched.assign_stream`
        compile, the cold table-build+refine chain, AND both fused warm
        executables (:func:`..ops.streaming._warm_fused_resident` /
        ``_warm_fused_build``, at the padded bucket shape) happen here,
        not on any rebalance's critical path.  Must match the production
        ``refine_iters`` passed to
        :class:`..ops.streaming.StreamingAssignor` (iters, pairs, and
        exchange budget are static args — a different budget is a
        different executable).
      coalesce_max_batch: > 1 additionally warms the MEGABATCH
        executables (ops/coalesce) the sidecar dispatches when several
        streams coalesce: one synthetic multi-stream wave pair per
        batch-pow2 bucket (2, 4, ... up to the cap) drives both the
        re-stack executable and the roster-LOCKED executable at each
        (shape bucket, batch bucket), so the first coalesced wave of a
        scaled-out deployment never pays its compile on the serving
        path.  Must match the production ``coalesce_max_batch`` /
        ``stream_refine_iters`` (batch bucket and exchange budget are
        both part of the executable signature).  Recorded as
        ``("coalesce", batch_bucket, P, C, seconds)`` rows.
      mesh_manager: an ACTIVE :class:`..sharded.mesh.MeshManager` warms
        the P-axis-sharded cold-solve executable at this mesh size
        (per-mesh-size executables: the sharded program is one compile
        per (mesh, bucket, C, budget)) — the LINEAR quality variant
        the streaming cold hook dispatches unless the quality mode is
        pinned "sinkhorn" (recorded as ``("sharded_linear", D, P, C,
        s)`` / ``("sharded", D, P, C, s)`` rows accordingly).  Shapes
        the manager elects for the P backend ALSO warm the P-sharded
        RESIDENT placement variants (the fused warm executables
        recompile for sharded inputs; ``("sharded_resident", D, P, C,
        s)`` rows).  The stream-sharded — and, on the 2-D rung, the
        cross-axis ("streams", "p") — MEGABATCH variants warm through
        the ``coalesce`` jobs automatically while the manager is the
        process-active one (the warm-up waves lock onto the sharded
        placement exactly like production waves).  None skips.
      delta_buckets: > 0 additionally warms the DELTA-EPOCH executables
        (ops/streaming "delta epochs"): one synthetic delta dispatch
        per pow2 K rung of the ladder on the inline path (rungs whose
        padded upload would not beat the dense payload at this shape
        are skipped — production skips them identically), and — with
        ``coalesce_max_batch > 1`` — one stacked delta WAVE per batch
        bucket on the roster-locked megabatch path (which always pads
        to the ladder top; the wave rides inside each ``coalesce``
        job).  Must match the production ``delta_buckets`` knob; 0
        skips (delta disabled).  Inline rungs are recorded as
        ``("stream_delta", K, P, C, s)`` rows.

    Returns a list of (solver, T, P_bucket, C, seconds) for each shape
    compiled.  Failures are logged and skipped — warm-up must never take a
    deployment down.
    """
    from .ops.batched import (
        assign_batched_rounds,
        assign_batched_scan,
        totals_rank_bits_for,
    )
    from .ops.dispatch import ensure_x64
    from .ops.rounds_kernel import assign_global_rounds
    from .ops.scan_kernel import pack_shift_for
    from .utils.observability import install_compile_counter

    ensure_x64()
    # Boot-time tile autotune BEFORE any quality job compiles: the
    # jobs below must warm the geometry production will actually run
    # (on CPU this records the static default and changes nothing).
    from .ops.dispatch import autotune_quality_tile

    autotune_quality_tile()
    # Compiles from here on are observable: deployments (and the bench)
    # snapshot utils/observability.compile_count() after warm-up and
    # assert the steady-state loop's delta is ZERO.
    install_compile_counter()
    p_buckets = (
        bucket_range(max_partitions)
        if all_partition_buckets
        else [pad_bucket(max_partitions)]
    )
    t_buckets = sorted({pad_bucket(t, minimum=1) for t in topics})

    done: List[Tuple[str, int, int, int, float]] = []
    rng = np.random.default_rng(0)
    for P in p_buckets:
        lags1d = rng.integers(0, 1000, size=P).astype(np.int64)
        pids1d = np.arange(P, dtype=np.int32)
        for C in consumers:
            jobs = []
            if "stream" in solvers:

                def stream_job(lags1d=lags1d, C=C):
                    # Cold + warm pair through the production engine: the
                    # cold call compiles assign_stream AND the cold-chain
                    # table-build + resident-refine executable (its
                    # iters/max_pairs static args differ from the warm
                    # path's, so it is a separate compile); the warm call
                    # compiles the fused warm RESIDENT executable at the
                    # padded bucket shape with the production exchange
                    # budget.  refine_threshold=None forces the warm
                    # dispatch — with the default threshold a warm-up
                    # epoch on unchanged lags would skip it (the no-op
                    # fast path) and leave the warm executable cold.
                    from .ops.batched import assign_stream
                    from .ops.rounds_pallas import rounds_pallas_available
                    from .ops.streaming import StreamingAssignor

                    # Resolve the Pallas round-scan gate here (parity +
                    # speed race on the device, several compiles) so no
                    # rebalance ever pays it; assign_stream below then
                    # warms whichever kernel the gate selected.
                    rounds_pallas_available(run_probe=True)
                    # delta_enabled=False pins THIS job's warm dispatches
                    # to the DENSE executables (an enabled engine would
                    # route its unchanged-lags warm epoch through the
                    # K=16 delta variant and leave the dense one cold);
                    # the delta ladder warms via its own jobs below.
                    # mesh_backend=None pins THIS job's cold solves to
                    # the SINGLE-device chain even while a mesh manager
                    # is active: the single-device executables are the
                    # mesh's degradation target and must be warm
                    # regardless (the sharded program warms via its own
                    # job below).
                    engine = StreamingAssignor(
                        num_consumers=C, refine_iters=stream_refine_iters,
                        refine_threshold=None, delta_enabled=False,
                        mesh_backend=None,
                    )
                    engine.rebalance(lags1d)
                    out = engine.rebalance(lags1d)
                    # The table-BUILDING fused variant serves epochs whose
                    # resident state is stale (membership repair, remap):
                    # an identity remap invalidates the device state
                    # without moving a row, so the next warm dispatch
                    # compiles exactly that executable.
                    engine.remap_members(np.arange(C, dtype=np.int32), C)
                    engine.rebalance(lags1d)
                    # Warm-restart recovery (service._recover) replays
                    # seed_choice + rebalance: a host-seeded choice with
                    # stale device state, the same table-build
                    # executable as the repair epoch above — driven
                    # explicitly so the recovery path stays pinned to
                    # warmed code even if the two variants ever drift.
                    engine.seed_choice(np.asarray(out))
                    engine.rebalance(lags1d)
                    # Pre-stacked recovery (service recovery_prestack /
                    # --recovery-prestack) replays seed_choice ->
                    # prestack_resident (zero-lag table build) -> a
                    # dense RESIDENT dispatch.  Both executables are
                    # compiled by the epochs above today; driven
                    # explicitly so the prestacked boot path stays
                    # pinned warm even if the variants ever drift.
                    engine.seed_choice(np.asarray(out))
                    engine.prestack_resident()
                    engine.rebalance(lags1d)
                    # Quarantine -> heal replay (utils/scrub): a failed
                    # integrity check drops the resident state and the
                    # next epoch rebuilds it from the host previous
                    # choice — the same table-BUILD executable the
                    # repair/seed_choice epochs above compiled, driven
                    # explicitly so the heal path stays pinned warm
                    # even if the variants ever drift.
                    # record=False: a synthetic drill must not show up
                    # in the production quarantine counters per boot.
                    engine.quarantine_resident(
                        ["choice"], source="warmup", record=False
                    )
                    engine.rebalance(lags1d)
                    # assign_stream downcasts the upload to int32 when the
                    # lag range allows; ALSO warm the wide-lag (int64)
                    # variants of both the stream kernel and the fused
                    # warm refine so a later rebalance whose lags exceed
                    # int32 doesn't hit a fresh compile mid-rebalance.
                    wide = lags1d + (np.int64(1) << 32)
                    assign_stream(wide, num_consumers=C)
                    engine.rebalance(wide)
                    # Wide COLD chain too (guardrail trips re-solve cold
                    # with whatever lags the epoch has; its refine iters
                    # are a different static arg than the warm path's).
                    engine.reset()
                    engine.rebalance(wide)
                    return out

                jobs.append(("stream", 1, stream_job))
            if (
                "stream" in solvers
                and mesh_manager is not None
                and mesh_manager.active
            ):

                from .ops import dispatch as _dispatch_mod

                sharded_linear = _dispatch_mod.quality_mode() != "sinkhorn"

                def sharded_job(
                    lags1d=lags1d, C=C, linear=sharded_linear
                ):
                    # The production cold hook dispatches the sharded
                    # backend with the engine's cold budget
                    # (StreamingAssignor default — _fresh_engine) when
                    # the manager elects this shape — the LINEAR
                    # quality variant unless the mode is pinned
                    # "sinkhorn" (ops/streaming._sharded_cold_solve);
                    # warm exactly that executable.  A shape below the
                    # manager's row floor warms nothing it will never
                    # serve — the solve still runs (cheap) so the
                    # (mesh, bucket) program exists if an operator
                    # lowers the floor.
                    from .ops.streaming import StreamingAssignor
                    from .sharded.solve import (
                        solve_linear_sharded,
                        solve_sharded,
                    )

                    budget = StreamingAssignor(
                        num_consumers=C
                    ).cold_refine_iters
                    solver = (
                        solve_linear_sharded if linear else solve_sharded
                    )
                    out = solver(
                        mesh_manager.solve_mesh(), lags1d, C,
                        refine_iters=budget,
                    )
                    return out[0]

                jobs.append(
                    (
                        "sharded_linear" if sharded_linear else "sharded",
                        mesh_manager.size,
                        sharded_job,
                    )
                )
            if (
                "stream" in solvers
                and mesh_manager is not None
                and mesh_manager.active
                and mesh_manager.should_shard_solve(P)
            ):

                def resident_job(lags1d=lags1d, C=C):
                    # P-sharded RESIDENT placement (sharded/resident):
                    # a fused warm executable's jit cache keys include
                    # the input SHARDINGS, so the placed choice/lags
                    # buffers are a separate compile from the
                    # single-device twins stream_job warmed.  Drive
                    # cold + dense warm + delta epochs with the active
                    # manager as the engine's backend — exactly the
                    # placement the production adopt hook applies —
                    # so every sharded-input variant compiles here.
                    from .ops.streaming import StreamingAssignor

                    eng = StreamingAssignor(
                        num_consumers=C,
                        refine_iters=stream_refine_iters,
                        refine_threshold=None,
                        delta_enabled=delta_buckets > 0,
                        delta_max_fraction=1.0,
                        delta_buckets=max(delta_buckets, 1),
                        mesh_backend=mesh_manager,
                    )
                    cur = lags1d.copy()
                    eng.rebalance(cur)
                    cur = cur + 1  # dense warm epoch, placed resident
                    out = eng.rebalance(cur)
                    if delta_buckets > 0:
                        nxt = cur.copy()
                        nxt[:8] = nxt[:8] + 1 + (np.arange(8) % 7)
                        out = eng.rebalance(nxt)
                    return out

                jobs.append(
                    ("sharded_resident", mesh_manager.size, resident_job)
                )
            if "stream" in solvers and delta_buckets > 0:
                from .ops.streaming import delta_k_ladder

                for K in delta_k_ladder(delta_buckets):
                    if K > P:
                        break

                    def delta_job(lags1d=lags1d, C=C, K=K):
                        # One synthetic delta dispatch at exactly this K
                        # rung: seed the resident lag buffer with two
                        # dense epochs (executables already warmed by
                        # stream_job), then change exactly K entries so
                        # the host differ buckets to K.  fraction=1.0
                        # forces eligibility at any warmed shape; the
                        # bytes gate still applies, exactly as it will
                        # in production at this shape — an ineligible
                        # rung dispatches the (warm) dense executable
                        # and costs nothing new.
                        from .ops.streaming import StreamingAssignor

                        eng = StreamingAssignor(
                            num_consumers=C,
                            refine_iters=stream_refine_iters,
                            refine_threshold=None,
                            delta_max_fraction=1.0,
                            delta_buckets=delta_buckets,
                            mesh_backend=None,
                        )
                        cur = lags1d.copy()
                        eng.rebalance(cur)
                        eng.rebalance(cur)
                        nxt = cur.copy()
                        nxt[:K] = nxt[:K] + 1 + (np.arange(K) % 7)
                        return eng.rebalance(nxt)

                    jobs.append(("stream_delta", K, delta_job))
            if "stream" in solvers and coalesce_max_batch > 1:
                # Megabatch coverage: one synthetic multi-stream wave
                # pair per batch-pow2 bucket — wave 1 compiles the
                # re-stack executable (and locks the roster), wave 2
                # compiles the roster-LOCKED executable, so neither is
                # ever paid on the serving path (ops/coalesce).
                n = 2
                while n <= coalesce_max_batch:

                    def coalesce_job(lags1d=lags1d, C=C, n=n):
                        import threading

                        from .ops.coalesce import MegabatchCoalescer
                        from .ops.streaming import (
                            StreamingAssignor,
                            delta_k_ladder,
                        )

                        rng_j = np.random.default_rng(n)
                        engines = [
                            StreamingAssignor(
                                num_consumers=C,
                                refine_iters=stream_refine_iters,
                                refine_threshold=None,
                                delta_max_fraction=1.0,
                                delta_buckets=max(delta_buckets, 1),
                                mesh_backend=None,
                            )
                            for _ in range(n)
                        ]
                        for eng in engines:
                            eng.rebalance(lags1d)
                        # The production stacked-delta K (the ladder
                        # top); 0 keeps the delta wave dense-only.
                        ladder = delta_k_ladder(delta_buckets)
                        delta_k = ladder[-1] if ladder else 0
                        coal = MegabatchCoalescer(
                            window_s=2.0, max_batch=n, lock_waves=1,
                            delta_k=delta_k,
                        )
                        # Mixed SLO placement (utils/overload): the
                        # warm-up waves submit under alternating
                        # classes with far-future deadlines, so the
                        # deadline-ordered flush path (class-rank sort
                        # + deadline triage) runs here too — host-side
                        # code, but the one wave shape production
                        # serves must be the one warm-up drove.
                        from .utils.metrics import REGISTRY
                        from .utils.overload import SLO_CLASSES, class_rank

                        out = None
                        try:
                            # Wave 1 compiles the re-stack executable
                            # (and locks the roster), wave 2 the locked
                            # DENSE executable, wave 3 — every row a
                            # small perturbation of wave 2, so every
                            # engine submits a delta plan — the locked
                            # DELTA executable (skipped bucket-
                            # consistently when the stacked delta would
                            # not beat dense at this shape, exactly as
                            # production skips it).
                            waves = 3 if delta_k > 0 else 2
                            arrs = None
                            for _wave in range(waves):
                                if _wave < 2:
                                    arrs = [
                                        rng_j.integers(
                                            0, 1000, lags1d.shape[0]
                                        ).astype(np.int64)
                                        for _ in engines
                                    ]
                                else:
                                    arrs = [
                                        a + np.where(
                                            np.arange(a.shape[0]) < 8,
                                            1, 0,
                                        )
                                        for a in arrs
                                    ]
                                errs = []

                                def run(eng, arr, i=0):
                                    klass = SLO_CLASSES[i % len(SLO_CLASSES)]
                                    try:
                                        eng.submit_epoch(
                                            arr, coal,
                                            slo_class=klass,
                                            rank=class_rank(klass),
                                            deadline_at=(
                                                REGISTRY.clock() + 600.0
                                            ),
                                        )
                                    except Exception as exc:  # noqa: L011
                                        errs.append(exc)  # re-raised below

                                threads = [
                                    threading.Thread(
                                        target=run, args=(eng, arr, i)
                                    )
                                    for i, (eng, arr) in enumerate(
                                        zip(engines, arrs)
                                    )
                                ]
                                for t in threads:
                                    t.start()
                                for t in threads:
                                    t.join()
                                if errs:
                                    raise errs[0]
                                out = arrs
                        finally:
                            coal.close()
                        return out

                    jobs.append(("coalesce", n, coalesce_job))
                    n *= 2
            if "sinkhorn" in solvers or "linear" in solvers:
                # PER-MODE quality jobs (ops/dispatch quality routing):
                # the dense Sinkhorn executables warm under a pinned
                # "sinkhorn" scope (so an auto-routed process still
                # compiles the dense variant it serves below the linear
                # floor), and the linear-space executables warm when
                # they are explicitly requested OR when the dispatch
                # layer would route this (P, C) to them — exactly the
                # executables production dispatches, nothing more.
                from .ops import dispatch as dispatch_mod
                from .models.sinkhorn import assign_topic_sinkhorn

                valid1d = np.ones(P, dtype=bool)
                want_linear = "linear" in solvers or (
                    "sinkhorn" in solvers
                    and dispatch_mod.resolve_quality_mode(P, C) == "linear"
                )
                if "sinkhorn" in solvers and (
                    dispatch_mod.quality_mode() != "linear"
                ):

                    def sinkhorn_job(lags1d=lags1d, C=C):
                        with dispatch_mod.quality_scope("sinkhorn"):
                            return assign_topic_sinkhorn(
                                lags1d, pids1d, valid1d,
                                num_consumers=C, iters=sinkhorn_iters,
                                refine_iters=refine_iters,
                            )

                    jobs.append(("sinkhorn", 1, sinkhorn_job))
                if want_linear:

                    def linear_job(lags1d=lags1d, C=C):
                        from .ops.linear_ot import assign_topic_linear
                        from .ops.linear_ot_pallas import (
                            linear_pallas_available,
                        )

                        # Resolve the linear-OT kernel-plane gate here
                        # (duals parity + speed race AND the digest
                        # parity, several compiles on the device) so no
                        # rebalance ever pays it; the solve below then
                        # warms whichever lowering the gate selected.
                        linear_pallas_available(run_probe=True)
                        return assign_topic_linear(
                            lags1d, pids1d, valid1d, num_consumers=C,
                            iters=sinkhorn_iters,
                            refine_iters=refine_iters,
                        )

                    jobs.append(("linear", 1, linear_job))
            for T in t_buckets:
                lags = np.broadcast_to(lags1d, (T, P)).copy()
                pids = np.broadcast_to(pids1d, (T, P)).copy()
                valid = np.ones((T, P), dtype=bool)
                # Production dispatch (ops/dispatch.assign_group_device)
                # derives pack_shift AND totals_rank_bits from the group's
                # value ranges — warm the SAME static-arg variants, or the
                # warmed executable is never hit.  Dense pids 0..P-1 give
                # the same shift as production dense groups; realistic
                # lags stay under the packing/overflow bounds, so both
                # helpers return the same values for both (rank bits
                # depend only on C unless the lag sum nears 2^61).
                shift = pack_shift_for(int(lags.max()), int(pids.max()))
                rb = totals_rank_bits_for(lags, C)
                rb_g = totals_rank_bits_for(lags.reshape(1, -1), C)
                # The quality mode is a different static-arg executable:
                # warm the variant production will actually dispatch
                # (assignor._solve_accelerated passes the configured
                # refine budget to assign_device for rounds/scan).  Pass
                # the kwarg only when ON — jit cache keys include WHICH
                # kwargs were passed (ops/dispatch does the same).
                parity_refine = (
                    {"refine_iters": int(refine_iters)}
                    if refine_iters else {}
                )
                if "rounds" in solvers:
                    jobs.append(
                        (
                            "rounds",
                            T,
                            lambda lags=lags, pids=pids, valid=valid,
                            shift=shift, rb=rb, ri=parity_refine: (
                                assign_batched_rounds(
                                    lags, pids, valid, num_consumers=C,
                                    pack_shift=shift, totals_rank_bits=rb,
                                    **ri,
                                )
                            ),
                        )
                    )
                if "scan" in solvers:
                    jobs.append(
                        (
                            "scan",
                            T,
                            lambda lags=lags, pids=pids, valid=valid,
                            ri=parity_refine: (
                                assign_batched_scan(
                                    lags, pids, valid, num_consumers=C,
                                    **ri,
                                )
                            ),
                        )
                    )
                if "global" in solvers:
                    jobs.append(
                        (
                            "global",
                            T,
                            lambda lags=lags, pids=pids, valid=valid,
                            shift=shift, rb_g=rb_g: (
                                assign_global_rounds(
                                    lags, pids, valid, num_consumers=C,
                                    pack_shift=shift, totals_rank_bits=rb_g,
                                )
                            ),
                        )
                    )
            for name, T, job in jobs:
                ok = True
                with stopwatch() as t:
                    try:
                        import jax

                        jax.block_until_ready(job())
                    except Exception:
                        LOGGER.warning(
                            "warmup %s T=%d P=%d C=%d failed (skipped)",
                            name, T, P, C, exc_info=True,
                        )
                        ok = False
                if not ok:
                    continue
                secs = t[0] / 1000.0
                done.append((name, T, P, C, secs))
                LOGGER.info(
                    "warmup %s T=%d P=%d C=%d in %.1fs", name, T, P, C, secs
                )
    return done
