"""Test doubles: an in-memory broker client and cluster builder.

The reference never tested its I/O shell (SURVEY §4 coverage gaps); this
fake implements the :class:`..lag.MetadataConsumer` protocol so the lag
reader and the full plugin adapter are testable without a broker — and it
doubles as the synthetic-workload source for benchmarks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Mapping, Optional, Sequence, Set

from .types import Cluster, OffsetAndMetadata, PartitionInfo, TopicPartition


@dataclass
class FakeBroker:
    """In-memory offsets store implementing the MetadataConsumer protocol.

    ``raise_on`` simulates broker RPC failures: any listed method raises,
    letting tests assert that exceptions propagate and fail the rebalance
    (reference has no try/catch around the RPCs, SURVEY §2.4.9).
    """

    begin: Dict[TopicPartition, int] = field(default_factory=dict)
    end: Dict[TopicPartition, int] = field(default_factory=dict)
    committed_offsets: Dict[TopicPartition, Optional[OffsetAndMetadata]] = field(
        default_factory=dict
    )
    raise_on: Set[str] = field(default_factory=set)
    calls: list = field(default_factory=list)

    def beginning_offsets(
        self, partitions: Sequence[TopicPartition]
    ) -> Mapping[TopicPartition, int]:
        self.calls.append("beginning_offsets")
        if "beginning_offsets" in self.raise_on:
            raise TimeoutError("simulated broker timeout (ListOffsets)")
        return {tp: self.begin.get(tp, 0) for tp in partitions}

    def end_offsets(
        self, partitions: Sequence[TopicPartition]
    ) -> Mapping[TopicPartition, int]:
        self.calls.append("end_offsets")
        if "end_offsets" in self.raise_on:
            raise TimeoutError("simulated broker timeout (ListOffsets)")
        return {tp: self.end.get(tp, 0) for tp in partitions}

    def committed(
        self, partitions: Set[TopicPartition]
    ) -> Mapping[TopicPartition, Optional[OffsetAndMetadata]]:
        self.calls.append("committed")
        if "committed" in self.raise_on:
            raise TimeoutError("simulated broker timeout (OffsetFetch)")
        return {tp: self.committed_offsets.get(tp) for tp in partitions}

    # -- builder helpers ---------------------------------------------------

    def with_partition(
        self,
        topic: str,
        partition: int,
        end: int,
        committed: Optional[int] = None,
        begin: int = 0,
    ) -> "FakeBroker":
        tp = TopicPartition(topic, partition)
        self.begin[tp] = begin
        self.end[tp] = end
        if committed is not None:
            self.committed_offsets[tp] = OffsetAndMetadata(committed)
        return self

    def cluster(self) -> Cluster:
        """A Cluster whose metadata covers every partition this broker knows."""
        topics: Dict[str, list] = {}
        for tp in self.end:
            topics.setdefault(tp.topic, []).append(
                PartitionInfo(tp.topic, tp.partition)
            )
        for infos in topics.values():
            infos.sort(key=lambda p: p.partition)
        return Cluster(topics)


# -- shared overload/chaos assertions -------------------------------------
#
# One walker over klba_shed_total and one count-balance invariant, shared
# by bench.py's overload gates and the chaos/overload test suites — a
# shed-label schema change that updated only one hand-rolled copy would
# silently skew the others' per-class totals and weaken the very gates
# (critical-never-shed, bottom-up shedding) they enforce.


def shed_totals_by_class() -> Dict[Optional[str], float]:
    """Current ``klba_shed_total`` value per class, summed over rungs."""
    from .utils import metrics

    out: Dict[Optional[str], float] = {}
    for counter in metrics.REGISTRY.series("klba_shed_total"):
        klass = counter.labels.get("class")
        out[klass] = out.get(klass, 0) + counter.value
    return out


def assert_valid_assignment(assignments, expect_partitions: int) -> None:
    """Count-balanced (max - min <= 1), complete, no duplicates."""
    sizes = [len(v) for v in assignments.values()]
    got = [tuple(tp) for tps in assignments.values() for tp in tps]
    assert sorted(got) == sorted(set(got)), "duplicate partitions"
    assert len(got) == expect_partitions, (len(got), expect_partitions)
    assert max(sizes) - min(sizes) <= 1, sizes


def choice_from_assignments(assignments, members, partitions: int):
    """Decode a wire ``assignments`` dict back into the dense
    partition->consumer-index vector the engine reasons in (int32[P],
    -1 for unassigned) — the shape bit-exactness comparisons and churn
    measurements need.  Shared by bench.py's restart probe and the
    scenario fleet's replay engine so the two decoders cannot drift."""
    import numpy as np

    midx = {m: j for j, m in enumerate(members)}
    choice = np.full(partitions, -1, np.int32)
    for m, tps in assignments.items():
        for _t, p in tps:
            choice[p] = midx[m]
    return choice


def moved_fraction(prev_choice, choice) -> float:
    """Fraction of partitions whose owner changed between two epochs'
    decoded choice vectors (the wire-level churn observable)."""
    import numpy as np

    prev = np.asarray(prev_choice)
    cur = np.asarray(choice)
    if prev.shape != cur.shape or prev.size == 0:
        return 1.0
    return float(np.count_nonzero(prev != cur)) / prev.size
