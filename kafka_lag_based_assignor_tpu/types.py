"""Core value types for the TPU-native lag-based partition assignor.

These mirror the slice of the Kafka client object model that the reference
plugin touches (reference: LagBasedPartitionAssignor.java:28-35 imports), plus
the one value type the reference defines itself:

* ``TopicPartition``        — org.apache.kafka.common.TopicPartition
* ``TopicPartitionLag``     — LagBasedPartitionAssignor.java:431-455
* ``PartitionInfo``         — org.apache.kafka.common.PartitionInfo (topic/partition slice)
* ``OffsetAndMetadata``     — org.apache.kafka.clients.consumer.OffsetAndMetadata (offset slice)
* ``Cluster``               — org.apache.kafka.common.Cluster (partitionsForTopic slice)
* ``Subscription`` / ``GroupSubscription`` / ``Assignment`` / ``GroupAssignment``
                            — ConsumerPartitionAssignor protocol value types used by
                              assign() (LagBasedPartitionAssignor.java:138-157)

Everything here is plain host-side Python: frozen dataclasses, hashable where
the reference type is used as a map key.  No JAX.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence


@dataclass(frozen=True, order=True)
class TopicPartition:
    """A (topic, partition) pair; hashable, usable as a dict key."""

    topic: str
    partition: int

    def __str__(self) -> str:  # matches Kafka's "topic-partition" rendering
        return f"{self.topic}-{self.partition}"


@dataclass(frozen=True)
class TopicPartitionLag:
    """A (topic, partition, lag) triple — the element type of the core's input.

    Reference: LagBasedPartitionAssignor.java:431-455.  Unlike the reference's
    mutable-list-of-objects representation, the TPU core consumes columnar
    arrays; this type exists for the host-side API surface and tests.
    """

    topic: str
    partition: int
    lag: int

    def topic_partition(self) -> TopicPartition:
        return TopicPartition(self.topic, self.partition)


@dataclass(frozen=True)
class PartitionInfo:
    """Slice of org.apache.kafka.common.PartitionInfo the assignor reads."""

    topic: str
    partition: int


@dataclass(frozen=True)
class OffsetAndMetadata:
    """Slice of OffsetAndMetadata the assignor reads (just the offset)."""

    offset: int
    metadata: str = ""


@dataclass(frozen=True)
class Cluster:
    """Slice of org.apache.kafka.common.Cluster used by the assignor.

    Only ``partitions_for_topic`` is consumed (reference :329).  Topics absent
    from ``partitions_by_topic`` return None, matching the reference's
    null-metadata branch (:358-360).
    """

    partitions_by_topic: Mapping[str, Sequence[PartitionInfo]] = field(
        default_factory=dict
    )

    def partitions_for_topic(self, topic: str) -> Optional[Sequence[PartitionInfo]]:
        return self.partitions_by_topic.get(topic)


@dataclass(frozen=True)
class Subscription:
    """A member's subscription: the topics it wants (reference :143)."""

    topics: Sequence[str]


@dataclass(frozen=True)
class GroupSubscription:
    """member id -> Subscription (reference :142)."""

    group_subscription: Mapping[str, Subscription]


@dataclass(frozen=True)
class Assignment:
    """The per-member result wrapper; the reference attaches no user data
    (reference :151-155)."""

    partitions: Sequence[TopicPartition]


@dataclass(frozen=True)
class GroupAssignment:
    """member id -> Assignment (reference :156)."""

    group_assignment: Mapping[str, Assignment]


# Convenience aliases used across the package.
LagMap = Dict[str, List[TopicPartitionLag]]  # topic -> per-partition lag rows
SubscriptionMap = Dict[str, List[str]]  # member id -> subscribed topics
AssignmentMap = Dict[str, List[TopicPartition]]  # member id -> assigned partitions
