"""TPU-native lag-balanced Kafka partition-assignment framework.

A ground-up JAX/XLA re-design of the capabilities of
``com.github.grantneale:kafka-lag-based-assignor`` (reference under
/root/reference): a ``partition.assignment.strategy`` plugin whose
combinatorial core — per-topic greedy LPT multiway number partitioning in
descending-lag order with count-primary / lag-secondary / member-id-tertiary
selection — runs as a batched TPU kernel, while lag acquisition and group
bookkeeping stay host-side.

Layers (mirroring SURVEY.md §1):
* L1 plugin adapter  — :mod:`.assignor` (configure / name / assign)
* L2 lag acquisition — :mod:`.lag` (broker RPC shell + pure lag formula)
* L3 assignment core — :mod:`.models.greedy` (host oracle),
                       :mod:`.ops` (TPU kernels),
                       :mod:`.models.sinkhorn` (OT relaxation)
* parallel           — :mod:`.parallel` (device-mesh sharding)
* utils              — :mod:`.utils` (config, structured observability)
"""

from .types import (
    Assignment,
    Cluster,
    GroupAssignment,
    GroupSubscription,
    OffsetAndMetadata,
    PartitionInfo,
    Subscription,
    TopicPartition,
    TopicPartitionLag,
)
from .lag import compute_partition_lag, read_topic_partition_lags
from .models.greedy import assign_greedy, consumers_per_topic
from .assignor import LagBasedPartitionAssignor
from .utils.config import AssignorConfig, parse_config
from .utils.observability import RebalanceStats

__version__ = "0.1.0"

__all__ = [
    "Assignment",
    "Cluster",
    "GroupAssignment",
    "GroupSubscription",
    "OffsetAndMetadata",
    "PartitionInfo",
    "Subscription",
    "TopicPartition",
    "TopicPartitionLag",
    "compute_partition_lag",
    "read_topic_partition_lags",
    "assign_greedy",
    "consumers_per_topic",
    "LagBasedPartitionAssignor",
    "AssignorConfig",
    "parse_config",
    "RebalanceStats",
    "__version__",
]
