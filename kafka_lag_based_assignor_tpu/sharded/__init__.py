"""Mesh-native multi-device backend (first-class, tier-1 tested).

One subsystem for every topology decision (lint L020 confines
``Mesh``/``shard_map``/``NamedSharding`` construction here):

* :mod:`.mesh` — the mesh manager: discover/validate once at service
  start (``tpu.assignor.mesh.devices``), degrade to single-device on a
  lost device or a ``mesh.collective`` fault.
* :mod:`.solve` — the P-axis-sharded solve (seed sort + plan stats +
  exchange refine; replicated consumer-axis state all-reduced per
  round; bit-identical to ops/refine at mesh size 1).
* :mod:`.megabatch` — stream-axis placement for the roster-locked
  megabatch (N tenants spread over D devices, zero collectives).
* :mod:`.topics` — the topic-axis batch backend (absorbed from the old
  ``parallel/`` side module).

Backend selection lives in :mod:`..ops.dispatch`
(``sharded_solve_manager``): single-device remains the default and the
degradation target.  Tier-1 runs every sharded path on the virtual
8-device CPU mesh (``XLA_FLAGS=--xla_force_host_platform_device_count``,
forced by tests/conftest.py).
"""

from .mesh import (
    MeshCollectiveError,
    MeshManager,
    activate,
    active_manager,
    deactivate,
    managed,
)
from .solve import (
    plan_stats_sharded,
    refine_sharded,
    seed_reference,
    solve_sharded,
)

__all__ = [
    "MeshCollectiveError",
    "MeshManager",
    "activate",
    "active_manager",
    "deactivate",
    "managed",
    "plan_stats_sharded",
    "refine_sharded",
    "seed_reference",
    "solve_sharded",
]
