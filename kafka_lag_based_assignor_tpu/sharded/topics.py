"""Topic-axis mesh backend: shard a topic batch over a device mesh.

Absorbed from the old ``parallel/mesh.py`` side module into the
``sharded/`` subsystem (``parallel.mesh`` remains as an import shim):
the topic axis is the data-parallel dimension of the BATCHED solve —
per-topic assignment is independent (SURVEY §2.4.3), so a topic batch
[T, P] shards across devices with zero communication in the solve
itself, and the per-member global stats reduce with ``psum``.  The
PARTITION axis of one huge solve shards in :mod:`.solve`; the
megabatch's stream axis in :mod:`.megabatch`.

Scaling model (the framework's analog of the scaling-book recipe — pick
a mesh, annotate shardings, let XLA insert collectives):

* **topic axis ("topics")** — data parallel; rides ICI when T outgrows
  one chip.
* **member axis ("members")** — the tensor-parallel analog: the [T, C]
  totals reduce over topics with ``psum``; the resulting [C] vector is
  computed shard-locally over a member-axis sharding, so at very large
  C no device materializes all members' accumulators during stats.

The greedy solve inside one topic is sequential over rounds (inherent
to LPT), so it is never split across devices — sequential depth stays
on-chip where it is cheap, and the mesh buys throughput across topics.

Everything compiles under ``jit`` over a ``jax.sharding.Mesh``; tested
on the virtual 8-device CPU mesh (tests/test_parallel.py) and dry-run
by the driver via ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.rounds_kernel import assign_topic_rounds
from .mesh import CHECK_KW, shard_map as _shard_map


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    topics_axis: Optional[int] = None,
    members_axis: int = 1,
) -> Mesh:
    """Build a 2D ("topics", "members") mesh.

    Default: all topic parallelism — ("topics", 1).  Pass ``members_axis``
    > 1 to carve devices for the member-axis stats sharding.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if topics_axis is None:
        topics_axis = n // members_axis
    if topics_axis * members_axis != n:
        raise ValueError(
            f"mesh {topics_axis}x{members_axis} != {n} devices"
        )
    dev_array = np.asarray(devices).reshape(topics_axis, members_axis)
    return Mesh(dev_array, axis_names=("topics", "members"))


def _sharded_step(
    lags, partition_ids, valid, *, num_consumers: int, members_axis: int,
    refine_iters: int = 0,
):
    """Per-shard body under shard_map: local topic block [T_loc, P] solved
    with the vmapped rounds kernel, then cross-shard psum for global stats.

    ``refine_iters`` chains the per-topic exchange refinement onto each
    local topic — refinement is per-topic like the solve itself, so it
    shards over the "topics" axis with ZERO additional communication (the
    stats psum below already reflects the refined totals).

    The member-axis devices each reduce only their C/members_axis slice of
    the [T_loc, C] totals before the psum over "topics" — so the global
    per-member stats are computed and stored member-sharded (no device
    materializes all members' accumulators)."""
    fn = functools.partial(assign_topic_rounds, num_consumers=num_consumers)
    choice, counts, totals = jax.vmap(fn)(lags, partition_ids, valid)
    if refine_iters:
        from ..ops.refine import refine_assignment

        rfn = functools.partial(
            refine_assignment, num_consumers=num_consumers,
            iters=refine_iters,
        )
        choice, counts, totals = jax.vmap(rfn)(lags, valid, choice)
    c_local = num_consumers // members_axis
    offset = jax.lax.axis_index("members") * c_local
    local_load = jax.lax.dynamic_slice_in_dim(
        jnp.sum(totals, axis=0), offset, c_local
    )
    local_count = jax.lax.dynamic_slice_in_dim(
        jnp.sum(counts, axis=0), offset, c_local
    )
    member_load = jax.lax.psum(local_load, axis_name="topics")
    member_count = jax.lax.psum(local_count, axis_name="topics")
    return choice, counts, totals, member_load, member_count


def assign_sharded(
    mesh: Mesh,
    lags,
    partition_ids,
    valid,
    num_consumers: int,
    refine_iters: int = 0,
):
    """Solve a topic batch sharded over ``mesh``.

    Args: arrays of shape [T, P] with T divisible by the mesh's "topics"
    axis size and ``num_consumers`` divisible by its "members" axis size.
    ``refine_iters`` (static, 0 = strict parity) chains the per-topic
    exchange refinement onto each shard-local topic — no additional
    cross-device communication (see :func:`_sharded_step`).
    Returns (choice [T, P], counts [T, C], totals [T, C], member_load [C],
    member_count [C]) — the per-member global stats are computed and stored
    member-sharded.

    The whole path is jitted; the collectives (psum over "topics") are
    inserted by XLA from the shard_map specs and ride ICI.
    """
    members_axis = mesh.shape["members"]
    if num_consumers % members_axis:
        raise ValueError(
            f"num_consumers={num_consumers} not divisible by members axis "
            f"{members_axis}"
        )
    step = _jitted_sharded_step(
        mesh, num_consumers, members_axis, int(refine_iters)
    )
    return step(lags, partition_ids, valid)


@functools.lru_cache(maxsize=64)
def _jitted_sharded_step(
    mesh: Mesh, num_consumers: int, members_axis: int, refine_iters: int = 0
):
    """Build + jit the shard_map step once per (mesh, C, members-axis,
    refine budget) — jax.jit caches per function object, so constructing a
    fresh wrapper on every call would retrace and recompile each
    rebalance."""
    step = _shard_map(
        functools.partial(
            _sharded_step,
            num_consumers=num_consumers,
            members_axis=members_axis,
            refine_iters=refine_iters,
        ),
        mesh=mesh,
        in_specs=(P("topics", None), P("topics", None), P("topics", None)),
        out_specs=(
            P("topics", None),  # choice
            P("topics", None),  # counts
            P("topics", None),  # totals
            P("members"),       # member_load: sharded over member axis
            P("members"),       # member_count
        ),
        # The rounds kernel's scan carry starts from literal zeros, which the
        # varying-manual-axes checker types as unvarying even though the data
        # flowing into it varies over "topics"; parity with the unsharded
        # kernel is asserted by tests instead.  (check_vma on current jax,
        # check_rep on the 0.4.x experimental API — resolved in .mesh.)
        **{CHECK_KW: False},
    )
    return jax.jit(step)


def assign_global_replicated(mesh: Mesh, lags, partition_ids, valid,
                             num_consumers: int):
    """The cross-topic GLOBAL quality mode on a mesh: an explicit, tested
    REPLICATION decision rather than a sharding.

    The global kernel carries member totals across topics sequentially
    (topic t+1's seating depends on totals after topic t —
    ops/rounds_kernel.assign_global_rounds), so the topic axis cannot be
    data-parallel without changing semantics; and C-axis sharding would
    put the per-round C-sized sort/argmin under collectives for no win at
    realistic C.  Replicating the solve on every device is the honest
    mapping: each device computes the identical assignment (deterministic
    kernel — bit-identical replicas), so downstream topic-sharded
    consumers (e.g. the refine pass or stats) can read their slice with
    no broadcast step.

    Returns (choice [T, P], counts [T, C], totals [C]) fully replicated.
    """
    from ..ops.rounds_kernel import assign_global_rounds

    rep = NamedSharding(mesh, P())
    fn = jax.jit(
        functools.partial(
            assign_global_rounds, num_consumers=num_consumers
        ),
        in_shardings=(rep, rep, rep),
        out_shardings=(rep, rep, rep),
    )
    return fn(
        jax.device_put(lags, rep),
        jax.device_put(partition_ids, rep),
        jax.device_put(valid, rep),
    )


def shard_topic_batch(mesh: Mesh, lags, partition_ids, valid):
    """Device-put a host topic batch with the mesh's topic sharding, so the
    transfer lands each shard directly on its device (no host gather)."""
    spec = NamedSharding(mesh, P("topics", None))
    return (
        jax.device_put(lags, spec),
        jax.device_put(partition_ids, spec),
        jax.device_put(valid, spec),
    )
