"""P-axis placement for a stream's device-RESIDENT warm state.

The streaming engine's four resident buffers — padded choice [B],
row table [C, M], counts [C], padded lags [B] — live on ONE chip even
when the cold solve sharded, so the warm loop's capacity still caps at
a single device's HBM.  This module owns the placement decision (lint
rule L020 confines mesh/sharding construction to ``sharded/``): when
the active mesh manager elects the P backend for the stream's shape,
:func:`place_resident` re-places the freshly adopted buffers across
the tenant's ("p",) mesh slice —

* the two [B] row-axis buffers (choice, lags) shard over "p";
* the two consumer-axis buffers (row_tab [C, M], counts [C]) stay
  replicated — C << P, and the exchange refine walks whole per-pair
  [K, M] slices, so splitting them would trade one chip's bytes for a
  gather per round.

Placement is INPUT sharding, not a new code path: the warm fused
executables are unchanged and the SPMD partitioner propagates the
layout through them, so the donated successors come back sharded and
the steady state pays no per-epoch re-placement.  Every value is
bit-identical under re-placement, which is exactly why the
digest/quarantine/seed_choice contracts survive untouched: the fused
digest hashes the same ints, quarantine drops handles not layouts, and
a seed_choice rebuild simply adopts (and re-places) fresh buffers.

Eligibility (:func:`shardable_rows`) mirrors the megabatch rule on the
other axis: the padded row bucket must cover and divide the mesh.  Any
placement failure is non-fatal — the caller keeps the single-device
buffers and degrades the manager so the fleet falls back too.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from .mesh import SOLVE_AXIS


def shardable_rows(mesh, bucket: int) -> bool:
    """True when a padded row bucket splits evenly over ``mesh``'s
    "p" axis (pow2 buckets over pow2 meshes always divide once
    ``bucket >= D``)."""
    if mesh is None:
        return False
    D = mesh.shape[SOLVE_AXIS]
    return D > 1 and bucket >= D and bucket % D == 0


def row_sharding(mesh) -> NamedSharding:
    """[B] row-axis sharding: rows spread over the "p" devices."""
    return NamedSharding(mesh, PartitionSpec(SOLVE_AXIS))


def replicated(mesh) -> NamedSharding:
    """Consumer-axis buffers: replicated on every "p" device."""
    return NamedSharding(mesh, PartitionSpec())


def place_resident(mesh, resident):
    """Re-place a freshly adopted resident 4-tuple ``(choice [B],
    row_tab [C, M], counts [C], lags [B])`` with the P-axis layout.
    Values are unchanged (a reshard moves bytes, not bits), so the
    next dispatch's digest sees exactly the state it would have seen
    single-device.  Returns the placed tuple in input order."""
    choice, row_tab, counts, lags = resident
    rows = row_sharding(mesh)
    rep = replicated(mesh)
    return (
        jax.device_put(choice, rows),
        jax.device_put(row_tab, rep),
        jax.device_put(counts, rep),
        jax.device_put(lags, rows),
    )
