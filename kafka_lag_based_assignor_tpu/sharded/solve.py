"""P-axis-sharded solve: seed sort + plan stats + exchange refine over a
device mesh.

The single-leader solve caps the north star at one chip's HBM/FLOPs: a
1M-partition lag vector, its sort, and the refine working set must all
fit one device.  This module shards the PARTITION axis over the mesh
manager's 1-D ``("p",)`` mesh (:mod:`.mesh`) so one huge solve spans
devices, with the consumer-axis state — per-consumer totals and counts,
C << P — kept REPLICATED and all-reduced per round:

* **Seed** (:func:`_seed_local`): each shard sorts its local rows lag
  descending (one local P/D-sized sort — the expensive sort never
  crosses devices), a one-scalar ``all_gather`` fixes each shard's
  global valid-rank offset, and row with global rank g takes consumer
  ``g % C``.  Global ranks are a bijection over the valid rows, so the
  seed is count-balanced (``max - min <= 1``) by construction at ANY
  mesh size.

* **Refine** (:func:`_refine_loop`): the EXACT round structure of
  :func:`..ops.refine.refine_assignment` — rank consumers by replicated
  totals, pair heavy with rotated light partners, score move/swap
  candidates with the same quantized packed-key sort + neighbour scans
  + segmented argmin — run per shard over LOCAL rows, then ONE
  ``pmin``-based all-reduce per round picks each pair's globally best
  exchange and a ``psum`` folds the winner's transfer back into the
  replicated totals/counts.  At mesh size 1 the local candidate set IS
  the global set and every reduce is the identity, so the result is
  **bit-identical** to ``refine_assignment`` (pinned by
  tests/test_sharded.py); at sizes 2-8 swaps are found within a shard
  (moves anywhere), so the output is count-balanced and quality-gated
  rather than bit-equal — the documented contract.

* **Plan stats** (:func:`plan_stats_sharded`): the per-consumer
  load/count marginals of an assignment as one shard-local segment sum
  + ``psum`` — no device ever materializes another shard's rows.

* **Linear-OT quality duals** (:func:`solve_linear_sharded`): the
  linear-space mirror-prox quality mode (:mod:`..ops.linear_ot`)
  composed with this mesh — each shard tile-streams its LOCAL rows'
  marginal partials per fixed superblock, one ``all_gather`` per outer
  iteration replicates the per-block partials, and the ordered f32
  combine + dual update run identically on every shard (consumer-axis
  duals all-reduced per outer iteration, the replicated-state pattern
  above).  Because the superblock decomposition and combine order are
  mesh-size-independent, the duals trajectory — and the final rounded
  assignment, which runs the single-device rounding pass on the
  replicated duals — is **bit-identical at mesh size 1 vs 2-8**
  (pinned by tests/test_linear_ot.py).

Executable discipline: one jitted ``shard_map`` program per (mesh, C,
budget, bucket) via an lru-cached builder — repeated solves at a shape
compile NOTHING after the first (the differential fuzz and the bench's
``sharded_scale`` probe gate on ``utils/observability.compile_count``).

Dispatch boundary: :func:`solve_sharded` / :func:`refine_sharded` fire
the ``mesh.collective`` fault point on entry; callers (the streaming
engine's cold hook, ops/dispatch selection) catch any failure, degrade
the mesh manager, and fall back to the single-device backend inside the
same request budget.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from ..ops.packing import pad_bucket, pad_chunk
from ..ops.refine import _PAIR_BITS, _SBIG_INT, _VBITS
from ..ops.sortops import bincount_sorted, segment_argmin_first, segment_sum
from ..utils import faults, metrics
from .mesh import CHECK_KW, SOLVE_AXIS, dispatch_gate, shard_map


def _quant_shift_all(lags, assigned, axis: str):
    """:func:`..ops.refine._quant_shift` with the max taken over EVERY
    shard (``pmax``), so all devices quantize identically; identity at
    mesh size 1."""
    maxlag = jnp.maximum(jnp.max(jnp.where(assigned, lags, 0)), 1)
    maxlag = lax.pmax(maxlag, axis)
    bitlen = 64 - lax.clz(maxlag.astype(jnp.int64))
    return jnp.maximum(bitlen - _VBITS, 0).astype(jnp.int64)


def _seed_local(lags, valid, num_consumers: int, axis: str, num_shards: int):
    """Count-balanced sharded seed (module docstring): local lag-desc
    sort, cross-shard valid-rank offsets, consumer = global rank mod C.
    Returns choice int32[L] in local input order (-1 on padding)."""
    L = lags.shape[0]
    C = int(num_consumers)
    arangeL = jnp.arange(L, dtype=jnp.int32)
    key = jnp.where(valid, -lags, jnp.iinfo(jnp.int64).max)
    _, srow = lax.sort((key, arangeL), num_keys=1)
    v_loc = jnp.sum(valid.astype(jnp.int32))
    counts_all = lax.all_gather(v_loc, axis)  # [D] scalar gather
    d = lax.axis_index(axis)
    offset = jnp.sum(
        jnp.where(jnp.arange(num_shards, dtype=jnp.int32) < d,
                  counts_all, 0)
    ).astype(jnp.int32)
    g = offset + arangeL
    seat = jnp.where(
        arangeL < v_loc, (g % C).astype(jnp.int32), jnp.int32(-1)
    )
    return jnp.zeros((L,), jnp.int32).at[srow].set(seat)


def _refine_loop(
    lags, valid, choice, num_consumers: int, iters: int,
    max_pairs: Optional[int], patience: int, axis: str, num_shards: int,
):
    """The :func:`..ops.refine.refine_assignment` round loop over LOCAL
    rows with replicated consumer-axis state all-reduced per round (one
    ``pmin`` winner election + one ``psum`` transfer fold); identity
    reduces — and therefore bit-parity — at mesh size 1."""
    C = int(num_consumers)
    L = lags.shape[0]
    K = max(1, min(C // 2, max_pairs if max_pairs is not None else C // 2))
    if K >= (1 << _PAIR_BITS) - 1:
        raise ValueError(
            f"max_pairs={K} exceeds the packed pair-id field "
            f"({_PAIR_BITS} bits)"
        )
    arangeC = jnp.arange(C, dtype=jnp.int32)
    arangeL = jnp.arange(L, dtype=jnp.int32)
    key_big = jnp.iinfo(jnp.int64).max
    vmask = (jnp.int64(1) << _VBITS) - 1
    sbig = jnp.asarray(_SBIG_INT, jnp.int64)
    D = int(num_shards)

    choice = choice.astype(jnp.int32)
    assigned = valid & (choice >= 0)
    seg0 = jnp.where(assigned, choice, -1)
    totals0 = lax.psum(
        segment_sum(jnp.where(assigned, lags, 0), seg0, C), axis
    )
    counts0 = lax.psum(bincount_sorted(seg0, C), axis)
    zero32 = jnp.int32(0)
    if C < 2 or iters <= 0:
        return choice, counts0, totals0, zero32
    pshift = _quant_shift_all(lags, assigned, axis)
    n_light = C - K
    didx = lax.axis_index(axis)

    def body(state):
        it, since, choice, totals, counts = state
        safe_choice = jnp.clip(choice, 0, C - 1)

        # Pairing over the REPLICATED totals: identical on every shard
        # (deterministic argsort of identical inputs).
        order = jnp.argsort(totals).astype(jnp.int32)
        rank = jnp.zeros((C,), jnp.int32).at[order].set(arangeC)
        shift = it % jnp.int32(n_light)
        light_slot = (jnp.arange(K, dtype=jnp.int32) + shift) % n_light
        light = order[light_slot]
        heavy = order[C - 1 - jnp.arange(K)]
        diff = totals[heavy] - totals[light]

        slot_to_pair = (
            jnp.full((n_light,), K, jnp.int32)
            .at[light_slot]
            .set(jnp.arange(K, dtype=jnp.int32))
        )
        pair_of = jnp.where(
            rank < n_light,
            slot_to_pair[jnp.clip(rank, 0, n_light - 1)],
            C - 1 - rank,
        )
        heavy_side = rank >= C - K
        move_ok_pair = counts[heavy] > counts[light]
        move_ok_of = jnp.where(
            heavy_side,
            jnp.pad(move_ok_pair, (0, 1))[jnp.clip(pair_of, 0, K)],
            False,
        )
        combo_tab = (
            pair_of
            | (heavy_side.astype(jnp.int32) << _PAIR_BITS)
            | (move_ok_of.astype(jnp.int32) << (_PAIR_BITS + 1))
        )
        combo = jnp.where(assigned, combo_tab[safe_choice], -1)
        k_p = combo & ((1 << _PAIR_BITS) - 1)
        row_heavy = (combo >> _PAIR_BITS) & 1
        row_move_ok = (combo >> (_PAIR_BITS + 1)) & 1
        participates = (combo >= 0) & (k_p < K)
        kc = jnp.clip(k_p, 0, K - 1)
        diff_p = jnp.where(participates, diff[kc], 0)
        delta_p = diff_p >> 1

        # LOCAL candidate sort (the expensive P-sized work stays on
        # shard; the oracle's key layout verbatim).
        qself = lags >> pshift
        tgt = jnp.clip(lags - delta_p, 0, None) >> pshift
        qval = jnp.where(row_heavy == 1, tgt, qself)
        key = jnp.where(
            participates,
            (k_p.astype(jnp.int64) << (_VBITS + 1))
            | (jnp.clip(qval, 0, vmask) << 1)
            | row_heavy.astype(jnp.int64),
            key_big,
        )
        skey, slag, srow, smove_ok = lax.sort(
            (key, lags, arangeL, row_move_ok), num_keys=1
        )
        part_s = skey < key_big
        pair_s = (skey >> (_VBITS + 1)).astype(jnp.int32)
        heavy_s = part_s & ((skey & 1) == 1)
        light_s = part_s & ((skey & 1) == 0)
        qlag_s = slag >> pshift
        diff_s = jnp.where(heavy_s, diff[jnp.clip(pair_s, 0, K - 1)], 0)
        delta_q_s = (diff_s >> 1) >> pshift
        diff_q_s = diff_s >> pshift

        prev_l = lax.cummax(jnp.where(light_s, arangeL, -1))
        nxt_l = lax.cummin(
            jnp.where(light_s, arangeL, L), reverse=True
        )

        def neighbour(nb):
            inb = jnp.clip(nb, 0, L - 1)
            nkey = skey[inb]
            okq = (
                (nb >= 0) & (nb < L)
                & ((nkey & 1) == 0)
                & ((nkey >> (_VBITS + 1)).astype(jnp.int32) == pair_s)
            )
            d_q = qlag_s - ((nkey >> 1) & vmask)
            ok = heavy_s & okq & (d_q > 0) & (d_q < diff_q_s)
            return jnp.where(ok, jnp.abs(d_q - delta_q_s), sbig)

        err_a = neighbour(prev_l)
        err_b = neighbour(nxt_l)
        use_b = err_b < err_a
        err_swap = jnp.where(use_b, err_b, err_a)
        nb_sel = jnp.where(use_b, nxt_l, prev_l)

        ok_move = (
            heavy_s & (smove_ok == 1) & (slag > 0) & (slag < diff_s)
        )
        score_move = jnp.where(
            ok_move, jnp.abs(qlag_s - delta_q_s), sbig
        )
        combined = jnp.where(
            score_move <= err_swap,
            score_move << 1,
            (err_swap << 1) | 1,
        )
        seg_h = jnp.where(heavy_s, pair_s, K)
        minv, widx = segment_argmin_first(combined, seg_h, K, L)

        # Per-pair winner ELECTION across shards: the smallest packed
        # score wins, ties to the lowest device index.  All-reduced so
        # every shard agrees; identity at D=1.
        gmin = lax.pmin(minv, axis)
        has = minv == gmin
        win_d = lax.pmin(
            jnp.where(has, jnp.full((K,), didx, jnp.int32), D), axis
        )
        mine = win_d == didx
        do = gmin < (sbig << 1)
        is_swap = (gmin & 1) == 1

        wclip = jnp.clip(widx, 0, L - 1)
        p_sel = srow[wclip]
        lag_p = slag[wclip]
        nb_k = jnp.clip(nb_sel[wclip], 0, L - 1)
        q_sel = srow[nb_k]
        lag_q = slag[nb_k]
        use_swap = do & is_swap
        d_amt = jnp.where(use_swap, lag_p - lag_q, lag_p)
        d_amt = jnp.where(do, d_amt, 0)
        # The winner's exact transfer, folded into the replicated
        # totals (only the winning shard contributes non-zero).
        d_k = lax.psum(jnp.where(mine, d_amt, 0), axis)

        upd_p = jnp.where(mine & do, p_sel, jnp.int32(L))
        upd_q = jnp.where(mine & use_swap, q_sel, jnp.int32(L))
        new_choice = choice.at[upd_p].set(light, mode="drop")
        new_choice = new_choice.at[upd_q].set(heavy, mode="drop")
        new_totals = totals.at[heavy].add(-d_k).at[light].add(d_k)
        dc = (do & ~is_swap).astype(jnp.int32)
        new_counts = counts.at[heavy].add(-dc).at[light].add(dc)
        peak_dropped = jnp.max(new_totals) < jnp.max(totals)
        new_since = jnp.where(peak_dropped, zero32, since + 1)
        return it + 1, new_since, new_choice, new_totals, new_counts

    def cond(state):
        it, since = state[0], state[1]
        return (it < iters) & (since < patience)

    it, _, choice, totals, counts = lax.while_loop(
        cond, body, (zero32, zero32, choice, totals0, counts0)
    )
    return choice, counts, totals, it


@functools.lru_cache(maxsize=32)
def _sharded_executable(
    mesh, num_consumers: int, iters: int, max_pairs, patience: int,
    seeded: bool,
):
    """Build + jit ONE shard_map program per (mesh, C, budget, mode) —
    the builder is lru-cached so repeated solves retrace nothing."""
    D = mesh.shape[SOLVE_AXIS]

    if seeded:

        def step(lags, valid):
            choice = _seed_local(
                lags, valid, num_consumers, SOLVE_AXIS, D
            )
            return _refine_loop(
                lags, valid, choice, num_consumers, iters, max_pairs,
                patience, SOLVE_AXIS, D,
            )

        in_specs = (
            PartitionSpec(SOLVE_AXIS), PartitionSpec(SOLVE_AXIS),
        )
    else:

        def step(lags, valid, choice):
            return _refine_loop(
                lags, valid, choice, num_consumers, iters, max_pairs,
                patience, SOLVE_AXIS, D,
            )

        in_specs = (
            PartitionSpec(SOLVE_AXIS), PartitionSpec(SOLVE_AXIS),
            PartitionSpec(SOLVE_AXIS),
        )
    mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(
            PartitionSpec(SOLVE_AXIS),  # choice
            PartitionSpec(),            # counts: replicated
            PartitionSpec(),            # totals: replicated
            PartitionSpec(),            # rounds
        ),
        # The while_loop carry starts from literal zeros (typed
        # unvarying by the manual-axes checker even though data varies
        # over "p"); parity with the unsharded kernel is asserted by
        # tests instead — the same waiver the topic-axis backend uses.
        **{CHECK_KW: False},
    )
    return jax.jit(mapped)


def shard_bucket(num_rows: int, num_shards: int) -> int:
    """Padded solve shape: the streaming buckets (pow2 on accelerators,
    4096-chunks on CPU) rounded up to a multiple of the mesh size so
    the P axis splits evenly."""
    B = (
        pad_chunk(num_rows)
        if jax.default_backend() == "cpu"
        else pad_bucket(num_rows)
    )
    D = int(num_shards)
    if B % D:
        B += D - (B % D)
    return B


def _place_inputs(mesh, *host_arrays):
    """Device-put padded host inputs with the "p" sharding so each
    shard's slice lands directly on its device (no host gather)."""
    spec = NamedSharding(mesh, PartitionSpec(SOLVE_AXIS))
    return tuple(jax.device_put(a, spec) for a in host_arrays)


def solve_sharded(
    mesh,
    lags: np.ndarray,
    num_consumers: int,
    refine_iters: int = 64,
    max_pairs: Optional[int] = None,
    patience: int = 8,
):
    """One P-axis-sharded cold solve (seed + refine) on ``mesh``.

    ``lags`` is the exact host [P] int64 vector; padding to the
    mesh-divisible bucket happens here.  Fires ``mesh.collective`` on
    entry (the sharded dispatch boundary — callers degrade to the
    single-device backend on any failure).  Returns ``(choice int32[P]
    in input order, counts int32[C], totals int64[C], rounds)`` as host
    arrays; the choice is count-balanced at any mesh size.
    """
    from ..ops.dispatch import ensure_x64

    ensure_x64()
    faults.fire("mesh.collective")
    C = int(num_consumers)
    lags = np.ascontiguousarray(lags, dtype=np.int64)
    P_len = int(lags.shape[0])
    D = mesh.shape[SOLVE_AXIS]
    B = shard_bucket(P_len, D)
    lags_p = np.zeros(B, dtype=np.int64)
    lags_p[:P_len] = lags
    valid = np.zeros(B, dtype=bool)
    valid[:P_len] = True
    step = _sharded_executable(
        mesh, C, int(refine_iters), max_pairs, int(patience), True
    )
    with metrics.span("sharded.solve"), dispatch_gate():
        choice, counts, totals, rounds = step(
            *_place_inputs(mesh, lags_p, valid)
        )
        choice_np, counts_np, totals_np, rounds_np = jax.device_get(
            (choice, counts, totals, rounds)
        )
    metrics.REGISTRY.counter(
        "klba_sharded_dispatch_total", {"path": "solve"}
    ).inc()
    return (
        np.asarray(choice_np)[:P_len].astype(np.int32),
        np.asarray(counts_np),
        np.asarray(totals_np),
        int(rounds_np),
    )


def refine_sharded(
    mesh,
    lags: np.ndarray,
    valid: np.ndarray,
    choice: np.ndarray,
    num_consumers: int,
    iters: int = 16,
    max_pairs: Optional[int] = None,
    patience: int = 8,
):
    """Mesh-parity refinement entry: the P-sharded equivalent of
    :func:`..ops.refine.refine_assignment` — bit-identical to it at
    mesh size 1, count-preserving and quality-gated at sizes 2-8.
    Inputs are host arrays of one padded length divisible by the mesh
    size.  Returns host ``(choice int32[P], counts, totals, rounds)``.
    """
    from ..ops.dispatch import ensure_x64

    ensure_x64()
    faults.fire("mesh.collective")
    C = int(num_consumers)
    D = mesh.shape[SOLVE_AXIS]
    lags = np.ascontiguousarray(lags, dtype=np.int64)
    if lags.shape[0] % D:
        raise ValueError(
            f"refine_sharded input length {lags.shape[0]} must divide "
            f"the mesh size {D} (pad with valid=False rows)"
        )
    step = _sharded_executable(
        mesh, C, int(iters), max_pairs, int(patience), False
    )
    with metrics.span("sharded.refine"), dispatch_gate():
        out = step(
            *_place_inputs(
                mesh,
                lags,
                np.ascontiguousarray(valid, dtype=bool),
                np.ascontiguousarray(choice, dtype=np.int32),
            )
        )
        choice_o, counts_o, totals_o, rounds_o = jax.device_get(out)
    metrics.REGISTRY.counter(
        "klba_sharded_dispatch_total", {"path": "refine"}
    ).inc()
    return (
        np.asarray(choice_o).astype(np.int32),
        np.asarray(counts_o),
        np.asarray(totals_o),
        int(rounds_o),
    )


@functools.lru_cache(maxsize=32)
def _plan_stats_executable(mesh, num_consumers: int):
    def step(lags, valid, choice):
        assigned = valid & (choice >= 0)
        seg = jnp.where(assigned, choice, -1)
        totals = lax.psum(
            segment_sum(jnp.where(assigned, lags, 0), seg, num_consumers),
            SOLVE_AXIS,
        )
        counts = lax.psum(
            bincount_sorted(seg, num_consumers), SOLVE_AXIS
        )
        return totals, counts

    mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=(
            PartitionSpec(SOLVE_AXIS), PartitionSpec(SOLVE_AXIS),
            PartitionSpec(SOLVE_AXIS),
        ),
        out_specs=(PartitionSpec(), PartitionSpec()),
        **{CHECK_KW: False},
    )
    return jax.jit(mapped)


def plan_stats_sharded(mesh, lags, valid, choice, num_consumers: int):
    """Sharded plan stats: per-consumer ``(totals int64[C], counts
    int32[C])`` of an assignment via shard-local segment sums + one
    ``psum`` — no device materializes another shard's rows.  Inputs are
    host arrays of one mesh-divisible padded length."""
    from ..ops.dispatch import ensure_x64

    ensure_x64()
    step = _plan_stats_executable(mesh, int(num_consumers))
    with dispatch_gate():
        totals, counts = step(
            *_place_inputs(
                mesh,
                np.ascontiguousarray(lags, dtype=np.int64),
                np.ascontiguousarray(valid, dtype=bool),
                np.ascontiguousarray(choice, dtype=np.int32),
            )
        )
    return np.asarray(totals), np.asarray(counts)


@functools.lru_cache(maxsize=32)
def _linear_duals_executable(
    mesh, num_consumers: int, iters: int, tile: int,
    kernel: bool = False,
):
    """Build + jit the P-sharded mirror-prox dual program: one
    executable per (mesh, C, iters, tile, kernel) — shapes
    re-specialize via the jit cache like every other sharded program
    here.  ``kernel`` swaps the shard-local marginal partials for the
    Pallas tile kernel (:func:`..ops.linear_ot_pallas.
    superblock_partials_pallas` — bit-identical partials, same
    all-gather + ordered combine, so mesh parity is untouched);
    callers gate it on the probe-once verdict + per-shard admission."""
    from ..ops import linear_ot
    from ..ops import linear_ot_pallas

    D = mesh.shape[SOLVE_AXIS]
    S = linear_ot._SUPERBLOCKS
    C = int(num_consumers)

    def step(lags, valid, scale, n_valid):
        # Local rows -> local superblocks (shard d owns whole blocks
        # d*S/D .. (d+1)*S/D - 1 of the GLOBAL decomposition; padding
        # sits at the global tail, so block contents match the
        # single-device layout exactly).
        L = lags.shape[0]
        ws, cnt = linear_ot._ws_cnt(lags, valid, scale)
        ws_b = linear_ot._to_blocks(ws, L, S // D, tile)
        cnt_b = linear_ot._to_blocks(cnt, L, S // D, tile)

        def stats_fn(A, B):
            if kernel:
                pl, pc = linear_ot_pallas.superblock_partials_pallas(
                    ws_b, cnt_b, A, B
                )
            else:
                pl, pc = linear_ot._superblock_partials(
                    ws_b, cnt_b, A, B
                )
            # Consumer-axis all-reduce per outer iteration: gather the
            # per-block partials into GLOBAL block order, then the
            # same fixed left-to-right combine as the single-device
            # path — bit-identical marginals at any mesh size.
            pl = lax.all_gather(pl, SOLVE_AXIS, axis=0, tiled=True)
            pc = lax.all_gather(pc, SOLVE_AXIS, axis=0, tiled=True)
            return (
                linear_ot._ordered_sum(pl),
                linear_ot._ordered_sum(pc),
            )

        return linear_ot.mirror_prox(stats_fn, C, int(iters), n_valid)

    mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=(
            PartitionSpec(SOLVE_AXIS), PartitionSpec(SOLVE_AXIS),
            PartitionSpec(), PartitionSpec(),
        ),
        out_specs=(
            PartitionSpec(),  # A: replicated duals
            PartitionSpec(),  # B
            PartitionSpec(),  # rounds
        ),
        **{CHECK_KW: False},
    )
    return jax.jit(mapped)


# ---------------------------------------------------------------------------
# P-sharded rounding tail
# ---------------------------------------------------------------------------


def _bincount_scatter(vals, num_segments: int):
    """Backend-independent integer histogram (pure scatter-add): the
    same ints as :func:`..ops.sortops.bincount_sorted` without its
    accelerator sort branch — the sharded tail's lowering must stay
    free of P-sized sorts on every backend."""
    S = int(num_segments)
    in_range = (vals >= 0) & (vals < S)
    return (
        jnp.zeros((S,), jnp.int32)
        .at[jnp.clip(vals, 0, S - 1)]
        .add(in_range.astype(jnp.int32))
    )


def _segsum_scatter(vals, seg, num_segments: int):
    """Sort-free integer segment sum (exact on ints in any order)."""
    S = int(num_segments)
    in_range = (seg >= 0) & (seg < S)
    return (
        jnp.zeros((S,), vals.dtype)
        .at[jnp.clip(seg, 0, S - 1)]
        .add(jnp.where(in_range, vals, 0))
    )


def _lex_rank(sorted_keys, query_keys):
    """Global rank of each query row under the lexicographic composite
    key order, WITHOUT a cross-shard sort: ``sorted_keys`` are per-key
    ``[D, L]`` gathers of each shard's locally sorted key columns,
    ``query_keys`` the per-key ``[N]`` local queries.  The rank is the
    count of entries strictly below the query summed over every shard's
    sorted column — computed by a vectorized lexicographic binary
    search (``L.bit_length()`` unrolled steps of ``[N, D]`` gathers).
    Callers append the unique global row id as the last key, so the
    count IS the row's position in the virtual global sort.  Returns
    int32[N]."""
    D, L = sorted_keys[0].shape
    N = query_keys[0].shape[0]
    lo = jnp.zeros((N, D), jnp.int32)
    hi = jnp.full((N, D), L, jnp.int32)

    def fetch(col2d, mid):
        return jax.vmap(
            lambda col, m: col[m], in_axes=(0, 1), out_axes=1
        )(col2d, mid)

    for _ in range(max(1, int(L).bit_length())):
        active = lo < hi
        mid = jnp.minimum((lo + hi) >> 1, L - 1)
        less = jnp.zeros((N, D), bool)
        tie = jnp.ones((N, D), bool)
        for k, q in zip(sorted_keys, query_keys):
            v = fetch(k, mid)
            less = less | (tie & (v < q[:, None]))
            tie = tie & (v == q[:, None])
        lo = jnp.where(active & less, mid + 1, lo)
        hi = jnp.where(active & ~less, mid, hi)
    return jnp.sum(lo, axis=1).astype(jnp.int32)


def _rank_scatter(rank_loc, val_loc, P2: int, axis: str):
    """Rebuild the replicated SORTED-LAYOUT array from per-shard values
    and their global ranks: gather both, then one permutation scatter
    (ranks are unique, so the scatter is deterministic).  This is how
    the tail materializes ``x[perm]`` without ever sorting [P2]."""
    ranks = lax.all_gather(rank_loc, axis, axis=0, tiled=True)
    vals = lax.all_gather(val_loc, axis, axis=0, tiled=True)
    return jnp.zeros((P2,), val_loc.dtype).at[ranks].set(vals)


@functools.lru_cache(maxsize=32)
def _linear_tail_executable(mesh, num_consumers: int, refine_iters: int):
    """Build + jit the P-SHARDED linear rounding tail: the exact math
    of :func:`..models.sinkhorn._round_refine_portfolio` (parallel
    rounding branch) with every P-sized SORT replaced by shard-local
    sorts + distributed rank election, so no device ever sorts [P2]:

    * the plan-argmax grouping sort, the greedy processing order, and
      the overflow repair order each become a LOCAL [P2/D] sort plus a
      :func:`_lex_rank` lexicographic binary search over the gathered
      per-shard sorted keys (composite keys end in the unique global
      row id — ranks are a bijection, hence bit-equal layouts);
    * the sorted layouts the f32 kept-load cumsum and the rounds scan
      walk are rebuilt REPLICATED via permutation scatters
      (:func:`_rank_scatter`) — same op on same input bits as the
      single-device path, so the order-sensitive float reductions match
      bit-for-bit;
    * overflow seating drops ``_round_parallel``'s C*cap_max slot sort
      for the closed form: ``cum_slots[r] = sum_j min(rem_j, r)`` open
      slots precede round ``r``, so overflow rank k seats at round
      ``r = searchsorted(cum_slots, k, 'right') - 1``, position
      ``k - cum_slots[r]`` in kept-load rank order — integer-exact
      against the slot sort because all open-slot keys are distinct;
    * the exchange refine runs the ACTUAL
      :func:`..ops.refine.refine_rounds_resident` code replicated on
      all-gathered rows (its per-round working sets are [K, M] with
      M = table_rows(P2, C) < P2 for C >= 2 — not P-sized), over a
      choice table built DISTRIBUTED: local segment sorts, one
      all-gathered count prefix, and a psum'd position scatter.  The
      round body only consumes each consumer's valid-row multiset plus
      the valid-prefix invariant, both of which the distributed build
      reproduces exactly, so the refine trajectory is bit-identical to
      the single-device ``build_choice_tables`` table.

    Scale contract: total lag must stay below 2**53 (the documented
    ``_scale_np`` contract) so the psum'd f64 scale — and therefore
    every per-row f32 ws — is exact and mesh-invariant."""
    from ..models.sinkhorn import _START_SLACK
    from ..ops.packing import table_rows
    from ..ops.plan_stats import implicit_plan_argmax
    from ..ops.refine import refine_rounds_resident
    from ..ops.rounds_kernel import _rounds_scan

    C = int(num_consumers)
    D = mesh.shape[SOLVE_AXIS]
    axis = SOLVE_AXIS
    i32max = jnp.iinfo(jnp.int32).max
    i64max = jnp.iinfo(jnp.int64).max

    def step(lags, valid, A, B):
        L = lags.shape[0]
        P2 = L * D
        M = table_rows(P2, C)
        cap_max = P2 // C + 1
        arangeL = jnp.arange(L, dtype=jnp.int32)
        didx = lax.axis_index(axis).astype(jnp.int32)
        gidx = didx * L + arangeL

        # _scaled_ws with the f64 total psum-reduced: integer partial
        # sums below 2**53 are exact in any order, so ws bits match the
        # single-device path per row.
        w = jnp.where(valid, lags, 0).astype(jnp.float64)
        scale = jnp.maximum(lax.psum(jnp.sum(w), axis), 1.0) / C
        ws = (w / scale).astype(jnp.float32)

        jstar = implicit_plan_argmax(ws, valid, A, B, tie_noise=False)
        neg_lag = jnp.where(valid, -lags, i64max)

        # Rank in _round_parallel's (jstar, neg_lag, row) grouping
        # order — local sort + lexicographic binary search.
        s1, s2, s3 = lax.sort((jstar, neg_lag, gidx), num_keys=3)
        rank_par = _lex_rank(
            (lax.all_gather(s1, axis), lax.all_gather(s2, axis),
             lax.all_gather(s3, axis)),
            (jstar, neg_lag, gidx),
        )

        # Replicated sorted-layout twins (permutation scatters).
        sj_s = _rank_scatter(rank_par, jstar, P2, axis)
        ws_s = _rank_scatter(rank_par, ws, P2, axis)

        n_valid = lax.psum(jnp.sum(valid.astype(jnp.int32)), axis)
        floor_cap = n_valid // C
        extras = n_valid - floor_cap * C
        cap = floor_cap + (
            jnp.arange(C, dtype=jnp.int32) < extras
        ).astype(jnp.int32)

        idx_p = jnp.arange(P2, dtype=jnp.int32)
        bnd = jnp.searchsorted(
            sj_s, jnp.arange(C + 1, dtype=jnp.int32)
        ).astype(jnp.int32)
        pos = idx_p - bnd[jnp.clip(sj_s, 0, C)]
        keep_s = (sj_s < C) & (pos < cap[jnp.clip(sj_s, 0, C - 1)])
        kept_cnt = jnp.minimum(bnd[1:] - bnd[:-1], cap)
        # Order-sensitive f32 cumsum over the EXACT single-device
        # sorted layout — mesh-invariant kept-load bits.
        csum = jnp.concatenate(
            [jnp.zeros((1,), jnp.float32),
             jnp.cumsum(jnp.where(keep_s, ws_s, jnp.float32(0.0)))]
        )
        kept_load = csum[bnd[1:]] - csum[bnd[:-1]]
        rem = cap - kept_cnt
        lr_order = jnp.argsort(kept_load).astype(jnp.int32)

        # Closed-form seat table: round r opens the consumers with
        # rem > r, in kept-load rank order.
        sorted_rem = jnp.sort(rem)
        prefix_rem = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(sorted_rem)]
        )
        rr = jnp.arange(cap_max + 1, dtype=jnp.int32)
        t_r = jnp.searchsorted(
            sorted_rem, rr, side="right"
        ).astype(jnp.int32)
        cum_slots = prefix_rem[t_r] + rr * (jnp.int32(C) - t_r)
        open_mask = rem[lr_order][None, :] > rr[:, None]
        open_cum = jnp.cumsum(open_mask.astype(jnp.int32), axis=1)
        seat_dest = jnp.where(
            open_mask,
            rr[:, None] * C + open_cum - 1,
            jnp.int32((cap_max + 1) * C),
        )
        seat_tab = (
            jnp.zeros(((cap_max + 1) * C,), jnp.int32)
            .at[seat_dest.reshape(-1)]
            .set(
                jnp.broadcast_to(
                    lr_order[None, :], open_mask.shape
                ).reshape(-1),
                mode="drop",
            )
            .reshape(cap_max + 1, C)
        )

        # Overflow rank in (neg_lag, sorted-layout position) order —
        # the stable tiebreak _round_parallel's okey sort uses.
        keep_loc = keep_s[rank_par]
        overflow = valid & ~keep_loc
        okey = jnp.where(overflow, neg_lag, i64max)
        o1, o2 = lax.sort((okey, rank_par), num_keys=2)
        orank = _lex_rank(
            (lax.all_gather(o1, axis), lax.all_gather(o2, axis)),
            (okey, rank_par),
        )
        r_of = (
            jnp.searchsorted(
                cum_slots, orank, side="right"
            ).astype(jnp.int32)
            - 1
        )
        m_of = orank - cum_slots[jnp.clip(r_of, 0, cap_max)]
        seat = seat_tab[
            jnp.clip(r_of, 0, cap_max), jnp.clip(m_of, 0, C - 1)
        ]
        choice_loc = jnp.where(
            keep_loc, jstar, jnp.where(overflow, seat, -1)
        ).astype(jnp.int32)

        # Greedy twin: distributed processing-order ranks feeding the
        # ACTUAL rounds-scan kernel, run replicated.
        neg_g = jnp.where(valid, -lags, 1)
        pid_key = jnp.where(valid, gidx, i32max)
        h1, h2, h3 = lax.sort((neg_g, pid_key, gidx), num_keys=3)
        rank_g = _lex_rank(
            (lax.all_gather(h1, axis), lax.all_gather(h2, axis),
             lax.all_gather(h3, axis)),
            (neg_g, pid_key, gidx),
        )
        lag_gs = _rank_scatter(rank_g, lags, P2, axis)
        valid_gs = _rank_scatter(rank_g, valid, P2, axis)
        g_totals, g_sorted_choice = _rounds_scan(
            lag_gs, valid_gs, jnp.zeros((C,), lags.dtype), C
        )
        g_choice_loc = g_sorted_choice[rank_g]
        g_counts = _bincount_scatter(g_sorted_choice, C)

        ot_totals = lax.psum(
            _segsum_scatter(
                jnp.where(valid, lags, 0),
                jnp.where(valid, choice_loc, -1),
                C,
            ),
            axis,
        )
        use_ot = jnp.max(ot_totals) <= _START_SLACK * jnp.max(g_totals)
        start_loc = jnp.where(use_ot, choice_loc, g_choice_loc)

        # Distributed choice-table build: local segment sort, one
        # all-gathered count prefix, psum'd position scatter.  Each
        # consumer's segment holds its assigned-row multiset in a
        # valid-prefix layout — all the refine round body consumes.
        lags_full = lax.all_gather(lags, axis, axis=0, tiled=True)
        start_full = lax.all_gather(start_loc, axis, axis=0, tiled=True)
        seg_loc = jnp.where(
            valid & (start_loc >= 0), start_loc, C
        ).astype(jnp.int32)
        sseg, srow_g = lax.sort((seg_loc, gidx), num_keys=1)
        bnd_l = jnp.searchsorted(
            sseg, jnp.arange(C + 1, dtype=jnp.int32)
        ).astype(jnp.int32)
        cnt_loc = bnd_l[1:] - bnd_l[:-1]
        cnt_all = lax.all_gather(cnt_loc, axis)  # [D, C]
        prefix = jnp.sum(
            jnp.where(
                jnp.arange(D, dtype=jnp.int32)[:, None] < didx,
                cnt_all, 0,
            ),
            axis=0,
        ).astype(jnp.int32)
        pos_l = arangeL - bnd_l[jnp.clip(sseg, 0, C)]
        dest = jnp.where(
            sseg < C,
            sseg * M + prefix[jnp.clip(sseg, 0, C - 1)] + pos_l,
            jnp.int32(C * M),
        )
        tab_flat = lax.psum(
            jnp.zeros((C * M,), jnp.int32)
            .at[dest]
            .set(srow_g + 1, mode="drop"),
            axis,
        )
        row_tab = jnp.where(
            tab_flat > 0, tab_flat - 1, jnp.int32(P2)
        ).reshape(C, M)
        r_counts = lax.psum(cnt_loc, axis)
        r_totals = lax.psum(
            _segsum_scatter(jnp.where(valid, lags, 0), seg_loc, C),
            axis,
        )

        s_choice, _, s_counts, s_totals, _, _ = refine_rounds_resident(
            lags_full, start_full, row_tab, r_counts, r_totals,
            num_consumers=C, iters=int(refine_iters),
            max_pairs=min(C // 2, 64),
        )
        use_s = jnp.max(s_totals) < jnp.max(g_totals)
        g_choice_full = lax.all_gather(
            g_choice_loc, axis, axis=0, tiled=True
        )
        fin_choice = jnp.where(use_s, s_choice, g_choice_full)
        fin_counts = jnp.where(use_s, s_counts, g_counts)
        fin_totals = jnp.where(use_s, s_totals, g_totals)
        out_loc = lax.dynamic_slice(fin_choice, (didx * L,), (L,))
        return out_loc.astype(jnp.int32), fin_counts, fin_totals

    mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=(
            PartitionSpec(SOLVE_AXIS), PartitionSpec(SOLVE_AXIS),
            PartitionSpec(), PartitionSpec(),
        ),
        out_specs=(
            PartitionSpec(SOLVE_AXIS),  # choice
            PartitionSpec(),            # counts: replicated
            PartitionSpec(),            # totals: replicated
        ),
        **{CHECK_KW: False},
    )
    return jax.jit(mapped)


def _finish_sharded_tail(
    mesh, lags_p: np.ndarray, valid: np.ndarray, A, B,
    num_consumers: int, refine_iters: int, *,
    tiles: int, tile: int, rounds: int, kernel: bool,
):
    """Host wrapper of the P-sharded rounding tail: place the padded
    inputs with the "p" sharding, run the tail executable, then the
    SAME epilogue as :func:`..ops.linear_ot.finish_from_duals` (the
    additive-bound assert, quality metrics, ``_LAST`` row) via the
    shared :func:`..ops.linear_ot.record_linear_solve`."""
    from ..ops import linear_ot

    C = int(num_consumers)
    D = mesh.shape[SOLVE_AXIS]
    step = _linear_tail_executable(mesh, C, int(refine_iters))
    lags_d, valid_d = _place_inputs(mesh, lags_p, valid)
    rspec = NamedSharding(mesh, PartitionSpec())
    A_d = jax.device_put(np.asarray(A, np.float32), rspec)
    B_d = jax.device_put(np.asarray(B, np.float32), rspec)
    with metrics.device_phase("rounding"), dispatch_gate():
        choice, counts, totals = step(lags_d, valid_d, A_d, B_d)
        jax.block_until_ready((choice, counts, totals))
    choice_np, counts_np, totals_np = (
        np.asarray(x)
        for x in jax.device_get((choice, counts, totals))
    )
    metrics.REGISTRY.counter(
        "klba_sharded_dispatch_total", {"path": "rounding"}
    ).inc()
    linear_ot.record_linear_solve(
        lags_p, valid, totals_np, C,
        tiles=tiles, tile=tile, rounds=rounds,
        backend=f"sharded:{D}", kernel=kernel,
    )
    return choice_np, counts_np, totals_np


def solve_linear_sharded(
    mesh,
    lags: np.ndarray,
    num_consumers: int,
    iters: int = 24,
    refine_iters: int = 64,
    tile: Optional[int] = None,
):
    """One linear-OT quality cold solve with BOTH halves P-sharded over
    ``mesh`` (module docstring): the O(iters * P * C) marginal scans
    split across shards, and — above the sequential-rounding threshold
    — the O(P log P) rounding tail runs P-sharded too
    (:func:`_linear_tail_executable`: distributed rank election +
    segmented repair + the replicated exchange refine over a
    distributed-built table, no P-sized sort on any device).  Both
    halves are bit-identical to
    :func:`..ops.linear_ot.assign_topic_linear` at ANY mesh size.

    ``lags`` is the exact host [P] int64 vector.  Fires
    ``mesh.collective`` on entry (callers degrade to the single-device
    backend on any failure).  Returns ``(choice int32[P] in input
    order, counts, totals, duals_rounds)`` as host arrays."""
    from ..models.sinkhorn import _scale_np
    from ..ops import linear_ot
    from ..ops.dispatch import ensure_x64, quality_tile

    ensure_x64()
    faults.fire("mesh.collective")
    C = int(num_consumers)
    lags = np.ascontiguousarray(lags, dtype=np.int64)
    P_len = int(lags.shape[0])
    D = mesh.shape[SOLVE_AXIS]
    tile_knob = quality_tile() if tile is None else tile
    # The pow2 plan bucket divides by any pow2 mesh size <= the
    # superblock count; larger (or non-pow2) meshes cannot take whole
    # superblocks, so the composition declines them loudly.
    S = linear_ot._SUPERBLOCKS
    if D > S or S % D:
        raise ValueError(
            f"solve_linear_sharded needs a pow2 mesh size <= {S}, "
            f"got {D}"
        )
    P2, tile_e, n_tiles = linear_ot.plan_shape(P_len, tile_knob)
    lags_p = np.zeros(P2, dtype=np.int64)
    lags_p[:P_len] = lags
    valid = np.zeros(P2, dtype=bool)
    valid[:P_len] = True
    scale = _scale_np(lags_p, valid, C)
    # Kernel plane: probe-once verdict + per-shard admission (each
    # shard's partials kernel sees P2/D rows).  Any dispatch failure
    # falls back to the XLA executable and pins the kernel off.
    from ..ops import linear_ot_pallas

    kernel = bool(
        linear_ot_pallas.linear_pallas_available(kind="duals")
        and linear_ot_pallas.linear_pallas_admit_sharded(
            P2 // D, C, tile_e
        )
    )
    step = _linear_duals_executable(
        mesh, C, int(iters), tile_e, kernel=kernel
    )
    lags_d, valid_d = _place_inputs(mesh, lags_p, valid)
    with metrics.span("sharded.linear_duals"), dispatch_gate():
        with metrics.device_phase("duals"):
            try:
                A, B, rounds = step(
                    lags_d, valid_d,
                    np.float64(scale), np.float32(int(valid.sum())),
                )
                A, B, rounds_np = jax.device_get((A, B, rounds))
            except Exception as exc:
                if not kernel:
                    raise
                linear_ot_pallas.mark_linear_kernel_bad(
                    "duals", repr(exc)
                )
                kernel = False
                step = _linear_duals_executable(
                    mesh, C, int(iters), tile_e, kernel=False
                )
                A, B, rounds = step(
                    lags_d, valid_d,
                    np.float64(scale), np.float32(int(valid.sum())),
                )
                A, B, rounds_np = jax.device_get((A, B, rounds))
    metrics.REGISTRY.counter(
        "klba_sharded_dispatch_total", {"path": "linear"}
    ).inc()
    from ..models.sinkhorn import _SCAN_ROUNDING_MAX_P

    if D > 1 and C >= 2 and P2 > _SCAN_ROUNDING_MAX_P:
        # Above the sequential-rounding threshold the single-device
        # tail takes the parallel branch — the one the sharded tail
        # reproduces bit-for-bit — so the rounding runs P-sharded.
        choice, counts, totals = _finish_sharded_tail(
            mesh, lags_p, valid, np.asarray(A), np.asarray(B), C,
            int(refine_iters), tiles=n_tiles, tile=tile_e,
            rounds=int(rounds_np), kernel=kernel,
        )
    else:
        pids_p = np.arange(P2, dtype=np.int32)
        choice, counts, totals = linear_ot.finish_from_duals(
            lags_p, pids_p, valid, np.asarray(A), np.asarray(B), C,
            int(refine_iters), tiles=n_tiles, tile=tile_e,
            rounds=int(rounds_np), backend=f"sharded:{D}",
            kernel=kernel,
        )
    return (
        choice[:P_len].astype(np.int32),
        counts,
        totals,
        int(rounds_np),
    )


def seed_reference(lags: np.ndarray, num_consumers: int) -> np.ndarray:
    """Host twin of the mesh-1 sharded seed (tests + the bench's
    single-device comparator): lag-descending stable sort, consumer =
    rank mod C.  ``solve_sharded`` on a 1-device mesh with
    ``refine_iters=0`` is bit-identical to this."""
    C = int(num_consumers)
    lags = np.asarray(lags, dtype=np.int64)
    order = np.lexsort((np.arange(lags.shape[0]), -lags))
    choice = np.empty(lags.shape[0], dtype=np.int32)
    choice[order] = np.arange(lags.shape[0], dtype=np.int32) % C
    return choice
