"""P-axis-sharded solve: seed sort + plan stats + exchange refine over a
device mesh.

The single-leader solve caps the north star at one chip's HBM/FLOPs: a
1M-partition lag vector, its sort, and the refine working set must all
fit one device.  This module shards the PARTITION axis over the mesh
manager's 1-D ``("p",)`` mesh (:mod:`.mesh`) so one huge solve spans
devices, with the consumer-axis state — per-consumer totals and counts,
C << P — kept REPLICATED and all-reduced per round:

* **Seed** (:func:`_seed_local`): each shard sorts its local rows lag
  descending (one local P/D-sized sort — the expensive sort never
  crosses devices), a one-scalar ``all_gather`` fixes each shard's
  global valid-rank offset, and row with global rank g takes consumer
  ``g % C``.  Global ranks are a bijection over the valid rows, so the
  seed is count-balanced (``max - min <= 1``) by construction at ANY
  mesh size.

* **Refine** (:func:`_refine_loop`): the EXACT round structure of
  :func:`..ops.refine.refine_assignment` — rank consumers by replicated
  totals, pair heavy with rotated light partners, score move/swap
  candidates with the same quantized packed-key sort + neighbour scans
  + segmented argmin — run per shard over LOCAL rows, then ONE
  ``pmin``-based all-reduce per round picks each pair's globally best
  exchange and a ``psum`` folds the winner's transfer back into the
  replicated totals/counts.  At mesh size 1 the local candidate set IS
  the global set and every reduce is the identity, so the result is
  **bit-identical** to ``refine_assignment`` (pinned by
  tests/test_sharded.py); at sizes 2-8 swaps are found within a shard
  (moves anywhere), so the output is count-balanced and quality-gated
  rather than bit-equal — the documented contract.

* **Plan stats** (:func:`plan_stats_sharded`): the per-consumer
  load/count marginals of an assignment as one shard-local segment sum
  + ``psum`` — no device ever materializes another shard's rows.

* **Linear-OT quality duals** (:func:`solve_linear_sharded`): the
  linear-space mirror-prox quality mode (:mod:`..ops.linear_ot`)
  composed with this mesh — each shard tile-streams its LOCAL rows'
  marginal partials per fixed superblock, one ``all_gather`` per outer
  iteration replicates the per-block partials, and the ordered f32
  combine + dual update run identically on every shard (consumer-axis
  duals all-reduced per outer iteration, the replicated-state pattern
  above).  Because the superblock decomposition and combine order are
  mesh-size-independent, the duals trajectory — and the final rounded
  assignment, which runs the single-device rounding pass on the
  replicated duals — is **bit-identical at mesh size 1 vs 2-8**
  (pinned by tests/test_linear_ot.py).

Executable discipline: one jitted ``shard_map`` program per (mesh, C,
budget, bucket) via an lru-cached builder — repeated solves at a shape
compile NOTHING after the first (the differential fuzz and the bench's
``sharded_scale`` probe gate on ``utils/observability.compile_count``).

Dispatch boundary: :func:`solve_sharded` / :func:`refine_sharded` fire
the ``mesh.collective`` fault point on entry; callers (the streaming
engine's cold hook, ops/dispatch selection) catch any failure, degrade
the mesh manager, and fall back to the single-device backend inside the
same request budget.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from ..ops.packing import pad_bucket, pad_chunk
from ..ops.refine import _PAIR_BITS, _SBIG_INT, _VBITS
from ..ops.sortops import bincount_sorted, segment_argmin_first, segment_sum
from ..utils import faults, metrics
from .mesh import CHECK_KW, SOLVE_AXIS, shard_map


def _quant_shift_all(lags, assigned, axis: str):
    """:func:`..ops.refine._quant_shift` with the max taken over EVERY
    shard (``pmax``), so all devices quantize identically; identity at
    mesh size 1."""
    maxlag = jnp.maximum(jnp.max(jnp.where(assigned, lags, 0)), 1)
    maxlag = lax.pmax(maxlag, axis)
    bitlen = 64 - lax.clz(maxlag.astype(jnp.int64))
    return jnp.maximum(bitlen - _VBITS, 0).astype(jnp.int64)


def _seed_local(lags, valid, num_consumers: int, axis: str, num_shards: int):
    """Count-balanced sharded seed (module docstring): local lag-desc
    sort, cross-shard valid-rank offsets, consumer = global rank mod C.
    Returns choice int32[L] in local input order (-1 on padding)."""
    L = lags.shape[0]
    C = int(num_consumers)
    arangeL = jnp.arange(L, dtype=jnp.int32)
    key = jnp.where(valid, -lags, jnp.iinfo(jnp.int64).max)
    _, srow = lax.sort((key, arangeL), num_keys=1)
    v_loc = jnp.sum(valid.astype(jnp.int32))
    counts_all = lax.all_gather(v_loc, axis)  # [D] scalar gather
    d = lax.axis_index(axis)
    offset = jnp.sum(
        jnp.where(jnp.arange(num_shards, dtype=jnp.int32) < d,
                  counts_all, 0)
    ).astype(jnp.int32)
    g = offset + arangeL
    seat = jnp.where(
        arangeL < v_loc, (g % C).astype(jnp.int32), jnp.int32(-1)
    )
    return jnp.zeros((L,), jnp.int32).at[srow].set(seat)


def _refine_loop(
    lags, valid, choice, num_consumers: int, iters: int,
    max_pairs: Optional[int], patience: int, axis: str, num_shards: int,
):
    """The :func:`..ops.refine.refine_assignment` round loop over LOCAL
    rows with replicated consumer-axis state all-reduced per round (one
    ``pmin`` winner election + one ``psum`` transfer fold); identity
    reduces — and therefore bit-parity — at mesh size 1."""
    C = int(num_consumers)
    L = lags.shape[0]
    K = max(1, min(C // 2, max_pairs if max_pairs is not None else C // 2))
    if K >= (1 << _PAIR_BITS) - 1:
        raise ValueError(
            f"max_pairs={K} exceeds the packed pair-id field "
            f"({_PAIR_BITS} bits)"
        )
    arangeC = jnp.arange(C, dtype=jnp.int32)
    arangeL = jnp.arange(L, dtype=jnp.int32)
    key_big = jnp.iinfo(jnp.int64).max
    vmask = (jnp.int64(1) << _VBITS) - 1
    sbig = jnp.asarray(_SBIG_INT, jnp.int64)
    D = int(num_shards)

    choice = choice.astype(jnp.int32)
    assigned = valid & (choice >= 0)
    seg0 = jnp.where(assigned, choice, -1)
    totals0 = lax.psum(
        segment_sum(jnp.where(assigned, lags, 0), seg0, C), axis
    )
    counts0 = lax.psum(bincount_sorted(seg0, C), axis)
    zero32 = jnp.int32(0)
    if C < 2 or iters <= 0:
        return choice, counts0, totals0, zero32
    pshift = _quant_shift_all(lags, assigned, axis)
    n_light = C - K
    didx = lax.axis_index(axis)

    def body(state):
        it, since, choice, totals, counts = state
        safe_choice = jnp.clip(choice, 0, C - 1)

        # Pairing over the REPLICATED totals: identical on every shard
        # (deterministic argsort of identical inputs).
        order = jnp.argsort(totals).astype(jnp.int32)
        rank = jnp.zeros((C,), jnp.int32).at[order].set(arangeC)
        shift = it % jnp.int32(n_light)
        light_slot = (jnp.arange(K, dtype=jnp.int32) + shift) % n_light
        light = order[light_slot]
        heavy = order[C - 1 - jnp.arange(K)]
        diff = totals[heavy] - totals[light]

        slot_to_pair = (
            jnp.full((n_light,), K, jnp.int32)
            .at[light_slot]
            .set(jnp.arange(K, dtype=jnp.int32))
        )
        pair_of = jnp.where(
            rank < n_light,
            slot_to_pair[jnp.clip(rank, 0, n_light - 1)],
            C - 1 - rank,
        )
        heavy_side = rank >= C - K
        move_ok_pair = counts[heavy] > counts[light]
        move_ok_of = jnp.where(
            heavy_side,
            jnp.pad(move_ok_pair, (0, 1))[jnp.clip(pair_of, 0, K)],
            False,
        )
        combo_tab = (
            pair_of
            | (heavy_side.astype(jnp.int32) << _PAIR_BITS)
            | (move_ok_of.astype(jnp.int32) << (_PAIR_BITS + 1))
        )
        combo = jnp.where(assigned, combo_tab[safe_choice], -1)
        k_p = combo & ((1 << _PAIR_BITS) - 1)
        row_heavy = (combo >> _PAIR_BITS) & 1
        row_move_ok = (combo >> (_PAIR_BITS + 1)) & 1
        participates = (combo >= 0) & (k_p < K)
        kc = jnp.clip(k_p, 0, K - 1)
        diff_p = jnp.where(participates, diff[kc], 0)
        delta_p = diff_p >> 1

        # LOCAL candidate sort (the expensive P-sized work stays on
        # shard; the oracle's key layout verbatim).
        qself = lags >> pshift
        tgt = jnp.clip(lags - delta_p, 0, None) >> pshift
        qval = jnp.where(row_heavy == 1, tgt, qself)
        key = jnp.where(
            participates,
            (k_p.astype(jnp.int64) << (_VBITS + 1))
            | (jnp.clip(qval, 0, vmask) << 1)
            | row_heavy.astype(jnp.int64),
            key_big,
        )
        skey, slag, srow, smove_ok = lax.sort(
            (key, lags, arangeL, row_move_ok), num_keys=1
        )
        part_s = skey < key_big
        pair_s = (skey >> (_VBITS + 1)).astype(jnp.int32)
        heavy_s = part_s & ((skey & 1) == 1)
        light_s = part_s & ((skey & 1) == 0)
        qlag_s = slag >> pshift
        diff_s = jnp.where(heavy_s, diff[jnp.clip(pair_s, 0, K - 1)], 0)
        delta_q_s = (diff_s >> 1) >> pshift
        diff_q_s = diff_s >> pshift

        prev_l = lax.cummax(jnp.where(light_s, arangeL, -1))
        nxt_l = lax.cummin(
            jnp.where(light_s, arangeL, L), reverse=True
        )

        def neighbour(nb):
            inb = jnp.clip(nb, 0, L - 1)
            nkey = skey[inb]
            okq = (
                (nb >= 0) & (nb < L)
                & ((nkey & 1) == 0)
                & ((nkey >> (_VBITS + 1)).astype(jnp.int32) == pair_s)
            )
            d_q = qlag_s - ((nkey >> 1) & vmask)
            ok = heavy_s & okq & (d_q > 0) & (d_q < diff_q_s)
            return jnp.where(ok, jnp.abs(d_q - delta_q_s), sbig)

        err_a = neighbour(prev_l)
        err_b = neighbour(nxt_l)
        use_b = err_b < err_a
        err_swap = jnp.where(use_b, err_b, err_a)
        nb_sel = jnp.where(use_b, nxt_l, prev_l)

        ok_move = (
            heavy_s & (smove_ok == 1) & (slag > 0) & (slag < diff_s)
        )
        score_move = jnp.where(
            ok_move, jnp.abs(qlag_s - delta_q_s), sbig
        )
        combined = jnp.where(
            score_move <= err_swap,
            score_move << 1,
            (err_swap << 1) | 1,
        )
        seg_h = jnp.where(heavy_s, pair_s, K)
        minv, widx = segment_argmin_first(combined, seg_h, K, L)

        # Per-pair winner ELECTION across shards: the smallest packed
        # score wins, ties to the lowest device index.  All-reduced so
        # every shard agrees; identity at D=1.
        gmin = lax.pmin(minv, axis)
        has = minv == gmin
        win_d = lax.pmin(
            jnp.where(has, jnp.full((K,), didx, jnp.int32), D), axis
        )
        mine = win_d == didx
        do = gmin < (sbig << 1)
        is_swap = (gmin & 1) == 1

        wclip = jnp.clip(widx, 0, L - 1)
        p_sel = srow[wclip]
        lag_p = slag[wclip]
        nb_k = jnp.clip(nb_sel[wclip], 0, L - 1)
        q_sel = srow[nb_k]
        lag_q = slag[nb_k]
        use_swap = do & is_swap
        d_amt = jnp.where(use_swap, lag_p - lag_q, lag_p)
        d_amt = jnp.where(do, d_amt, 0)
        # The winner's exact transfer, folded into the replicated
        # totals (only the winning shard contributes non-zero).
        d_k = lax.psum(jnp.where(mine, d_amt, 0), axis)

        upd_p = jnp.where(mine & do, p_sel, jnp.int32(L))
        upd_q = jnp.where(mine & use_swap, q_sel, jnp.int32(L))
        new_choice = choice.at[upd_p].set(light, mode="drop")
        new_choice = new_choice.at[upd_q].set(heavy, mode="drop")
        new_totals = totals.at[heavy].add(-d_k).at[light].add(d_k)
        dc = (do & ~is_swap).astype(jnp.int32)
        new_counts = counts.at[heavy].add(-dc).at[light].add(dc)
        peak_dropped = jnp.max(new_totals) < jnp.max(totals)
        new_since = jnp.where(peak_dropped, zero32, since + 1)
        return it + 1, new_since, new_choice, new_totals, new_counts

    def cond(state):
        it, since = state[0], state[1]
        return (it < iters) & (since < patience)

    it, _, choice, totals, counts = lax.while_loop(
        cond, body, (zero32, zero32, choice, totals0, counts0)
    )
    return choice, counts, totals, it


@functools.lru_cache(maxsize=32)
def _sharded_executable(
    mesh, num_consumers: int, iters: int, max_pairs, patience: int,
    seeded: bool,
):
    """Build + jit ONE shard_map program per (mesh, C, budget, mode) —
    the builder is lru-cached so repeated solves retrace nothing."""
    D = mesh.shape[SOLVE_AXIS]

    if seeded:

        def step(lags, valid):
            choice = _seed_local(
                lags, valid, num_consumers, SOLVE_AXIS, D
            )
            return _refine_loop(
                lags, valid, choice, num_consumers, iters, max_pairs,
                patience, SOLVE_AXIS, D,
            )

        in_specs = (
            PartitionSpec(SOLVE_AXIS), PartitionSpec(SOLVE_AXIS),
        )
    else:

        def step(lags, valid, choice):
            return _refine_loop(
                lags, valid, choice, num_consumers, iters, max_pairs,
                patience, SOLVE_AXIS, D,
            )

        in_specs = (
            PartitionSpec(SOLVE_AXIS), PartitionSpec(SOLVE_AXIS),
            PartitionSpec(SOLVE_AXIS),
        )
    mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(
            PartitionSpec(SOLVE_AXIS),  # choice
            PartitionSpec(),            # counts: replicated
            PartitionSpec(),            # totals: replicated
            PartitionSpec(),            # rounds
        ),
        # The while_loop carry starts from literal zeros (typed
        # unvarying by the manual-axes checker even though data varies
        # over "p"); parity with the unsharded kernel is asserted by
        # tests instead — the same waiver the topic-axis backend uses.
        **{CHECK_KW: False},
    )
    return jax.jit(mapped)


def shard_bucket(num_rows: int, num_shards: int) -> int:
    """Padded solve shape: the streaming buckets (pow2 on accelerators,
    4096-chunks on CPU) rounded up to a multiple of the mesh size so
    the P axis splits evenly."""
    B = (
        pad_chunk(num_rows)
        if jax.default_backend() == "cpu"
        else pad_bucket(num_rows)
    )
    D = int(num_shards)
    if B % D:
        B += D - (B % D)
    return B


def _place_inputs(mesh, *host_arrays):
    """Device-put padded host inputs with the "p" sharding so each
    shard's slice lands directly on its device (no host gather)."""
    spec = NamedSharding(mesh, PartitionSpec(SOLVE_AXIS))
    return tuple(jax.device_put(a, spec) for a in host_arrays)


def solve_sharded(
    mesh,
    lags: np.ndarray,
    num_consumers: int,
    refine_iters: int = 64,
    max_pairs: Optional[int] = None,
    patience: int = 8,
):
    """One P-axis-sharded cold solve (seed + refine) on ``mesh``.

    ``lags`` is the exact host [P] int64 vector; padding to the
    mesh-divisible bucket happens here.  Fires ``mesh.collective`` on
    entry (the sharded dispatch boundary — callers degrade to the
    single-device backend on any failure).  Returns ``(choice int32[P]
    in input order, counts int32[C], totals int64[C], rounds)`` as host
    arrays; the choice is count-balanced at any mesh size.
    """
    from ..ops.dispatch import ensure_x64

    ensure_x64()
    faults.fire("mesh.collective")
    C = int(num_consumers)
    lags = np.ascontiguousarray(lags, dtype=np.int64)
    P_len = int(lags.shape[0])
    D = mesh.shape[SOLVE_AXIS]
    B = shard_bucket(P_len, D)
    lags_p = np.zeros(B, dtype=np.int64)
    lags_p[:P_len] = lags
    valid = np.zeros(B, dtype=bool)
    valid[:P_len] = True
    step = _sharded_executable(
        mesh, C, int(refine_iters), max_pairs, int(patience), True
    )
    with metrics.span("sharded.solve"):
        choice, counts, totals, rounds = step(
            *_place_inputs(mesh, lags_p, valid)
        )
        choice_np, counts_np, totals_np, rounds_np = jax.device_get(
            (choice, counts, totals, rounds)
        )
    metrics.REGISTRY.counter(
        "klba_sharded_dispatch_total", {"path": "solve"}
    ).inc()
    return (
        np.asarray(choice_np)[:P_len].astype(np.int32),
        np.asarray(counts_np),
        np.asarray(totals_np),
        int(rounds_np),
    )


def refine_sharded(
    mesh,
    lags: np.ndarray,
    valid: np.ndarray,
    choice: np.ndarray,
    num_consumers: int,
    iters: int = 16,
    max_pairs: Optional[int] = None,
    patience: int = 8,
):
    """Mesh-parity refinement entry: the P-sharded equivalent of
    :func:`..ops.refine.refine_assignment` — bit-identical to it at
    mesh size 1, count-preserving and quality-gated at sizes 2-8.
    Inputs are host arrays of one padded length divisible by the mesh
    size.  Returns host ``(choice int32[P], counts, totals, rounds)``.
    """
    from ..ops.dispatch import ensure_x64

    ensure_x64()
    faults.fire("mesh.collective")
    C = int(num_consumers)
    D = mesh.shape[SOLVE_AXIS]
    lags = np.ascontiguousarray(lags, dtype=np.int64)
    if lags.shape[0] % D:
        raise ValueError(
            f"refine_sharded input length {lags.shape[0]} must divide "
            f"the mesh size {D} (pad with valid=False rows)"
        )
    step = _sharded_executable(
        mesh, C, int(iters), max_pairs, int(patience), False
    )
    with metrics.span("sharded.refine"):
        out = step(
            *_place_inputs(
                mesh,
                lags,
                np.ascontiguousarray(valid, dtype=bool),
                np.ascontiguousarray(choice, dtype=np.int32),
            )
        )
        choice_o, counts_o, totals_o, rounds_o = jax.device_get(out)
    metrics.REGISTRY.counter(
        "klba_sharded_dispatch_total", {"path": "refine"}
    ).inc()
    return (
        np.asarray(choice_o).astype(np.int32),
        np.asarray(counts_o),
        np.asarray(totals_o),
        int(rounds_o),
    )


@functools.lru_cache(maxsize=32)
def _plan_stats_executable(mesh, num_consumers: int):
    def step(lags, valid, choice):
        assigned = valid & (choice >= 0)
        seg = jnp.where(assigned, choice, -1)
        totals = lax.psum(
            segment_sum(jnp.where(assigned, lags, 0), seg, num_consumers),
            SOLVE_AXIS,
        )
        counts = lax.psum(
            bincount_sorted(seg, num_consumers), SOLVE_AXIS
        )
        return totals, counts

    mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=(
            PartitionSpec(SOLVE_AXIS), PartitionSpec(SOLVE_AXIS),
            PartitionSpec(SOLVE_AXIS),
        ),
        out_specs=(PartitionSpec(), PartitionSpec()),
        **{CHECK_KW: False},
    )
    return jax.jit(mapped)


def plan_stats_sharded(mesh, lags, valid, choice, num_consumers: int):
    """Sharded plan stats: per-consumer ``(totals int64[C], counts
    int32[C])`` of an assignment via shard-local segment sums + one
    ``psum`` — no device materializes another shard's rows.  Inputs are
    host arrays of one mesh-divisible padded length."""
    from ..ops.dispatch import ensure_x64

    ensure_x64()
    step = _plan_stats_executable(mesh, int(num_consumers))
    totals, counts = step(
        *_place_inputs(
            mesh,
            np.ascontiguousarray(lags, dtype=np.int64),
            np.ascontiguousarray(valid, dtype=bool),
            np.ascontiguousarray(choice, dtype=np.int32),
        )
    )
    return np.asarray(totals), np.asarray(counts)


@functools.lru_cache(maxsize=32)
def _linear_duals_executable(
    mesh, num_consumers: int, iters: int, tile: int,
    kernel: bool = False,
):
    """Build + jit the P-sharded mirror-prox dual program: one
    executable per (mesh, C, iters, tile, kernel) — shapes
    re-specialize via the jit cache like every other sharded program
    here.  ``kernel`` swaps the shard-local marginal partials for the
    Pallas tile kernel (:func:`..ops.linear_ot_pallas.
    superblock_partials_pallas` — bit-identical partials, same
    all-gather + ordered combine, so mesh parity is untouched);
    callers gate it on the probe-once verdict + per-shard admission."""
    from ..ops import linear_ot
    from ..ops import linear_ot_pallas

    D = mesh.shape[SOLVE_AXIS]
    S = linear_ot._SUPERBLOCKS
    C = int(num_consumers)

    def step(lags, valid, scale, n_valid):
        # Local rows -> local superblocks (shard d owns whole blocks
        # d*S/D .. (d+1)*S/D - 1 of the GLOBAL decomposition; padding
        # sits at the global tail, so block contents match the
        # single-device layout exactly).
        L = lags.shape[0]
        ws, cnt = linear_ot._ws_cnt(lags, valid, scale)
        ws_b = linear_ot._to_blocks(ws, L, S // D, tile)
        cnt_b = linear_ot._to_blocks(cnt, L, S // D, tile)

        def stats_fn(A, B):
            if kernel:
                pl, pc = linear_ot_pallas.superblock_partials_pallas(
                    ws_b, cnt_b, A, B
                )
            else:
                pl, pc = linear_ot._superblock_partials(
                    ws_b, cnt_b, A, B
                )
            # Consumer-axis all-reduce per outer iteration: gather the
            # per-block partials into GLOBAL block order, then the
            # same fixed left-to-right combine as the single-device
            # path — bit-identical marginals at any mesh size.
            pl = lax.all_gather(pl, SOLVE_AXIS, axis=0, tiled=True)
            pc = lax.all_gather(pc, SOLVE_AXIS, axis=0, tiled=True)
            return (
                linear_ot._ordered_sum(pl),
                linear_ot._ordered_sum(pc),
            )

        return linear_ot.mirror_prox(stats_fn, C, int(iters), n_valid)

    mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=(
            PartitionSpec(SOLVE_AXIS), PartitionSpec(SOLVE_AXIS),
            PartitionSpec(), PartitionSpec(),
        ),
        out_specs=(
            PartitionSpec(),  # A: replicated duals
            PartitionSpec(),  # B
            PartitionSpec(),  # rounds
        ),
        **{CHECK_KW: False},
    )
    return jax.jit(mapped)


def solve_linear_sharded(
    mesh,
    lags: np.ndarray,
    num_consumers: int,
    iters: int = 24,
    refine_iters: int = 64,
    tile: Optional[int] = None,
):
    """One linear-OT quality cold solve with the DUALS P-sharded over
    ``mesh`` (module docstring): the O(iters * P * C) marginal scans —
    the dominant cost — split across shards; the O(P log P) rounding
    pass then runs the unchanged single-device linear rounding on the
    replicated duals, so the result is bit-identical to
    :func:`..ops.linear_ot.assign_topic_linear` at ANY mesh size.

    ``lags`` is the exact host [P] int64 vector.  Fires
    ``mesh.collective`` on entry (callers degrade to the single-device
    backend on any failure).  Returns ``(choice int32[P] in input
    order, counts, totals, duals_rounds)`` as host arrays."""
    from ..models.sinkhorn import _scale_np
    from ..ops import linear_ot
    from ..ops.dispatch import ensure_x64, quality_tile

    ensure_x64()
    faults.fire("mesh.collective")
    C = int(num_consumers)
    lags = np.ascontiguousarray(lags, dtype=np.int64)
    P_len = int(lags.shape[0])
    D = mesh.shape[SOLVE_AXIS]
    tile_knob = quality_tile() if tile is None else tile
    # The pow2 plan bucket divides by any pow2 mesh size <= the
    # superblock count; larger (or non-pow2) meshes cannot take whole
    # superblocks, so the composition declines them loudly.
    S = linear_ot._SUPERBLOCKS
    if D > S or S % D:
        raise ValueError(
            f"solve_linear_sharded needs a pow2 mesh size <= {S}, "
            f"got {D}"
        )
    P2, tile_e, n_tiles = linear_ot.plan_shape(P_len, tile_knob)
    lags_p = np.zeros(P2, dtype=np.int64)
    lags_p[:P_len] = lags
    valid = np.zeros(P2, dtype=bool)
    valid[:P_len] = True
    scale = _scale_np(lags_p, valid, C)
    # Kernel plane: probe-once verdict + per-shard admission (each
    # shard's partials kernel sees P2/D rows).  Any dispatch failure
    # falls back to the XLA executable and pins the kernel off.
    from ..ops import linear_ot_pallas

    kernel = bool(
        linear_ot_pallas.linear_pallas_available(kind="duals")
        and linear_ot_pallas.linear_pallas_admit_sharded(
            P2 // D, C, tile_e
        )
    )
    step = _linear_duals_executable(
        mesh, C, int(iters), tile_e, kernel=kernel
    )
    lags_d, valid_d = _place_inputs(mesh, lags_p, valid)
    with metrics.span("sharded.linear_duals"):
        with metrics.device_phase("duals"):
            try:
                A, B, rounds = step(
                    lags_d, valid_d,
                    np.float64(scale), np.float32(int(valid.sum())),
                )
                A, B, rounds_np = jax.device_get((A, B, rounds))
            except Exception as exc:
                if not kernel:
                    raise
                linear_ot_pallas.mark_linear_kernel_bad(
                    "duals", repr(exc)
                )
                kernel = False
                step = _linear_duals_executable(
                    mesh, C, int(iters), tile_e, kernel=False
                )
                A, B, rounds = step(
                    lags_d, valid_d,
                    np.float64(scale), np.float32(int(valid.sum())),
                )
                A, B, rounds_np = jax.device_get((A, B, rounds))
    metrics.REGISTRY.counter(
        "klba_sharded_dispatch_total", {"path": "linear"}
    ).inc()
    pids_p = np.arange(P2, dtype=np.int32)
    choice, counts, totals = linear_ot.finish_from_duals(
        lags_p, pids_p, valid, np.asarray(A), np.asarray(B), C,
        int(refine_iters), tiles=n_tiles, tile=tile_e,
        rounds=int(rounds_np), backend=f"sharded:{D}",
        kernel=kernel,
    )
    return (
        choice[:P_len].astype(np.int32),
        counts,
        totals,
        int(rounds_np),
    )


def seed_reference(lags: np.ndarray, num_consumers: int) -> np.ndarray:
    """Host twin of the mesh-1 sharded seed (tests + the bench's
    single-device comparator): lag-descending stable sort, consumer =
    rank mod C.  ``solve_sharded`` on a 1-device mesh with
    ``refine_iters=0`` is bit-identical to this."""
    C = int(num_consumers)
    lags = np.asarray(lags, dtype=np.int64)
    order = np.lexsort((np.arange(lags.shape[0]), -lags))
    choice = np.empty(lags.shape[0], dtype=np.int32)
    choice[order] = np.arange(lags.shape[0], dtype=np.int32) % C
    return choice
