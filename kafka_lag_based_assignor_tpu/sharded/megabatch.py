"""Stream-axis (and cross-axis 2-D) sharding for the roster-locked
megabatch.

The megabatch coalescer (:mod:`..ops.coalesce`) stacks N tenants' warm
epochs into ONE vmapped fused dispatch — but on a single device those N
independent rows still queue on one chip.  The rows are embarrassingly
parallel (each tenant's refine loop touches only its own [B]/[C, M]
slices), so the stacked batch partitions perfectly over a leading
``("streams",)`` mesh axis with ZERO collectives: this module owns the
placement decisions, and the coalescer stays the only caller.

* :func:`place_batch` shards a locked roster's stacked resident
  successors ``(choice [N, B], row_tab [N, C, M], counts [N, C], lags
  [N, B])`` across the streams mesh ONCE at lock time — the locked
  executable then donates sharded buffers and returns sharded
  successors, so the steady state pays no per-flush re-placement
  (exactly the zero-re-stack contract, now spread over D devices).
* :func:`place_rows` lands a wave's staged host uploads (lags/limits,
  or the delta idx/vals) directly on their row's device — each shard's
  H2D slice transfers to its own chip, no gather hop.
* :func:`shardable` is the eligibility rule: the padded batch axis must
  cover and divide the mesh (pow2 n_pad over pow2 D always divides once
  n_pad >= D).

**Cross-axis composition** (the 2-D ``("streams", "p")`` mesh,
:meth:`..sharded.mesh.MeshManager.mesh2d`): a 2-D shape gives the
"streams" axis only S of the pool's S*D devices, so a batch locked
stream-only on that rung would cap at S-way row parallelism while D-1
of every group's chips idle.  :func:`place_batch2d` composes BOTH
axes on the batch dimension — ``PartitionSpec(("streams", "p"))``
flattens the full 2-D grid under the stacked N axis, every roster row
lands WHOLE on exactly one of the S*D chips, and the vmapped locked
executable stays collective-free (bit-for-bit the stream-sharded
program, just spread over the full pool).  The row axis is
deliberately NOT split here: slicing [B] under the vmapped refine
forces the partitioner into per-wave all-gather + replicated-sort
round trips (measured ~4x a steady wave on the 8-device virtual
mesh, scaling with B) — a single tenant whose [B] exceeds one chip
is served by the resident P-shard plane (:mod:`.resident`) and the
P-sharded solve/rounding tail (:mod:`.solve`) on the SAME mesh's "p"
axis, which is exactly the cross-axis contract: one (S, D) grid,
batch rows over all of it, per-tenant row state over "p".
Eligibility (:func:`shardable2d`): the padded batch axis must cover
and divide the flattened S*D extent.  The executables are unchanged
— placement remains input sharding, the SPMD partitioner propagates
it, and the integer refine is exact under any placement, so roster
lock, donation, delta staging, and the per-row digest lanes all read
identically.

Round-10 invariants are preserved by construction: the executables and
their donation signatures are unchanged (placement is input sharding,
not new code paths), churn still invalidates the roster exactly once,
and per-row failure isolation/digest quarantine read per-row outputs
that slicing a sharded array serves identically.  A ``mesh.collective``
fault (or a real placement/dispatch failure) degrades the coalescer
down the manager's ladder (2-D -> streams -> single-device) — in-flight
rows resolve through the existing single-stream fallback, never an
invalid answer.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from .mesh import SOLVE_AXIS, STREAMS_AXIS


def shardable(mesh, n_pad: int) -> bool:
    """True when a padded batch of ``n_pad`` rows splits evenly over
    ``mesh``'s streams axis."""
    if mesh is None:
        return False
    D = mesh.shape[STREAMS_AXIS]
    return D > 1 and n_pad >= D and n_pad % D == 0


def shardable2d(mesh2d, n_pad: int) -> bool:
    """Cross-axis eligibility: the padded batch axis must cover and
    divide the FLATTENED S*D extent (pow2 n_pad over a pow2 grid
    always divides once ``n_pad >= S*D``)."""
    if mesh2d is None:
        return False
    SD = mesh2d.shape[STREAMS_AXIS] * mesh2d.shape[SOLVE_AXIS]
    return SD > 1 and n_pad >= SD and n_pad % SD == 0


def stream_sharding(mesh, rank: int) -> NamedSharding:
    """Leading-axis ("streams") sharding for a rank-``rank`` stacked
    array: rows spread over devices, every trailing axis replicated
    within its row's shard (on a 2-D mesh the unused "p" axis
    replicates)."""
    spec = PartitionSpec(STREAMS_AXIS, *([None] * (rank - 1)))
    return NamedSharding(mesh, spec)


def cross_sharding(mesh2d, rank: int) -> NamedSharding:
    """Devices-flattened batch-axis sharding for a rank-``rank``
    stacked array on the 2-D mesh: the leading N axis spreads over the
    FULL ("streams", "p") grid — each row whole on one of the S*D
    chips — and every trailing axis stays unsplit within it."""
    spec = PartitionSpec(
        (STREAMS_AXIS, SOLVE_AXIS), *([None] * (rank - 1))
    )
    return NamedSharding(mesh2d, spec)


def _leading_sharding(mesh, rank: int) -> NamedSharding:
    """The leading-axis sharding for ``mesh`` — flattened cross-axis
    when the mesh carries a "p" extent, plain streams otherwise."""
    if dict(getattr(mesh, "shape", {})).get(SOLVE_AXIS, 1) > 1:
        return cross_sharding(mesh, rank)
    return stream_sharding(mesh, rank)


def place_batch(mesh, arrays):
    """Shard a locked batch's stacked device buffers over the streams
    axis (one reshard per LOCK, not per flush).  Returns the placed
    tuple in input order."""
    return tuple(
        jax.device_put(a, stream_sharding(mesh, a.ndim)) for a in arrays
    )


def place_batch2d(mesh2d, arrays):
    """Shard a locked batch's resident 4-tuple ``(choice [N, B],
    row_tab [N, C, M], counts [N, C], lags [N, B])`` on the full 2-D
    mesh: every buffer's batch axis spreads over the flattened
    ("streams", "p") grid, rows whole per chip.  One reshard per LOCK,
    exactly like :func:`place_batch`."""
    return tuple(
        jax.device_put(a, cross_sharding(mesh2d, a.ndim)) for a in arrays
    )


def place_rows(mesh, *host_arrays):
    """Start the async H2D of a wave's staged host arrays with the
    batch's leading-axis sharding — each row's slice lands on its own
    device (on the 2-D mesh, one of the S*D flattened chips), no
    gather hop.  The caller (the coalescer's counted ``_stage_upload``
    / ``_stage_delta_upload`` sites) owns the byte accounting."""
    return tuple(
        jax.device_put(a, _leading_sharding(mesh, a.ndim))
        for a in host_arrays
    )
