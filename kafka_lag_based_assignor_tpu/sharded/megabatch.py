"""Stream-axis sharding for the roster-locked megabatch.

The megabatch coalescer (:mod:`..ops.coalesce`) stacks N tenants' warm
epochs into ONE vmapped fused dispatch — but on a single device those N
independent rows still queue on one chip.  The rows are embarrassingly
parallel (each tenant's refine loop touches only its own [B]/[C, M]
slices), so the stacked batch partitions perfectly over a leading
``("streams",)`` mesh axis with ZERO collectives: this module owns the
placement decisions, and the coalescer stays the only caller.

* :func:`place_batch` shards a locked roster's stacked resident
  successors ``(choice [N, B], row_tab [N, C, M], counts [N, C], lags
  [N, B])`` across the streams mesh ONCE at lock time — the locked
  executable then donates sharded buffers and returns sharded
  successors, so the steady state pays no per-flush re-placement
  (exactly the zero-re-stack contract, now spread over D devices).
* :func:`place_rows` lands a wave's staged host uploads (lags/limits,
  or the delta idx/vals) directly on their row's device — each shard's
  H2D slice transfers to its own chip, no gather hop.
* :func:`shardable` is the eligibility rule: the padded batch axis must
  cover and divide the mesh (pow2 n_pad over pow2 D always divides once
  n_pad >= D).

Round-10 invariants are preserved by construction: the executables and
their donation signatures are unchanged (placement is input sharding,
not new code paths), churn still invalidates the roster exactly once,
and per-row failure isolation/digest quarantine read per-row outputs
that slicing a sharded array serves identically.  A ``mesh.collective``
fault (or a real placement/dispatch failure) degrades the coalescer to
the single-device placement via the mesh manager — in-flight rows
resolve through the existing single-stream fallback, never an invalid
answer.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from .mesh import STREAMS_AXIS


def shardable(mesh, n_pad: int) -> bool:
    """True when a padded batch of ``n_pad`` rows splits evenly over
    ``mesh``'s streams axis."""
    if mesh is None:
        return False
    D = mesh.shape[STREAMS_AXIS]
    return D > 1 and n_pad >= D and n_pad % D == 0


def stream_sharding(mesh, rank: int) -> NamedSharding:
    """Leading-axis ("streams") sharding for a rank-``rank`` stacked
    array: rows spread over devices, every trailing axis replicated
    within its row's shard."""
    spec = PartitionSpec(STREAMS_AXIS, *([None] * (rank - 1)))
    return NamedSharding(mesh, spec)


def place_batch(mesh, arrays):
    """Shard a locked batch's stacked device buffers over the streams
    axis (one reshard per LOCK, not per flush).  Returns the placed
    tuple in input order."""
    return tuple(
        jax.device_put(a, stream_sharding(mesh, a.ndim)) for a in arrays
    )


def place_rows(mesh, *host_arrays):
    """Start the async H2D of a wave's staged host arrays with the
    streams sharding — each row's slice lands on its own device.  The
    caller (the coalescer's counted ``_stage_upload`` /
    ``_stage_delta_upload`` sites) owns the byte accounting."""
    return tuple(
        jax.device_put(a, stream_sharding(mesh, a.ndim))
        for a in host_arrays
    )
