"""Mesh manager: discover + validate the device mesh ONCE at service start.

Multi-device is a first-class, tested backend (ROADMAP "shard the solve
and the megabatch"): the P axis of one huge solve shards over the mesh
(:mod:`.solve`), the megabatch's stream axis spreads tenants across
devices (:mod:`.megabatch`), and the topic-axis batch backend lives in
:mod:`.topics`.  This module owns the topology decisions every one of
those paths shares:

* **Discovery/validation at start, not per request.**  The service (or a
  library embedder) builds one :class:`MeshManager` from the
  ``tpu.assignor.mesh.devices`` knob ("off" | "auto" | an integer),
  calls :meth:`MeshManager.configure` once at boot — real TPUs, or the
  8-device virtual CPU mesh via
  ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` so every
  sharded path runs in tier-1 — and :func:`activate` installs it as the
  process-wide backend selection input (the :mod:`..utils.faults`
  ``_ACTIVE`` pattern: one global load on the off path).

* **Single-device is the default AND the degradation target.**  An
  unconfigured process never builds a mesh; a configured one that loses
  devices (``configure`` finding fewer than asked), takes an injected
  ``mesh.collective`` fault, or sees a sharded dispatch raise is
  :meth:`degraded <MeshManager.degrade>` — every later backend
  selection answers "single-device" and the existing degraded-mode
  ladder serves the in-flight request (the callers catch, never the
  mesh).  Degradation is observable: ``klba_mesh_active`` /
  ``klba_mesh_devices`` gauges, ``klba_mesh_degraded_total{reason}``.

Lint rule L020 confines ``Mesh``/``shard_map``/``NamedSharding``
construction to this package, so topology cannot leak back into ad-hoc
side modules (the old ``parallel/`` dead end this subsystem absorbed).
"""

from __future__ import annotations

import inspect
import logging
import threading
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

import jax
from jax.sharding import Mesh

from ..utils import faults, metrics

LOGGER = logging.getLogger(__name__)

# shard_map moved to the jax namespace (and its replication-check kwarg
# was renamed check_rep -> check_vma) across the jax versions this
# package supports; resolve both ONCE so every sharded step in this
# package builds on either API without a per-call probe.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax <= 0.4.x: the experimental home
    from jax.experimental.shard_map import shard_map
CHECK_KW = (
    "check_vma"
    if "check_vma" in inspect.signature(shard_map).parameters
    else "check_rep"
)

#: Axis names: the P-sharded solve partitions partition rows over "p";
#: the megabatch spreads tenant rows over "streams".
SOLVE_AXIS = "p"
STREAMS_AXIS = "streams"

#: Default P floor below which a single device wins outright (the
#: sharded seed/refine pays collectives per round; a small solve's
#: whole working set fits one chip).  Deployments override via
#: ``tpu.assignor.mesh.solve.min.rows``.
DEFAULT_SOLVE_MIN_ROWS = 65536


class MeshCollectiveError(RuntimeError):
    """A sharded dispatch lost a collective (injected ``mesh.collective``
    fault or a real cross-device failure): the mesh manager has already
    degraded to the single-device backend; the caller serves this
    request down the existing ladder."""


def _parse_spec(spec: Any) -> Any:
    """``"off"`` | ``"auto"`` | positive int (accepts int-like strings)."""
    if spec in (None, "", "off", "0", 0, False):
        return "off"
    if spec == "auto":
        return "auto"
    try:
        n = int(spec)
    except (TypeError, ValueError):
        raise ValueError(
            f"mesh devices spec {spec!r} invalid; use 'off', 'auto', or "
            "a positive integer"
        )
    if n < 1:
        raise ValueError(f"mesh devices spec {n} must be >= 1")
    return n


class MeshManager:
    """One process's device-mesh topology + health state.

    ``devices`` is the ``tpu.assignor.mesh.devices`` spec: ``"off"``
    (never shard — the constructor is cheap and inert), ``"auto"`` (all
    visible devices; inactive when only one is visible), or an integer
    N (exactly the first N visible devices; fewer visible = boot-time
    degrade, not an exception — fail open to single-device).
    ``solve_min_rows`` gates the P-sharded solve backend: below it the
    single-device path wins outright.
    """

    def __init__(
        self,
        devices: Any = "auto",
        solve_min_rows: int = DEFAULT_SOLVE_MIN_ROWS,
    ):
        self.spec = _parse_spec(devices)
        self.solve_min_rows = int(solve_min_rows)
        self._lock = threading.Lock()
        self._devices: List[Any] = []
        self._degraded: Optional[str] = None
        self._configured = False
        self._solve_mesh: Optional[Mesh] = None
        self._streams_mesh: Optional[Mesh] = None
        self._m_active = metrics.REGISTRY.gauge("klba_mesh_active")
        self._m_devices = metrics.REGISTRY.gauge("klba_mesh_devices")

    # -- discovery ----------------------------------------------------------

    def configure(self) -> "MeshManager":
        """Discover + validate the mesh (call once at service start,
        NEVER per request).  A spec the visible devices cannot satisfy
        degrades to single-device — boot keeps serving — rather than
        raising; re-calling re-validates (a shrunk device set degrades
        here too)."""
        with self._lock:
            self._configured = True
            if self.spec == "off":
                self._install([], None)
                return self
            visible = list(jax.devices())
            want = len(visible) if self.spec == "auto" else int(self.spec)
            if want < 2:
                # One device is not a mesh: quietly single-device (the
                # "auto" default on a lone chip must not look degraded).
                self._install([], None)
                return self
            if len(visible) < want:
                LOGGER.warning(
                    "mesh.devices=%s but only %d device(s) visible; "
                    "degrading to the single-device backend",
                    self.spec, len(visible),
                )
                self._install([], "missing_devices")
                return self
            self._install(visible[:want], None)
            LOGGER.info(
                "device mesh configured: %d device(s) on %s",
                want, visible[0].platform,
            )
        return self

    def _install(self, devices: List[Any], degraded: Optional[str]) -> None:
        """Caller holds the lock: adopt a device set (or none) and
        rebuild the cached axis meshes."""
        self._devices = devices
        self._degraded = degraded
        if devices:
            self._solve_mesh = Mesh(devices, axis_names=(SOLVE_AXIS,))
            self._streams_mesh = Mesh(devices, axis_names=(STREAMS_AXIS,))
        else:
            self._solve_mesh = None
            self._streams_mesh = None
        if degraded is not None:
            metrics.REGISTRY.counter(
                "klba_mesh_degraded_total", {"reason": degraded}
            ).inc()
        self._m_active.set(1 if devices else 0)
        self._m_devices.set(len(devices))

    # -- selection ----------------------------------------------------------

    @property
    def active(self) -> bool:
        """True while the sharded backends may be selected (configured,
        >= 2 devices, not degraded)."""
        return bool(self._devices) and self._degraded is None

    @property
    def size(self) -> int:
        return len(self._devices) if self.active else 0

    def solve_mesh(self) -> Mesh:
        """The 1-D ("p",) mesh of the P-sharded solve."""
        m = self._solve_mesh
        if m is None or not self.active:
            raise RuntimeError("mesh manager is not active")
        return m

    def streams_mesh(self) -> Mesh:
        """The 1-D ("streams",) mesh of the stream-sharded megabatch."""
        m = self._streams_mesh
        if m is None or not self.active:
            raise RuntimeError("mesh manager is not active")
        return m

    def should_shard_solve(self, num_rows: int) -> bool:
        """Backend selection for one P-sized solve: mesh active AND the
        row count clears the single-device-wins floor."""
        return self.active and int(num_rows) >= self.solve_min_rows

    # -- degradation --------------------------------------------------------

    def check_collective(self) -> None:
        """The ``mesh.collective`` fault point for callers about to
        enter a sharded dispatch: a firing plan degrades the manager
        (every later selection answers single-device) and raises
        :class:`MeshCollectiveError` so THIS request walks the
        caller's existing ladder — no invalid assignment is ever
        served off a half-dead mesh."""
        try:
            faults.fire("mesh.collective")
        except Exception as exc:
            self.degrade("collective")
            raise MeshCollectiveError(
                "mesh collective failed; degraded to the single-device "
                "backend"
            ) from exc

    def degrade(self, reason: str) -> None:
        """Fall back to the single-device backend process-wide (a lost
        device, a collective fault, a sharded dispatch raising).
        Idempotent; :meth:`restore` / :meth:`configure` re-arms."""
        with self._lock:
            if self._degraded is not None or not self._devices:
                return
            LOGGER.warning(
                "device mesh degraded (%s): sharded backends disabled, "
                "single-device serves", reason,
            )
            self._install([], reason)

    def restore(self) -> "MeshManager":
        """Re-validate after an operator fixed the topology (the mesh
        analog of a breaker's half-open probe, but operator-driven —
        a flapping device must not re-arm itself)."""
        return self.configure()

    def status(self) -> Dict[str, Any]:
        """The service ``stats.mesh`` section."""
        return {
            "spec": self.spec,
            "configured": self._configured,
            "active": self.active,
            "devices": len(self._devices),
            "degraded": self._degraded,
            "solve_min_rows": self.solve_min_rows,
        }


# The active manager.  ``active_manager`` is the backend-selection hook
# compiled into ops/dispatch: ONE global load + None compare when no
# mesh is configured (the faults._ACTIVE pattern).
_ACTIVE: Optional[MeshManager] = None


def active_manager() -> Optional[MeshManager]:
    return _ACTIVE


def activate(manager: MeshManager) -> MeshManager:
    global _ACTIVE
    _ACTIVE = manager
    return manager


def deactivate(manager: Optional[MeshManager] = None) -> None:
    """Clear the active manager (pass ``manager`` to only clear when it
    is still the installed one — a stopping service must not clobber a
    replacement's mesh)."""
    global _ACTIVE
    if manager is None or _ACTIVE is manager:
        _ACTIVE = None


@contextmanager
def managed(manager: MeshManager) -> Iterator[MeshManager]:
    """Scope an active manager to a block (tests, bench probes)."""
    activate(manager)
    try:
        yield manager
    finally:
        deactivate(manager)
