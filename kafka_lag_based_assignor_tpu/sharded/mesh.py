"""Mesh manager: discover + validate the device mesh ONCE at service start.

Multi-device is a first-class, tested backend (ROADMAP "shard the solve
and the megabatch"): the P axis of one huge solve shards over the mesh
(:mod:`.solve`), the megabatch's stream axis spreads tenants across
devices (:mod:`.megabatch`), and the topic-axis batch backend lives in
:mod:`.topics`.  This module owns the topology decisions every one of
those paths shares:

* **Discovery/validation at start, not per request.**  The service (or a
  library embedder) builds one :class:`MeshManager` from the
  ``tpu.assignor.mesh.devices`` knob ("off" | "auto" | an integer),
  calls :meth:`MeshManager.configure` once at boot — real TPUs, or the
  8-device virtual CPU mesh via
  ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` so every
  sharded path runs in tier-1 — and :func:`activate` installs it as the
  process-wide backend selection input (the :mod:`..utils.faults`
  ``_ACTIVE`` pattern: one global load on the off path).

* **Cross-axis composition** (``tpu.assignor.mesh.shape``): the same
  device set can additionally factor as a 2-D ("streams", "p") mesh —
  ``S x D`` tenants-by-rows — so a stream-sharded megabatch holds
  P-sharded rows per tenant (:mod:`.megabatch` 2-D placement) and the
  warm loop's resident buffers live P-sharded.  ``"auto"`` picks the
  most square (S, D) factorization favouring the "p" axis; an explicit
  ``"SxD"`` string pins it; a shape the device count cannot satisfy
  falls back to the 1-D rung at boot (fail open, never raise).

* **Single-device is the default AND the degradation target**, reached
  down a documented ladder.  An unconfigured process never builds a
  mesh.  A configured one that loses devices, takes an injected
  ``mesh.collective`` fault, or sees a sharded dispatch raise is
  :meth:`degraded <MeshManager.degrade>` one rung at a time:
  2-D -> 1-D streams -> 1-D p -> single device (:data:`LADDER`);
  1-D-only configurations keep the historical one-step drop
  (1d -> single).  Every selection hook answers from the current rung,
  so no request is ever served off a half-dead mesh.  Degradation is
  observable: ``klba_mesh_active`` / ``klba_mesh_devices`` /
  ``klba_mesh_shape{axis}`` gauges, ``klba_mesh_degraded_total{reason}``
  and the per-transition ``klba_mesh_degrade_total{from,to}``.

Lint rule L020 confines ``Mesh``/``shard_map``/``NamedSharding``
construction to this package, so topology cannot leak back into ad-hoc
side modules (the old ``parallel/`` dead end this subsystem absorbed).
"""

from __future__ import annotations

import inspect
import logging
import threading
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

from ..utils import faults, metrics

LOGGER = logging.getLogger(__name__)

# shard_map moved to the jax namespace (and its replication-check kwarg
# was renamed check_rep -> check_vma) across the jax versions this
# package supports; resolve both ONCE so every sharded step in this
# package builds on either API without a per-call probe.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax <= 0.4.x: the experimental home
    from jax.experimental.shard_map import shard_map
CHECK_KW = (
    "check_vma"
    if "check_vma" in inspect.signature(shard_map).parameters
    else "check_rep"
)

#: Axis names: the P-sharded solve partitions partition rows over "p";
#: the megabatch spreads tenant rows over "streams".  The 2-D mesh
#: composes both: axis order ("streams", "p").
SOLVE_AXIS = "p"
STREAMS_AXIS = "streams"

#: The documented degrade ladder for a 2-D ("streams", "p") mesh, least
#: to most degraded.  Each ``mesh.collective`` fault (or sharded
#: dispatch failure) steps exactly ONE rung; 1-D-only configurations
#: use the two-rung ("1d", "single") ladder instead (the historical
#: one-step drop).  Scenario envelopes gate observed
#: ``klba_mesh_degrade_total{from,to}`` transitions against this order.
LADDER: Tuple[str, ...] = ("2d", "streams", "p", "single")

#: Rungs where each sharded capability remains available.
_SOLVE_RUNGS = frozenset(("2d", "1d", "p"))
_STREAMS_RUNGS = frozenset(("2d", "1d", "streams"))

#: Default P floor below which a single device wins outright (the
#: sharded seed/refine pays collectives per round; a small solve's
#: whole working set fits one chip).  Deployments override via
#: ``tpu.assignor.mesh.solve.min.rows``.
DEFAULT_SOLVE_MIN_ROWS = 65536

# Collective-dispatch gate.  N request threads each launching a
# D-participant collective program starve the runtime's rendezvous
# (observed on the virtual CPU mesh as "waiting for all participants
# to arrive at rendezvous" stalls across interleaved RunIds until the
# solve watchdog fires): each in-flight program holds threads hostage
# waiting for peers that can never be scheduled.  One collective
# program in flight at a time is both safe and fast — the program
# itself already uses every device.  Re-entrant so a gated entry may
# call another gated entry (cold solve -> sharded tail).  The locked
# megabatch path is collective-free by construction and does NOT take
# the gate: concurrency there is the whole point.
_DISPATCH_GATE = threading.RLock()


def dispatch_gate() -> threading.RLock:
    """The process-wide collective-dispatch serialization gate.

    Every entry that launches a multi-participant collective program
    (``solve_sharded``, ``refine_sharded``, ``solve_linear_sharded``,
    ``plan_stats_sharded``, the resident warm refine) holds this for
    the duration of its dispatch."""
    return _DISPATCH_GATE


class MeshCollectiveError(RuntimeError):
    """A sharded dispatch lost a collective (injected ``mesh.collective``
    fault or a real cross-device failure): the mesh manager has already
    degraded one rung down the ladder; the caller serves this request
    down the existing degraded-mode ladder."""


def _parse_spec(spec: Any) -> Any:
    """``"off"`` | ``"auto"`` | positive int (accepts int-like strings)."""
    if spec in (None, "", "off", "0", 0, False):
        return "off"
    if spec == "auto":
        return "auto"
    try:
        n = int(spec)
    except (TypeError, ValueError):
        raise ValueError(
            f"mesh devices spec {spec!r} invalid; use 'off', 'auto', or "
            "a positive integer"
        )
    if n < 1:
        raise ValueError(f"mesh devices spec {n} must be >= 1")
    return n


def _parse_shape(spec: Any) -> Any:
    """``"off"`` | ``"auto"`` | an ``"SxD"`` string / (S, D) pair."""
    if spec in (None, "", "off", "0", 0, False):
        return "off"
    if spec == "auto":
        return "auto"
    if isinstance(spec, str):
        parts = spec.lower().replace("*", "x").split("x")
        if len(parts) != 2:
            raise ValueError(
                f"mesh shape spec {spec!r} invalid; use 'off', 'auto', "
                "or 'SxD' (e.g. '2x4')"
            )
        spec = parts
    try:
        s, d = (int(v) for v in spec)
    except (TypeError, ValueError):
        raise ValueError(
            f"mesh shape spec {spec!r} invalid; use 'off', 'auto', or "
            "'SxD' (e.g. '2x4')"
        )
    if s < 1 or d < 1:
        raise ValueError(f"mesh shape {s}x{d}: both axes must be >= 1")
    return (s, d)


def auto_shape(n: int) -> Tuple[int, int]:
    """The ``"auto"`` (S, D) factorization of ``n`` devices: the most
    square split favouring the "p" axis (D >= S) — 8 -> (2, 4),
    4 -> (2, 2), 2 -> (1, 2), primes -> (1, n)."""
    s = int(n) ** 0.5
    s = int(s)
    while s > 1 and n % s:
        s -= 1
    return (max(s, 1), n // max(s, 1))


class MeshManager:
    """One process's device-mesh topology + health state.

    ``devices`` is the ``tpu.assignor.mesh.devices`` spec: ``"off"``
    (never shard — the constructor is cheap and inert), ``"auto"`` (all
    visible devices; inactive when only one is visible), or an integer
    N (exactly the first N visible devices; fewer visible = boot-time
    degrade, not an exception — fail open to single-device).
    ``shape`` is the ``tpu.assignor.mesh.shape`` spec: ``"off"`` (1-D
    meshes only, the historical behavior), ``"auto"``, or ``"SxD"`` —
    a satisfiable shape starts the manager on the "2d" rung of
    :data:`LADDER`.  ``solve_min_rows`` gates the P-sharded solve
    backend: below it the single-device path wins outright.
    """

    def __init__(
        self,
        devices: Any = "auto",
        solve_min_rows: int = DEFAULT_SOLVE_MIN_ROWS,
        shape: Any = "off",
    ):
        self.spec = _parse_spec(devices)
        self.shape_spec = _parse_shape(shape)
        self.solve_min_rows = int(solve_min_rows)
        self._lock = threading.Lock()
        self._devices: List[Any] = []
        self._degraded: Optional[str] = None
        self._configured = False
        self._rung = "single"
        self._shape: Optional[Tuple[int, int]] = None
        self._solve_mesh: Optional[Mesh] = None
        self._streams_mesh: Optional[Mesh] = None
        self._mesh2d: Optional[Mesh] = None
        self._m_active = metrics.REGISTRY.gauge("klba_mesh_active")
        self._m_devices = metrics.REGISTRY.gauge("klba_mesh_devices")

    # -- discovery ----------------------------------------------------------

    def configure(self) -> "MeshManager":
        """Discover + validate the mesh (call once at service start,
        NEVER per request).  A spec the visible devices cannot satisfy
        degrades to single-device — boot keeps serving — rather than
        raising; an unsatisfiable 2-D shape falls back to the 1-D rung;
        re-calling re-validates (a shrunk device set degrades here
        too)."""
        with self._lock:
            self._configured = True
            if self.spec == "off":
                self._install([], None, "single")
                return self
            visible = list(jax.devices())
            want = len(visible) if self.spec == "auto" else int(self.spec)
            if want < 2:
                # One device is not a mesh: quietly single-device (the
                # "auto" default on a lone chip must not look degraded).
                self._install([], None, "single")
                return self
            if len(visible) < want:
                LOGGER.warning(
                    "mesh.devices=%s but only %d device(s) visible; "
                    "degrading to the single-device backend",
                    self.spec, len(visible),
                )
                self._install([], "missing_devices", "single")
                return self
            devices = visible[:want]
            rung, shape = "1d", None
            if self.shape_spec != "off":
                shape = (
                    auto_shape(want)
                    if self.shape_spec == "auto" else self.shape_spec
                )
                if shape[0] * shape[1] != want:
                    LOGGER.warning(
                        "mesh.shape=%dx%d does not factor %d device(s); "
                        "falling back to the 1-D rung",
                        shape[0], shape[1], want,
                    )
                    shape = None
                else:
                    rung = "2d"
            self._install(devices, None, rung, shape)
            LOGGER.info(
                "device mesh configured: %d device(s) on %s (rung %s%s)",
                want, visible[0].platform, rung,
                f", shape {shape[0]}x{shape[1]}" if shape else "",
            )
        return self

    def _install(
        self,
        devices: List[Any],
        degraded: Optional[str],
        rung: str,
        shape: Optional[Tuple[int, int]] = None,
    ) -> None:
        """Caller holds the lock: adopt a device set (or none) at one
        ladder rung and rebuild the cached axis meshes."""
        self._devices = devices
        self._degraded = degraded
        self._rung = rung if devices else "single"
        self._shape = shape if (devices and rung == "2d") else None
        self._solve_mesh = (
            Mesh(devices, axis_names=(SOLVE_AXIS,))
            if devices and rung in _SOLVE_RUNGS else None
        )
        self._streams_mesh = (
            Mesh(devices, axis_names=(STREAMS_AXIS,))
            if devices and rung in _STREAMS_RUNGS else None
        )
        self._mesh2d = (
            Mesh(
                np.asarray(devices, dtype=object).reshape(self._shape),
                axis_names=(STREAMS_AXIS, SOLVE_AXIS),
            )
            if self._shape is not None else None
        )
        if degraded is not None:
            metrics.REGISTRY.counter(
                "klba_mesh_degraded_total", {"reason": degraded}
            ).inc()
        self._m_active.set(1 if self.active else 0)
        self._m_devices.set(len(devices))
        s, d = self._shape if self._shape else (0, 0)
        metrics.REGISTRY.gauge(
            "klba_mesh_shape", {"axis": STREAMS_AXIS}
        ).set(s)
        metrics.REGISTRY.gauge(
            "klba_mesh_shape", {"axis": SOLVE_AXIS}
        ).set(d)

    # -- selection ----------------------------------------------------------

    @property
    def active(self) -> bool:
        """True while ANY sharded backend may be selected (configured,
        >= 2 devices, not on the single-device rung)."""
        return bool(self._devices) and self._rung != "single"

    @property
    def rung(self) -> str:
        """The current ladder rung ("2d" | "streams" | "p" | "single",
        or "1d" for shape-off configurations)."""
        return self._rung

    @property
    def size(self) -> int:
        return len(self._devices) if self.active else 0

    @property
    def mesh_shape(self) -> Optional[Tuple[int, int]]:
        """The active (S, D) factorization, or None below the 2-D rung."""
        return self._shape

    def solve_mesh(self) -> Mesh:
        """The 1-D ("p",) mesh of the P-sharded solve."""
        m = self._solve_mesh
        if m is None or not self.active:
            raise RuntimeError("mesh manager is not active")
        return m

    def streams_mesh(self) -> Mesh:
        """The 1-D ("streams",) mesh of the stream-sharded megabatch."""
        m = self._streams_mesh
        if m is None or not self.active:
            raise RuntimeError("mesh manager is not active")
        return m

    def mesh2d(self) -> Mesh:
        """The 2-D ("streams", "p") mesh (the "2d" rung only)."""
        m = self._mesh2d
        if m is None or not self.active:
            raise RuntimeError("mesh manager is not on the 2-D rung")
        return m

    @property
    def solve_available(self) -> bool:
        """P-axis sharding available at the current rung."""
        return self.active and self._solve_mesh is not None

    @property
    def streams_available(self) -> bool:
        """Stream-axis sharding available at the current rung."""
        return self.active and self._streams_mesh is not None

    @property
    def mesh2d_available(self) -> bool:
        """Cross-axis ("streams", "p") placement available (2-D rung)."""
        return self.active and self._mesh2d is not None

    def should_shard_solve(self, num_rows: int) -> bool:
        """Backend selection for one P-sized solve: the "p" capability
        live at the current rung AND the row count clears the
        single-device-wins floor."""
        return self.solve_available and int(num_rows) >= self.solve_min_rows

    # -- degradation --------------------------------------------------------

    def check_collective(self) -> None:
        """The ``mesh.collective`` fault point for callers about to
        enter a sharded dispatch: a firing plan degrades the manager
        ONE ladder rung (every later selection answers from the new
        rung) and raises :class:`MeshCollectiveError` so THIS request
        walks the caller's existing ladder — no invalid assignment is
        ever served off a half-dead mesh."""
        try:
            faults.fire("mesh.collective")
        except Exception as exc:
            self.degrade("collective")
            raise MeshCollectiveError(
                "mesh collective failed; degraded one rung toward the "
                "single-device backend"
            ) from exc

    def degrade(self, reason: str) -> None:
        """Step ONE rung down the documented ladder (a lost device, a
        collective fault, a sharded dispatch raising): 2-D configs walk
        2d -> streams -> p -> single; 1-D configs keep the historical
        one-step drop to single.  Idempotent at the bottom;
        :meth:`restore` / :meth:`configure` re-arms."""
        with self._lock:
            if not self._devices or self._rung == "single":
                return
            frm = self._rung
            # "p" and "1d" are both last sharded rungs: one step to single.
            nxt = {"2d": "streams", "streams": "p"}.get(frm, "single")
            LOGGER.warning(
                "device mesh degraded (%s): rung %s -> %s", reason,
                frm, nxt,
            )
            metrics.REGISTRY.counter(
                "klba_mesh_degrade_total", {"from": frm, "to": nxt}
            ).inc()
            if nxt == "single":
                self._install([], reason, "single")
            else:
                metrics.REGISTRY.counter(
                    "klba_mesh_degraded_total", {"reason": reason}
                ).inc()
                self._install(self._devices, None, nxt)
                self._degraded = reason

    def restore(self) -> "MeshManager":
        """Re-validate after an operator fixed the topology (the mesh
        analog of a breaker's half-open probe, but operator-driven —
        a flapping device must not re-arm itself)."""
        return self.configure()

    def status(self) -> Dict[str, Any]:
        """The service ``stats.mesh`` section."""
        return {
            "spec": self.spec,
            "configured": self._configured,
            "active": self.active,
            "devices": len(self._devices),
            "degraded": self._degraded,
            "solve_min_rows": self.solve_min_rows,
            "shape": (
                f"{self._shape[0]}x{self._shape[1]}"
                if self._shape else None
            ),
            "rung": self._rung,
        }


# The active manager.  ``active_manager`` is the backend-selection hook
# compiled into ops/dispatch: ONE global load + None compare when no
# mesh is configured (the faults._ACTIVE pattern).
_ACTIVE: Optional[MeshManager] = None


def active_manager() -> Optional[MeshManager]:
    return _ACTIVE


def activate(manager: MeshManager) -> MeshManager:
    global _ACTIVE
    _ACTIVE = manager
    return manager


def deactivate(manager: Optional[MeshManager] = None) -> None:
    """Clear the active manager (pass ``manager`` to only clear when it
    is still the installed one — a stopping service must not clobber a
    replacement's mesh)."""
    global _ACTIVE
    if manager is None or _ACTIVE is manager:
        _ACTIVE = None


@contextmanager
def managed(manager: MeshManager) -> Iterator[MeshManager]:
    """Scope an active manager to a block (tests, bench probes)."""
    activate(manager)
    try:
        yield manager
    finally:
        deactivate(manager)
