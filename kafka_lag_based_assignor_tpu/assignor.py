"""The plugin adapter (L1): the ``ConsumerPartitionAssignor`` surface.

Mirrors the reference's protocol contract
(LagBasedPartitionAssignor.java:83-157):

* ``configure(configs)`` — validates ``group.id``, derives metadata-consumer
  properties (auto-commit off, ``client.id=<group>.assignor``);
* ``name()`` — returns ``"lag"``, the protocol name embedded in JoinGroup
  metadata (all group members must support it);
* ``assign(cluster, group_subscription)`` — runs on the elected group
  leader: unions subscribed topics, reads lags (the only network boundary),
  solves the assignment, wraps results with no user data.

Differences by design (each one a SURVEY §5 requirement):
* the combinatorial core runs on TPU via :mod:`.ops.dispatch`, with an
  automatic host-greedy fallback so a rebalance never fails because the
  accelerator is unreachable — broker-RPC exceptions still propagate and
  fail the rebalance exactly like the reference (SURVEY §2.4.9);
* every rebalance emits a structured :class:`RebalanceStats` record
  (imbalance ratio, timings) instead of only debug text.

Statelessness matches the reference: no ``on_assignment`` state carryover;
durable state is the group's committed offsets, which are only read.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Mapping, Optional

from .lag import LagRetryPolicy, MetadataConsumer, read_topic_partition_lags
from .models.greedy import assign_greedy, host_fallback_for
from .types import (
    Assignment,
    Cluster,
    GroupAssignment,
    GroupSubscription,
    TopicPartition,
)
from .utils import faults, metrics
from .utils.config import PARITY_SOLVERS, AssignorConfig, parse_config
from .utils.watchdog import Watchdog
from .utils.observability import (
    TRACE,
    RebalanceStats,
    log_rebalance,
    log_topic_summaries,
    profile_trace,
    stopwatch,
    summarize_assignment,
    summarize_topics,
    trace_decisions,
)

LOGGER = logging.getLogger(__name__)

# A factory so tests (and the real deployment) inject their broker client;
# the reference constructs a KafkaConsumer from the derived props lazily on
# first use and never closes it (:322-324) — same lifecycle here.
MetadataConsumerFactory = Callable[[Mapping[str, Any]], MetadataConsumer]


class LagBasedPartitionAssignor:
    """TPU-native drop-in for the reference assignor."""

    def __init__(
        self, metadata_consumer_factory: Optional[MetadataConsumerFactory] = None
    ):
        self._config: Optional[AssignorConfig] = None
        self._metadata_consumer: Optional[MetadataConsumer] = None
        self._metadata_consumer_factory = metadata_consumer_factory
        self._watchdog: Optional[Watchdog] = None
        self._lag_retry: Optional[LagRetryPolicy] = None
        self.last_stats: Optional[RebalanceStats] = None

    # -- Configurable SPI --------------------------------------------------

    def configure(self, configs: Mapping[str, Any]) -> None:
        """Reference :97-130 — fails fast if ``group.id`` is absent."""
        self._config = parse_config(configs)
        self._watchdog = Watchdog(
            self._config.solve_timeout_s,
            cooldown_s=self._config.breaker_cooldown_s,
            failure_threshold=self._config.breaker_failures,
        )
        # Opt-in bounded lag-RPC retry; 0 retries = the reference's
        # broker-exception-aborts-the-rebalance semantics, untouched.
        self._lag_retry = (
            LagRetryPolicy(
                attempts=self._config.lag_retries + 1,
                backoff_s=self._config.lag_retry_backoff_s,
            )
            if self._config.lag_retries > 0
            else None
        )
        LOGGER.debug(
            "Configured LagBasedPartitionAssignor with values:\n"
            "\tgroup.id = %s\n\tclient.id = %s\n\tsolver = %s",
            self._config.group_id,
            self._config.client_id,
            self._config.solver,
        )
        # Full derived metadata-consumer property map (reference :122-128).
        LOGGER.debug(
            "Derived metadata consumer properties:\n%s",
            "".join(
                f"\t{k} = {v}\n"
                for k, v in sorted(
                    self._config.metadata_consumer_props.items(),
                    key=lambda kv: kv[0],
                )
            ),
        )
        # Optional kernel pre-compilation at consumer startup
        # (tpu.assignor.warmup.shapes) so the first rebalance of the
        # CONFIGURED solver never pays an XLA compile; same semantics as
        # the sidecar's --warmup flag.  Only the configured solver is
        # warmed (the plugin never dispatches the sidecar-only "stream"
        # path, and "native"/"host" have no device executables).  Best
        # effort by contract: a failing warm-up is logged and skipped —
        # it must never prevent the consumer from starting (the host
        # fallback still covers a broken accelerator at rebalance time).
        from .utils.config import DEVICE_SOLVERS

        solver = self._config.solver
        if self._config.warmup_shapes and solver in DEVICE_SOLVERS:
            try:
                from .warmup import warmup

                for max_p, consumers, topics in self._config.warmup_shapes:
                    warmup(
                        max_partitions=max_p,
                        consumers=[consumers],
                        topics=[topics],
                        solvers=(solver,),
                        sinkhorn_iters=self._config.sinkhorn_iters,
                        refine_iters=self._config.refine_iters,
                    )
            except Exception:
                LOGGER.warning(
                    "configure-time warm-up failed; continuing without it "
                    "(first rebalance may pay an XLA compile)",
                    exc_info=True,
                )
        elif self._config.warmup_shapes:
            LOGGER.info(
                "solver %r has no device executables; warmup.shapes ignored",
                self._config.solver,
            )

    # -- ConsumerPartitionAssignor SPI ------------------------------------

    def name(self) -> str:
        """The protocol name (reference :132-135)."""
        return "lag"

    def assign(
        self, metadata: Cluster, subscriptions: GroupSubscription
    ) -> GroupAssignment:
        """The rebalance entry point; runs on the group leader
        (reference :137-157)."""
        if self._config is None:
            raise RuntimeError("configure() must be called before assign()")

        stats = RebalanceStats(
            solver=self._config.solver,
            # Only solvers that actually consume the budget record it:
            # an operator must be able to tell refined from bit-parity.
            refine_iters=(
                self._config.refine_iters
                if self._config.solver in ("rounds", "scan", "sinkhorn")
                else None
            ),
        )
        with stopwatch() as wall:
            with profile_trace(self._config.profile):
                # Client wire edge: the rebalance mints the trace, so
                # the lag read, the solve, and any sidecar call from
                # this thread ride ONE client-rooted trace (the sidecar
                # joins via the request's traceparent).
                with metrics.request_scope(
                    kind="client", root_name="client"
                ):
                    group_assignment = self._assign_inner(
                        metadata, subscriptions, stats
                    )
        stats.wall_ms = wall[0]
        log_rebalance(stats)
        self.last_stats = stats
        # Registry + flight-recorder export (utils/metrics): the
        # structured record, queryable after the socket closes.
        metrics.REGISTRY.histogram(
            "klba_rebalance_wall_ms", {"solver": stats.solver}
        ).observe(stats.wall_ms)
        metrics.FLIGHT.record(
            "rebalance",
            {
                "solver": stats.solver,
                "num_topics": stats.num_topics,
                "num_partitions": stats.num_partitions,
                "num_members": stats.num_members,
                "wall_ms": stats.wall_ms,
                "lag_read_ms": stats.lag_read_ms,
                "solve_ms": stats.solve_ms,
                "total_lag": stats.total_lag,
                "quality_ratio": stats.quality_ratio,
                "fallback_used": stats.fallback_used,
                "breaker_state": stats.breaker_state,
                "refine_iters": stats.refine_iters,
            },
        )
        if stats.fallback_used:
            # The in-process ladder descended past its first rung — the
            # same incident class the wire service dumps on.
            metrics.FLIGHT.auto_dump(
                "ladder", {"method": "assign", "rung": "host_greedy"}
            )
        return group_assignment

    def _assign_inner(
        self,
        metadata: Cluster,
        subscriptions: GroupSubscription,
        stats: RebalanceStats,
    ) -> GroupAssignment:
        # Union all members' subscribed topics (reference :140-146).
        topic_subscriptions = {
            member: list(sub.topics)
            for member, sub in subscriptions.group_subscription.items()
        }
        all_subscribed = set()
        for topics in topic_subscriptions.values():
            all_subscribed.update(topics)

        # Lag acquisition — exceptions propagate and fail the rebalance,
        # matching the reference's absence of try/catch (:339-342), unless
        # the deployment opted into the bounded retry policy.
        with stopwatch() as lag_ms:
            lags = read_topic_partition_lags(
                self._get_metadata_consumer(),
                metadata,
                all_subscribed,
                self._config.auto_offset_reset,
                retry=self._lag_retry,
            )
        stats.lag_read_ms = lag_ms[0]

        with stopwatch() as solve_ms:
            with metrics.span("assign.solve"):
                raw = self._solve(lags, topic_subscriptions, stats)
        stats.solve_ms = solve_ms[0]

        stats.num_topics = len(lags)
        stats.num_partitions = sum(len(v) for v in lags.values())
        stats.num_members = len(topic_subscriptions)
        lag_by_tp = {
            TopicPartition(r.topic, r.partition): r.lag
            for rows in lags.values()
            for r in rows
        }
        stats.total_lag = sum(lag_by_tp.values())
        summarize_assignment(stats, raw, lag_by_tp)
        # Per-topic breakdown + per-decision trace + per-topic debug
        # summary, all gated like the reference's isDebugEnabled guard
        # (:280) so the O(partitions) aggregation and the multi-KB log
        # payloads are only paid when the level is on.
        if LOGGER.isEnabledFor(logging.DEBUG):
            summarize_topics(stats, raw, lags)
            # The decision replay assumes per-topic sequential greedy —
            # only true for the parity solvers; 'global' carries totals
            # across topics, 'sinkhorn' has no decision sequence, and an
            # explicit refine budget post-edits the greedy output (the
            # quality mode intentionally breaks replayability).
            refined = self._config.solver in (
                "rounds", "scan"
            ) and bool(self._config.refine_iters)
            if (
                self._config.solver in PARITY_SOLVERS
                and not refined
                and LOGGER.isEnabledFor(TRACE)
            ):
                trace_decisions(raw, lags, logger=LOGGER)
            log_topic_summaries(stats, raw, logger=LOGGER)

        return GroupAssignment(
            {member: Assignment(tuple(tps)) for member, tps in raw.items()}
        )

    def _solve(self, lags, topic_subscriptions, stats: RebalanceStats):
        solver = self._config.solver
        if solver == "host":
            return assign_greedy(lags, topic_subscriptions)
        options = {
            "sinkhorn_iters": self._config.sinkhorn_iters,
            "refine_iters": self._config.refine_iters,
        }
        try:
            # Device/native solves run under the watchdog: a wedged
            # accelerator transport can HANG rather than raise, and a
            # rebalance must never block past its deadline (SURVEY §5,
            # failure-detection row).  The breaker key is the SOLVER so a
            # wedged sinkhorn compile cannot banish the rounds kernel.
            result = self._watchdog.call(
                self._solve_accelerated, solver, lags, topic_subscriptions,
                options, key=solver,
            )
            stats.breaker_state = self._watchdog.state(solver)
            return result
        except Exception:
            stats.breaker_state = self._watchdog.state(solver)
            if not self._config.host_fallback:
                raise
            LOGGER.warning(
                "device solver %r failed; falling back to host greedy",
                solver,
                exc_info=True,
            )
            stats.fallback_used = True
            stats.refine_iters = None  # the host fallback never refines
            metrics.REGISTRY.counter(
                "klba_ladder_rung_total",
                {"method": "assign", "rung": "host_greedy"},
            ).inc()
            return host_fallback_for(solver)(lags, topic_subscriptions)

    @staticmethod
    def _solve_accelerated(solver, lags, topic_subscriptions, options=None):
        faults.fire("device.solve")
        options = options or {}
        if solver == "sinkhorn":
            from .models.sinkhorn import assign_sinkhorn

            refine = options.get("refine_iters")
            return assign_sinkhorn(
                lags,
                topic_subscriptions,
                iters=int(options.get("sinkhorn_iters", 24)),
                refine_iters=None if refine is None else int(refine),
            )
        if solver == "native":
            from .native import assign_native

            return assign_native(lags, topic_subscriptions)
        from .ops.dispatch import assign_device

        # The one-shot quality option: an EXPLICIT refine budget appends
        # the exchange refinement to the per-topic parity kernels (None =
        # strict reference parity).  global+refine is invalid and raises
        # in the dispatch layer; every entry point (config parse, the
        # service wire) validates it before reaching here.
        return assign_device(
            lags, topic_subscriptions, kernel=solver,
            refine_iters=options.get("refine_iters"),
        )

    def _get_metadata_consumer(self) -> MetadataConsumer:
        """Lazily create the shared metadata consumer (reference :322-324);
        it lives as long as the assignor and is never closed."""
        if self._metadata_consumer is None:
            if self._metadata_consumer_factory is None:
                raise RuntimeError(
                    "no metadata consumer factory configured; inject one at "
                    "construction or call set_metadata_consumer()"
                )
            self._metadata_consumer = self._metadata_consumer_factory(
                self._config.metadata_consumer_props
            )
        return self._metadata_consumer

    def set_metadata_consumer(self, consumer: MetadataConsumer) -> None:
        """Directly inject a broker client (tests, embedding runtimes)."""
        self._metadata_consumer = consumer

    def reset_accelerator(self) -> None:
        """Clear a tripped solve watchdog so the next rebalance probes the
        accelerator again (the trip also auto-expires after its cooldown)."""
        if self._watchdog is not None:
            self._watchdog.reset()
