"""Pad-and-mask packing of ragged multi-topic workloads for batched kernels.

The reference assigns topics one at a time in a host loop
(LagBasedPartitionAssignor.java:177-184).  On TPU we instead batch topics
into one ``vmap``-ed kernel launch.  Two facts make this safe:

* per-topic independence — lag is never balanced across topics
  (SURVEY §2.4.3), so topics can execute concurrently;
* the rounds kernel's pre-condition (every consumer eligible for every
  partition of its topic) holds within a **group of topics whose subscriber
  sets are identical**, after re-ranking that subscriber set densely.

So packing = group topics by ``frozenset(subscribers)``, then pad each
group's topics to a shared power-of-two partition budget.  In the common
Kafka deployment every member subscribes to every topic, so there is exactly
one group and one kernel launch per rebalance.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Mapping, Sequence, Tuple

import numpy as np

from ..types import TopicPartitionLag


def pad_bucket(n: int, minimum: int = 8) -> int:
    """Next power-of-two bucket >= n (bounds the jit cache size)."""
    b = minimum
    while b < n:
        b *= 2
    return b


def pad_chunk(n: int, chunk: int = 4096) -> int:
    """Next multiple of ``chunk`` >= n — the fine-grained bucket for
    backends where exact-ish shapes are cheap (XLA:CPU's comparison sort
    costs O(n log n) regardless of shape, so a power-of-two pad wastes up
    to ~2x work) but the jit cache still needs bounding as n drifts:
    quantizing to 4096 keeps at most P_max/4096 executables alive instead
    of one per distinct n."""
    return max(chunk, -(-n // chunk) * chunk)


def table_rows(num_rows: int, num_consumers: int) -> int:
    """Per-consumer slot budget for the resident refine's [C, M] row
    table (ops/refine.build_choice_tables): the count invariant
    ``max - min <= 1`` bounds any consumer at ``ceil(P / C)`` rows, and
    exchange moves never push a consumer past the current maximum, so
    ``ceil(P / C) + 1`` slots hold every reachable state with one slot of
    headroom.  One definition, so the fused warm-path executables and the
    standalone resident refine agree on the (P-bucket, C) -> M geometry
    (a mismatched M is a different executable signature)."""
    C = max(int(num_consumers), 1)
    return -(-int(num_rows) // C) + 1


def pad_topic_rows(lags, partition_ids=None):
    """Pad one topic's columns to its power-of-two bucket.

    The single place the (lags, partition_ids, valid) pad-and-mask triple
    is built for per-topic solvers — production paths and benchmarks must
    measure the same padded shapes.  Returns
    (lags int64[P_pad], partition_ids int32[P_pad], valid bool[P_pad]).
    """
    P = len(lags)
    P_pad = pad_bucket(P)
    lags_p = np.zeros(P_pad, dtype=np.int64)
    pids_p = np.zeros(P_pad, dtype=np.int32)
    valid = np.zeros(P_pad, dtype=bool)
    lags_p[:P] = lags
    pids_p[:P] = (
        np.arange(P, dtype=np.int32) if partition_ids is None else partition_ids
    )
    valid[:P] = True
    return lags_p, pids_p, valid


@dataclass
class TopicGroup:
    """A batch of topics sharing one (deduped, rank-ordered) subscriber set.

    Array shapes: [T, P_pad] with ``valid`` masking ragged padding.
    ``members[rank]`` is the member id for kernel consumer index ``rank``
    (lexicographic order, so integer tie-breaks match string tie-breaks).
    """

    topics: List[str]
    members: List[str]
    lags: np.ndarray  # int64 [T, P_pad]
    partition_ids: np.ndarray  # int32 [T, P_pad]
    valid: np.ndarray  # bool  [T, P_pad]

    @property
    def num_consumers(self) -> int:
        return len(self.members)


def build_groups(
    partition_lag_per_topic: Mapping[str, Sequence[TopicPartitionLag]],
    consumers_by_topic: Mapping[str, Sequence[str]],
) -> List[TopicGroup]:
    """Group topics by subscriber set and pack each group into padded columns.

    Topics with no consumers or no lag rows are dropped here, mirroring the
    reference's early-return (:211-213) and getOrDefault-empty (:182) paths.
    Topic order within a group is sorted, and groups are emitted in sorted
    order of their first topic, for deterministic output.
    """
    by_subscribers: Dict[Tuple[str, ...], List[str]] = {}
    for topic in sorted(consumers_by_topic):
        members = tuple(sorted(set(consumers_by_topic[topic])))
        rows = partition_lag_per_topic.get(topic)
        if not members or not rows:
            continue
        by_subscribers.setdefault(members, []).append(topic)

    groups: List[TopicGroup] = []
    for members, topics in sorted(by_subscribers.items(), key=lambda kv: kv[1][0]):
        # Bucket BOTH dims so rebalances retrace only on bucket crossings:
        # adding one topic (or partition) must not recompile the jitted
        # kernel on the latency-critical rebalance path.  T buckets start at
        # 1 so the flagship single-topic shape pays no batch padding.
        T = pad_bucket(len(topics), minimum=1)
        P_pad = pad_bucket(
            max(len(partition_lag_per_topic[t]) for t in topics)
        )
        lags = np.zeros((T, P_pad), dtype=np.int64)
        pids = np.zeros((T, P_pad), dtype=np.int32)
        valid = np.zeros((T, P_pad), dtype=bool)
        for ti, topic in enumerate(topics):
            rows = partition_lag_per_topic[topic]
            P = len(rows)
            lags[ti, :P] = np.fromiter((r.lag for r in rows), np.int64, count=P)
            pids[ti, :P] = np.fromiter(
                (r.partition for r in rows), np.int32, count=P
            )
            valid[ti, :P] = True
        groups.append(
            TopicGroup(
                topics=topics,
                members=list(members),
                lags=lags,
                partition_ids=pids,
                valid=valid,
            )
        )
    return groups
