"""Sort-based, scatter-free array primitives for the TPU kernels.

Measurement status (retired probes, git history — fetch-synchronized; the
earlier probe numbers were dispatch times, because
``block_until_ready`` returns at dispatch on this platform): a P-sized
``lax.sort`` costs ~0.4 ms at P=131072, which is cheap enough that
sort-based formulations set the floor for every primitive here.  XLA:TPU
lowers scatters with dynamic indices to serialized updates (the classic
hazard these primitives exist to avoid); re-expressing every P-sized
scatter on a latency-critical path as a sort keeps the cost model simple
and measured:

* permutation inversion (``unsort``) — co-sort the permutation with its
  payloads instead of ``out.at[perm].set(vals)``;
* histogram (``bincount_sorted``) — sort + bucket boundaries via
  ``searchsorted`` with C+1 queries instead of ``at[].add``;
* segmented sum (``segment_sum``) — sort + cumulative sum + boundary
  differences instead of ``at[].add``;
* segmented argmin (``segment_argmin_first``) — one packed-key sort taking
  the first row per segment instead of two ``at[].min`` scatters.

The cost profile INVERTS on XLA:CPU (scatters are cheap there, big sorts
slow), so each primitive picks its implementation by backend at trace
time: scatter-based on the ``cpu`` backend, sort-based everywhere else.
Both implementations satisfy the same contracts (results are equal except
where documented — ``segment_argmin_first``'s winner may differ among
near-minimal candidates) and are pinned by tests/test_sortops.py on both
paths.  All are deterministic per backend (``lax.sort`` is stable;
scatter-min uses a first-index rule).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _cpu_backend() -> bool:
    """Trace-time backend check: this repo always executes on the default
    backend (no explicit device placement), so the trace-time default
    matches the execution backend."""
    return jax.default_backend() == "cpu"


def sort_with(keys: jax.Array, *payloads: jax.Array):
    """Stable co-sort: payloads ride along a single-key sort (saves the
    post-sort gathers ``payload[perm]``).

    Returns (sorted_keys, *sorted_payloads).
    """
    return lax.sort((keys, *payloads), num_keys=1)


def unsort(perm: jax.Array, *sorted_vals: jax.Array):
    """Invert a permutation.

    Given ``sorted_vals[i]`` belonging to input row ``perm[i]``, returns
    each values array re-ordered to input rows — ``out.at[perm].set(vals)``
    for a true permutation.  Accelerators use one stable sort on ``perm``
    (whose sorted order is 0..P-1) instead of the scatter.

    Returns a single array for one payload, else a tuple.
    """
    if _cpu_backend():
        out = tuple(
            jnp.zeros_like(v).at[perm].set(v) for v in sorted_vals
        )
        return out[0] if len(out) == 1 else out
    out = lax.sort((perm, *sorted_vals), num_keys=1)[1:]
    return out[0] if len(out) == 1 else out


def _boundaries(sorted_vals: jax.Array, num_segments: int) -> jax.Array:
    """First index of each segment id 0..S in a sorted int array (plus the
    end sentinel): ``searchsorted`` with S+1 scalar queries — the queries
    are C-sized, not P-sized, so the sequential method is cheap."""
    q = jnp.arange(num_segments + 1, dtype=sorted_vals.dtype)
    return jnp.searchsorted(sorted_vals, q).astype(jnp.int32)


def bincount_sorted(vals: jax.Array, num_segments: int) -> jax.Array:
    """Histogram of ``vals`` over bins 0..S-1.

    Out-of-range values (negative padding markers, sentinel S) fall outside
    the counted range.  Returns int32[S].
    """
    S = int(num_segments)
    if _cpu_backend():
        in_range = (vals >= 0) & (vals < S)
        return (
            jnp.zeros((S,), jnp.int32)
            .at[jnp.clip(vals, 0, S - 1)]
            .add(in_range.astype(jnp.int32))
        )
    sv = jnp.sort(vals)
    b = _boundaries(sv.astype(jnp.int32), S)
    return b[1:] - b[:-1]


def segment_sum(
    vals: jax.Array, seg: jax.Array, num_segments: int
) -> jax.Array:
    """Sum ``vals`` per segment id (accelerators: sort + cumsum + boundary
    differences).  ``seg`` entries outside 0..S-1 are excluded.  Exact for
    integer dtypes (cumsum in the value dtype).  Returns vals-dtype[S]."""
    S = int(num_segments)
    if _cpu_backend():
        in_range = (seg >= 0) & (seg < S)
        return (
            jnp.zeros((S,), vals.dtype)
            .at[jnp.clip(seg, 0, S - 1)]
            .add(jnp.where(in_range, vals, 0))
        )
    sseg, svals = sort_with(
        jnp.clip(seg, -1, S).astype(jnp.int32), vals
    )
    csum = jnp.cumsum(svals)
    csum0 = jnp.concatenate([jnp.zeros((1,), csum.dtype), csum])
    b = _boundaries(sseg, S)
    return csum0[b[1:]] - csum0[b[:-1]]


def segment_argmin_first(
    score: jax.Array, seg: jax.Array, num_segments: int, P: int
):
    """Approximate-key, exact-value segmented argmin via one packed sort.

    Packs (segment, score quantized by dropping its low ``segbits`` bits)
    into one int64 key; the stable sort's first row per segment is the
    argmin under the quantized score.  Ties that quantization introduces
    resolve to the smallest row index (stable sort) — callers re-read the
    EXACT score at the returned index, so quantization only ever perturbs
    which near-minimal candidate is picked, never validity.

    ``seg`` entries equal to ``num_segments`` — or out of range entirely
    (negative padding markers, > S) — are discarded on both paths.
    Returns (exact score at winner, winner index; index == P and score ==
    dtype-max for empty segments).

    CPU backend: exact scatter-min argmin (first index attaining the true
    minimum) — same contract, winner may differ from the sort path's
    among near-minimal candidates.
    """
    S = int(num_segments)
    big = jnp.iinfo(score.dtype).max
    if _cpu_backend():
        # Out-of-range seg entries (negative padding or the S sentinel)
        # park in the discard bin S so they cannot contaminate bin 0.
        seg_safe = jnp.where((seg < 0) | (seg > S), S, seg)
        minv = jnp.full((S + 1,), big, score.dtype).at[seg_safe].min(score)
        hit = (score == minv[seg_safe]) & (seg_safe < S)
        idx_cand = jnp.where(hit, jnp.arange(P, dtype=jnp.int32), P)
        idx = jnp.full((S + 1,), P, jnp.int32).at[seg_safe].min(idx_cand)
        return minv[:S], idx[:S]
    segbits = max(1, S.bit_length())
    key = (seg.astype(jnp.int64) << (63 - segbits)) | (
        score.astype(jnp.int64) >> segbits
    )
    skey, sidx = sort_with(key, jnp.arange(P, dtype=jnp.int32))
    # Segment starts come from the same sorted keys (segment id is the
    # primary bit range): S+1 scalar queries, not a second P-sized sort.
    b = jnp.searchsorted(
        skey, jnp.arange(S + 1, dtype=jnp.int64) << (63 - segbits)
    ).astype(jnp.int32)
    starts = b[:-1]
    empty = starts == b[1:]
    idx = jnp.where(empty, P, sidx[jnp.clip(starts, 0, P - 1)])
    minv = jnp.where(empty, big, score[jnp.clip(idx, 0, P - 1)])
    return minv, idx.astype(jnp.int32)


__all__ = [
    "bincount_sorted",
    "segment_argmin_first",
    "segment_sum",
    "sort_with",
    "unsort",
]
