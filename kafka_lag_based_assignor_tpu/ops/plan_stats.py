"""Fused implicit-transport-plan statistics — the framework's Pallas hot op.

The Sinkhorn solver's iteration state admits an exact rank-structured form
(see :mod:`..models.sinkhorn`): the log-plan is

    logX[p, j] = noise(p, j) - ws_p * A_j + B_j     (ws_p = lag_p / scale)

up to a per-row normalizer that cancels in the softmax, so the [P, C] plan
never needs to exist in HBM.  Each solver iteration only needs the two
marginal statistics of the implicit plan X = softmax_j(logX), and — because
the iteration's plan rows are noise-free — partitions with EQUAL scaled lag
have identical rows, so the marginals collapse onto the deduplicated
lag-value axis u:

    load_j   = sum_u  wsum_u  * X_u[j]     (scaled consumer loads)
    colsum_j = sum_u  count_u * X_u[j]     (count marginal)

with host-aggregated per-value weights (count_u = #rows, wsum_u = sum of
ws).  On heavy-skew inputs (BASELINE config 4: 90% zero lag) U << P cuts
the iteration's work by >10x; in the worst case (all-distinct lags) U = P
and nothing is lost.  This module computes both marginals in ONE fused pass
over U-tiles.  The Pallas kernel keeps a (C, TILE) logits tile in VMEM,
does the softmax and both reductions in-register, and accumulates across
loop steps — HBM traffic is O(U) instead of O(U*C) for a materialized
plan, turning the memory-bound iteration into a compute-bound one (the TPU
analog of the tile-streaming FlashSinkhorn pattern, PAPERS.md — pattern
only).  Symmetry breaking lives in the duals' B0 seed
(:func:`..models.sinkhorn.sinkhorn_duals`); the per-(p, j) hash noise is
used only by the rounding helpers (:func:`implicit_plan_rows` /
:func:`implicit_plan_argmax`) as a deterministic tie-break.

A pure-`lax` tiled reference (`lax.map` over the same row tiles, identical
arithmetic) serves as the fallback on backends without Pallas support and
as the exactness oracle in tests (the two paths are bit-compared in Pallas
interpret mode on CPU).
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
from jax import lax

LOGGER = logging.getLogger(__name__)

# Hash-noise amplitude: large enough to break the symmetric fixpoint of
# mirror descent (all-identical consumers), small enough (<< 1, the scale
# of ws*A terms after a few iterations) not to distort the converged plan.
NOISE_AMP = 0.02

_TILE_P = 512  # rows per grid step; (512, C<=2048) f32 tiles fit VMEM easily


def noise(p_idx: jax.Array, j_idx: jax.Array) -> jax.Array:
    """Deterministic per-(partition, consumer) symmetry-breaking noise in
    [-NOISE_AMP/2, NOISE_AMP/2], from a cheap integer hash (Knuth
    multiplicative mixing) — identical on every backend and recomputable
    anywhere without carrying PRNG state into kernels."""
    h = p_idx.astype(jnp.int32) * jnp.int32(-1640531527) + j_idx.astype(
        jnp.int32
    ) * jnp.int32(40503)
    h = h ^ (h >> 15)
    h = h * jnp.int32(-1028477387)
    h = h ^ (h >> 13)
    u = (h >> 8) & jnp.int32(0xFFFF)
    # Explicit f32 scalars: under x64 mode a weak Python float can lower as
    # an f64 constant, which Mosaic cannot legalize inside the TPU kernel.
    return jnp.float32(NOISE_AMP) * (
        u.astype(jnp.float32) / jnp.float32(65536.0) - jnp.float32(0.5)
    )


def implicit_plan_rows(
    p_idx: jax.Array, ws: jax.Array, A: jax.Array, B: jax.Array
) -> jax.Array:
    """Materialize rows of the implicit plan: X[p] = softmax_j(logits) for
    the given partition indices.  ``ws`` are the rows' scaled lags.  Shapes:
    p_idx int[R], ws f32[R], A/B f32[C] -> f32[R, C]."""
    logits = (
        noise(p_idx[:, None], jnp.arange(A.shape[0], dtype=jnp.int32)[None, :])
        # noqa: L021 — [R, C], not [P, C]: callers materialize a few
        # requested rows (the rounding scan passes R=1), never the plan.
        - ws[:, None] * A[None, :]  # noqa: L021
        + B[None, :]
    )
    return jax.nn.softmax(logits, axis=1)


def _pad_rows(x: jax.Array, P_pad: int) -> jax.Array:
    return jnp.pad(x, (0, P_pad - x.shape[0]))


def implicit_plan_argmax(ws, valid, A, B, tie_noise: bool = True):
    """Each partition's most-preferred consumer under the implicit plan:
    argmax_j(noise(p, j) - ws_p * A_j + B_j), computed in O(TILE x C) live
    memory by the same tile streaming as :func:`plan_stats_lax` (softmax is
    monotone, so the logits argmax IS the plan argmax).  Invalid rows
    return C (a sentinel one past the last consumer).  int32[P].

    ``tie_noise=False`` drops the per-(p, j) hash term — the noise-free
    logits are a pure fused-multiply-add, roughly 3x cheaper per element
    on the CPU backend at the [100k, 1k] north star.  Equal-ws rows then
    share one argmax (argmax's first-max rule), which only matters to
    callers whose downstream step cannot redistribute ties (the parallel
    rounding's capacity repair can, so it opts out of the noise)."""
    P, C = ws.shape[0], A.shape[0]
    P_pad = -(-P // _TILE_P) * _TILE_P
    nt = P_pad // _TILE_P
    ws_t = _pad_rows(ws, P_pad).reshape(nt, _TILE_P)
    p_t = jnp.arange(P_pad, dtype=jnp.int32).reshape(nt, _TILE_P)

    def tile_argmax(args):
        w_i, p_i = args
        logits = -w_i[:, None] * A[None, :] + B[None, :]
        if tie_noise:
            logits = logits + noise(
                p_i[:, None], jnp.arange(C, dtype=jnp.int32)[None, :]
            )
        return jnp.argmax(logits, axis=1).astype(jnp.int32)

    jstar = lax.map(tile_argmax, (ws_t, p_t)).reshape(P_pad)[:P]
    return jnp.where(valid, jstar, jnp.int32(C))


def plan_stats_lax(ws_u, count_u, wsum_u, A, B, need: str = "both"):
    """Reference implementation: same tile loop as the Pallas kernel, in
    pure lax (`lax.map` keeps live memory at one (TILE_U, C) tile).

    Operates on the DEDUPLICATED lag-value axis: partitions with equal
    scaled lag have identical (noise-free) plan rows
    ``X_u = softmax_j(-ws_u * A_j + B_j)``, so the marginals collapse to

        load_j   = sum_u wsum_u  * X_u[j]
        colsum_j = sum_u count_u * X_u[j]

    where ``count_u`` / ``wsum_u`` aggregate the valid-row count and ws-sum
    per unique value (host-computed; padding rows have count=wsum=0 and
    contribute exactly nothing).  On heavy-skew inputs (BASELINE config 4:
    90% zero lag) this cuts the iteration's work by >10x; symmetry breaking
    lives in the B0 seed (:func:`..models.sinkhorn.sinkhorn_duals`), not in
    per-(p, j) noise.

    Args:
      ws_u: f32[U] unique scaled lag values (padded rows arbitrary).
      count_u: f32[U] number of valid rows with that value (0 = padding).
      wsum_u: f32[U] sum of ws over those rows.
      A, B: f32[C] dual-like state vectors.
      need: "both" (default), "load", or "colsum" — each duals
        half-step consumes exactly one marginal, and skipping the other
        weighted reduction shaves ~20% off the pass (the softmax is
        shared and unavoidable).  The skipped output is returned as
        None.
    Returns (load f32[C] — in ws units — and colsum f32[C]).
    """
    U, C = ws_u.shape[0], A.shape[0]
    U_pad = -(-U // _TILE_P) * _TILE_P
    nt = U_pad // _TILE_P
    ws_t = _pad_rows(ws_u, U_pad).reshape(nt, _TILE_P)
    cnt_t = _pad_rows(count_u, U_pad).reshape(nt, _TILE_P)
    wsum_t = _pad_rows(wsum_u, U_pad).reshape(nt, _TILE_P)

    def tile_stats(args):
        w_i, c_i, s_i = args
        logits = -w_i[:, None] * A[None, :] + B[None, :]
        x = jax.nn.softmax(logits, axis=1)
        out = []
        if need in ("both", "load"):
            out.append((s_i[:, None] * x).sum(axis=0))
        if need in ("both", "colsum"):
            out.append((c_i[:, None] * x).sum(axis=0))
        return tuple(out)

    parts = lax.map(tile_stats, (ws_t, cnt_t, wsum_t))
    reduced = [p.sum(axis=0) for p in parts]
    if need == "load":
        return reduced[0], None
    if need == "colsum":
        return None, reduced[0]
    return reduced[0], reduced[1]


def plan_stats_pallas(ws_u, count_u, wsum_u, A, B, interpret: bool = False):
    """Pallas TPU path of :func:`plan_stats_lax` (identical arithmetic, on
    the same deduplicated lag-value axis).

    Toolchain-shaped design (this image's Mosaic AOT path rejects ANY
    ``grid``— even a trivial one — with "failed to legalize func.return"):
    a single grid-less invocation with an in-kernel ``fori_loop`` over
    value tiles, accumulators loop-carried, and a **transposed tile
    layout** — consumers on the sublane axis, lag values on the lane axis.
    The transpose matters for VMEM: a column-vector [U, 1] input would be
    tiled T(8, 128), padding the lane dim 128x (64 MB at U=131072); packing
    values along lanes as an [nt, TILE_P] matrix keeps the whole input at
    its true size.  All loop offsets are explicit int32: under x64 mode a
    weak Python int lowers as an i64 constant, which Mosaic cannot
    legalize.

    ``interpret=True`` runs the kernel in the Pallas interpreter (any
    backend) — used by the CPU test suite to compare against the lax
    reference without TPU hardware."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    U, C = ws_u.shape[0], A.shape[0]
    C_pad = max(128, -(-C // 128) * 128)
    U_pad = -(-U // _TILE_P) * _TILE_P
    nt = U_pad // _TILE_P

    ws_p = _pad_rows(ws_u, U_pad).reshape(nt, _TILE_P)
    cnt_p = _pad_rows(count_u, U_pad).reshape(nt, _TILE_P)
    wsum_p = _pad_rows(wsum_u, U_pad).reshape(nt, _TILE_P)
    A_p = jnp.pad(A, (0, C_pad - C)).reshape(C_pad, 1)
    B_p = jnp.pad(B, (0, C_pad - C)).reshape(C_pad, 1)

    def kernel(ws_ref, cnt_ref, wsum_ref, A_ref, B_ref, load_ref, col_ref):
        # Tile axes: sublanes = consumers j, lanes = unique values u.
        j_idx = lax.broadcasted_iota(jnp.int32, (C_pad, _TILE_P), 0)

        def tile(t, acc):
            acc_load, acc_col = acc
            w = ws_ref[pl.ds(t, 1), :]  # (1, TILE_P)
            c_t = cnt_ref[pl.ds(t, 1), :]
            s_t = wsum_ref[pl.ds(t, 1), :]
            logits = -w * A_ref[:] + B_ref[:]
            logits = jnp.where(j_idx < C, logits, jnp.float32(-1e30))
            mx = jnp.max(logits, axis=0, keepdims=True)
            e = jnp.exp(logits - mx)
            x = e / jnp.sum(e, axis=0, keepdims=True)  # softmax over j
            acc_load = acc_load + jnp.sum(s_t * x, axis=1, keepdims=True)
            acc_col = acc_col + jnp.sum(c_t * x, axis=1, keepdims=True)
            return acc_load, acc_col

        zero = jnp.zeros((C_pad, 1), jnp.float32)
        acc_load, acc_col = lax.fori_loop(
            jnp.int32(0), jnp.int32(nt), tile, (zero, zero)
        )
        load_ref[:] = acc_load
        col_ref[:] = acc_col

    load, colsum = pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec((nt, _TILE_P), lambda: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((nt, _TILE_P), lambda: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((nt, _TILE_P), lambda: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((C_pad, 1), lambda: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((C_pad, 1), lambda: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((C_pad, 1), lambda: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((C_pad, 1), lambda: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((C_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((C_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(ws_p, cnt_p, wsum_p, A_p, B_p)
    return load[:C, 0], colsum[:C, 0]


_pallas_ok: bool | None = None


def _trace_state_clean() -> bool:
    """True when not inside any JAX trace (safe to execute ops for real)."""
    try:
        from jax._src.core import trace_state_clean  # not in public jax.core

        return trace_state_clean()
    except Exception:  # API moved — assume tracing to stay safe
        LOGGER.debug(
            "trace_state_clean probe unavailable; assuming an active trace",
            exc_info=True,
        )
        return False


def _pallas_available() -> bool:
    """Probe-once gate: Pallas lowering may be unsupported on a backend (or
    an experimental platform plugin); any failure falls back to the lax
    path permanently for the process.

    Inside a jit trace the probe cannot run for real (its ops would be
    staged, block_until_ready would no-op on tracers, and a lowering
    failure would abort the enclosing compile with no fallback), so under
    an active trace an unknown state conservatively answers False WITHOUT
    caching — the decision is baked per-trace anyway.  The jitted solver
    entry points call this eagerly before tracing
    (:func:`..models.sinkhorn.sinkhorn_duals`), so the real probe happens
    exactly once, outside any trace."""
    global _pallas_ok
    if _pallas_ok is None:
        if not _trace_state_clean():
            return False  # unknown while tracing: don't probe, don't cache
        try:
            # Probe on any accelerator backend (the image's TPU registers
            # as an experimental platform plugin, so don't gate on the
            # name "tpu"); CPU always takes the lax path.
            if jax.default_backend() == "cpu":
                _pallas_ok = False
            else:
                ws = jnp.ones((4,), jnp.float32)
                z = jnp.zeros((4,), jnp.float32)
                jax.block_until_ready(plan_stats_pallas(ws, ws, ws, z, z))
                _pallas_ok = True
        except Exception:
            LOGGER.warning(
                "Pallas plan-stats kernel unavailable; using lax fallback",
                exc_info=True,
            )
            _pallas_ok = False
    return _pallas_ok


# THE VMEM budget and this kernel's byte model live with the other
# kernels' admission math (ops/kernel_admission) so the constants
# cannot drift across kernels.
def _fits_vmem(U: int, C: int) -> bool:
    """Shape guard for the grid-less kernel: ALL inputs live in VMEM at
    once plus the per-tile temporaries, so availability of the kernel is
    shape-dependent — the probe's verdict alone is not enough (byte
    model: :func:`..ops.kernel_admission.plan_stats_bytes`)."""
    from .kernel_admission import fits_vmem, plan_stats_bytes

    return fits_vmem(plan_stats_bytes(U, C, _TILE_P))


def plan_stats(ws_u, count_u, wsum_u, A, B, need: str = "both"):
    """Dispatch: fused Pallas kernel on TPU (when the shape fits the VMEM
    budget), tiled lax everywhere else.  ``need`` ("load" / "colsum")
    lets the lax path skip the unused weighted reduction; the fused
    Pallas kernel computes both marginals in-register either way (its
    cost is HBM-bound, not reduction-bound), so it ignores the hint and
    always returns both."""
    if _fits_vmem(ws_u.shape[0], A.shape[0]) and _pallas_available():
        return plan_stats_pallas(ws_u, count_u, wsum_u, A, B)
    return plan_stats_lax(ws_u, count_u, wsum_u, A, B, need=need)
