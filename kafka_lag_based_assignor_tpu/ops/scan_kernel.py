"""General greedy-LPT assignment kernel: ``lax.scan`` over sorted partitions.

This is the direct device-side statement of the reference's hot loop
(LagBasedPartitionAssignor.java:237-277): process partitions in descending
lag (ties: ascending partition id, :228-235) and give each to the consumer
minimizing the 3-level key (assigned count, total assigned lag, member rank)
(:246-259).  The O(C) linear ``Collections.min`` becomes a C-wide vectorized
two-stage lexicographic argmin per scan step; the scan has P sequential
steps.

Use this kernel as the always-correct reference path and for differential
testing; :mod:`.rounds_kernel` is the fast path (P/C sequential steps) that
exploits the count-primary round structure.

Conventions (shared by all kernels in :mod:`..ops`):
* consumers are dense indices ``0..C-1`` = rank in the lexicographically
  sorted member-id list, so "lowest index" == "lexicographically smallest
  member id" and integer argmin reproduces the string tie-break exactly;
* ``lags`` are non-negative (the lag formula clamps, reference :400-402);
* padding rows have ``valid=False`` and are ignored;
* output ``choice[i]`` is the consumer index for input row ``i``
  (input order, NOT sorted order), ``-1`` for padding rows.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def pack_shift_for(max_lag: int, max_pid: int) -> int:
    """Pick the pid bit-shift for a packed single-key processing-order sort,
    or 0 if the value ranges make packing unsafe.

    The packed key is ``-(lag << shift) + pid``: lag descending is the
    primary order, pid ascending breaks ties (reference :228-235) — valid
    whenever every pid fits in ``shift`` bits and ``lag << shift`` cannot
    overflow int64.  The host checks both from the numpy inputs (O(P) max,
    ~microseconds) and passes the shift as a static jit argument; 0 selects
    the general two-key lexicographic sort.  A single-key sort halves the
    comparator stages, which is the dominant cost of the device sort at
    north-star scale.
    """
    shift = max(1, int(max_pid)).bit_length()
    if int(max_lag) < (1 << (62 - shift)):
        return shift
    return 0


def sort_partitions(
    lags: jax.Array,
    partition_ids: jax.Array,
    valid: jax.Array,
    pack_shift: int = 0,
):
    """Return the processing-order permutation: lag desc, partition id asc,
    padding last (reference :228-235).

    Works because valid lags are >= 0: negated they are <= 0, and padding
    gets key +1 (two-key path) / int64 max (packed path), which sorts after
    every valid row in ascending order.

    ``pack_shift`` (static, from :func:`pack_shift_for`) selects the packed
    single-key sort; 0 the general two-key sort.  Identical permutations —
    enforced by differential fuzzing in tests/test_kernels.py.
    """
    if pack_shift:
        key = jnp.where(
            valid,
            -(lags.astype(jnp.int64) << pack_shift)
            + partition_ids.astype(jnp.int64),
            jnp.iinfo(jnp.int64).max,
        )
        return jnp.argsort(key).astype(jnp.int32)
    neg_lag = jnp.where(valid, -lags, 1)
    pid_key = jnp.where(valid, partition_ids, jnp.iinfo(jnp.int32).max)
    idx = jnp.arange(lags.shape[0], dtype=jnp.int32)
    _, _, perm = lax.sort((neg_lag, pid_key, idx), num_keys=2)
    return perm


def sort_partitions_with(
    lags: jax.Array,
    partition_ids: jax.Array,
    valid: jax.Array,
    pack_shift: int = 0,
):
    """:func:`sort_partitions` with the lags and validity co-sorted in the
    same ``lax.sort`` call — payloads ride the sort instead of two
    post-sort P-sized gathers ``lags[perm]`` / ``valid[perm]`` (the co-sort
    itself is ~0.4 ms at north-star scale, retired probe, git history).

    Returns (perm int32[P], sorted_lags, sorted_valid) — identical values
    to ``(p := sort_partitions(...), lags[p], valid[p])``.
    """
    idx = jnp.arange(lags.shape[0], dtype=jnp.int32)
    if pack_shift:
        key = jnp.where(
            valid,
            -(lags.astype(jnp.int64) << pack_shift)
            + partition_ids.astype(jnp.int64),
            jnp.iinfo(jnp.int64).max,
        )
        _, perm, sorted_lags, sorted_valid = lax.sort(
            (key, idx, lags, valid), num_keys=1
        )
        return perm, sorted_lags, sorted_valid
    neg_lag = jnp.where(valid, -lags, 1)
    pid_key = jnp.where(valid, partition_ids, jnp.iinfo(jnp.int32).max)
    _, _, perm, sorted_lags, sorted_valid = lax.sort(
        (neg_lag, pid_key, idx, lags, valid), num_keys=2
    )
    return perm, sorted_lags, sorted_valid


def _argmin_consumer(counts: jax.Array, totals: jax.Array, eligible: jax.Array):
    """Two-stage lexicographic argmin over (count, total lag, index).

    Exact analogue of the reference comparator (:246-259): smallest assigned
    count, then smallest total lag, then smallest index (= lexicographically
    smallest member id under the rank convention).  No key packing — lags
    use the full int64 range, so a packed single key would overflow
    (SURVEY §7 hard parts).
    """
    big_count = jnp.iinfo(counts.dtype).max
    key1 = jnp.where(eligible, counts, big_count)
    mask1 = key1 == jnp.min(key1)
    big_total = jnp.iinfo(totals.dtype).max
    key2 = jnp.where(mask1, totals, big_total)
    mask2 = mask1 & (key2 == jnp.min(key2))
    return jnp.argmax(mask2).astype(jnp.int32)  # first True = smallest index


@functools.partial(jax.jit, static_argnames=("num_consumers",))
def assign_topic_scan(
    lags: jax.Array,
    partition_ids: jax.Array,
    valid: jax.Array,
    num_consumers: int,
    eligible: jax.Array | None = None,
):
    """Assign one topic's partitions to ``num_consumers`` consumers.

    Args:
      lags: int lag per partition row, shape [P] (padded).
      partition_ids: int32 partition id per row, shape [P].
      valid: bool mask, shape [P]; False rows are padding.
      num_consumers: static consumer count C.
      eligible: optional bool[C]; ineligible consumers never receive
        partitions.  Default: all eligible (the host passes only subscribed
        consumers per topic, reference :176-183).

    Returns:
      (choice int32[P] in input order with -1 padding,
       counts int32[C], totals lag-dtype[C]).
    """
    P = lags.shape[0]
    C = int(num_consumers)
    if eligible is None:
        eligible = jnp.ones((C,), dtype=bool)

    perm, sorted_lags, sorted_valid = sort_partitions_with(
        lags, partition_ids, valid
    )

    # With no eligible consumer nothing may be assigned; without this guard
    # the masked argmin would degenerate (all keys saturate to the sentinel)
    # and silently hand every partition to consumer 0.
    any_eligible = jnp.any(eligible)

    def step(carry, x):
        counts, totals = carry
        lag, is_valid = x
        assignable = is_valid & any_eligible
        who = _argmin_consumer(counts, totals, eligible)
        one_hot = (jnp.arange(C, dtype=jnp.int32) == who) & assignable
        counts = counts + one_hot.astype(counts.dtype)
        totals = totals + jnp.where(one_hot, lag, 0).astype(totals.dtype)
        return (counts, totals), jnp.where(assignable, who, -1)

    init = (
        jnp.zeros((C,), dtype=jnp.int32),
        jnp.zeros((C,), dtype=lags.dtype),
    )
    (counts, totals), sorted_choice = lax.scan(
        step, init, (sorted_lags, sorted_valid)
    )

    # Back to input row order — sort-based permutation inversion
    # (P-sized sorts are ~0.4 ms measured, retired probe, git history; XLA:TPU
    # serializes dynamic-index scatters).
    from .sortops import unsort

    choice = unsort(perm, sorted_choice)
    return choice, counts, totals
