"""Federated Sinkhorn building blocks: dual-seeded rounds over lag shards.

The Sinkhorn quality solver (:mod:`..models.sinkhorn`) keeps its whole
iteration state in two f32[C] dual-like vectors ``(A, B)`` and consumes
only two marginal statistics of the implicit plan per step — which is
exactly the structure Federated Sinkhorn (arXiv:2502.07021, PAPERS.md —
pattern only) exploits: N parties each holding a SHARD of the row axis
can run the identical global iteration by exchanging their local
marginal contributions, because both marginals are plain sums over rows

    load_j   = sum_shards  load_j^(s)
    colsum_j = sum_shards  colsum_j^(s)

and the dual update depends on the rows only through those sums.  Raw
per-partition lags never have to leave a shard: everything on the wire
is C-dimensional (consumer-axis) aggregates plus three scalars.

This module is the device math of the federated plane
(:mod:`..federated` owns the protocol, robustness, and caching):

* :func:`shard_summary` — the handshake scalars (total lag, valid
  count) whose global sums fix the shared normalization ``scale =
  max(total_global, 1) / C`` and the balanced count marginal ``cap =
  n_global / C``.  Every peer must use the SAME scale or the duals
  describe different units; the coordinator exchanges these first.
* :func:`shard_dedup` — the host-side dedup aggregation of one shard
  under an EXPLICIT (global) scale; same log-bucketing cap as the
  single-leader path so iteration cost stays bounded per shard.
* :func:`shard_marginals` — one fused pass producing this shard's
  ``(load, colsum)`` contribution under the current duals (the payload
  of a ``peer_sync`` response).
* :func:`dual_step` — ONE step of the damped mirror/Sinkhorn update on
  globally summed marginals: the same arithmetic as
  ``models.sinkhorn._sinkhorn_duals_jit``'s loop body, factored to one
  step so the exchange loop can interleave network rounds.  Feeding it
  the marginals of a single full shard reproduces the single-leader
  trajectory (pinned by tests/test_federated.py).
* :func:`initial_duals` — the shared deterministic starting point
  (zero A, hash-seeded B0): every peer starts identically, so peers
  applying the same summed marginals hold bit-identical duals without
  ever exchanging them authoritatively.
* :func:`round_local_shard` — the dual-seeded rounding: integral,
  locally count-balanced assignment of THIS shard's partitions, steered
  by the global duals, with the OTHER shards' converged loads as a
  fixed base offset so the exchange refinement balances the GLOBAL
  peaks with local moves only.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..models.sinkhorn import (
    _DEDUP_CAP,
    _quantize_tail,
    _require_concrete,
    _round_parallel,
)
from .plan_stats import noise, plan_stats

#: Default cap on refine pair width for the dual-seeded local round —
#: the same bound the single-leader Sinkhorn path uses.
_MAX_PAIRS = 64

#: Convergence tolerance of the exchange loop (same as the leader's).
DUAL_TOL = 2e-5


def shard_summary(lags, valid) -> Tuple[int, int]:
    """Host scalars of one shard: ``(total_lag, n_valid)``.  Their
    global sums fix the shared scale/cap every peer must agree on."""
    lags_np = np.asarray(lags)
    valid_np = np.asarray(valid)
    return int(lags_np[valid_np].sum()), int(valid_np.sum())


def shard_dedup(lags, valid, scale: float):
    """Dedup one shard's rows onto the unique-lag-value axis under an
    explicit GLOBAL scale (``models.sinkhorn._dedup_weights`` derives
    the scale from the local rows, which a shard must not do — its
    local total is not the normalization the global duals live in).
    Returns ``(ws_u, count_u, wsum_u)`` f32, pow2-padded."""
    from .packing import pad_bucket

    lags_np = np.asarray(lags)
    valid_np = np.asarray(valid)
    vals = lags_np[valid_np]
    uniq, counts = np.unique(vals, return_counts=True)
    if len(uniq) > _DEDUP_CAP:
        vals_r, cnts_r, vsums_r = _quantize_tail(uniq, counts)
    else:
        vals_r = uniq.astype(np.float64)
        cnts_r = counts.astype(np.float64)
        vsums_r = vals_r * cnts_r
    scale = max(float(scale), 1e-9)
    U = max(len(vals_r), 1)
    U_pad = pad_bucket(U)
    ws_u = np.zeros(U_pad, np.float32)
    count_u = np.zeros(U_pad, np.float32)
    wsum_u = np.zeros(U_pad, np.float32)
    ws_u[: len(vals_r)] = vals_r / scale
    count_u[: len(vals_r)] = cnts_r
    wsum_u[: len(vals_r)] = vsums_r / scale
    return ws_u, count_u, wsum_u


@jax.jit
def _shard_marginals_jit(ws_u, count_u, wsum_u, A, B):
    return plan_stats(ws_u, count_u, wsum_u, A, B, need="both")


def shard_marginals(ws_u, count_u, wsum_u, A, B):
    """This shard's marginal contribution under duals ``(A, B)``:
    ``(load f32[C], colsum f32[C])`` — the exchanged payload.  Padding
    rows carry count=wsum=0 and contribute exactly nothing, so shards
    of different (padded) sizes sum correctly."""
    from .dispatch import ensure_x64

    ensure_x64()
    load, colsum = _shard_marginals_jit(ws_u, count_u, wsum_u, A, B)
    return np.asarray(load), np.asarray(colsum)


@functools.partial(jax.jit, static_argnames=("num_consumers",))
def _dual_step_jit(A, B, load, colsum, cap, step_scale, prev_spread,
                   num_consumers: int, eta: float = 8.0):
    # ``cap`` is the count-marginal target: a scalar (the uniform
    # n_global / C) or an f32[C] vector (capacity-weighted shards,
    # ROADMAP federated (c)) — the update is elementwise either way.
    del num_consumers  # shape key only (cache hygiene across C)
    eta32 = jnp.float32(eta)
    spread = jnp.max(load) - jnp.min(load)
    grew = spread > prev_spread
    step_scale = jnp.where(
        grew,
        step_scale * jnp.float32(0.5),
        jnp.minimum(step_scale * jnp.float32(1.2), jnp.float32(1.0)),
    )
    A = A + eta32 * step_scale * (load - jnp.mean(load))
    upd = jnp.log(cap / (colsum + jnp.float32(1e-9)))
    B = B + upd
    delta = jnp.maximum(spread, jnp.max(jnp.abs(upd)))
    return A, B, step_scale, spread, delta


def dual_step(A, B, load_sum, colsum_sum, cap, step_scale: float,
              prev_spread: float):
    """One damped mirror/Sinkhorn step on globally summed marginals.

    ``cap`` is the count-marginal target — the uniform scalar
    ``n_global / C``, or an [C] vector of capacity-weighted per-consumer
    count targets (summing to ``n_global``) when the shards carried a
    capacity vector through the handshake (ROADMAP federated (c)).

    The ``load`` half-step uses the CURRENT duals' load marginal and the
    ``colsum`` half-step re-reads the column marginal — the leader's
    loop computes the colsum AFTER moving A, which one network exchange
    per step cannot afford; the federated loop instead applies both
    half-steps from the same round's marginals.  The trajectory differs
    from the leader's by one half-step of lag but converges to the same
    fixpoint (the bench gate pins quality within 5% of the leader).

    Returns ``(A, B, step_scale, spread, delta)`` with ``spread``/
    ``delta`` as Python floats (the convergence test is host-side, in
    the exchange loop between network rounds).
    """
    A2, B2, s2, spread, delta = _dual_step_jit(
        jnp.asarray(A), jnp.asarray(B),
        jnp.asarray(load_sum, dtype=jnp.float32),
        jnp.asarray(colsum_sum, dtype=jnp.float32),
        jnp.asarray(cap, dtype=jnp.float32), jnp.float32(step_scale),
        jnp.float32(prev_spread),
        # A's length IS C (the consumer-group size): a membership
        # constant that changes only on rebalance, not a per-epoch
        # runtime value — one executable per group size is the design.
        num_consumers=int(np.asarray(A).shape[0]),  # noqa: A003
    )
    return (
        np.asarray(A2), np.asarray(B2), float(s2), float(spread),
        float(delta),
    )


def initial_duals(num_consumers: int):
    """The shared deterministic dual seed: zero ``A`` plus the same
    hash-noise ``B0`` the single-leader iteration uses for symmetry
    breaking — every peer computes it locally and identically."""
    C = int(num_consumers)
    A0 = np.zeros(C, np.float32)
    B0 = np.asarray(
        noise(jnp.zeros((C,), jnp.int32), jnp.arange(C, dtype=jnp.int32))
    )
    return A0, B0


@functools.partial(
    jax.jit,
    static_argnames=("num_consumers", "refine_iters", "cap_max"),
)
def _round_local_jit(lags, valid, ws, A, B, base_totals,
                     num_consumers: int, refine_iters: int,
                     cap_vec=None, cap_max: int = 0):
    from .packing import table_rows
    from .refine import build_choice_tables, refine_rounds_resident

    C = int(num_consumers)
    P = lags.shape[0]
    n_valid = jnp.sum(valid.astype(jnp.int32))
    floor_cap = n_valid // C
    extras = n_valid - floor_cap * C
    # Weighted shards (ROADMAP federated (c)): an explicit per-consumer
    # seat vector replaces the uniform floor/ceil capacities, and the
    # exchange refinement runs SWAP-ONLY so the capacity-proportional
    # counts it seats are never eroded back toward uniform by
    # count-changing moves.
    choice = _round_parallel(
        lags, ws, valid, A, B, C, floor_cap, extras,
        cap_vec=cap_vec, cap_max=cap_max if cap_vec is not None else None,
    )
    # Weighted caps overflow the uniform ceil(P/C)+1 table: size the
    # row table to the LARGEST per-consumer seat count (static — the
    # host passed it) or its totals silently truncate to the first M
    # rows and the refinement balances a fiction.
    m_rows = max(table_rows(P, C), int(cap_max))
    row_tab, r_counts, r_totals = build_choice_tables(
        lags, valid, choice, C, m_rows
    )
    # The other shards' converged loads ride as a FIXED per-consumer
    # base: local exchanges then minimize the GLOBAL peak (local totals
    # + base) — a consumer hot on remote shards sheds local load even
    # when locally light.
    s_choice, _, s_counts, s_totals, _, _ = refine_rounds_resident(
        lags, choice, row_tab, r_counts,
        r_totals + base_totals.astype(r_totals.dtype),
        num_consumers=C, iters=refine_iters,
        max_pairs=min(C // 2, _MAX_PAIRS),
        allow_moves=cap_vec is None,
    )
    return s_choice, s_counts, s_totals - base_totals.astype(r_totals.dtype)


def apportion_counts(n: int, weights) -> np.ndarray:
    """Largest-remainder apportionment of ``n`` seats over non-negative
    ``weights`` (uniform when they are degenerate).  Returns int32[C]
    summing to exactly ``n`` — the per-consumer seat vector of the
    weighted-shard rounding and the global count-marginal targets."""
    w = np.asarray(weights, dtype=np.float64)
    w = np.where(np.isfinite(w) & (w > 0), w, 0.0)
    if w.sum() <= 0:
        w = np.ones_like(w)
    quota = float(n) * w / w.sum()
    base = np.floor(quota).astype(np.int64)
    rem = int(n - base.sum())
    if rem > 0:
        order = np.argsort(-(quota - base), kind="stable")
        base[order[:rem]] += 1
    return base.astype(np.int32)


def round_local_shard(lags, num_consumers: int, A, B,
                      scale: float, base_load,
                      refine_iters: Optional[int] = None,
                      capacity_frac=None):
    """Dual-seeded integral rounding of ONE shard (host entry point).

    ``lags`` are the UNPADDED local rows (sorted-pid order; padding to
    the pow2 bucket happens here so the jit cache stays bounded as P
    drifts); ``A``/``B`` the converged GLOBAL duals; ``scale`` the
    shared global normalization; ``base_load`` f32[C] the summed load
    marginal of every OTHER shard (ws units) — converted to lag units
    and held fixed while the local exchange refinement balances global
    peaks.  Locally count-balanced by construction (capacities
    floor/ceil of the LOCAL row count) — unless ``capacity_frac``
    (f64[C] fractions summing to ~1, the handshake's capacity-weighted
    shares) is given, in which case the local seats are apportioned
    capacity-proportionally (:func:`apportion_counts`) and the
    refinement runs swap-only so the weighted counts hold exactly.

    Returns ``(choice int32[P] — input order — counts int32[C],
    local_totals[C] in lag units)``.
    """
    from .dispatch import ensure_x64
    from .packing import pad_topic_rows

    ensure_x64()
    P = int(np.asarray(lags).shape[0])
    lags_p, _, valid = pad_topic_rows(np.asarray(lags, dtype=np.int64))
    if refine_iters is None:
        # Auto budget, scaled with the shard: the parallel argmax
        # rounding leaves O(P) repair work that max_pairs exchanges per
        # round must absorb — 64 rounds that suffice at P=512 leave a
        # 1.4x peak at P=2048 (measured; 256 recovers 1.0001).  Pow2 by
        # construction (P_pad is), so the executable count stays one
        # per (P_pad, C) bucket.  The WEIGHTED path converges slower —
        # swap-only exchanges from a capacity-skewed start move one row
        # pair per (pair, round) — so its auto budget is deeper
        # (measured at P=1024/4x-capacity: 128 rounds leave 1.64x,
        # 512 reach 1.085x and plateau).
        if capacity_frac is not None:
            refine_iters = min(2048, max(512, int(lags_p.shape[0]) // 2))
        else:
            refine_iters = min(1024, max(128, int(lags_p.shape[0]) // 8))
    _require_concrete(lags_p, valid, "round_local_shard")
    lags_j = jnp.asarray(lags_p)
    valid_j = jnp.asarray(valid)
    ws = (
        jnp.where(valid_j, lags_j, 0).astype(jnp.float64)
        / jnp.float64(max(float(scale), 1e-9))
    ).astype(jnp.float32)
    base_totals = jnp.asarray(
        np.asarray(base_load, dtype=np.float64) * max(float(scale), 1e-9)
    ).astype(jnp.int64)
    if capacity_frac is not None:
        cap_np = apportion_counts(P, capacity_frac)
        # cap_max is a STATIC jit arg (it sizes the open-slot
        # enumeration, and the table rows): quantize it to the next
        # pow2 (bounded by the padded row count) so a drifting P or a
        # shifting capacity split reuses one executable per (P_pad, C,
        # pow2-cap) rung instead of recompiling the serving path on
        # every seat-count change — the same bucketing discipline as
        # every other static in this package.  Over-sizing is safe:
        # the enumeration masks on the true cap vector.
        cap_ceil = 1 << max(int(cap_np.max()) - 1, 0).bit_length()
        choice, counts, totals = _round_local_jit(
            lags_j, valid_j, ws, jnp.asarray(A), jnp.asarray(B),
            base_totals, num_consumers=int(num_consumers),
            refine_iters=int(refine_iters),
            cap_vec=jnp.asarray(cap_np),
            # lags_p arrives pre-padded to a pow2 bucket (see the
            # cap_ceil comment above): min() of two pow2-bounded
            # values stays on the ladder — no per-P executable mint.
            cap_max=min(cap_ceil, int(lags_p.shape[0])),  # noqa: A003
        )
    else:
        choice, counts, totals = _round_local_jit(
            lags_j, valid_j, ws, jnp.asarray(A), jnp.asarray(B),
            base_totals, num_consumers=int(num_consumers),
            refine_iters=int(refine_iters),
        )
    return np.asarray(choice)[:P], np.asarray(counts), np.asarray(totals)
