"""Pallas TPU kernel plane for the linear-OT mirror-prox solve.

Why: the round-18 linear-space quality mode is memory-right (O(P + C)
peak) but its marginal scan is a plain ``lax.scan`` over XLA-lowered
tile bodies — every mirror-prox iteration re-streams the ws/count
vectors through HBM TWICE (predictor and corrector evaluations are
separate executable regions), paying the same per-pass sequencing
overhead that motivated the round-14 Pallas round scan
(:mod:`.rounds_pallas`).  This module keeps the whole extragradient
step resident in VMEM:

* **Fused mirror-prox step** (:func:`mirror_prox_step_pallas`): ONE
  grid-less invocation evaluates the predictor marginals at the
  current duals, derives the damped step scale and the extrapolated
  dual point IN-KERNEL, and immediately re-evaluates both marginals
  there (the corrector) — the ws/count planes are loaded into VMEM
  once per iteration instead of twice, and the (C_pad, tile) logits
  block never leaves VMEM (the FlashSinkhorn IO-bound framing,
  arXiv:2602.03067 — pattern only).

* **Bit-parity by construction**: the kernel's tile body is the SAME
  traced helper the XLA scan uses (:func:`.linear_ot._tile_softmax` —
  one definition, transposed C_pad-padded geometry, masked softmax),
  the per-superblock partials accumulate tile-sequentially and combine
  in the same left-to-right order as :func:`.linear_ot._ordered_sum`,
  and the in-kernel extrapolation mean is the same padded-lane
  reduction as :func:`.linear_ot._mean_padded` — so the duals
  trajectory is bit-identical to the XLA tile scan (pinned and fuzzed
  in interpret mode by tests/test_linear_ot_pallas.py), and the
  mesh-1 vs 2-8 parity contract of :mod:`..sharded.solve` survives
  with the kernel enabled (:func:`superblock_partials_pallas` is the
  per-shard drop-in behind the same all-gather + ordered combine).

* **Fused integrity-digest epilogue** (:func:`state_digest_pallas`):
  the round-15 resident-state digest — int64[4]
  ``[counts_sum, range_violations, lags_sum, counts_vs_choice_L1]`` —
  folded into one kernel pass instead of a separate XLA reduction
  tree.  All-integer arithmetic, so it is order-exact with the XLA
  reference (:func:`.refine.state_digest` is the dispatch seam).

Production dispatch reuses the :mod:`.rounds_pallas` safety
scaffolding verbatim: host admission against the shared VMEM model
(:mod:`.kernel_admission`), a probe-once device gate
(:func:`linear_pallas_available`) that bit-compares the real Mosaic
lowering against the XLA tile scan AND races it (margin 1.0 — the
kernel must be at least as fast on the probe shape), automatic
fall-back to the XLA path on ANY failure (including a runtime
dispatch error, :func:`mark_linear_kernel_bad`), and a probe that is
only ever invoked by warm-up/bench (``run_probe=True``), never on a
cold rebalance.

Toolchain shape (same constraints as :mod:`.plan_stats` /
:mod:`.rounds_pallas`): this image's Mosaic AOT path rejects any
``grid``, so every kernel is a single grid-less invocation with
``lax.fori_loop`` over tiles, explicit int32 loop offsets, and
full-array VMEM BlockSpecs; ``interpret=True`` runs the same trace as
plain jnp ops for CPU tests.
"""

from __future__ import annotations

import functools
import json
import logging
import os
import threading
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .kernel_admission import (
    LANE,
    digest_bytes,
    fits_vmem,
    lane_pad,
    linear_ot_bytes,
)

LOGGER = logging.getLogger(__name__)

#: The device-gate probe instance (north-star-adjacent: C=1000 pads to
#: one (1024, tile) logits plane; tile=256 is the largest pow2 the
#: shared VMEM model admits at that C).  The bench's
#: ``linear_ot_kernel`` gate races exactly this shape.
PROBE_ROWS = 65536
PROBE_CONSUMERS = 1000
PROBE_TILE = 256
_PROBE_ITERS = 12

_linear_pallas_ok: dict | None = None  # {"duals": bool, "digest": bool}
# Probe-once means once PER PROCESS (threaded sidecar: concurrent
# configure-time warm-ups must not race two multi-compile probes, or
# read a partially-decided verdict).  Double-checked under this lock.
_linear_pallas_lock = threading.Lock()

# Most recent speed-race timings (ms) — surfaced in the kernel report
# and the bench's linear_ot_kernel config.
_LAST_RACE: Optional[dict] = None


# ---------------------------------------------------------------------------
# admission (host-side; shared VMEM model)
# ---------------------------------------------------------------------------


def linear_pallas_admit(num_rows: int, num_consumers: int,
                        tile: int) -> bool:
    """Host admission for the fused duals kernel: the effective solve
    geometry (:func:`.linear_ot.plan_shape` — the same geometry the
    XLA scan uses, because tile size is part of the bit-parity
    contract) must fit the shared VMEM byte model.  One definition for
    the single-device entry, the sharded per-shard check
    (:func:`linear_pallas_admit_sharded`), and the probes."""
    if int(num_consumers) < 2:
        return False
    from .linear_ot import plan_shape

    P2, t, _ = plan_shape(num_rows, tile)
    return fits_vmem(linear_ot_bytes(P2, num_consumers, t))


def linear_pallas_admit_sharded(rows_per_shard: int, num_consumers: int,
                                tile: int) -> bool:
    """Per-shard admission for the sharded composition: each shard runs
    the partials kernel over its LOCAL superblocks, so the byte model
    applies to the local row slice."""
    if int(num_consumers) < 2:
        return False
    return fits_vmem(linear_ot_bytes(rows_per_shard, num_consumers, tile))


def digest_pallas_admit(num_rows: int, num_consumers: int) -> bool:
    """Host admission for the fused digest epilogue (int64 rows are the
    dominant term — the resident buffers are already padded)."""
    if int(num_consumers) < 1:
        return False
    return fits_vmem(digest_bytes(num_rows, num_consumers))


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def _pad_cols(v, C_pad: int):
    """[C] f32 -> (C_pad, 1) zero-padded column (consumers on
    sublanes — the transposed geometry shared with the XLA scan)."""
    C = v.shape[0]
    return jnp.pad(v.astype(jnp.float32), (0, C_pad - C)).reshape(C_pad, 1)


def _block_specs(shapes, dtypes=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def spec(shape):
        ndim = len(shape)
        return pl.BlockSpec(
            shape, lambda *a, n=ndim: (0,) * n, memory_space=pltpu.VMEM
        )

    return [spec(s) for s in shapes]


def superblock_partials_pallas(ws_b, cnt_b, A, B, *,
                               interpret: bool = False):
    """Grid-less drop-in for :func:`.linear_ot._superblock_partials`:
    per-superblock marginal partials ``(load[Sb, C], colsum[Sb, C])``
    with each block's tiles accumulated sequentially in VMEM.  The
    tile body is the SAME traced helper the XLA scan uses, so the
    partials are bit-identical — the sharded composition swaps this in
    per shard and keeps its all-gather + ordered combine unchanged."""
    from jax.experimental import pallas as pl

    from .linear_ot import _tile_softmax

    Sb, tpb, tile = ws_b.shape
    C = A.shape[0]
    C_pad = lane_pad(C)
    nt = Sb * tpb
    ws2 = ws_b.reshape(nt, tile)
    cnt2 = cnt_b.reshape(nt, tile)
    A_p = _pad_cols(A, C_pad)
    B_p = _pad_cols(B, C_pad)

    def kernel(ws_ref, cnt_ref, A_ref, B_ref, l_ref, c_ref):
        j_idx = lax.broadcasted_iota(jnp.int32, (C_pad, 1), 0)
        A_col = A_ref[:]
        B_col = B_ref[:]
        zero = jnp.zeros((C_pad, 1), jnp.float32)
        for s in range(Sb):
            def tile_fn(t, acc, s=s):
                acc_l, acc_c = acc
                w_t = ws_ref[pl.ds(jnp.int32(s * tpb) + t, 1), :]
                c_t = cnt_ref[pl.ds(jnp.int32(s * tpb) + t, 1), :]
                x = _tile_softmax(w_t, A_col, B_col, j_idx, C)
                acc_l = acc_l + jnp.sum(w_t * x, axis=1, keepdims=True)
                acc_c = acc_c + jnp.sum(c_t * x, axis=1, keepdims=True)
                return acc_l, acc_c

            l_b, c_b = lax.fori_loop(
                jnp.int32(0), jnp.int32(tpb), tile_fn, (zero, zero)
            )
            l_ref[pl.ds(s, 1), :] = l_b.reshape(1, C_pad)
            c_ref[pl.ds(s, 1), :] = c_b.reshape(1, C_pad)

    l, c = pl.pallas_call(
        kernel,
        in_specs=_block_specs(
            [(nt, tile), (nt, tile), (C_pad, 1), (C_pad, 1)]
        ),
        out_specs=_block_specs([(Sb, C_pad), (Sb, C_pad)]),
        out_shape=[
            jax.ShapeDtypeStruct((Sb, C_pad), jnp.float32),
            jax.ShapeDtypeStruct((Sb, C_pad), jnp.float32),
        ],
        interpret=interpret,
    )(ws2, cnt2, A_p, B_p)
    return l[:, :C], c[:, :C]


def mirror_prox_step_pallas(ws_b, cnt_b, A, B, sc, prev_spread, *,
                            eta: float, interpret: bool = False):
    """ONE fused extragradient step: predictor marginals at ``(A, B)``,
    in-kernel step-scale damping + extrapolation to ``A_half``, and
    corrector marginals at ``(A_half, B)`` — a single VMEM-resident
    invocation per mirror-prox iteration.

    Returns ``(load1[C], load2[C], colsum2[C])``; the (cheap, exact)
    scale/commit arithmetic is recomputed by the XLA loop body from
    ``load1`` so the while-loop carry stays in plain XLA.  Every
    reduction shape matches the XLA path's (same tile helper, same
    left-to-right block combine, same padded-lane mean), which is what
    makes the two trajectories bit-identical."""
    from jax.experimental import pallas as pl

    from .linear_ot import _tile_softmax

    Sb, tpb, tile = ws_b.shape
    C = A.shape[0]
    C_pad = lane_pad(C)
    nt = Sb * tpb
    ws2 = ws_b.reshape(nt, tile)
    cnt2 = cnt_b.reshape(nt, tile)
    A_p = _pad_cols(A, C_pad)
    B_p = _pad_cols(B, C_pad)
    sc2 = jnp.asarray(sc, jnp.float32).reshape(1, 1)
    sp2 = jnp.asarray(prev_spread, jnp.float32).reshape(1, 1)
    eta_f = float(eta)  # baked into the kernel as a literal

    def kernel(ws_ref, cnt_ref, A_ref, B_ref, sc_ref, sp_ref,
               l1_ref, l2_ref, c2_ref):
        j_idx = lax.broadcasted_iota(jnp.int32, (C_pad, 1), 0)
        B_col = B_ref[:]
        zero = jnp.zeros((C_pad, 1), jnp.float32)

        def eval_load(A_col):
            # Predictor marginal: per-superblock tile-sequential
            # partials, then the SAME left-to-right block combine as
            # _ordered_sum (parts[0] seeds the fold — not zero — so
            # the addition sequence matches exactly).
            parts = []
            for s in range(Sb):
                def tile_fn(t, acc, s=s):
                    w_t = ws_ref[pl.ds(jnp.int32(s * tpb) + t, 1), :]
                    x = _tile_softmax(w_t, A_col, B_col, j_idx, C)
                    return acc + jnp.sum(w_t * x, axis=1, keepdims=True)

                parts.append(lax.fori_loop(
                    jnp.int32(0), jnp.int32(tpb), tile_fn, zero
                ))
            total = parts[0]
            for s in range(1, Sb):
                total = total + parts[s]
            return total

        def eval_pair(A_col):
            parts = []
            for s in range(Sb):
                def tile_fn(t, acc, s=s):
                    acc_l, acc_c = acc
                    w_t = ws_ref[pl.ds(jnp.int32(s * tpb) + t, 1), :]
                    c_t = cnt_ref[pl.ds(jnp.int32(s * tpb) + t, 1), :]
                    x = _tile_softmax(w_t, A_col, B_col, j_idx, C)
                    acc_l = acc_l + jnp.sum(w_t * x, axis=1, keepdims=True)
                    acc_c = acc_c + jnp.sum(c_t * x, axis=1, keepdims=True)
                    return acc_l, acc_c

                parts.append(lax.fori_loop(
                    jnp.int32(0), jnp.int32(tpb), tile_fn, (zero, zero)
                ))
            tl = parts[0][0]
            tc = parts[0][1]
            for s in range(1, Sb):
                tl = tl + parts[s][0]
                tc = tc + parts[s][1]
            return tl, tc

        A_col = A_ref[:]
        load1 = eval_load(A_col)
        # Step-scale damping — value-exact ops (masked max/min,
        # compares, f32 multiplies) that the XLA body reproduces from
        # the returned load1.
        valid_j = j_idx < C
        lmax = jnp.max(
            jnp.where(valid_j, load1, -jnp.inf), axis=0, keepdims=True
        )
        lmin = jnp.min(
            jnp.where(valid_j, load1, jnp.inf), axis=0, keepdims=True
        )
        spread = lmax - lmin
        sc_cur = sc_ref[:]
        grew = spread > sp_ref[:]
        sc_new = jnp.where(
            grew,
            sc_cur * jnp.float32(0.5),
            jnp.minimum(sc_cur * jnp.float32(1.2), jnp.float32(1.0)),
        )
        # Extrapolation mean: the padded-lane reduction of
        # _mean_padded — load1's pad rows are exact zeros, so the
        # element set (and the reduce shape) matches the XLA side.
        mean1 = jnp.sum(load1, axis=0, keepdims=True) / jnp.float32(C)
        A_half = A_col + jnp.float32(eta_f) * sc_new * (load1 - mean1)
        load2, colsum2 = eval_pair(A_half)
        l1_ref[:] = load1
        l2_ref[:] = load2
        c2_ref[:] = colsum2

    l1, l2, c2 = pl.pallas_call(
        kernel,
        in_specs=_block_specs(
            [(nt, tile), (nt, tile), (C_pad, 1), (C_pad, 1), (1, 1),
             (1, 1)]
        ),
        out_specs=_block_specs([(C_pad, 1), (C_pad, 1), (C_pad, 1)]),
        out_shape=[
            jax.ShapeDtypeStruct((C_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((C_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((C_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(ws2, cnt2, A_p, B_p, sc2, sp2)
    return l1[:C, 0], l2[:C, 0], c2[:C, 0]


def state_digest_pallas(lags_p, choice_p, counts, num_consumers: int, *,
                        interpret: bool = False):
    """Fused integrity-digest epilogue: the round-15 resident-state
    digest — int64[4] ``[counts_sum, range_violations, lags_sum,
    counts_vs_choice_L1]`` — in ONE kernel pass over the resident
    buffers, replacing the separate XLA reduction tree + bincount
    scatter.  The per-consumer occupancy is rebuilt as a one-hot
    lane reduction per row tile (no scatter — Mosaic-friendly), and
    every slot is integer arithmetic, so the result is order-exact
    against the XLA reference for ANY accumulation schedule (the probe
    still bit-compares the real lowering; int64 lanes are the risky
    part)."""
    from jax.experimental import pallas as pl

    C = int(num_consumers)
    P = lags_p.shape[0]
    P_pad = lane_pad(P)
    rows = P_pad // LANE
    C_pad = lane_pad(C)
    # Pad rows are digest-neutral: lag 0 adds nothing to the sum,
    # choice -1 is neither a violation nor in-range, and the padded
    # count rows are zero on both sides of the L1.
    lags2 = jnp.pad(
        lags_p.astype(jnp.int64), (0, P_pad - P)
    ).reshape(rows, LANE)
    ch2 = jnp.pad(
        choice_p.astype(jnp.int32), (0, P_pad - P), constant_values=-1
    ).reshape(rows, LANE)
    counts_p = jnp.pad(
        counts.astype(jnp.int64), (0, C_pad - C)
    ).reshape(C_pad, 1)

    def kernel(lags_ref, ch_ref, counts_ref, d0, d1, d2, d3):
        j_idx = lax.broadcasted_iota(jnp.int32, (C_pad, LANE), 0)

        def row_fn(t, acc):
            lag_sum, viol, cnt = acc
            lag_row = lags_ref[pl.ds(t, 1), :]
            ch_row = ch_ref[pl.ds(t, 1), :]
            lag_sum = lag_sum + jnp.sum(
                lag_row, axis=1, keepdims=True, dtype=jnp.int64
            )
            viol = viol + jnp.sum(
                (ch_row < -1) | (ch_row >= C),
                axis=1, keepdims=True, dtype=jnp.int32,
            )
            in_range = (ch_row >= 0) & (ch_row < C)
            onehot = (ch_row == j_idx) & in_range
            cnt = cnt + jnp.sum(
                onehot, axis=1, keepdims=True, dtype=jnp.int32
            )
            return lag_sum, viol, cnt

        lag_sum, viol, cnt = lax.fori_loop(
            jnp.int32(0), jnp.int32(rows), row_fn,
            (
                jnp.zeros((1, 1), jnp.int64),
                jnp.zeros((1, 1), jnp.int32),
                jnp.zeros((C_pad, 1), jnp.int32),
            ),
        )
        counts64 = counts_ref[:]
        d0[:] = jnp.sum(counts64, axis=0, keepdims=True, dtype=jnp.int64)
        d1[:] = viol.astype(jnp.int64)
        d2[:] = lag_sum
        d3[:] = jnp.sum(
            jnp.abs(cnt.astype(jnp.int64) - counts64),
            axis=0, keepdims=True, dtype=jnp.int64,
        )

    d0, d1, d2, d3 = pl.pallas_call(
        kernel,
        in_specs=_block_specs(
            [(rows, LANE), (rows, LANE), (C_pad, 1)]
        ),
        out_specs=_block_specs([(1, 1)] * 4),
        out_shape=[jax.ShapeDtypeStruct((1, 1), jnp.int64)] * 4,
        interpret=interpret,
    )(lags2, ch2, counts_p)
    return jnp.stack([d0[0, 0], d1[0, 0], d2[0, 0], d3[0, 0]])


# ---------------------------------------------------------------------------
# probe-once device gate (the rounds_pallas scaffolding, verbatim)
# ---------------------------------------------------------------------------


def _probe_instance():
    from .dispatch import ensure_x64

    ensure_x64()  # the production entries always run in x64 mode
    rng = np.random.default_rng(0)
    lags = rng.integers(0, 10**6, size=PROBE_ROWS).astype(np.int64)
    valid = np.ones(PROBE_ROWS, bool)
    from ..models.sinkhorn import _scale_np

    scale = _scale_np(lags, valid, PROBE_CONSUMERS)
    return lags, valid, np.float64(scale), np.float32(PROBE_ROWS)


def _probe_parity_duals() -> bool:
    """Bit-compare the real Mosaic lowering of the fused step against
    the XLA tile scan over a full multi-iteration duals solve — a
    kernel that compiles but miscompiles must never reach a rebalance,
    because duals wrongness is silent assignment skew, not an error."""
    from .linear_ot import _linear_duals_jit

    assert linear_pallas_admit(
        PROBE_ROWS, PROBE_CONSUMERS, PROBE_TILE
    ), "probe shape no longer admits — fix PROBE_* or the byte model"
    lags, valid, scale, nv = _probe_instance()
    kw = dict(
        num_consumers=PROBE_CONSUMERS, iters=_PROBE_ITERS,
        tile=PROBE_TILE,
    )
    A0, B0, r0 = _linear_duals_jit(lags, valid, scale, nv, **kw)
    A1, B1, r1 = _linear_duals_jit(
        lags, valid, scale, nv, kernel=True, **kw
    )
    return bool(
        (np.asarray(A0) == np.asarray(A1)).all()
        and (np.asarray(B0) == np.asarray(B1)).all()
        and int(r0) == int(r1)
    )


def _probe_speed_duals(margin: float = 1.0) -> bool:
    """Race the fused kernel against the XLA tile scan on the probe
    shape (batched in-executable repeats, scalar fetch — the only
    valid clock on this platform).  margin=1.0: the kernel must be at
    least as fast — a correct-but-slow lowering must not regress the
    quality plane just because it compiled."""
    global _LAST_RACE
    from ..utils.observability import stopwatch
    from .linear_ot import _linear_duals_jit

    lags, valid, scale, nv = _probe_instance()
    n = 4
    batch = jax.device_put(
        np.stack([np.roll(lags, 7919 * i) for i in range(n)])
    )

    @functools.partial(jax.jit, static_argnames=("kernel",))
    def many(b, kernel: bool):
        def one(v):
            A, B, r = _linear_duals_jit(
                v, valid, scale, nv, num_consumers=PROBE_CONSUMERS,
                iters=_PROBE_ITERS, tile=PROBE_TILE, kernel=kernel,
            )
            return A.sum() + B.sum() + r.astype(jnp.float32)

        return lax.map(one, b).sum()

    def timed(kernel: bool) -> float:
        float(many(batch, kernel=kernel))  # warm-up/compile
        ts = []
        for _ in range(5):
            with stopwatch() as t:
                float(many(batch, kernel=kernel))
            ts.append(t[0])
        return float(np.median(ts))

    t_xla, t_pal = timed(False), timed(True)
    _LAST_RACE = {"xla_ms": t_xla, "pallas_ms": t_pal, "margin": margin}
    LOGGER.info(
        "linear-OT kernel race: xla %.2f ms vs pallas %.2f ms (x%d "
        "in-executable)", t_xla, t_pal, n,
    )
    return t_pal < t_xla * margin


def _probe_parity_digest() -> bool:
    """Bit-compare the fused digest against the XLA reference on the
    real lowering (int64 lanes may not legalize on every Mosaic
    toolchain — failure here just keeps the XLA reduction tree)."""
    from .dispatch import ensure_x64
    from .refine import _state_digest_xla

    ensure_x64()
    rng = np.random.default_rng(2)
    P, C = 4096, 1000
    lags = jnp.asarray(rng.integers(0, 2**40, size=P).astype(np.int64))
    choice = jnp.asarray(
        rng.integers(-1, C, size=P).astype(np.int32)
    )
    counts = jnp.asarray(
        np.bincount(
            np.asarray(choice)[np.asarray(choice) >= 0], minlength=C
        ).astype(np.int64)
    )
    ref = _state_digest_xla(lags, choice, counts, C)
    got = state_digest_pallas(lags, choice, counts, C)
    return bool((np.asarray(ref) == np.asarray(got)).all())


def linear_pallas_available(
    run_probe: bool = False, kind: str = "duals"
) -> bool:
    """Probe-once gate for PRODUCTION dispatch of the linear-OT kernel
    plane (``kind`` in {"duals", "digest"}).

    The probe (full-trajectory parity bit-compare + a speed race vs
    the XLA tile scan, plus the digest parity, all on the real device)
    costs several executable compiles — minutes through a
    remote-compile transport — so it NEVER runs implicitly on a
    rebalance path: callers that can afford it (configure-time
    warm-up, the benchmark harness) pass ``run_probe=True`` once;
    until then, and on any failure, the answer is False and the XLA
    tile scan serves.  Resolve EAGERLY before any jit trace (same
    contract as rounds_pallas_available)."""
    global _linear_pallas_ok
    if _linear_pallas_ok is None:
        from .plan_stats import _trace_state_clean

        if not run_probe or not _trace_state_clean():
            return False  # unprobed (or mid-trace): stay on the XLA scan
        with _linear_pallas_lock:
            if _linear_pallas_ok is not None:  # lost the race: decided
                return _linear_pallas_ok.get(kind, False)
            if jax.default_backend() == "cpu":
                _linear_pallas_ok = dict(duals=False, digest=False)
                return False
            try:
                duals = _probe_parity_duals()
                if not duals:
                    LOGGER.warning(
                        "linear-OT Pallas kernel compiled but FAILED "
                        "device parity; staying on the XLA tile scan"
                    )
                duals = duals and _probe_speed_duals()
            except Exception:
                LOGGER.warning(
                    "linear-OT Pallas kernel unavailable; using the "
                    "XLA tile scan", exc_info=True,
                )
                duals = False
            try:
                digest = _probe_parity_digest()
                if not digest:
                    LOGGER.warning(
                        "fused digest epilogue FAILED device parity; "
                        "keeping the XLA digest reduction"
                    )
            except Exception:
                LOGGER.warning(
                    "fused digest epilogue unavailable; keeping the "
                    "XLA digest reduction", exc_info=True,
                )
                digest = False
            _linear_pallas_ok = dict(duals=duals, digest=digest)
    return _linear_pallas_ok.get(kind, False)


def mark_linear_kernel_bad(kind: str, reason: str = "") -> None:
    """Permanently disable one kernel plane for this process after a
    RUNTIME failure (the probe can only vouch for the shapes it ran;
    a dispatch that faults later must fall back AND stay fallen
    back)."""
    global _linear_pallas_ok
    with _linear_pallas_lock:
        state = dict(_linear_pallas_ok or
                     dict(duals=False, digest=False))
        state[kind] = False
        _linear_pallas_ok = state
    LOGGER.warning(
        "linear-OT %s kernel disabled after runtime failure%s; the XLA "
        "path serves from here on", kind,
        f": {reason}" if reason else "",
    )


def _reset_gate_for_tests() -> None:
    """Test hook: forget the probe verdict (mirrors the rounds_pallas
    test idiom of monkeypatching the module flag)."""
    global _linear_pallas_ok, _LAST_RACE
    with _linear_pallas_lock:
        _linear_pallas_ok = None
        _LAST_RACE = None


# ---------------------------------------------------------------------------
# kernel report (CI artifact + dump_metrics --summary `kernel:` row)
# ---------------------------------------------------------------------------

#: Where the report lands unless overridden (env wins — the CI step
#: and dump_metrics --summary read the same resolution).
KERNEL_REPORT_ENV = "KLBA_KERNEL_REPORT"
KERNEL_REPORT_DEFAULT = "kernel_report.json"


def interpret_parity_check() -> dict:
    """CPU-runnable bit-parity self-check (interpret mode executes the
    kernel trace as plain jnp ops): the fused duals step and the
    digest epilogue against their XLA references on a small
    non-lane-aligned shape.  This is what the CI artifact records on
    backends where the device probe cannot run."""
    from .dispatch import ensure_x64
    from .linear_ot import _linear_duals_jit
    from .refine import _state_digest_xla

    ensure_x64()
    rng = np.random.default_rng(5)
    P, C, tile = 512, 37, 64
    lags = rng.integers(0, 10**6, size=P).astype(np.int64)
    valid = np.ones(P, bool)
    from ..models.sinkhorn import _scale_np

    scale = np.float64(_scale_np(lags, valid, C))
    nv = np.float32(P)
    kw = dict(num_consumers=C, iters=8, tile=tile)
    A0, B0, r0 = _linear_duals_jit(lags, valid, scale, nv, **kw)
    A1, B1, r1 = _linear_duals_jit(
        lags, valid, scale, nv, kernel="interpret", **kw
    )
    duals_ok = bool(
        (np.asarray(A0) == np.asarray(A1)).all()
        and (np.asarray(B0) == np.asarray(B1)).all()
        and int(r0) == int(r1)
    )
    choice = jnp.asarray(rng.integers(-1, C, size=P).astype(np.int32))
    counts = jnp.asarray(
        np.bincount(
            np.asarray(choice)[np.asarray(choice) >= 0], minlength=C
        ).astype(np.int64)
    )
    lags_j = jnp.asarray(lags)
    ref = _state_digest_xla(lags_j, choice, counts, C)
    got = state_digest_pallas(lags_j, choice, counts, C, interpret=True)
    digest_ok = bool((np.asarray(ref) == np.asarray(got)).all())
    return dict(duals=duals_ok, digest=digest_ok)


def kernel_report(run_probe: bool = False) -> dict:
    """The probe/parity report: gate verdicts, race timings, the
    interpret-mode parity self-check, and the phase-metric names — the
    payload behind the CI artifact and the ``kernel:`` summary row."""
    duals = linear_pallas_available(run_probe=run_probe, kind="duals")
    digest = linear_pallas_available(kind="digest")
    report = {
        "backend": jax.default_backend(),
        "probed": _linear_pallas_ok is not None,
        "duals_kernel": duals,
        "digest_kernel": digest,
        "probe_shape": {
            "rows": PROBE_ROWS,
            "consumers": PROBE_CONSUMERS,
            "tile": PROBE_TILE,
            "iters": _PROBE_ITERS,
        },
        "race_ms": _LAST_RACE,
        "interpret_parity": interpret_parity_check(),
        "phase_metric": (
            "klba_device_phase_ms{phase=h2d|duals|rounding|refine}"
        ),
    }
    from ..utils import metrics

    for plane, on in (("linear_duals", duals), ("digest", digest)):
        metrics.REGISTRY.gauge(
            "klba_kernel_plane_enabled", {"plane": plane}
        ).set(1 if on else 0)
    return report


def write_kernel_report(
    path: Optional[str] = None, run_probe: bool = False
) -> str:
    """Serialize :func:`kernel_report` where the CI artifact step and
    ``dump_metrics --summary`` expect it (``$KLBA_KERNEL_REPORT`` or
    ./kernel_report.json).  Returns the path written."""
    from ..utils.snapshot import atomic_write_bytes

    out = path or os.environ.get(
        KERNEL_REPORT_ENV, KERNEL_REPORT_DEFAULT
    )
    report = kernel_report(run_probe=run_probe)
    payload = json.dumps(report, indent=2, sort_keys=True) + "\n"
    # noqa-reason: a CI diagnostics artifact, not resident snapshot
    # state — no CAS/fencing story applies, atomicity alone suffices.
    atomic_write_bytes(out, payload.encode("utf-8"))  # noqa: L017
    LOGGER.info("kernel plane report written to %s", out)
    return out
