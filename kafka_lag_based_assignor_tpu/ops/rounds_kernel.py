"""Fast greedy-LPT kernel via exact round decomposition — the TPU-first path.

**Theorem (round decomposition of count-primary greedy LPT).**  Consider the
reference selection rule (LagBasedPartitionAssignor.java:246-259): each
partition, in descending-lag order, goes to the consumer minimizing
(assigned count, total assigned lag, member id).  Because *count* is the
primary key and every consumer is eligible for every partition of the topic,
the process decomposes into rounds of C consecutive partitions:

1. At the start of round r every consumer has count r, so within the round a
   consumer that receives a partition (count r+1) cannot receive another
   until all consumers have r+1 — i.e. each consumer receives **exactly one**
   partition per full round (a prefix of consumers in the final partial
   round).
2. Within a round, receiving a partition removes a consumer from contention
   for the rest of the round, and the (total lag, id) keys of the consumers
   still in contention are unchanged.  Hence the j-th partition of the round
   (descending lag) goes to the consumer with the (j+1)-th smallest
   (total lag, member id) **at the start of the round**.

So a round is: sort consumers by (total lag, rank) and match them
positionally to the round's descending-lag partitions.  The sequential depth
drops from P scan steps to ceil(P/C) rounds, each a C-element ``lax.sort``
that XLA lowers to its optimized bitonic sorter — at the north-star scale
(P=100k, C=1k) that is 100 sequential steps instead of 100k, which is what
makes the <50 ms budget reachable on one chip.

Bit-exact parity with the scan kernel and the host oracle is enforced by
differential fuzzing in tests/test_kernels.py.

Pre-condition: all C consumers are eligible for the topic.  The host layer
guarantees this by passing, per topic (or per group of topics with identical
subscriber sets, see :mod:`.packing`), only that topic's subscribed
consumers re-ranked densely — mirroring how the reference's ``assignTopic``
receives exactly the topic's consumer list (reference :204-213).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .scan_kernel import sort_partitions_with
from .sortops import bincount_sorted, unsort


def _rounds_body(totals: jax.Array, xs, C: int):
    """One round: sort consumers by (total lag, rank), match positionally."""
    round_lags, round_valid = xs  # [C] descending-lag partitions (maybe padded)
    ranks = jnp.arange(C, dtype=jnp.int32)
    _, order = lax.sort((totals, ranks), num_keys=2)
    # order[j] = consumer with (j+1)-th smallest (total, rank);
    # partition j of the round goes to consumer order[j].
    gain = jnp.where(round_valid, round_lags, 0)
    totals = totals.at[order].add(gain.astype(totals.dtype))
    choice = jnp.where(round_valid, order, -1)
    return totals, choice


def _rounds_body_packed(carry, xs, C: int, rank_bits: int):
    """Scatter-free round body: the carry holds (total, consumer id) pairs
    in the PREVIOUS round's sorted order, packed per round into one int64
    key ``(total << rank_bits) | id`` whose single-key sort IS the
    (total, id) lexicographic order (totals are non-negative and the
    caller verified the shifted total cannot overflow).  The round's j-th
    partition belongs to the j-th smallest key — which after the sort is
    position j — so the gain add is POSITIONAL: no scatter, no gather,
    and the sort carries one array instead of two.  At ~90 us/round of
    tiny-op overhead in the scan body (retired probe, git history), dropping
    ops per round is exactly what makes the 100-round north-star scan
    cheaper.
    """
    totals_s, ids_s = carry
    round_lags, round_valid = xs
    key = (totals_s << rank_bits) | ids_s.astype(totals_s.dtype)
    skey = lax.sort(key)
    ids_new = (skey & ((1 << rank_bits) - 1)).astype(jnp.int32)
    gain = jnp.where(round_valid, round_lags, 0)
    totals_new = (skey >> rank_bits) + gain.astype(totals_s.dtype)
    choice = jnp.where(round_valid, ids_new, -1)
    return (totals_new, ids_new), choice


def round_rows(sorted_lags, sorted_valid, C: int, n_valid: int | None):
    """THE round-prefix shaping shared by the XLA scan and the Pallas
    adapter (their bit-parity contract depends on identical trimming):
    trim the sorted axis to ceil(L / C) rounds — padding when P < C fills
    the single partial round — and return (lags_head, valid_head, R,
    head) with head == R * C elements."""
    P = sorted_lags.shape[0]
    L = P if n_valid is None else min(int(n_valid), P)
    R = -(-L // C) if L else 0
    head = R * C
    if head <= P:
        return sorted_lags[:head], sorted_valid[:head], R, head
    return (
        jnp.pad(sorted_lags, (0, head - P)),
        jnp.pad(sorted_valid, (0, head - P)),
        R,
        head,
    )


def _rounds_scan(
    sorted_lags, sorted_valid, totals0, C: int,
    n_valid: int | None = None, totals_rank_bits: int = 0,
    scan_unroll: int | None = None,
):
    """Scan the round decomposition over one topic's sorted partitions.

    Pads the sorted axis to a whole number of rounds.  Padding sorts last
    (sort_partitions), so valid rows form a prefix and each round's valid
    entries are a prefix of the row — exactly the partial-round shape the
    theorem requires.  ``totals0`` is the starting per-consumer load: zeros
    for reference semantics (lag tiebreak local to the topic, SURVEY
    §2.4.3), or the running global totals for the cross-topic quality mode.

    ``n_valid`` (static) is an upper bound on the number of valid rows —
    when the caller knows it (the dense stream paths: P exact-size rows
    padded to a pow2 bucket), the scan stops after ceil(n_valid / C)
    rounds instead of burning ~90 us/round on rounds made only of padding
    (24% of the north-star scan at P=100k in a 131072 bucket).  Rows past
    the scanned prefix are all padding and get choice -1.

    ``totals_rank_bits`` (static) > 0 selects the packed scatter-free
    round body (:func:`_rounds_body_packed`); the caller guarantees
    ``(max possible total) << totals_rank_bits`` fits the lag dtype —
    including any non-zero ``totals0`` (the first round's sort orders the
    carry regardless of its initial order, so a running cross-topic
    start is fine).  0 = the general two-key body.

    Returns (totals[C], sorted_choice int32[P] in sorted order).
    """
    lags_h, valid_h, R, head = round_rows(
        sorted_lags, sorted_valid, C, n_valid
    )
    P = sorted_lags.shape[0]
    xs = (lags_h.reshape(R, C), valid_h.reshape(R, C))
    # Unrolling amortizes the scan's per-iteration bookkeeping — the round
    # body is ~90 us of tiny ops (retired probe, git history), so loop
    # overhead is a real fraction of it.  Purely a lowering choice:
    # results are bit-identical.  ``scan_unroll`` (static) overrides the
    # default factor so the (retired) hardware probe — git history — can
    # sweep it; None keeps the measured default.
    unroll = min(scan_unroll if scan_unroll else 4, max(R, 1))
    if totals_rank_bits > 0:
        ids0 = jnp.arange(C, dtype=jnp.int32)
        (totals_s, ids_s), round_choice = lax.scan(
            functools.partial(
                _rounds_body_packed, C=C, rank_bits=totals_rank_bits
            ),
            (totals0, ids0),
            xs,
            unroll=unroll,
        )
        # Restore consumer order for the totals (one C-sized sort).
        _, totals = lax.sort((ids_s, totals_s), num_keys=1)
    else:
        totals, round_choice = lax.scan(
            functools.partial(_rounds_body, C=C), totals0, xs,
            unroll=unroll,
        )
    flat = round_choice.reshape(head)[: min(head, P)]
    if head < P:
        flat = jnp.concatenate(
            [flat, jnp.full((P - head,), -1, jnp.int32)]
        )
    return totals, flat


def _unsort_choice(perm, sorted_choice, P: int, C: int):
    """Sorted-order choices back to input row order plus per-consumer
    counts (-1 padding rows excluded) — both scatter-free (sort-based, see
    :mod:`.sortops`; a P-sized sort is ~0.4 ms measured,
    a retired probe (git history), vs XLA:TPU's serialized dynamic-index
    scatters)."""
    choice = unsort(perm, sorted_choice)
    counts = bincount_sorted(sorted_choice, C)
    return choice, counts


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_consumers", "pack_shift", "n_valid", "totals_rank_bits",
        "scan_unroll",
    ),
)
def assign_topic_rounds(
    lags: jax.Array,
    partition_ids: jax.Array,
    valid: jax.Array,
    num_consumers: int,
    pack_shift: int = 0,
    n_valid: int | None = None,
    totals_rank_bits: int = 0,
    scan_unroll: int | None = None,
):
    """Assign one topic's partitions via the round decomposition.

    Same contract as :func:`..ops.scan_kernel.assign_topic_scan` minus the
    ``eligible`` mask (all consumers eligible by pre-condition).
    ``pack_shift`` (static, see :func:`..ops.scan_kernel.pack_shift_for`)
    selects the packed single-key processing-order sort; ``n_valid`` /
    ``totals_rank_bits`` (static) select the trimmed scan and the
    scatter-free packed round body (see :func:`_rounds_scan` — callers
    guarantee their preconditions: valid rows <= n_valid, and shifted
    totals cannot overflow).  All variants are bit-exact.

    Returns (choice int32[P] input order, counts int32[C], totals[C]).
    """
    P = lags.shape[0]
    C = int(num_consumers)

    perm, sorted_lags, sorted_valid = sort_partitions_with(
        lags, partition_ids, valid, pack_shift
    )
    totals0 = jnp.zeros((C,), dtype=lags.dtype)
    totals, sorted_choice = _rounds_scan(
        sorted_lags, sorted_valid, totals0, C,
        n_valid=n_valid, totals_rank_bits=totals_rank_bits,
        scan_unroll=scan_unroll,
    )
    choice, counts = _unsort_choice(perm, sorted_choice, P, C)
    return choice, counts, totals


@functools.partial(jax.jit, static_argnames=("num_consumers",))
def assign_presorted_rounds(
    sorted_lags: jax.Array,
    perm: jax.Array,
    num_consumers: int,
):
    """Round decomposition over a host-presorted dense topic.

    The CPU-backend fast path for the streaming/north-star shape: the host
    already computed the processing-order permutation (``np.argsort`` is
    ~3x faster than XLA:CPU's comparator sort at P=100k) and gathered the
    lags; every row is valid and the shape is exact (no power-of-two pad),
    so the scan runs the minimum ceil(P/C) rounds.

    Args:
      sorted_lags: [P] lags in processing order (descending, ties pid asc).
      perm: int32[P] the permutation used, for unsorting the choices.

    Returns (choice int32[P] in input order, counts int32[C], totals[C]).
    """
    P = sorted_lags.shape[0]
    C = int(num_consumers)
    totals0 = jnp.zeros((C,), dtype=sorted_lags.dtype)
    totals, sorted_choice = _rounds_scan(
        sorted_lags, jnp.ones((P,), dtype=bool), totals0, C
    )
    choice, counts = _unsort_choice(perm, sorted_choice, P, C)
    return choice, counts, totals


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_consumers", "pack_shift", "totals_rank_bits", "n_valid"
    ),
)
def assign_global_rounds(
    lags: jax.Array,
    partition_ids: jax.Array,
    valid: jax.Array,
    num_consumers: int,
    pack_shift: int = 0,
    totals_rank_bits: int = 0,
    n_valid: int | None = None,
):
    """Cross-topic global-balance quality mode (beyond-reference feature).

    The reference never balances lag across topics — ``consumerTotalLags``
    is local to ``assignTopic`` (reference :216, SURVEY §2.4.3) — so a
    consumer can end up with every topic's hottest partitions.  This kernel
    keeps the per-topic **count** invariant (max − min ≤ 1 per topic, the
    primary criterion) but carries the lag-tiebreak totals **across
    topics**: a ``lax.scan`` over the topic axis threads the running global
    per-consumer load through each topic's round decomposition.  The round
    theorem (module docstring) holds unchanged with a non-zero starting
    load, because within a topic count is still primary and a round still
    retires exactly one partition per consumer.

    Sequential depth is sum over topics of ceil(P_t/C) rounds — the same
    total round count as the vmap path, traded for cross-topic quality
    (global max/mean lag imbalance →~1 instead of ~2 on uniform loads).

    Args/returns as :func:`..ops.batched.assign_batched_rounds`, except
    ``totals`` is the single global [C] vector (the north-star metric's
    denominator), not per-topic.
    """
    T, P = lags.shape
    C = int(num_consumers)

    # Only the totals carry is sequential across topics; the per-topic sorts
    # are independent, so hoist them out of the scan and run them as one
    # parallel vmap batch (same parallelism as the reference-semantics path).
    perms, sorted_lags, sorted_valid = jax.vmap(
        functools.partial(sort_partitions_with, pack_shift=pack_shift)
    )(lags, partition_ids, valid)

    def topic_step(totals, xs):
        sl_t, sv_t, perm = xs
        totals, sorted_choice = _rounds_scan(
            sl_t, sv_t, totals, C, n_valid=n_valid,
            totals_rank_bits=totals_rank_bits,
        )
        choice, counts = _unsort_choice(perm, sorted_choice, P, C)
        return totals, (choice, counts)

    totals0 = jnp.zeros((C,), dtype=lags.dtype)
    totals, (choice, counts) = lax.scan(
        topic_step, totals0, (sorted_lags, sorted_valid, perms)
    )
    return choice, counts, totals
