"""Linear-space OT quality mode: O(P + C)-memory mirror-prox solve.

The dense Sinkhorn quality path (:mod:`..models.sinkhorn`) keeps its
iteration STATE in O(C) — the rank-structured duals — but every duals
iteration and every rounding pass still *streams* [U, C] / [P, C]-
proportional logits buffers, and at the 1M x 10k north star that
working set (~40 GB of f32) can never ship (ROADMAP "Linear-space
quality mode at megascale").  This module recasts the whole quality
solve in **linear memory**:

* **Mirror-prox duals** (Log-Averaged Mirror Prox, arXiv:2511.11359 —
  pattern only): the same implicit plan ``logX[p, j] = -ws_p*A_j + B_j``
  as the Sinkhorn solver, iterated with an extragradient
  (predictor/corrector) step — the gradient is re-evaluated at the
  extrapolated dual point before the committed update, which is what
  lets the linear-space iteration keep Sinkhorn-grade convergence
  without the host-side dedup pre-pass.  Each marginal evaluation scans
  the P axis in FIXED-SIZE tiles inside one fused executable
  (``lax.scan`` over tiles of a pow2 knob, ``tpu.assignor.quality.tile``)
  so peak device memory is **O(tile*C + P + C)**: the f32 ws/count
  vectors, one live (tile, C) logits block, and the dual vectors —
  never a [P, C] (or [U, C]) plan.

* **Mesh-size-independent accumulation**: tiles are grouped into
  ``_SUPERBLOCKS`` fixed row blocks (>= the largest supported mesh)
  whose partial marginals are ALWAYS combined in the same left-to-right
  order.  The P-axis-sharded composition (:func:`..sharded.solve.
  solve_linear_sharded`) assigns whole superblocks to mesh shards and
  all-gathers the per-block partials before the identical ordered
  combine — so the duals trajectory, and therefore the final
  assignment, is **bit-identical at mesh size 1 vs 2-8** (the round-17
  replicated-consumer-state pattern, now with deterministic f32
  reduction order; pinned by tests/test_linear_ot.py).

* **Push-relabel-style additive rounding** (arXiv:2203.03732 — pattern
  only): rows take their implicit-plan argmax consumer (tile-streamed),
  over-capacity consumers *push* their surplus rows to open seats in
  ascending-load round-robin order (the capacity repair of
  :func:`..models.sinkhorn._round_parallel`), and the exchange-refine +
  greedy portfolio tail is shared verbatim with the Sinkhorn solver
  (:func:`..models.sinkhorn._round_refine_portfolio`).  The additive
  guarantee — ``max consumer load <= total/C + max_lag`` — is asserted
  on every solve; it maps directly onto the bench's ``imbalance_bound``
  (the count-constrained lower bound is >= total/C, so quality_ratio is
  bounded by ``1 + max_lag/(total/C)``).

Mode selection lives in :mod:`.dispatch` (``tpu.assignor.quality.mode``
= ``sinkhorn | linear | auto``): ``assign_topic_sinkhorn`` callers and
the streaming cold path pick the linear mode up without any API change.
Lint rule L021 confines [P, C]-proportional dense materialization to
the Sinkhorn legacy path and this module's tile body.
"""

from __future__ import annotations

import functools
import logging
from typing import Mapping, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..types import AssignmentMap, TopicPartitionLag

LOGGER = logging.getLogger(__name__)

#: Fixed number of accumulation blocks along the P axis.  Per-block
#: partial marginals are combined in a FIXED left-to-right order, and
#: the sharded composition assigns whole blocks to mesh shards — both
#: paths therefore run the identical f32 addition sequence, which is
#: what makes the solve bit-identical across mesh sizes (module
#: docstring).  Must be a pow2 >= the largest supported mesh (8).
_SUPERBLOCKS = 8

#: Default rows per tile (the ``tpu.assignor.quality.tile`` knob's
#: default, mirrored in utils/config).  (1024, C) f32 logits blocks are
#: ~4 MB at C=1000 — comfortably resident on any backend.
DEFAULT_TILE = 1024

# THE tile validator lives with the config key (utils/config) so the
# knob surface and this executable cannot drift.
from ..utils.config import validate_quality_tile as validate_tile

# Lane padding comes from the shared kernel admission model: the XLA
# tile body below runs the SAME consumer-padded transposed geometry as
# the Pallas kernel plane (ops/linear_ot_pallas), which is what makes
# the two lowerings bit-identical.
from .kernel_admission import lane_pad as _lane_pad

#: Mirror-prox extragradient step size — shared by the XLA loop body
#: and the fused kernel (the kernel bakes it in as a compile-time
#: constant, so it must be THE same literal).
MIRROR_PROX_ETA = 8.0


def plan_shape(num_rows: int, tile: int):
    """Padded solve geometry: ``(P2, tile_eff, n_tiles)``.  ``P2`` is
    the pow2 bucket (>= 64 so the 8 superblocks stay non-empty) and
    ``tile_eff`` the effective tile (shrunk so the superblock split is
    exact; both pow2, so every division below is exact).  Used by the
    single-device and sharded paths alike — the geometry is part of the
    bit-parity contract."""
    from .packing import pad_bucket

    P2 = pad_bucket(max(int(num_rows), _SUPERBLOCKS * 8))
    t = max(8, min(validate_tile(tile), P2 // _SUPERBLOCKS))
    return P2, t, P2 // t


def _ws_cnt(lags, valid, scale):
    """Per-row f32 scaled lags + validity weights (elementwise — the
    one form that is trivially identical under any P sharding).  The
    f64 divide matches :func:`..models.sinkhorn._scaled_ws` given the
    host-computed scale."""
    w = jnp.where(valid, lags, 0).astype(jnp.float64)
    ws = (w / scale).astype(jnp.float32)
    cnt = valid.astype(jnp.float32)
    return ws, cnt


def _to_blocks(x, P2: int, nblocks: int, tile: int):
    """Pad a [P] vector to P2 rows and reshape to
    [nblocks, tiles_per_block, tile] (padding rows carry weight 0 and
    contribute exactly nothing to any marginal)."""
    x = jnp.pad(x, (0, P2 - x.shape[0]))
    return x.reshape(nblocks, (P2 // nblocks) // tile, tile)


def _tile_softmax(w_row, A_col, B_col, j_idx, C: int):
    """THE tile body, shared op-for-op by the XLA scan and the Pallas
    kernels: masked softmax over the implicit-plan logits block
    ``-w * A + B`` in the TRANSPOSED padded geometry — consumers on
    the sublane axis as a (C_pad, 1) column, rows on the lane axis as
    a (1, tile) row, so the (C_pad, tile) logits block is exactly the
    VMEM-resident layout of :mod:`.linear_ot_pallas`.  Pad consumers
    (``j_idx >= C``) are masked to -1e30, which underflows to an exact
    0 after the exp — every padded marginal entry is a true f32 zero.
    (Lint L021 confines dense rank-1 x rank-1 broadcasts to functions
    like this one.)"""
    logits = -w_row * A_col + B_col
    logits = jnp.where(j_idx < C, logits, jnp.float32(-1e30))
    mx = jnp.max(logits, axis=0, keepdims=True)
    e = jnp.exp(logits - mx)
    return e / jnp.sum(e, axis=0, keepdims=True)


def _superblock_partials(ws_blocks, cnt_blocks, A, B):
    """Per-superblock marginal partials: ``(load[Sb, C], colsum[Sb, C])``
    with each block's tiles accumulated SEQUENTIALLY (``lax.scan``
    carries the f32 accumulators, so the addition order per block is
    fixed regardless of backend fusion).  Runs the same consumer-padded
    transposed tile body as the kernel plane (:func:`_tile_softmax`),
    so the partials are bit-identical to
    :func:`.linear_ot_pallas.superblock_partials_pallas`."""
    C = A.shape[0]
    C_pad = _lane_pad(C)
    A_p = jnp.pad(A, (0, C_pad - C)).reshape(C_pad, 1)
    B_p = jnp.pad(B, (0, C_pad - C)).reshape(C_pad, 1)
    j_idx = lax.broadcasted_iota(jnp.int32, (C_pad, 1), 0)

    def one_block(args):
        ws_t, cnt_t = args  # [tiles_per_block, tile]

        def tile_step(carry, wc):
            acc_l, acc_c = carry
            w_t, c_t = wc
            w_row = w_t.reshape(1, -1)
            c_row = c_t.reshape(1, -1)
            x = _tile_softmax(w_row, A_p, B_p, j_idx, C)
            acc_l = acc_l + jnp.sum(w_row * x, axis=1, keepdims=True)
            acc_c = acc_c + jnp.sum(c_row * x, axis=1, keepdims=True)
            return (acc_l, acc_c), None

        zero = jnp.zeros((C_pad, 1), jnp.float32)
        (l_b, c_b), _ = lax.scan(tile_step, (zero, zero), (ws_t, cnt_t))
        return l_b[:, 0], c_b[:, 0]

    pl_, pc_ = lax.map(one_block, (ws_blocks, cnt_blocks))
    return pl_[:, :C], pc_[:, :C]


def _ordered_sum(parts):
    """Fixed left-to-right combine of [S, C] partials — S is static, so
    the unrolled adds run in the same order on every path (the bit-
    parity contract of the module docstring)."""
    acc = parts[0]
    for s in range(1, parts.shape[0]):
        acc = acc + parts[s]
    return acc


def _mean_padded(v):
    """Mean of a [C] f32 vector computed as a zero-padded lane-width
    sum over C_pad elements divided by C.  f32 sums over the SAME
    element count reduce identically regardless of layout, but padded
    vs unpadded sums do NOT — so both the XLA loop body and the fused
    kernel (whose marginals live as exact-zero-padded (C_pad, 1)
    columns) must use THIS reduction shape for the extrapolation mean,
    or their trajectories fork at the first iteration."""
    C = v.shape[0]
    return jnp.sum(jnp.pad(v, (0, _lane_pad(C) - C))) / jnp.float32(C)


def mirror_prox(stats_fn, num_consumers: int, iters: int, n_valid,
                eta: float = MIRROR_PROX_ETA, tol: float = 2e-5,
                fused_fn=None):
    """The shared mirror-prox dual loop (single-device AND sharded —
    ``stats_fn(A, B) -> (load, colsum)`` is the only thing that
    differs, and both implementations are bit-identical by
    construction).

    Extragradient step: the mirror gradient is evaluated once at the
    current duals (predictor) and once at the extrapolated point
    (corrector); the COMMITTED update uses the look-ahead gradient.
    The damped step scale and the two-residual early exit mirror the
    Sinkhorn iteration (:func:`..models.sinkhorn._sinkhorn_duals_jit`)
    so the two quality modes share one convergence contract.

    ``fused_fn(A, B, sc, prev_spread) -> (load1, load2, colsum2)``,
    when given, replaces BOTH marginal evaluations AND the in-between
    extrapolation with one fused kernel invocation
    (:func:`.linear_ot_pallas.mirror_prox_step_pallas`); the loop body
    then re-derives the (exact: compares and f32 scalar arithmetic)
    step scale from ``load1`` so the while-loop carry stays in plain
    XLA — the carries are bit-identical to the unfused path.

    Returns ``(A, B, rounds)``."""
    C = int(num_consumers)
    cap = jnp.maximum(n_valid.astype(jnp.float32), 1.0) / C
    eta32 = jnp.float32(eta)

    from .plan_stats import noise

    def body(state):
        i, sc, prev_spread, _, A, B = state
        if fused_fn is not None:
            load1, load2, colsum2 = fused_fn(A, B, sc, prev_spread)
        else:
            load1, _ = stats_fn(A, B)
        spread = jnp.max(load1) - jnp.min(load1)
        grew = spread > prev_spread
        sc = jnp.where(
            grew,
            sc * jnp.float32(0.5),
            jnp.minimum(sc * jnp.float32(1.2), jnp.float32(1.0)),
        )
        if fused_fn is None:
            # Predictor: extrapolate the consumer duals along the
            # centered load gradient, then re-evaluate BOTH marginals
            # there.  (The fused kernel runs this same extrapolation
            # in VMEM with the same reduction shapes.)
            A_half = A + eta32 * sc * (load1 - _mean_padded(load1))
            load2, colsum2 = stats_fn(A_half, B)
        # Corrector: commit the update with the look-ahead gradient;
        # one Sinkhorn column scaling toward the balanced marginal.
        A2 = A + eta32 * sc * (load2 - _mean_padded(load2))
        upd = jnp.log(cap / (colsum2 + jnp.float32(1e-9)))
        B2 = B + upd
        delta = jnp.maximum(spread, jnp.max(jnp.abs(upd)))
        return i + 1, sc, spread, delta, A2, B2

    def cond(state):
        i, delta = state[0], state[3]
        return (i < iters) & (delta > jnp.float32(tol))

    A0 = jnp.zeros((C,), jnp.float32)
    B0 = noise(
        jnp.zeros((C,), jnp.int32), jnp.arange(C, dtype=jnp.int32)
    )
    inf32 = jnp.float32(jnp.inf)
    it, _, _, _, A, B = lax.while_loop(
        cond, body, (jnp.int32(0), jnp.float32(1.0), inf32, inf32, A0, B0)
    )
    return A, B, it


@functools.partial(
    jax.jit,
    static_argnames=("num_consumers", "iters", "tile", "kernel"),
)
def _linear_duals_jit(lags, valid, scale, n_valid, *,
                      num_consumers: int, iters: int, tile: int,
                      kernel=False):
    """ONE fused executable for the whole dual solve: the mirror-prox
    outer loop with tile-streamed marginal scans inside.  Peak live
    memory is the [P2] f32 ws/count vectors + one (C_pad, tile) block +
    the [_SUPERBLOCKS, C] partials + a handful of [C] vectors —
    O(P + tile*C + C), never [P, C].

    ``kernel`` (static) selects the marginal-scan lowering: ``False``
    is the XLA tile scan; ``True`` swaps in the fused Pallas
    mirror-prox step (callers must hold a probe verdict from
    :func:`.linear_ot_pallas.linear_pallas_available` AND pass host
    admission first); ``"interpret"`` runs the same kernel trace as
    plain jnp ops (CPU parity tests).  All three produce bit-identical
    duals."""
    C = int(num_consumers)
    P2, t, _ = plan_shape(lags.shape[0], tile)
    ws, cnt = _ws_cnt(lags, valid, scale)
    ws_b = _to_blocks(ws, P2, _SUPERBLOCKS, t)
    cnt_b = _to_blocks(cnt, P2, _SUPERBLOCKS, t)

    def stats_fn(A, B):
        pl, pc = _superblock_partials(ws_b, cnt_b, A, B)
        return _ordered_sum(pl), _ordered_sum(pc)

    fused_fn = None
    if kernel:
        from .linear_ot_pallas import mirror_prox_step_pallas

        interp = kernel == "interpret"

        def fused_fn(A, B, sc, prev_spread):
            return mirror_prox_step_pallas(
                ws_b, cnt_b, A, B, sc, prev_spread,
                eta=MIRROR_PROX_ETA, interpret=interp,
            )

    return mirror_prox(stats_fn, C, iters, n_valid, fused_fn=fused_fn)


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "refine_iters")
)
def _finish_linear_jit(lags, partition_ids, valid, A, B, *,
                       num_consumers: int, refine_iters: int):
    """The rounding pass: implicit-plan argmax (tile-streamed) +
    capacity push + the exchange-refine/greedy-portfolio tail shared
    verbatim with the Sinkhorn solver — every buffer [P]- or
    [C, M]-shaped (O(P + C) total)."""
    from ..models.sinkhorn import _round_refine_portfolio, _scaled_ws

    ws = _scaled_ws(lags, valid, num_consumers)
    return _round_refine_portfolio(
        lags, partition_ids, valid, ws, A, B,
        num_consumers=num_consumers, refine_iters=refine_iters,
    )


def additive_bound(lags, valid, num_consumers: int) -> float:
    """The push-relabel-style additive guarantee on the max consumer
    load: ``total_valid_lag / C + max_lag``.  Every linear-mode solve
    is asserted against it (:func:`finish_from_duals`); relative to
    the bench's ``imbalance_bound`` (whose load form is >= total/C)
    it bounds quality_ratio by ``1 + max_lag / (total/C)``."""
    lags_np = np.asarray(lags)
    valid_np = np.asarray(valid)
    vals = lags_np[valid_np]
    if vals.size == 0:
        return 0.0
    total = float(vals.sum(dtype=np.float64))
    return total / int(num_consumers) + float(vals.max())


# Last linear solve's observability record (dump_metrics --summary and
# the service stats `quality` section read it via
# ops/dispatch.quality_status): tile geometry, peak-memory estimate,
# duals rounds, and which backend ran the duals.
_LAST: Optional[dict] = None


def last_solve_info() -> Optional[dict]:
    return _LAST


def _peak_bytes_estimate(P2: int, C: int, tile: int) -> int:
    """Device-memory model of the duals executable (the bench's
    ``linear_ot_scale`` probe folds it into the measured-peak gate;
    also the operator-facing summary row).  O(P) term: the int64 lag
    input (8B), the bool valid mask (1B), the f64 ``_ws_cnt``
    intermediate (8B, x64 mode), and the f32 ws + count vectors
    (2 x 4B) — 25 bytes/row.  Plus ~3 live (tile, C) f32 blocks
    (logits, softmax, weighted product), the per-superblock partials,
    and the dual/marginal vectors."""
    return (
        25 * P2
        + 3 * tile * C * 4
        + 2 * _SUPERBLOCKS * C * 4
        + 8 * C * 4
    )


def finish_from_duals(
    lags_p: np.ndarray,
    pids_p: np.ndarray,
    valid_p: np.ndarray,
    A,
    B,
    num_consumers: int,
    refine_iters: int,
    *,
    tiles: int,
    tile: int,
    rounds: int,
    backend: str,
    kernel: bool = False,
):
    """Shared host tail of both linear entries: run the rounding
    executable, ASSERT the additive bound, record the quality-plane
    metrics, and return host ``(choice, counts, totals)``.

    Raising on a bound violation is deliberate: the portfolio tail can
    only return greedy-or-better, and greedy's least-loaded placement
    satisfies ``max <= total/C + max_lag`` by construction — a miss
    here means the rounding contract itself broke, which must surface
    loudly rather than serve a silently unbalanced assignment."""
    from ..utils import metrics

    C = int(num_consumers)
    with metrics.device_phase("rounding"):
        choice, counts, totals = _finish_linear_jit(
            lags_p, pids_p, valid_p, A, B,
            num_consumers=C, refine_iters=int(refine_iters),
        )
        jax.block_until_ready((choice, counts, totals))
    choice_np, counts_np, totals_np = (
        np.asarray(x) for x in jax.device_get((choice, counts, totals))
    )
    record_linear_solve(
        lags_p, valid_p, totals_np, C,
        tiles=tiles, tile=tile, rounds=rounds,
        backend=backend, kernel=kernel,
    )
    return choice_np, counts_np, totals_np


def record_linear_solve(
    lags_p: np.ndarray,
    valid_p: np.ndarray,
    totals_np: np.ndarray,
    num_consumers: int,
    *,
    tiles: int,
    tile: int,
    rounds: int,
    backend: str,
    kernel: bool = False,
) -> None:
    """Shared epilogue of EVERY linear-mode rounding backend (the
    single-device :func:`finish_from_duals` and the P-sharded tail in
    :mod:`..sharded.solve`): assert the additive bound against the
    solved totals, then record the quality-plane metrics and the
    ``_LAST`` observability row.  Factored out so a backend that runs
    the rounding elsewhere cannot silently skip the bound contract."""
    from ..utils import metrics

    global _LAST
    C = int(num_consumers)
    bound = additive_bound(lags_p, valid_p, C)
    max_tot = float(totals_np.max()) if totals_np.size else 0.0
    if bound > 0.0 and max_tot > bound * (1.0 + 1e-6) + 0.5:
        raise RuntimeError(
            f"linear OT additive rounding bound violated: max consumer "
            f"load {max_tot:.0f} > total/C + max_lag = {bound:.0f} "
            "(push-relabel additive guarantee, ops/linear_ot)"
        )
    P2 = int(lags_p.shape[0])
    _LAST = {
        "backend": backend,
        "rows": P2,
        "consumers": C,
        "tile": int(tile),
        "tiles": int(tiles),
        "duals_rounds": int(rounds),
        "duals_kernel": bool(kernel),
        "peak_bytes_estimate": _peak_bytes_estimate(P2, C, int(tile)),
    }
    metrics.REGISTRY.counter(
        "klba_quality_solve_total", {"mode": "linear"}
    ).inc()
    metrics.REGISTRY.gauge("klba_quality_last_tile_count").set(
        int(tiles)
    )
    metrics.REGISTRY.gauge("klba_quality_last_peak_bytes").set(
        _LAST["peak_bytes_estimate"]
    )


def _trivial_assignment(lags_np, valid_np, num_consumers: int):
    """Host fast path for C == 1 or an all-invalid topic (no duals
    worth running)."""
    C = int(num_consumers)
    choice = np.where(valid_np, 0, -1).astype(np.int32)
    counts = np.zeros(C, np.int64)
    totals = np.zeros(C, np.int64)
    counts[0] = int(valid_np.sum())
    totals[0] = int(lags_np[valid_np].sum(dtype=np.int64))
    return choice, counts, totals


def assign_topic_linear(
    lags,
    partition_ids,
    valid,
    num_consumers: int,
    iters: int = 24,
    refine_iters: Optional[int] = None,
    tile: Optional[int] = None,
):
    """Integral, count-balanced assignment from the linear-space
    mirror-prox duals — the O(P + C) twin of
    :func:`..models.sinkhorn.assign_topic_sinkhorn`, same output
    contract ``(choice int32[P] in input order, counts, totals)``.

    HOST-ONLY entry point (the scale/validity aggregation runs in
    numpy).  ``tile`` overrides the process-wide
    ``tpu.assignor.quality.tile`` knob; ``refine_iters=None`` selects
    the Sinkhorn solver's per-rounding-path auto budget."""
    from ..models.sinkhorn import (
        _AUTO_REFINE_PARALLEL,
        _AUTO_REFINE_SCAN,
        _SCAN_ROUNDING_MAX_P,
        _require_concrete,
        _scale_np,
    )
    from .dispatch import ensure_x64, quality_tile

    ensure_x64()
    _require_concrete(lags, valid, "assign_topic_linear")
    C = int(num_consumers)
    lags_np = np.ascontiguousarray(np.asarray(lags), dtype=np.int64)
    valid_np = np.ascontiguousarray(np.asarray(valid), dtype=bool)
    pids_np = np.asarray(partition_ids)
    n_valid = int(valid_np.sum())
    if C < 2 or n_valid == 0:
        return _trivial_assignment(lags_np, valid_np, max(C, 1))
    P = int(lags_np.shape[0])
    tile_knob = quality_tile() if tile is None else tile
    _, tile_e, n_tiles = plan_shape(P, tile_knob)
    if refine_iters is None:
        refine_iters = (
            _AUTO_REFINE_PARALLEL
            if P > _SCAN_ROUNDING_MAX_P
            else _AUTO_REFINE_SCAN
        )
    scale = _scale_np(lags_np, valid_np, C)
    from ..utils import metrics
    from . import linear_ot_pallas

    # Kernel plane dispatch: probe verdict first (False until warm-up
    # has probed, and after any runtime failure), then host admission
    # on the EFFECTIVE solve geometry.  The probe is never run from
    # here — this is a (possibly cold) rebalance path.
    kernel = bool(
        linear_ot_pallas.linear_pallas_available(kind="duals")
        and linear_ot_pallas.linear_pallas_admit(P, C, tile_e)
    )
    with metrics.device_phase("h2d"):
        lags_d, valid_d = jax.device_put((lags_np, valid_np))
        jax.block_until_ready((lags_d, valid_d))
    duals_args = (
        lags_d, valid_d, np.float64(scale), np.float32(n_valid),
    )
    duals_kw = dict(num_consumers=C, iters=int(iters), tile=tile_e)
    try:
        with metrics.device_phase("duals"):
            A, B, rounds = _linear_duals_jit(
                *duals_args, kernel=kernel, **duals_kw
            )
            jax.block_until_ready((A, B, rounds))
    except Exception as exc:
        if not kernel:
            raise
        # The probe vouched for the probe shape only; a dispatch that
        # faults at THIS shape falls back to the XLA tile scan and
        # pins the kernel off for the rest of the process.
        linear_ot_pallas.mark_linear_kernel_bad("duals", repr(exc))
        kernel = False
        with metrics.device_phase("duals"):
            A, B, rounds = _linear_duals_jit(
                *duals_args, kernel=False, **duals_kw
            )
            jax.block_until_ready((A, B, rounds))
    return finish_from_duals(
        lags_np, pids_np, valid_np, A, B, C, refine_iters,
        tiles=n_tiles, tile=tile_e, rounds=int(rounds),
        backend="single", kernel=kernel,
    )


def assign_linear(
    partition_lag_per_topic: Mapping[str, Sequence[TopicPartitionLag]],
    subscriptions: Mapping[str, Sequence[str]],
    iters: int = 24,
    refine_iters: Optional[int] = None,
) -> AssignmentMap:
    """Map-level linear-mode solve (same surface as
    :func:`..models.sinkhorn.assign_sinkhorn`); per-topic independence
    preserved."""
    from .dispatch import assign_per_topic, ensure_x64
    from .packing import pad_topic_rows

    ensure_x64()

    def solve_topic(lags, pids, num_consumers):
        lags_p, pids_p, valid = pad_topic_rows(lags, pids)
        choice, _, _ = assign_topic_linear(
            lags_p, pids_p, valid, num_consumers=num_consumers,
            iters=iters, refine_iters=refine_iters,
        )
        return choice

    return assign_per_topic(
        partition_lag_per_topic, subscriptions, solve_topic
    )
