"""Batched (vmap-over-topics) assignment kernels.

One kernel launch assigns every topic in a :class:`..ops.packing.TopicGroup`
— the vmap stress shape of BASELINE config 3 (256 topics x 64 partitions x
64 consumers) runs as a single [T, P] batch instead of 256 host-looped
launches.  Per-topic independence (SURVEY §2.4.3) makes the batch dimension
embarrassingly parallel, which is exactly what ``vmap`` models.
"""

from __future__ import annotations

import functools

import jax

from .rounds_kernel import assign_topic_rounds
from .scan_kernel import assign_topic_scan


@functools.partial(jax.jit, static_argnames=("num_consumers",))
def assign_batched_rounds(lags, partition_ids, valid, num_consumers: int):
    """Rounds kernel over a topic batch.

    Args: lags int64[T, P], partition_ids int32[T, P], valid bool[T, P].
    Returns (choice int32[T, P], counts int32[T, C], totals[T, C]).
    """
    fn = functools.partial(assign_topic_rounds, num_consumers=num_consumers)
    return jax.vmap(fn)(lags, partition_ids, valid)


@functools.partial(jax.jit, static_argnames=("num_consumers",))
def assign_batched_scan(lags, partition_ids, valid, num_consumers: int):
    """Scan kernel over a topic batch (same contract as
    :func:`assign_batched_rounds`)."""
    fn = functools.partial(assign_topic_scan, num_consumers=num_consumers)
    return jax.vmap(fn)(lags, partition_ids, valid)
