"""Batched (vmap-over-topics) assignment kernels + the streaming fast path.

One kernel launch assigns every topic in a :class:`..ops.packing.TopicGroup`
— the vmap stress shape of BASELINE config 3 (256 topics x 64 partitions x
64 consumers) runs as a single [T, P] batch instead of 256 host-looped
launches.  Per-topic independence (SURVEY §2.4.3) makes the batch dimension
embarrassingly parallel, which is exactly what ``vmap`` models.
"""

from __future__ import annotations

import functools

import numpy as np

import jax

from .rounds_kernel import (
    assign_presorted_rounds,
    assign_topic_rounds,
)
from .scan_kernel import assign_topic_scan, pack_shift_for


def _maybe_refine(lags, valid, choice, num_consumers: int, iters: int):
    """Trace-time helper: chain the exchange refinement onto a solve when
    a budget is set (0 = strict parity, choice passes through) — the one
    definition of the in-executable refine chaining used by every stream
    inner.  Uses the resident-table rounds (:mod:`.refine`'s fused warm
    core — O(K*M log M) per round instead of two P-sized sorts), which a
    greedy solve's count-balanced output always admits; selection is
    bit-identical to the oracle kernel's exact-argmin semantics."""
    if not iters:
        return choice
    from .packing import table_rows
    from .refine import build_choice_tables, refine_rounds_resident

    row_tab, counts, totals = build_choice_tables(
        lags, valid, choice, num_consumers,
        table_rows(lags.shape[0], num_consumers),
    )
    choice, _, _, _, _, _ = refine_rounds_resident(
        lags, choice, row_tab, counts, totals,
        num_consumers=num_consumers, iters=iters,
    )
    return choice


def _pallas_solve_padded(
    lags, bucket: int, num_consumers: int, pack_shift: int,
    wide: bool, interpret: bool = False,
):
    """Traced plumbing shared by the Pallas stream inners: pad the
    exact-shape lag vector to ``bucket``, sort in processing order, run
    the in-VMEM round scan, unsort.  Returns (padded lags, validity
    mask, choice int32[bucket])."""
    import jax.numpy as jnp

    from .rounds_pallas import sorted_rounds_pallas_core
    from .scan_kernel import sort_partitions_with
    from .sortops import unsort

    P = lags.shape[0]
    lags_p = jnp.pad(lags.astype(jnp.int64), (0, bucket - P))
    pids = jnp.arange(bucket, dtype=jnp.int32)
    valid = pids < P
    perm, sl, sv = sort_partitions_with(lags_p, pids, valid, pack_shift)
    _, flat = sorted_rounds_pallas_core(
        sl, sv, num_consumers=num_consumers, n_valid=P,
        interpret=interpret, wide=wide,
    )
    return lags_p, valid, unsort(perm, flat)


def _refine_vmapped(lags, valid, choice, num_consumers: int, iters: int):
    """Trace-time helper: the pairwise-exchange refinement (:mod:`.refine`)
    vmapped over the topic axis, for use INSIDE an already-jitted solve so
    the refined path stays one dispatch (no second upload of the batch).
    Returns the refined (choice, counts, totals) triple."""
    from .refine import refine_assignment

    fn = functools.partial(
        refine_assignment, num_consumers=num_consumers, iters=iters
    )
    return jax.vmap(fn)(lags, valid, choice)


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_consumers", "pack_shift", "totals_rank_bits", "refine_iters"
    ),
)
def assign_batched_rounds(
    lags, partition_ids, valid, num_consumers: int, pack_shift: int = 0,
    totals_rank_bits: int = 0, refine_iters: int = 0,
):
    """Rounds kernel over a topic batch.

    Args: lags int64[T, P], partition_ids int32[T, P], valid bool[T, P];
    ``pack_shift`` (static) as in :func:`..ops.scan_kernel.sort_partitions`;
    ``totals_rank_bits`` (static) selects the packed round body (see
    :func:`totals_rank_bits_for`; the caller guarantees the bound).
    ``refine_iters`` (static, default 0 = strict parity) appends that many
    rounds of per-topic exchange refinement inside the SAME executable —
    the one-shot quality mode (the reference's own TODO,
    LagBasedPartitionAssignorTest.java:226), opted into explicitly because
    it intentionally breaks bit-parity with the reference's greedy.
    Returns (choice int32[T, P], counts int32[T, C], totals[T, C]).
    """
    fn = functools.partial(
        assign_topic_rounds,
        num_consumers=num_consumers,
        pack_shift=pack_shift,
        totals_rank_bits=totals_rank_bits,
    )
    out = jax.vmap(fn)(lags, partition_ids, valid)
    if refine_iters:
        out = _refine_vmapped(
            lags, valid, out[0], num_consumers, refine_iters
        )
    return out


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "refine_iters")
)
def assign_batched_scan(
    lags, partition_ids, valid, num_consumers: int, refine_iters: int = 0
):
    """Scan kernel over a topic batch (same contract — including the
    static ``refine_iters`` quality option — as
    :func:`assign_batched_rounds`)."""
    fn = functools.partial(assign_topic_scan, num_consumers=num_consumers)
    out = jax.vmap(fn)(lags, partition_ids, valid)
    if refine_iters:
        out = _refine_vmapped(
            lags, valid, out[0], num_consumers, refine_iters
        )
    return out


def _narrow_choice(choice, num_consumers: int):
    import jax.numpy as jnp

    if num_consumers <= 32767:
        return choice.astype(jnp.int16)
    return choice


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "refine_iters")
)
def _stream_presorted(lags, perm, num_consumers: int, refine_iters: int = 0):
    """CPU-backend inner: host-presorted, exact shape, minimum rounds.
    ``refine_iters`` (static, 0 = parity) chains the exchange refinement
    into the same executable — see :func:`assign_stream_refined`."""
    import jax.numpy as jnp

    choice, _, _ = assign_presorted_rounds(
        lags[perm], perm, num_consumers=num_consumers
    )
    choice = _maybe_refine(
        lags, jnp.ones(lags.shape, bool), choice, num_consumers,
        refine_iters,
    )
    return _narrow_choice(choice, num_consumers)


def totals_rank_bits_for(lags: np.ndarray, num_consumers: int) -> int:
    """Static-arg helper for the packed scatter-free round body
    (:func:`..ops.rounds_kernel._rounds_body_packed`): any consumer's
    running total is bounded by the total lag sum, so packing
    ``(total << rank_bits) | id`` into one int64 key is sound whenever
    the shifted bound cannot overflow.  The sum is taken in f64 (cannot
    wrap) and checked against a half-range margin so rounding near the
    boundary stays conservative.  Returns the rank field width, or 0 when
    packing is unsafe (the general two-key body runs instead)."""
    rb = max(1, (int(num_consumers) - 1).bit_length())
    if lags.size == 0:
        return rb
    arr = np.asarray(lags)
    # Batched [T, P] inputs: each topic's totals are bounded by ITS row
    # sum, so the guard reads the max per-row sum, not the batch sum.
    total = float(arr.sum(axis=-1, dtype=np.float64).max())
    if int(arr.min()) >= 0 and total < float(1 << (61 - rb)):
        return rb
    return 0


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_consumers", "pack_shift", "totals_rank_bits", "refine_iters"
    ),
)
def _stream_device(
    lags, num_consumers: int, pack_shift: int = 0,
    totals_rank_bits: int = 0, refine_iters: int = 0,
):
    """Accelerator inner: device sort at a power-of-two padded shape.

    Pads device-side to a power-of-two bucket: the transfer stays
    exact-size while the sort network compiles at a friendly shape
    (non-power-of-two sorts compile pathologically slowly on some
    backends).  Accepts int32 lags (widened on device) — the host wrapper
    downcasts when the lag range allows, halving the host->device bytes
    on the latency-critical streaming path.  The exact row count P is
    static here, so the rounds scan stops at ceil(P / C) rounds instead
    of scanning the padding (n_valid), and ``totals_rank_bits`` (from
    :func:`totals_rank_bits_for`) selects the scatter-free packed round
    body.  ``refine_iters`` (static, 0 = parity) chains the exchange
    refinement into the same executable — one dispatch either way."""
    import jax.numpy as jnp

    from .packing import pad_bucket

    P = lags.shape[0]
    P_pad = pad_bucket(P)
    lags_p = jnp.pad(lags.astype(jnp.int64), (0, P_pad - P))
    pids = jnp.arange(P_pad, dtype=jnp.int32)
    valid = pids < P
    choice, _, _ = assign_topic_rounds(
        lags_p, pids, valid, num_consumers=num_consumers,
        pack_shift=pack_shift, n_valid=P,
        totals_rank_bits=totals_rank_bits,
    )
    choice = _maybe_refine(
        lags_p, valid, choice, num_consumers, refine_iters
    )
    return _narrow_choice(choice[:P], num_consumers)


@functools.partial(jax.jit, static_argnames=("num_consumers", "iters"))
def refine_batched(lags, valid, choice, num_consumers: int, iters: int):
    """Pairwise-exchange refinement (:mod:`.refine`) over a topic batch.

    Args: lags int64[T, P], valid bool[T, P], choice int32[T, P] (a
    count-balanced assignment, e.g. a batched kernel's output).  Returns
    (choice int32[T, P], counts int32[T, C], totals[T, C]) — per-topic
    count invariant preserved, max/mean lag imbalance tightened.  This is
    the standalone entry for refining an EXISTING batch assignment; the
    solve paths chain the same pass inside their own executables via the
    static ``refine_iters`` option instead (one dispatch, no re-upload).
    """
    return _refine_vmapped(lags, valid, choice, num_consumers, iters)


def assign_stream_refined(lags, num_consumers: int, refine_iters: int = 64):
    """One-shot QUALITY variant of :func:`assign_stream`: the greedy
    rounds kernel plus ``refine_iters`` rounds of the parallel
    pairwise-exchange refinement, chained into a single dispatch with one
    readback.  Count invariant identical to greedy; max/mean lag imbalance
    tightened toward the count-constrained bound (BASELINE's <=1.05
    quality target on Zipf-skewed lags, where plain greedy leaves real
    slack).  NOT bit-parity with the reference — this is the default
    solver's opt-in quality mode (``tpu.assignor.refine.iters``).

    Returns choice[P] (int16 if C <= 32767 else int32)."""
    return assign_stream(
        lags, num_consumers, refine_iters=int(refine_iters)
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_consumers", "pack_shift", "refine_iters", "wide"
    ),
)
def _stream_device_pallas(
    lags, num_consumers: int, pack_shift: int = 0, refine_iters: int = 0,
    wide: bool = False,
):
    """Accelerator inner with the Pallas in-VMEM round scan replacing the
    XLA scan (same transfer contract as :func:`_stream_device`).  Callers
    MUST have passed the host-side admission gate
    (:func:`..ops.rounds_pallas.pallas_rounds_supported` on the actual
    lag sum) AND the probe-once device parity gate
    (:func:`..ops.rounds_pallas.rounds_pallas_available`) — the core has
    no in-trace gate."""
    from .packing import pad_bucket

    P = lags.shape[0]
    lags_p, valid, choice = _pallas_solve_padded(
        lags, pad_bucket(P), num_consumers, pack_shift, wide
    )
    choice = _maybe_refine(
        lags_p, valid, choice, num_consumers, refine_iters
    )
    return _narrow_choice(choice[:P], num_consumers)


def _dense_batch_inputs(lags):
    """THE device-side derivation for dense [T, P] batches: pad the
    partition axis to the pow2 bucket, dense pids, valid = real-row mask.
    Shared by the batch and global stream inners so the dense-padding
    contract lives in one place.  Returns (lags_p, pids, valid, P)."""
    import jax.numpy as jnp

    from .packing import pad_bucket

    T, P = lags.shape
    P_pad = pad_bucket(P)
    lags_p = jnp.pad(lags.astype(jnp.int64), ((0, 0), (0, P_pad - P)))
    pids = jnp.broadcast_to(
        jnp.arange(P_pad, dtype=jnp.int32), (T, P_pad)
    )
    return lags_p, pids, pids < P, P


@functools.partial(
    jax.jit,
    static_argnames=("num_consumers", "pack_shift", "totals_rank_bits"),
)
def _stream_batch_device(
    lags, num_consumers: int, pack_shift: int = 0,
    totals_rank_bits: int = 0,
):
    """Accelerator inner for the dense topic-batch path: pids and the
    validity mask are derived on device (dense 0..P-1 rows, all valid), so
    the upload is the [T, P] lag matrix alone.  Pads the partition axis
    device-side to the power-of-two bucket like :func:`_stream_device`
    and shares its trimmed-scan / packed-round-body static args."""
    lags_p, pids, valid, P = _dense_batch_inputs(lags)
    fn = functools.partial(
        assign_topic_rounds, num_consumers=num_consumers,
        pack_shift=pack_shift, n_valid=P,
        totals_rank_bits=totals_rank_bits,
    )
    choice, _, _ = jax.vmap(fn)(lags_p, pids, valid)
    return _narrow_choice(choice[:, :P], num_consumers)


def assign_stream_batch(lags, num_consumers: int):
    """Transfer-lean batched path for dense topic batches (the BASELINE
    config-3 shape): every topic has partitions 0..P-1, all valid — so
    only the exact-size [T, P] lag matrix crosses the host->device
    boundary (int32 when the range allows), and the choice comes back
    int16 when C fits.  Semantics identical to
    :func:`assign_batched_rounds` with dense pids / all-true valid
    (pinned by tests/test_fast_paths.py).

    Returns choice[T, P] (int16 if C <= 32767 else int32)."""
    from .dispatch import ensure_x64, observe_pack_shift

    ensure_x64()  # int64 lags would silently truncate to int32 otherwise
    payload, shift = stream_payload(lags, partition_axis=1)
    rb = totals_rank_bits_for(payload, num_consumers)
    observe_pack_shift(
        ("stream_batch", payload.shape, num_consumers), (shift, rb)
    )
    return _stream_batch_device(
        payload, num_consumers=num_consumers, pack_shift=shift,
        totals_rank_bits=rb,
    )


@functools.partial(
    jax.jit,
    static_argnames=("num_consumers", "pack_shift", "totals_rank_bits"),
)
def _stream_global_device(
    lags, num_consumers: int, pack_shift: int = 0,
    totals_rank_bits: int = 0,
):
    """Dense transfer-lean inner for the cross-topic global quality mode
    (same upload contract as :func:`_stream_batch_device`: the [T, P] lag
    matrix alone)."""
    from .rounds_kernel import assign_global_rounds

    lags_p, pids, valid, P = _dense_batch_inputs(lags)
    choice, _, totals = assign_global_rounds(
        lags_p, pids, valid, num_consumers=num_consumers,
        pack_shift=pack_shift, totals_rank_bits=totals_rank_bits,
        n_valid=P,
    )
    return _narrow_choice(choice[:, :P], num_consumers), totals


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "pack_shift", "wide")
)
def _stream_global_device_pallas(
    lags, num_consumers: int, pack_shift: int = 0, wide: bool = False
):
    """Global-mode inner with the Pallas round scan: per-topic sorts are
    parallel (vmap), then the ENTIRE cross-topic sequential chain — every
    topic's rounds with carried totals — runs as one in-VMEM kernel.
    Same transfer contract as :func:`_stream_global_device`; callers must
    have passed both Pallas gates."""
    from .rounds_pallas import global_rounds_pallas_core
    from .scan_kernel import sort_partitions_with

    lags_p, pids, valid, P = _dense_batch_inputs(lags)
    perms, sl, sv = jax.vmap(
        functools.partial(sort_partitions_with, pack_shift=pack_shift)
    )(lags_p, pids, valid)
    totals, choice = global_rounds_pallas_core(
        sl, sv, perms, num_consumers=num_consumers, n_valid=P, wide=wide
    )
    return _narrow_choice(choice[:, :P], num_consumers), totals


def assign_stream_global(lags, num_consumers: int):
    """Transfer-lean dense batch path for the GLOBAL (cross-topic lag
    balance) quality mode: upload the [T, P] lag matrix only, read back
    the narrow choice plus the single global [C] totals vector.  Same
    semantics as :func:`..ops.rounds_kernel.assign_global_rounds` with
    dense pids / all-true valid.

    Returns (choice[T, P] int16/int32, totals int64[C])."""
    from .dispatch import ensure_x64, observe_pack_shift

    ensure_x64()
    payload, shift = stream_payload(lags, partition_axis=1)
    # The global kernel's totals carry across topics: bound by the WHOLE
    # batch's sum, not per-topic row sums.
    rb = totals_rank_bits_for(payload.reshape(1, -1), num_consumers)
    from .rounds_pallas import pallas_mode_for, rounds_pallas_available

    T, P = lags.shape
    rounds = T * max(-(-P // num_consumers), 1)
    mode = pallas_mode_for(lags, num_consumers, rounds)
    if mode and rounds_pallas_available(mode=mode):
            observe_pack_shift(
                ("stream_global_pallas", payload.shape, num_consumers),
                (shift, mode),
            )
            return _stream_global_device_pallas(
                payload, num_consumers=num_consumers, pack_shift=shift,
                wide=(mode == "wide"),
            )
    observe_pack_shift(
        ("stream_global", payload.shape, num_consumers), (shift, rb)
    )
    return _stream_global_device(
        payload, num_consumers=num_consumers, pack_shift=shift,
        totals_rank_bits=rb,
    )


def stream_payload(lags: np.ndarray, partition_axis: int = 0):
    """Host half of the accelerator stream paths: the upload dtype choice
    (int32 when the lag range allows — halves the bytes; the kernels widen
    back to int64 on device) and the packed-sort shift for the padded
    bucket shape.  THE single definition of the payload rule, shared by
    :func:`assign_stream`, :func:`assign_stream_batch`
    (``partition_axis=1`` — shift depends on the padded partition-axis
    length) and the streaming engine's cold chain, so every path uploads
    the identical payload.

    Returns (payload ndarray, pack_shift int)."""
    from .packing import pad_bucket

    lags = np.ascontiguousarray(lags, dtype=np.int64)
    max_lag = int(lags.max()) if lags.size else 0
    shift = pack_shift_for(
        max_lag, pad_bucket(lags.shape[partition_axis]) - 1
    )
    if 0 <= max_lag < 2**31 and (lags.size == 0 or int(lags.min()) >= 0):
        return lags.astype(np.int32), shift
    return lags, shift


def assign_stream(lags, num_consumers: int, refine_iters: int = 0):
    """Transfer-lean single-topic path for streaming rebalances.

    Takes ONLY the exact-size lag vector (int64[P]); partition ids are the
    dense 0..P-1 range and the validity mask is all-true, and the returned
    choice is int16 when C fits — so the host<->device traffic is the
    minimum possible (8 bytes/partition in, 2 bytes/partition out).
    Trace-cached per exact P, which is the shape stability profile of a
    streaming rebalance loop (BASELINE config 5: same topic every 30 s
    under drifting lag).

    Backend-aware host wrapper: on the CPU backend the processing-order
    permutation is computed host-side (``np.argsort``, ~3x faster than
    XLA:CPU's comparator sort at P=100k) and the scan runs at the exact
    shape; on accelerators the sort runs on-device at a padded
    power-of-two shape, packed single-key when the value ranges allow.

    ``refine_iters`` (static, default 0 = strict reference parity) chains
    the exchange-refinement quality pass into the same single dispatch on
    EITHER backend — see :func:`assign_stream_refined`.

    Returns choice[P] (int16 if C <= 32767 else int32).
    """
    from .dispatch import ensure_x64

    ensure_x64()  # int64 lags would silently truncate to int32 otherwise
    # Pass the static option only when ON: jax's jit cache keys include
    # WHICH kwargs were passed, so `refine_iters=0` explicit vs omitted
    # would compile two identical executables (and dodge the warm-up).
    refine = (
        {"refine_iters": int(refine_iters)} if refine_iters else {}
    )
    if isinstance(lags, np.ndarray):
        lags = np.ascontiguousarray(lags, dtype=np.int64)
        if jax.default_backend() == "cpu":
            # Stable argsort of -lags == (lag desc, pid asc): input row
            # order IS pid order on this dense path.
            perm = np.argsort(-lags, kind="stable").astype(np.int32)
            return _stream_presorted(
                lags, perm, num_consumers=num_consumers, **refine
            )
        payload, shift = stream_payload(lags)
        rb = totals_rank_bits_for(payload, num_consumers)
        from .dispatch import observe_pack_shift

        # Pallas in-VMEM round scan when the instance AND the device
        # qualify: host value gate first (pallas_mode_for gates C and the
        # value ranges), then the probe-once device parity+speed gate
        # (any failure permanently falls back to the XLA scan).
        from .rounds_pallas import (
            pallas_mode_for,
            rounds_pallas_available,
        )

        P = lags.shape[0]
        mode = pallas_mode_for(
            lags, num_consumers, -(-P // num_consumers)
        )
        if mode and rounds_pallas_available(mode=mode):
            observe_pack_shift(
                ("stream_pallas", lags.shape, num_consumers),
                (shift, mode),
            )
            return _stream_device_pallas(
                payload, num_consumers=num_consumers,
                pack_shift=shift, wide=(mode == "wide"), **refine,
            )
        # One observation key per executable-selecting tuple: a change in
        # EITHER static arg (pack shift or rank bits) recompiles.
        observe_pack_shift(
            ("stream", lags.shape, num_consumers), (shift, rb)
        )
        return _stream_device(
            payload, num_consumers=num_consumers, pack_shift=shift,
            totals_rank_bits=rb, **refine,
        )
    return _stream_device(lags, num_consumers=num_consumers, **refine)
