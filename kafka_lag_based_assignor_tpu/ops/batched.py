"""Batched (vmap-over-topics) assignment kernels.

One kernel launch assigns every topic in a :class:`..ops.packing.TopicGroup`
— the vmap stress shape of BASELINE config 3 (256 topics x 64 partitions x
64 consumers) runs as a single [T, P] batch instead of 256 host-looped
launches.  Per-topic independence (SURVEY §2.4.3) makes the batch dimension
embarrassingly parallel, which is exactly what ``vmap`` models.
"""

from __future__ import annotations

import functools

import jax

from .rounds_kernel import assign_topic_rounds
from .scan_kernel import assign_topic_scan


@functools.partial(jax.jit, static_argnames=("num_consumers",))
def assign_batched_rounds(lags, partition_ids, valid, num_consumers: int):
    """Rounds kernel over a topic batch.

    Args: lags int64[T, P], partition_ids int32[T, P], valid bool[T, P].
    Returns (choice int32[T, P], counts int32[T, C], totals[T, C]).
    """
    fn = functools.partial(assign_topic_rounds, num_consumers=num_consumers)
    return jax.vmap(fn)(lags, partition_ids, valid)


@functools.partial(jax.jit, static_argnames=("num_consumers",))
def assign_batched_scan(lags, partition_ids, valid, num_consumers: int):
    """Scan kernel over a topic batch (same contract as
    :func:`assign_batched_rounds`)."""
    fn = functools.partial(assign_topic_scan, num_consumers=num_consumers)
    return jax.vmap(fn)(lags, partition_ids, valid)


@functools.partial(jax.jit, static_argnames=("num_consumers",))
def assign_stream(lags, num_consumers: int):
    """Transfer-lean single-topic path for streaming rebalances.

    Takes ONLY the exact-size lag vector (int64[P]); partition ids are the
    dense 0..P-1 range and the validity mask is all-true, both generated
    device-side, and the returned choice is int16 when C fits — so the
    host<->device traffic is the minimum possible (8 bytes/partition in,
    2 bytes/partition out).  Trace-cached per exact P, which is the shape
    stability profile of a streaming rebalance loop (BASELINE config 5:
    same topic every 30 s under drifting lag).

    Returns choice[P] (int16 if C <= 32767 else int32).
    """
    import jax.numpy as jnp

    from .packing import pad_bucket

    # Pad device-side to a power-of-two bucket: the transfer stays
    # exact-size while the sort network compiles at a friendly shape
    # (non-power-of-two sorts compile pathologically slowly on some
    # backends).
    P = lags.shape[0]
    P_pad = pad_bucket(P)
    lags_p = jnp.pad(lags, (0, P_pad - P))
    pids = jnp.arange(P_pad, dtype=jnp.int32)
    valid = pids < P
    choice, _, _ = assign_topic_rounds(
        lags_p, pids, valid, num_consumers=num_consumers
    )
    choice = choice[:P]
    if num_consumers <= 32767:
        choice = choice.astype(jnp.int16)
    return choice
