"""Batched (vmap-over-topics) assignment kernels + the streaming fast path.

One kernel launch assigns every topic in a :class:`..ops.packing.TopicGroup`
— the vmap stress shape of BASELINE config 3 (256 topics x 64 partitions x
64 consumers) runs as a single [T, P] batch instead of 256 host-looped
launches.  Per-topic independence (SURVEY §2.4.3) makes the batch dimension
embarrassingly parallel, which is exactly what ``vmap`` models.
"""

from __future__ import annotations

import functools

import numpy as np

import jax

from .rounds_kernel import (
    assign_presorted_rounds,
    assign_topic_rounds,
)
from .scan_kernel import assign_topic_scan, pack_shift_for


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "pack_shift")
)
def assign_batched_rounds(
    lags, partition_ids, valid, num_consumers: int, pack_shift: int = 0
):
    """Rounds kernel over a topic batch.

    Args: lags int64[T, P], partition_ids int32[T, P], valid bool[T, P];
    ``pack_shift`` (static) as in :func:`..ops.scan_kernel.sort_partitions`.
    Returns (choice int32[T, P], counts int32[T, C], totals[T, C]).
    """
    fn = functools.partial(
        assign_topic_rounds,
        num_consumers=num_consumers,
        pack_shift=pack_shift,
    )
    return jax.vmap(fn)(lags, partition_ids, valid)


@functools.partial(jax.jit, static_argnames=("num_consumers",))
def assign_batched_scan(lags, partition_ids, valid, num_consumers: int):
    """Scan kernel over a topic batch (same contract as
    :func:`assign_batched_rounds`)."""
    fn = functools.partial(assign_topic_scan, num_consumers=num_consumers)
    return jax.vmap(fn)(lags, partition_ids, valid)


def _narrow_choice(choice, num_consumers: int):
    import jax.numpy as jnp

    if num_consumers <= 32767:
        return choice.astype(jnp.int16)
    return choice


@functools.partial(jax.jit, static_argnames=("num_consumers",))
def _stream_presorted(lags, perm, num_consumers: int):
    """CPU-backend inner: host-presorted, exact shape, minimum rounds."""
    choice, _, _ = assign_presorted_rounds(
        lags[perm], perm, num_consumers=num_consumers
    )
    return _narrow_choice(choice, num_consumers)


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "pack_shift")
)
def _stream_device(lags, num_consumers: int, pack_shift: int = 0):
    """Accelerator inner: device sort at a power-of-two padded shape.

    Pads device-side to a power-of-two bucket: the transfer stays
    exact-size while the sort network compiles at a friendly shape
    (non-power-of-two sorts compile pathologically slowly on some
    backends).  Accepts int32 lags (widened on device) — the host wrapper
    downcasts when the lag range allows, halving the host->device bytes
    on the latency-critical streaming path."""
    import jax.numpy as jnp

    from .packing import pad_bucket

    P = lags.shape[0]
    P_pad = pad_bucket(P)
    lags_p = jnp.pad(lags.astype(jnp.int64), (0, P_pad - P))
    pids = jnp.arange(P_pad, dtype=jnp.int32)
    valid = pids < P
    choice, _, _ = assign_topic_rounds(
        lags_p, pids, valid, num_consumers=num_consumers,
        pack_shift=pack_shift,
    )
    return _narrow_choice(choice[:P], num_consumers)


def assign_stream(lags, num_consumers: int):
    """Transfer-lean single-topic path for streaming rebalances.

    Takes ONLY the exact-size lag vector (int64[P]); partition ids are the
    dense 0..P-1 range and the validity mask is all-true, and the returned
    choice is int16 when C fits — so the host<->device traffic is the
    minimum possible (8 bytes/partition in, 2 bytes/partition out).
    Trace-cached per exact P, which is the shape stability profile of a
    streaming rebalance loop (BASELINE config 5: same topic every 30 s
    under drifting lag).

    Backend-aware host wrapper: on the CPU backend the processing-order
    permutation is computed host-side (``np.argsort``, ~3x faster than
    XLA:CPU's comparator sort at P=100k) and the scan runs at the exact
    shape; on accelerators the sort runs on-device at a padded
    power-of-two shape, packed single-key when the value ranges allow.

    Returns choice[P] (int16 if C <= 32767 else int32).
    """
    if isinstance(lags, np.ndarray):
        lags = np.ascontiguousarray(lags, dtype=np.int64)
        if jax.default_backend() == "cpu":
            # Stable argsort of -lags == (lag desc, pid asc): input row
            # order IS pid order on this dense path.
            perm = np.argsort(-lags, kind="stable").astype(np.int32)
            return _stream_presorted(lags, perm, num_consumers=num_consumers)
        from .packing import pad_bucket

        max_lag = int(lags.max()) if lags.size else 0
        shift = pack_shift_for(max_lag, pad_bucket(lags.shape[0]) - 1)
        from .dispatch import observe_pack_shift

        observe_pack_shift(("stream", lags.shape, num_consumers), shift)
        if 0 <= max_lag < 2**31 and (lags.size == 0 or int(lags.min()) >= 0):
            # Lag range fits int32: halve the transfer (the kernel widens
            # back to int64 on device; semantics unchanged).
            lags = lags.astype(np.int32)
        return _stream_device(
            lags, num_consumers=num_consumers, pack_shift=shift
        )
    return _stream_device(lags, num_consumers=num_consumers)
