"""Shared VMEM admission model for the Pallas kernel plane.

Every grid-less kernel in this package (:mod:`.plan_stats`,
:mod:`.rounds_pallas`, :mod:`.linear_ot_pallas`) keeps its ENTIRE
working set resident in VMEM for the whole invocation — that is the
design (no grid, no double-buffered HBM streaming), so admission is a
host-side byte estimate against one conservative per-core budget.
Before this module each kernel re-derived the budget and the padding
rules locally (and the prose in ``pallas_rounds_mode``'s docstring had
already drifted from the code once); the constants and the per-kernel
byte models now live HERE, and the dispatch sites consume them, so the
numbers cannot fork again.

The estimates deliberately over-count: Mosaic reuses buffers and
overlaps DMA, but a kernel rejected by a pessimistic model just runs
the XLA path — a kernel ADMITTED by an optimistic model OOMs VMEM at
compile time on a serving path.
"""

from __future__ import annotations

#: Conservative per-core VMEM budget (physical VMEM is ~16 MB; leave
#: headroom for Mosaic's own buffers and double-buffered DMA).
#: Calibrated so the hardware-verified north-star shape
#: (P=131072, C=1000) passes every kernel's model below.
VMEM_BUDGET_BYTES = 12 * 1024 * 1024

#: Mosaic tile geometry: the minor-most axis is padded to LANE lanes,
#: the second-minor to SUBLANE sublanes (f32/int32; wider dtypes only
#: appear on the probe-gated digest path).
LANE = 128
SUBLANE = 8


def lane_pad(n: int) -> int:
    """``n`` padded up to a full lane multiple (>= one lane)."""
    return max(LANE, -(-int(n) // LANE) * LANE)


def sublane_pad(n: int) -> int:
    """``n`` padded up to a full sublane multiple (>= one sublane)."""
    return max(SUBLANE, -(-int(n) // SUBLANE) * SUBLANE)


def fits_vmem(bytes_needed: int, budget: int = VMEM_BUDGET_BYTES) -> bool:
    return int(bytes_needed) <= int(budget)


def rounds_scan_bytes(num_rounds: int, c_pad: int) -> int:
    """Byte model of the Pallas round scan (:mod:`.rounds_pallas`): the
    [R, C_PAD] int32 gains and choice planes plus the resident
    (total, id) state planes (two extra pairs for the WIDE variant's
    carry planes — folded into the same estimate)."""
    return 2 * int(num_rounds) * int(c_pad) * 4 + 8 * int(c_pad) * 4


def plan_stats_bytes(num_rows: int, num_consumers: int, tile_p: int) -> int:
    """Byte model of the plan-stats marginal kernel
    (:mod:`.plan_stats`): ws/count/wsum inputs at [nt, TILE_P]
    (true-sized), ~4 live (C_pad, TILE_P) f32 temporaries per tile step
    (Mosaic reuses buffers), and the (C_pad, 1) dual/accumulator
    vectors at full lane padding."""
    c_pad = lane_pad(num_consumers)
    u_pad = -(-int(num_rows) // int(tile_p)) * int(tile_p)
    inputs = 3 * u_pad * 4
    temps = 4 * c_pad * int(tile_p) * 4
    vectors = 4 * c_pad * LANE * 4
    return inputs + temps + vectors


def linear_ot_bytes(num_rows_padded: int, num_consumers: int,
                    tile: int) -> int:
    """Byte model of the fused linear-OT mirror-prox kernel
    (:mod:`.linear_ot_pallas`): the ws/count inputs as [n_tiles, tile]
    f32 planes (sublane-padded), ~4 live (C_pad, tile) f32 logits
    temporaries per tile step, and ~8 (C_pad, 1) dual/marginal vectors
    at full lane padding (A, B, A_half, both marginal pairs, and the
    block accumulators)."""
    c_pad = lane_pad(num_consumers)
    nt = sublane_pad(int(num_rows_padded) // int(tile))
    inputs = 2 * nt * int(tile) * 4
    temps = 4 * c_pad * int(tile) * 4
    vectors = 8 * c_pad * LANE * 4
    return inputs + temps + vectors


def digest_bytes(num_rows_padded: int, num_consumers: int) -> int:
    """Byte model of the fused integrity-digest epilogue
    (:mod:`.linear_ot_pallas`): the int64 lag rows + int32 choice rows
    at [P_pad/LANE, LANE], one (C_pad, LANE) one-hot temporary pair per
    row step, and the (C_pad, 1) count vectors (int64)."""
    p_pad = lane_pad(num_rows_padded)
    c_pad = lane_pad(num_consumers)
    inputs = p_pad * (8 + 4)
    temps = 2 * c_pad * LANE * 4
    vectors = 3 * c_pad * LANE * 8
    return inputs + temps + vectors
