"""Pallas TPU round-scan: the whole greedy round decomposition in ONE
grid-less kernel with an in-VMEM bitonic sort per round.

Why: the XLA lowering of the rounds scan costs ~90 us per round of
sequencing overhead (retired probe, git history) — each round's C-sized
``lax.sort`` lowers to a multi-pass comparator network with HBM traffic
between passes, and at the north star that's ~100 sequential rounds =
~9 ms, essentially the whole device budget (BASELINE.md).  Keeping the
(total, id) state resident in VMEM across ALL rounds and running the
compare-exchange network on registers removes the per-pass overhead
entirely: a 1024-wide bitonic sort is 55 stages of roll/select/min-max
vector ops, i.e. microseconds, not tens of them.

Design (toolchain-shaped like :mod:`.plan_stats` — this image's Mosaic
AOT path rejects any ``grid``):

* one grid-less invocation; ``lax.fori_loop`` over rounds; the 55-stage
  bitonic network is a STATIC python loop inside the body (unrolled once
  in the compiled loop body, looped R times);
* state: two (8, 128) int32 planes — totals and consumer ids — i.e. the
  1024-slot consumer axis laid out sublane x lane.  The XOR-partner
  shuffle of the bitonic network is two ``pltpu.roll``s and a select:
  lane-axis rolls for distances < 128, sublane-axis rolls for 128+;
* comparisons are (total, id) lexicographic on the separate planes — no
  64-bit key packing, so the whole kernel is int32 (Mosaic-friendly);
* per round: sort ascending, emit the id plane as that round's choice
  row (-1 at invalid positions), add the round's gains positionally.

Admission (:func:`pallas_rounds_mode`, one shared helper at every
dispatch site): C <= 1024 consumers, gains + choice fitting VMEM, and
either total lag < 2**30 (NARROW: one int32 totals plane — the
north-star shape qualifies) or total < 2**62 with every lag < 2**31
(WIDE: totals as two int32 planes, biased low word, carry into the high
plane).  Anything else stays on the XLA scan.

Production dispatch (assign_stream / assign_stream_global / the
streaming cold chain) is DOUBLE-gated: the host admission above plus a
probe-once device gate (:func:`rounds_pallas_available`) that
bit-compares each kernel mode against the XLA scan on the real lowering
AND races it (a correct-but-slow lowering must not regress the
headline) — the probe is only ever invoked by warm-up/bench
(run_probe=True), never on a cold rebalance, and any failure falls back
to the XLA scan.  Bit-parity is pinned by interpret-mode tests
(tests/test_rounds_pallas.py: fixed shape classes, Hypothesis fuzz,
carry stress); hardware timing went through a retired probe (git history).
"""

from __future__ import annotations

import functools
import logging
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

LOGGER = logging.getLogger(__name__)

C_PAD = 1024  # consumer slots: one (8, 128) int32 tile plane
_SUB, _LANE = 8, 128
# int32 totals sentinel for pad consumers: above any admissible real
# total (gated < 2**30) and never incremented (pad positions carry -1
# gains), so pad slots sort strictly last every round.
_SENTINEL = np.int32(2**31 - 1)
# Total-lag admission bound for the NARROW (single-int32-plane) kernel:
# totals stay exactly representable in int32 with sentinel headroom.
TOTALS_BOUND = 1 << 30
# WIDE kernel bounds: totals as two int32 planes (63-bit effective with
# sentinel headroom in the high plane); per-round gains remain a single
# int32, so individual lags must fit 31 bits.
WIDE_TOTALS_BOUND = 1 << 62
MAX_LAG_BOUND = 1 << 31


def _xor_shuffle(x, d: int):
    """out[i] = x[i ^ d] over the linearized (8, 128) index, d a power of
    two < 1024.  Two circular rolls + a bit-select: the element whose
    ``d`` bit is set reads its lower partner (the +d roll) and vice
    versa; the roll's wraparound lanes are exactly the ones the select
    never reads."""
    from jax.experimental.pallas import tpu as pltpu

    if d < _LANE:
        a = pltpu.roll(x, shift=d, axis=1)            # a[l] = x[l - d]
        b = pltpu.roll(x, shift=_LANE - d, axis=1)    # b[l] = x[l + d]
        lane = lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 1)
        return jnp.where((lane & d) != 0, a, b)
    s = d // _LANE
    a = pltpu.roll(x, shift=s, axis=0)
    b = pltpu.roll(x, shift=_SUB - s, axis=0)
    sub = lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 0)
    return jnp.where((sub & s) != 0, a, b)


def _bitonic_sort(t, ids):
    """Ascending (total, id) lexicographic bitonic sort of the 1024-slot
    planes.  Ids are distinct, so the order is total and the network is
    exact.  55 compare-exchange stages, fully unrolled (static python
    loops — this function is traced once inside the round body)."""
    idx = (
        lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 0) * _LANE
        + lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 1)
    )
    k = 2
    while k <= C_PAD:
        j = k // 2
        while j >= 1:
            pt = _xor_shuffle(t, j)
            pid = _xor_shuffle(ids, j)
            gt = (t > pt) | ((t == pt) & (ids > pid))
            # Element keeps the min of the pair iff it is the lower
            # index of an ascending run (or the upper of a descending
            # one): the classic bitonic orientation rule.
            take_min = ((idx & k) == 0) == ((idx & j) == 0)
            swap = jnp.where(take_min, gt, ~gt)
            t = jnp.where(swap, pt, t)
            ids = jnp.where(swap, pid, ids)
            j //= 2
        k *= 2
    return t, ids


def _bitonic_sort_wide(hi, lob, ids):
    """Ascending (total, id) sort for WIDE (int64) totals held as two
    int32 planes: ``hi`` = bits 32..62, ``lob`` = bits 0..31 BIASED by
    xor 2^31 so SIGNED plane compares give the unsigned low-word order
    (x ^ 2^31 == x + 2^31 mod 2^32, so the bias also commutes with the
    wrap-add in the round body).  Same network as :func:`_bitonic_sort`
    with a 3-way lexicographic compare and a third shuffled plane."""
    idx = (
        lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 0) * _LANE
        + lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 1)
    )
    k = 2
    while k <= C_PAD:
        j = k // 2
        while j >= 1:
            phi = _xor_shuffle(hi, j)
            plob = _xor_shuffle(lob, j)
            pid = _xor_shuffle(ids, j)
            eq_hi = hi == phi
            gt = (
                (hi > phi)
                | (eq_hi & (lob > plob))
                | (eq_hi & (lob == plob) & (ids > pid))
            )
            take_min = ((idx & k) == 0) == ((idx & j) == 0)
            swap = jnp.where(take_min, gt, ~gt)
            hi = jnp.where(swap, phi, hi)
            lob = jnp.where(swap, plob, lob)
            ids = jnp.where(swap, pid, ids)
            j //= 2
        k *= 2
    return hi, lob, ids


def _rounds_kernel_wide(gains_ref, hi0_ref, lob0_ref, choice_ref,
                        hi_out_ref, lob_out_ref, idout_ref):
    """Wide-totals round kernel: totals as (hi, biased-lo) int32 plane
    pairs; per-round gains (int32, < 2^31) wrap-add into the low plane
    with an unsigned-carry into the high plane."""
    from jax.experimental import pallas as pl

    R = gains_ref.shape[0]
    ids0 = (
        lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 0) * _LANE
        + lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 1)
    )

    def body(r, carry):
        hi, lob, ids = carry
        hi, lob, ids = _bitonic_sort_wide(hi, lob, ids)
        g = gains_ref[pl.ds(r, 1)][0]
        valid = g >= 0
        choice_ref[pl.ds(r, 1)] = jnp.where(valid, ids, -1)[None]
        gain = jnp.where(valid, g, 0)
        new_lob = lob + gain  # int32 wrap-add; bias commutes (see sort)
        # Unsigned overflow of the raw low word == biased-signed compare
        # of the planes.
        carry_bit = (new_lob < lob).astype(jnp.int32)
        return hi + carry_bit, new_lob, ids

    hi, lob, ids = lax.fori_loop(
        jnp.int32(0), jnp.int32(R), body, (hi0_ref[:], lob0_ref[:], ids0)
    )
    hi_out_ref[:] = hi
    lob_out_ref[:] = lob
    idout_ref[:] = ids


def _rounds_kernel(gains_ref, t0_ref, choice_ref, tout_ref, idout_ref):
    """gains_ref int32[R, 8, 128] (-1 = invalid position), t0_ref
    int32[8, 128] starting totals (sentinel at pad slots).  Emits per
    round the sorted id plane (choice) and returns the final (total, id)
    planes — still in the LAST round's sorted order; the host unsorts."""
    from jax.experimental import pallas as pl

    R = gains_ref.shape[0]
    ids0 = (
        lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 0) * _LANE
        + lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 1)
    )

    def body(r, carry):
        t, ids = carry
        t, ids = _bitonic_sort(t, ids)
        g = gains_ref[pl.ds(r, 1)][0]
        valid = g >= 0
        choice_ref[pl.ds(r, 1)] = jnp.where(valid, ids, -1)[None]
        t = t + jnp.where(valid, g, 0)
        return t, ids

    t, ids = lax.fori_loop(
        jnp.int32(0), jnp.int32(R), body, (t0_ref[:], ids0)
    )
    tout_ref[:] = t
    idout_ref[:] = ids


# THE VMEM budget and the byte model live with the other kernels'
# admission math (ops/kernel_admission) so the constants cannot drift
# between prose, this gate, and the linear-OT kernel's gate.
from .kernel_admission import fits_vmem as _fits_vmem_budget
from .kernel_admission import rounds_scan_bytes as _rounds_scan_bytes

_pallas_rounds_ok: dict | None = None  # {"narrow": bool, "wide": bool}
# Probe-once means once PER PROCESS: a threaded service (the sidecar
# serves concurrent connections) could otherwise race two configure-time
# warm-ups into the multi-compile probe, or read a partially-decided
# verdict.  Double-checked under this lock.
_pallas_rounds_lock = threading.Lock()


def _probe_parity(wide: bool = False) -> bool:
    """Bit-compare the real Mosaic lowering against the XLA scan on a
    representative multi-round instance — a kernel that compiles but
    miscompiles (e.g. an unsupported roll silently mislowered) must
    never reach a rebalance, because round-scan wrongness is a silent
    assignment corruption, not an error.  ``wide`` probes the two-plane
    totals variant (big lags force it through the wide gate)."""
    from .rounds_kernel import _rounds_scan

    rng = np.random.default_rng(0)
    P, C = 4096, 1000
    # Value ranges chosen so the instance ADMITS to the intended mode
    # (asserted below): narrow needs total < 2^30, i.e. lags < ~2^17
    # here; wide needs total >= 2^30 with every lag < 2^31.
    lo, hi = (2**29, 2**31 - 1) if wide else (0, 2**17)
    lags_np = -np.sort(-rng.integers(lo, hi, size=P)).astype(np.int64)
    got_mode = pallas_rounds_mode(
        C, int(lags_np.sum()), -(-P // C), int(lags_np.max())
    )
    want_mode = "wide" if wide else "narrow"
    assert got_mode == want_mode, (
        f"probe instance admitted as {got_mode!r}, wanted {want_mode!r} "
        "— the probe would validate the WRONG kernel"
    )
    lags = jnp.asarray(lags_np)
    valid = jnp.ones((P,), bool)
    ref_t, ref_c = _rounds_scan(
        lags, valid, jnp.zeros((C,), jnp.int64), C, n_valid=P
    )
    p_t, p_c = assign_sorted_rounds_pallas(
        lags, valid, num_consumers=C, n_valid=P,
        total_lag_bound=int(np.asarray(lags).sum()),
        max_lag_bound=int(np.asarray(lags).max()),
    )
    return bool(
        (np.asarray(p_c) == np.asarray(ref_c)).all()
        and (np.asarray(p_t) == np.asarray(ref_t)).all()
    )


def _probe_speed(margin: float = 0.9) -> bool:
    """Race the two kernels at a round count large enough for the
    difference to clear the tunnel's RTT noise (n in-executable repeats,
    scalar fetch — the only valid clock on this platform): enable the
    Pallas path only when it is at least ``1/margin`` x faster.  A
    lowering that is correct but SLOW (e.g. rolls lowered as copies)
    must not regress the headline just because it compiled."""
    import functools

    from jax import lax

    from ..utils.observability import stopwatch
    from .rounds_kernel import _rounds_scan

    P, C, n = 65536, 1000, 8
    rng = np.random.default_rng(1)
    lags = -np.sort(-rng.integers(0, 10**6, size=P)).astype(np.int64)
    # The race instance's TOTAL (~3.3e10) sits outside the narrow gate it
    # certifies, which is fine for timing — but only because no sort key
    # ever overflows: the kernel compares PER-CONSUMER totals, bounded by
    # R * max_lag, and that must clear the int32 sentinel the narrow
    # planes reserve (the same soundness the parity probe asserts via its
    # admitted mode).
    R = -(-P // C)
    assert R * int(lags.max()) < int(_SENTINEL), (
        "speed-race instance's per-consumer total bound "
        f"{R * int(lags.max())} would overflow the narrow totals plane"
    )
    batch = jax.device_put(
        np.stack([np.roll(lags, 7919 * i) for i in range(n)])
    )
    valid = jnp.ones((P,), bool)

    @functools.partial(jax.jit, static_argnames=("kind",))
    def many(b, kind: str):
        def one(v):
            if kind == "pallas":
                _, c = sorted_rounds_pallas_core(
                    v, valid, num_consumers=C, n_valid=P
                )
            else:
                _, c = _rounds_scan(
                    v, valid, jnp.zeros((C,), jnp.int64), C, n_valid=P
                )
            return c.astype(jnp.int32).sum()

        return lax.map(one, b).sum()

    def timed(kind):
        int(many(batch, kind=kind))  # warm-up/compile
        ts = []
        for _ in range(5):
            with stopwatch() as t:
                int(many(batch, kind=kind))
            ts.append(t[0] / 1000.0)
        return float(np.median(ts))

    t_xla, t_pal = timed("xla"), timed("pallas")
    LOGGER.info(
        "pallas round-scan race: xla %.1f ms vs pallas %.1f ms (x%d "
        "in-executable)", t_xla * 1e3, t_pal * 1e3, n,
    )
    return t_pal < t_xla * margin


def rounds_pallas_available(
    run_probe: bool = False, mode: str = "narrow"
) -> bool:
    """Probe-once gate for PRODUCTION dispatch of the Pallas round scan.

    The probe (parity bit-compare + a speed race vs the XLA scan, both
    on the real device) costs several executable compiles — minutes
    through a remote-compile transport — so it NEVER runs implicitly on
    a rebalance path: callers that can afford it (configure-time warm-up,
    the benchmark harness, the hardware probe script) pass
    ``run_probe=True`` once; until then, and on any failure, the answer
    is False and the XLA scan serves.  Resolve EAGERLY before any jit
    trace (same contract as plan_stats._pallas_available)."""
    global _pallas_rounds_ok
    if _pallas_rounds_ok is None:
        import jax as _jax

        from .plan_stats import _trace_state_clean

        if not run_probe or not _trace_state_clean():
            return False  # unprobed (or mid-trace): stay on the XLA scan
        with _pallas_rounds_lock:
            if _pallas_rounds_ok is not None:  # lost the race: decided
                return _pallas_rounds_ok.get(mode, False)
            if _jax.default_backend() == "cpu":
                _pallas_rounds_ok = {"narrow": False, "wide": False}
                return False
            try:
                narrow = _probe_parity()
                if not narrow:
                    LOGGER.warning(
                        "Pallas round-scan compiled but FAILED device "
                        "parity; staying on the XLA scan"
                    )
                narrow = narrow and _probe_speed()
                wide = False
                if narrow:
                    # The wide variant shares the narrow race verdict
                    # (same network, ~1.5x the plane ops) but needs its
                    # OWN parity proof: the carry/bias logic is
                    # wide-only code.
                    try:
                        wide = _probe_parity(wide=True)
                    except Exception:
                        LOGGER.warning(
                            "Pallas wide-variant parity probe failed; "
                            "narrow-only",
                            exc_info=True,
                        )
                        wide = False
                _pallas_rounds_ok = {"narrow": narrow, "wide": wide}
            except Exception:
                LOGGER.warning(
                    "Pallas round-scan unavailable; using the XLA scan",
                    exc_info=True,
                )
                _pallas_rounds_ok = {"narrow": False, "wide": False}
    return _pallas_rounds_ok.get(mode, False)


def pallas_rounds_mode(
    num_consumers: int, total_lag_bound: int, num_rounds: int,
    max_lag_bound: int,
):
    """Shape/value admission for the Pallas path.  Returns the kernel
    variant to use — ``"narrow"`` (totals fit int32), ``"wide"`` (totals
    as two int32 planes; individual lags must fit 31 bits so the gains
    stay one plane) — or None when neither admits the instance (the XLA
    scan serves)."""
    if num_consumers > C_PAD:
        return None
    if not _fits_vmem_budget(_rounds_scan_bytes(num_rounds, C_PAD)):
        return None
    if total_lag_bound < TOTALS_BOUND:
        return "narrow"
    if total_lag_bound < WIDE_TOTALS_BOUND and max_lag_bound < MAX_LAG_BOUND:
        return "wide"
    return None


def pallas_rounds_supported(
    num_consumers: int, total_lag_bound: int, num_rounds: int
) -> bool:
    """Narrow-kernel admission (back-compat boolean view of
    :func:`pallas_rounds_mode`)."""
    return (
        pallas_rounds_mode(
            num_consumers, total_lag_bound, num_rounds, total_lag_bound
        )
        == "narrow"
    )


def pallas_mode_for(lags, num_consumers: int, num_rounds: int):
    """THE host-side admission helper for dispatch sites: derive the
    value bounds from a raw lag array (f64 sum — an int64 wrap could
    alias a huge total to a small admissible one) and return the kernel
    mode or None.  One definition, so the clamp and the empty/negative
    guards cannot drift across call sites."""
    if num_consumers > C_PAD:
        return None
    arr = np.asarray(lags)
    if arr.size == 0:
        # Zero rows would build a zero-round pallas_call with a
        # (0, 8, 128) VMEM block Mosaic may reject at compile time (the
        # production inners have no R == 0 early-return; only the test
        # adapter does).  The XLA scan handles empty scans natively.
        return None
    if int(arr.min()) < 0:
        # The kernels read g >= 0 as the validity test, so an
        # out-of-contract negative lag would silently be treated as
        # padding (partition left unassigned) instead of assigned the
        # way the XLA scan assigns it.  Keep contract violations on the
        # XLA path, where behavior is unchanged from before the Pallas
        # kernel existed.
        return None
    total = int(min(float(arr.sum(dtype=np.float64)), 2.0**63))
    return pallas_rounds_mode(
        num_consumers, total, num_rounds, int(arr.max())
    )


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "interpret", "wide")
)
def rounds_scan_pallas(
    round_gains: jax.Array,
    num_consumers: int,
    interpret: bool = False,
    wide: bool = False,
):
    """Run the round decomposition on pre-rounded gains.

    Args:
      round_gains: int32[R, C] — round r's positional gains (the sorted
        descending lags of that round's partitions); -1 marks an invalid
        (padding) position.  The caller produced this exactly as
        :func:`..ops.rounds_kernel._rounds_scan` reshapes its sorted
        prefix.
      num_consumers: static C <= 1024.
      wide: static — two-plane int64 totals (see
        :func:`pallas_rounds_mode`; gains stay one int32 plane).
    Returns (totals int64[C] in CONSUMER order, choice int32[R, C]:
    consumer id seated at each position, -1 at invalid positions) — the
    same per-round contract as the XLA packed body.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C = int(num_consumers)
    R = round_gains.shape[0]
    gains_p = jnp.pad(
        round_gains.astype(jnp.int32),
        ((0, 0), (0, C_PAD - C)),
        constant_values=-1,
    ).reshape(R, _SUB, _LANE)

    def spec3():
        return pl.BlockSpec(
            (R, _SUB, _LANE), lambda: (0, 0, 0), memory_space=pltpu.VMEM
        )

    def spec2():
        return pl.BlockSpec(
            (_SUB, _LANE), lambda: (0, 0), memory_space=pltpu.VMEM
        )

    def shape3():
        return jax.ShapeDtypeStruct((R, _SUB, _LANE), jnp.int32)

    def shape2():
        return jax.ShapeDtypeStruct((_SUB, _LANE), jnp.int32)

    if wide:
        # Real slots start at total 0: hi = 0, low word 0 biased by xor
        # 2^31 == INT32_MIN.  Pad slots: sentinel in the HIGH plane
        # (above any admissible real hi, never incremented).
        hi0 = jnp.full((C_PAD,), _SENTINEL, jnp.int32).at[:C].set(
            0
        ).reshape(_SUB, _LANE)
        lob0 = jnp.full(
            (C_PAD,), jnp.int32(-(2**31)), jnp.int32
        ).reshape(_SUB, _LANE)
        choice, hi, lob, idout = pl.pallas_call(
            _rounds_kernel_wide,
            in_specs=[spec3(), spec2(), spec2()],
            out_specs=[spec3(), spec2(), spec2(), spec2()],
            out_shape=[shape3(), shape2(), shape2(), shape2()],
            interpret=interpret,
        )(gains_p, hi0, lob0)
        # Reconstruct int64 totals: raw low word = biased plane xor 2^31
        # (as an unsigned 32-bit value).
        lo_u = (
            lob.reshape(C_PAD).astype(jnp.int64) & jnp.int64(0xFFFFFFFF)
        ) ^ jnp.int64(0x80000000)
        tot64 = (hi.reshape(C_PAD).astype(jnp.int64) << 32) + lo_u
        _, totals_by_id = lax.sort(
            (idout.reshape(C_PAD), tot64), num_keys=1
        )
        return totals_by_id[:C], choice.reshape(R, C_PAD)[:, :C]

    t0 = jnp.full((C_PAD,), _SENTINEL, jnp.int32).at[:C].set(0).reshape(
        _SUB, _LANE
    )
    choice, tout, idout = pl.pallas_call(
        _rounds_kernel,
        in_specs=[spec3(), spec2()],
        out_specs=[spec3(), spec2(), spec2()],
        out_shape=[shape3(), shape2(), shape2()],
        interpret=interpret,
    )(gains_p, t0)

    # Final planes are in the last round's sorted order: one small sort
    # by id restores consumer order (ids 0..C-1 first, pads after).
    _, totals_by_id = lax.sort(
        (idout.reshape(C_PAD), tout.reshape(C_PAD)), num_keys=1
    )
    return totals_by_id[:C].astype(jnp.int64), \
        choice.reshape(R, C_PAD)[:, :C]


def assign_sorted_rounds_pallas(
    sorted_lags, sorted_valid, num_consumers: int, n_valid: int,
    total_lag_bound: int,
    interpret: bool = False,
    max_lag_bound: int | None = None,
):
    """Adapter matching :func:`..ops.rounds_kernel._rounds_scan`'s
    sorted-prefix contract: reshape the trimmed prefix into round rows
    (the SAME shared shaping the XLA scan uses), run the Pallas scan,
    return (totals int64[C], sorted_choice int32[P]).  Host-side
    convenience for tests and the hardware probe — production dispatch
    stays on the XLA path until the probe proves a win.

    ``total_lag_bound`` is the host-known upper bound on the total valid
    lag (e.g. ``int(lags.sum())`` — the same host-side-guard idiom as
    :func:`..ops.batched.totals_rank_bits_for`): the admission gate is
    ENFORCED here, because an out-of-gate instance would not fail loudly
    — an int32-overflowing lag would silently read as padding.
    """
    C = int(num_consumers)
    P = sorted_lags.shape[0]
    L = min(int(n_valid), P)
    R = -(-L // C) if L else 0
    mode = pallas_rounds_mode(
        C, int(total_lag_bound), max(R, 1),
        int(total_lag_bound if max_lag_bound is None else max_lag_bound),
    )
    if mode is None:
        raise ValueError(
            f"instance outside the Pallas round-scan gate "
            f"(C={C} <= {C_PAD}, total lag bound {total_lag_bound}, "
            f"VMEM): use the XLA path"
        )
    if R == 0:
        # Zero valid rows: the XLA scan's empty-scan contract.
        return (
            jnp.zeros((C,), jnp.int64),
            jnp.full((P,), -1, jnp.int32),
        )
    return sorted_rounds_pallas_core(
        sorted_lags, sorted_valid, num_consumers=C, n_valid=n_valid,
        interpret=interpret, wide=(mode == "wide"),
    )


def global_rounds_pallas_core(
    sorted_lags, sorted_valid, perms, num_consumers: int, n_valid: int,
    interpret: bool = False, wide: bool = False,
):
    """Cross-topic GLOBAL mode through the same kernel: the global solve
    IS one long round sequence — each topic contributes ceil(P/C) rounds
    and the totals carry across topics without reset (exactly what the
    kernel's loop-carried planes do), so concatenating every topic's
    round rows into one [T*R, C] gains matrix reproduces
    :func:`..ops.rounds_kernel.assign_global_rounds` bit-exactly while
    the whole sequential chain stays in VMEM.

    Args: sorted_lags/sorted_valid [T, P] in per-topic processing order,
    perms int32[T, P] (each topic's unsort permutation), static n_valid
    (dense row count per topic).  Returns (totals int64[C] consumer
    order, choice int32[T, P] in input row order).
    """
    from .rounds_kernel import round_rows
    from .sortops import unsort

    C = int(num_consumers)
    T, P = sorted_lags.shape

    def topic_rows(sl, sv):
        lags_h, valid_h, R, head = round_rows(sl, sv, C, n_valid)
        return (
            jnp.where(valid_h, lags_h, -1).astype(jnp.int32).reshape(R, C)
        )

    gains = jax.vmap(topic_rows)(sorted_lags, sorted_valid)  # [T, R, C]
    R = gains.shape[1]
    totals, choice_rows = rounds_scan_pallas(
        gains.reshape(T * R, C), num_consumers=C, interpret=interpret,
        wide=wide,
    )
    head = R * C
    flat = choice_rows.reshape(T, head)
    if head < P:
        flat = jnp.concatenate(
            [flat, jnp.full((T, P - head), -1, jnp.int32)], axis=1
        )
    else:
        flat = flat[:, :P]
    choice = jax.vmap(unsort)(perms, flat)
    return totals, choice


def sorted_rounds_pallas_core(
    sorted_lags, sorted_valid, num_consumers: int, n_valid: int,
    interpret: bool = False, wide: bool = False,
):
    """Traced core of the adapter — NO admission gate, usable inside an
    outer jit (the gate bound is per-call data, so checking it here would
    either trace-specialize on it or silently skip it; callers verify
    :func:`pallas_rounds_mode` host-side first).  Same round-row
    shaping as the XLA scan (shared helper)."""
    from .rounds_kernel import round_rows

    C = int(num_consumers)
    P = sorted_lags.shape[0]
    lags_h, valid_h, R, head = round_rows(
        jnp.asarray(sorted_lags), jnp.asarray(sorted_valid), C, n_valid
    )
    gains = jnp.where(valid_h, lags_h, -1).astype(jnp.int32).reshape(R, C)
    totals, choice = rounds_scan_pallas(
        gains, num_consumers=C, interpret=interpret, wide=wide
    )
    flat = choice.reshape(head)[: min(head, P)]
    if head < P:
        flat = jnp.concatenate(
            [flat, jnp.full((P - head,), -1, jnp.int32)]
        )
    return totals, flat
