"""Device-computed changed-assignment extraction — the O(changed)
READBACK half of the delta plane (ISSUE 19; the round-13 delta plane in
:mod:`.streaming` made the lag *upload* O(changed)).

A warm fused refine dispatch already keeps everything device-resident
except one host materialization: the narrowed ``[P]`` choice vector.
For a steady-state epoch that readback is almost entirely redundant —
the budgeted bulk refine performs at most ``exchange_budget`` exchanges,
each moving one partition row, so at most ``2 * exchange_budget``
entries of the choice vector can differ from the entry state the host
already holds (``StreamingAssignor._prev_choice``; membership repair
and cold solves drop the resident state and take the dense path, so the
bound is exact on the resident path).  This module provides the three
pieces that turn that bound into an O(changed) device→host transfer:

- :func:`readback_k` — the STATIC padded compaction width ``K`` for a
  dispatch, derived only from ``(exchange_budget, P)``.  Both inputs
  are already compile-time constants of the fused executables
  (``exchange_budget`` is a static argname, ``P`` is the exact lag
  shape), so threading ``K`` through adds NO new jit cache keys and
  therefore no new warm-loop compiles — the property the delta-plane
  bench gates.
- :func:`compact_changed` — the jit-side epilogue fused into
  ``_refine_core``: diff entry vs exit choice over the live ``[:P]``
  prefix and emit a padded ``(indices, values, count)`` triple.
- :func:`apply_assignment_delta` — the host-side inverse: scatter the
  fetched entries onto the host's previous dense view, reproducing the
  dense narrow readback bit-exactly.

Overflow is detected host-side, not device-side: the true changed
count rides along, and a count past ``K`` (possible only off the
budgeted bulk path) falls back to fetching the dense narrow vector —
which the executable still returns, so the fallback is a second
``device_get``, never a re-dispatch.
"""

from __future__ import annotations

import numpy as np

# Smallest compaction width, mirroring the upload ladder's DELTA_MIN_K
# (kept as a separate constant: .streaming imports THIS module, so the
# dependency cannot point the other way).
RB_MIN_K = 16

# Per-entry device->host cost bound: int32 index + int32 value (the
# narrowed choice is int16 when C <= 32767, int32 past that — size the
# byte-win gate for the WORST case so the decision stays a pure
# function of (exchange_budget, P) and never keys on the narrow dtype).
_RB_ENTRY_BYTES_MAX = 4 + 4
_RB_DENSE_BYTES_MIN = 2  # int16 narrow — worst case FOR the delta side


def _pow2_ceil(n: int) -> int:
    k = RB_MIN_K
    while k < n:
        k <<= 1
    return k


def readback_k(exchange_budget: int, P: int) -> int:
    """Padded compaction width for a warm fused dispatch, or 0 to keep
    the dense readback.

    The budgeted bulk refine moves at most ``2 * exchange_budget``
    choice entries, so the pow2 ceiling of that bound (floored at
    ``RB_MIN_K``) captures every steady-state epoch with zero overflow.
    Returns 0 — dense readback — when the budget is unbounded
    (``exchange_budget <= 0``: cold chains, where churn has no device
    bound) or when the padded compaction would not beat the dense
    transfer even under the most delta-hostile dtype pairing
    (int32 entries vs an int16 dense vector: win requires
    ``K * 8 < P * 2``).
    """
    if exchange_budget <= 0 or P <= 0:
        return 0
    k = _pow2_ceil(max(2 * int(exchange_budget), RB_MIN_K))
    if k * _RB_ENTRY_BYTES_MAX >= P * _RB_DENSE_BYTES_MIN:
        return 0
    return k


def compact_changed(entry_choice, exit_choice, narrow, P: int, K: int):
    """Fused readback-compaction epilogue (traced inside the warm
    executables — see ``_refine_core``).

    Diffs the entry choice against the exit choice over the live
    ``[:P]`` prefix (padded rows past P never reach the host view and
    are excluded by construction) and returns

    ``(d_idx int32[K], d_vals narrow-dtype[K], d_n int32)``

    where ``d_n`` is the TRUE changed count (may exceed K — the host
    checks).  Padding entries are ``(0, narrow[0])``: index 0's real
    exit value, so even a buggy consumer that scattered the full padded
    vector would write only truth (mirrors the upload path's
    self-consistent padding discipline).
    """
    import jax.numpy as jnp

    changed = entry_choice[:P] != exit_choice[:P]
    d_n = changed.sum(dtype=jnp.int32)
    d_idx = jnp.nonzero(changed, size=K, fill_value=0)[0].astype(jnp.int32)
    d_vals = jnp.take(narrow, d_idx)
    return d_idx, d_vals, d_n


def apply_assignment_delta(
    base: np.ndarray, idx: np.ndarray, vals: np.ndarray, n: int
) -> np.ndarray:
    """Host-side inverse of :func:`compact_changed`: scatter the first
    ``n`` fetched entries onto a copy of the host's previous dense
    view.  Bit-parity with the dense readback is structural — the
    values ARE gathers from the very narrow vector the dense path would
    have fetched, and every unchanged entry equals the base by the
    definition of the diff."""
    out = np.ascontiguousarray(base, dtype=np.int32).copy()
    n = int(n)
    if n:
        out[np.asarray(idx[:n], dtype=np.int64)] = np.asarray(
            vals[:n]
        ).astype(np.int32)
    return out
