"""Cross-stream megabatch coalescer: ONE vmapped resident dispatch for
N concurrent consumer groups, roster-stable and pipeline-overlapped.

The streaming engine (ops/streaming.py) serves one consumer group per
rebalance, and each warm epoch that needs quality work costs one fused
device dispatch.  That is the right shape for a lone tenant — but a
sidecar serving 32 concurrent groups pays 32 serialized device
round-trips per rebalance wave even though the fused refine core is
shape-static and the epochs are independent.  On a tunneled/remote
accelerator the round-trip IS the cost (BASELINE.md: ~1.5 ms warm no-op
vs ~40+ ms dispatch+readback), so the multi-tenant fix is the
FlashSinkhorn playbook applied across tenants instead of within one:
amortize dispatch and H2D over every stream that is ready to go.

Mechanism
---------

:class:`MegabatchCoalescer` keeps a queue of pending epoch submissions
(:class:`EpochSubmission`: the exact-shape lag payload plus the stream's
device-resident warm state and its static refine arguments).  A
dedicated flusher thread admits submissions for a short window
(sub-millisecond by default; a full shape group — or a locked roster's
full wave — flushes immediately), then groups them by SHAPE BUCKET —
``(padded P bucket, C, payload dtype, iters, max_pairs,
exchange_budget)``, everything that is a static argument of the fused
executable — and dispatches each multi-row group as ONE vmapped fused
call over the exact single-stream warm core (totals re-derivation,
quality-target test, the resident bulk-exchange round loop).  The
batch's host-facing outputs come back in ONE device->host fetch.

Roster-stable fast path
-----------------------

The first wave a stream set serves together pays the RE-STACK path: the
per-stream resident ``(choice, row_tab, counts)`` buffers are gathered
host-side and stacked on a new leading batch axis inside
:func:`_megabatch_fused_resident`.  After ``lock_waves`` consecutive
waves from the same stream set (default 1) the roster LOCKS: the
stacked ``[N, ...]`` successors stay device-resident as ONE
:class:`_ResidentBatch` owned by the coalescer, each engine's resident
handle becomes a :class:`ResidentRow` (batch + stable row index) rather
than concrete per-stream buffers, and every subsequent wave dispatches
:func:`_megabatch_fused_locked` — the stacked buffers go in as DONATED
arguments and come back as their own successors, with each stream's
lags placed into its stable row host-side.  The N-per-flush re-stack
work (3N small device gathers to slice rows out, N buffer tuples in) is
gone from the steady state: ``klba_coalesce_restack_total`` stays flat
while ``klba_coalesce_roster_hits_total`` counts locked flushes.

Delta epochs ride the locked fast path (ISSUE 8): the stacked batch
also carries its rows' widened ``[N, B]`` lag buffer, and a locked
wave whose EVERY live row arrived with a delta plan (the submitting
engine's host-side diff, ops/streaming) dispatches
:func:`_megabatch_fused_locked_delta` — a stacked ``[N, K]``
index/value staging (through the same rotating upload buffers, so the
pipeline overlap is preserved) scatter-applied to the donated resident
lag buffer, cutting the wave's H2D bytes from O(N·B) to O(N·K).  Mixed
waves, re-stack waves, an injected ``delta.apply`` fault, or a row
failing the readback's lag-sum divergence check fall back to the dense
staging (the faulted/diverged row re-syncs through the single-stream
dense dispatch; ``klba_delta_epochs_total`` counts both outcomes).

The lock is invalidated — exactly once per churn event — whenever a
wave does not match the resident batch: a stream joined or left, a
stream was poisoned/warm-restarted (its engine then submits a concrete
tuple or nothing at all), or a stale-resident rebuild replaced a handle
with fresh buffers.  The churn wave falls back to the re-stack path
(handles of the now-frozen old batch materialize their rows with one
gather each — the one-wave cost), and the next stable wave re-locks.
Padding rows of a batch carry zero lags and a ``0.0`` quality limit, so
the fused while-loop exits before round one and they pass through
bit-identically at ~zero compute (short re-stack waves pad by cycling
the surviving rows' buffers, never a dead stream's).

Double-buffered flush pipeline
------------------------------

A flush is three stages: **upload** (fill one of two rotating
preallocated host staging buffers with the wave's lags/limits and start
the async H2D), **dispatch** (the fused call — async under jax), and
**readback** (the only blocking stage: ``jax.block_until_ready`` + the
bulk D2H fetch, then futures resolve).  With ``pipeline=True`` (the
default) readback runs on its own worker thread, so the flusher returns
to the admission window immediately — wave k+1's admission and upload
overlap wave k's D2H.  A staging buffer is reused only after the wave
that used it completed readback (its ``ready`` event), which also
proves the device consumed the H2D.  ``pipeline=False`` is the
strict-serial fallback knob (``tpu.assignor.coalesce.pipeline``).

SLO placement and deadline triage
---------------------------------

Every submission carries an SLO class/rank and an optional absolute
admission deadline (utils/overload; the sidecar fills them from the
stream's class).  The flush sorts live rows by **(class rank,
remaining deadline)** before grouping and chunking, so a critical
stream never parks behind a full best-effort wave; a row whose
remaining budget is below the measured flush-cost EWMA is re-routed
to the inline path (``klba_coalesce_deadline_reroutes_total``) — its
future fails with the :class:`DeadlineReroute` marker after the waves
dispatch, and the submitter's own parked worker runs the inline
dispatch (laggards resolve in parallel; the flusher thread stays
admission-only) — and a row whose budget already expired is shed with
:class:`DeadlineShed` — a :class:`..utils.watchdog.SolveRejected`
subtype, so the submitter's warm state is known-intact, the service
serves ``kept_previous``, and no breaker is charged
(``klba_shed_total{class,rung="admit_deadline"}``).  The service's
overload controller scales the admission window down under pressure
(:meth:`MegabatchCoalescer.set_window_scale`, shed-ladder rung 1) —
batch efficiency yields before latency.

Submitters park on a :class:`concurrent.futures.Future`
(:meth:`StreamingAssignor.submit_epoch` blocks on it inside the same
watchdog deadline that guards an inline dispatch), so the degraded-mode
ladder, per-solver breakers, and poisoned-stream handling from round 7
are untouched.  A submission whose parked waiter has already been
abandoned by its watchdog (``abandoned()`` true — the request deadline
passed between park and flush) is DROPPED before grouping: its future
fails with :class:`SubmitterGone` (unparking the orphaned worker) and
its row never pollutes the wave.

Isolation: a poisoned row falls OUT of the batch
------------------------------------------------

A flush that fails before dispatch (an injected ``coalesce.flush``
fault, a megabatch grouping error) never fails its batchmates
wholesale: every row of the failed group re-dispatches the
already-warmed SINGLE-stream resident executable on its own, and only
a row whose own dispatch fails sees an exception on its future.  The
roster (if any) is invalidated so surviving engines re-stack and
re-lock.  One caveat the locked path trades for zero-copy donation: a
dispatch or readback failure AFTER the resident batch was donated
poisons the batch (its buffers are gone), so those rows surface errors
and recover through the per-stream degraded-mode ladder instead of an
in-place re-dispatch — breakers and poisoning stay per-stream either
way.  A single-row flush uses the single-stream executable directly —
zero extra compiles for the lone-tenant path.

Executable-cache discipline: one re-stack executable and one locked
executable per (shape bucket, batch pow2 bucket) — ``2 * log2
(max_batch)`` compiles per shape bucket, covered off the serving path
by :mod:`..warmup`'s megabatch job.

Telemetry (utils/metrics): ``klba_coalesce_batch_size`` histogram,
``klba_coalesce_flushes_total{path=megabatch|single|fallback}``,
``klba_coalesce_roster_hits_total`` / ``klba_coalesce_restack_total`` /
``klba_coalesce_roster_invalidations_total`` /
``klba_coalesce_dead_rows_total`` counters, the ``coalesce.window`` /
``coalesce.upload`` / ``coalesce.dispatch`` / ``coalesce.readback``
pipeline-stage spans, and a ``coalesce_flush`` flight record carrying
the wave's request ids (``metrics.capture_scope``).  Per-row fallback
dispatches adopt the submitting request's scope.  Fault points:
``coalesce.flush`` (per-group flush) and ``coalesce.gather`` (resident
row materialization — the roster-churn path).
"""

from __future__ import annotations

import functools
import logging
import queue
import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, NamedTuple, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..utils import faults, metrics, observability
from ..utils import scrub as scrub_mod
from ..utils.overload import record_shed
from ..utils.watchdog import SolveRejected
from .batched import _narrow_choice
from .refine import refine_rounds_resident
from .streaming import (
    _DELTA_ENTRY_BYTES,
    _state_digest,
    _warm_fused_resident,
)

LOGGER = logging.getLogger(__name__)


class SubmitterGone(RuntimeError):
    """A parked submission's waiter abandoned its wait (its watchdog
    deadline passed) before the flush; the row was dropped from the
    wave and this exception unparks the orphaned worker thread."""


class DeadlineShed(SolveRejected):
    """A parked submission's SLO deadline expired before its flush: the
    row was shed from the wave WITHOUT touching the device, so the
    submitter's warm state is intact (the :class:`SolveRejected`
    contract) — the service then serves ``kept_previous`` instead of
    poisoning the stream, and the shed never charges a breaker."""


class DeadlineReroute(Exception):
    """Internal marker: the flush re-routed this row to the inline path
    (remaining budget below the flush-cost EWMA).  Never escapes
    :meth:`StreamingAssignor.submit_epoch` — the submitter's own parked
    worker thread catches it and runs the inline single-stream dispatch
    itself, so k laggards resolve on k already-parked threads in
    parallel instead of serially stalling the flusher's admission
    loop during the exact overload that produces laggards."""


def _epoch_rows(
    lags, choice, row_tab, cnt, limits, num_consumers: int, iters: int,
    max_pairs, exchange_budget: int,
):
    """The shared vmapped body of both megabatch executables: the exact
    single-stream warm core (:func:`..ops.streaming._warm_fused_resident`
    minus its pad, which the host already applied) over every row.
    ``vmap`` of the ``while_loop`` runs until every row's exit condition
    holds, masking finished rows — each row's result is bit-identical to
    its single-stream dispatch (pinned by tests/test_coalesce.py).
    Padding rows carry zero lags and a ``0.0`` limit, so their peak (0)
    meets the target before round one and they pass through unchanged.

    Returns ``(narrow [N, B], choice int32 [N, B], row_tab [N, C, M],
    counts [N, C], lags int64 [N, B], totals [N, C], rounds [N],
    exchanges [N], digest int64 [N, 5])`` — the widened lag rows ride
    along device-resident so a locked batch can carry them and accept
    stacked deltas (:func:`_megabatch_fused_locked_delta`), and each
    row's fused integrity digest
    (:func:`..ops.streaming._state_digest`) lets the readback verify
    every row against its submitter's host truth (utils/scrub)."""

    def one(lags_b, choice_b, tab_b, counts_b, limit):
        B = choice_b.shape[0]
        M = tab_b.shape[1]
        lags64 = lags_b.astype(jnp.int64)
        slot_ok = (
            jnp.arange(M, dtype=jnp.int32)[None, :] < counts_b[:, None]
        )
        totals = jnp.where(
            slot_ok, lags64[jnp.clip(tab_b, 0, B - 1)], 0
        ).sum(axis=1)
        # Input-side digest (see ..streaming._refine_core): audits the
        # resident row the wave STARTED from, so a corrupted locked
        # row is detected on its first dispatch deterministically —
        # the refine could silently repair the very entry it moved.
        # The row TABLE rides in the fifth lane (utils/scrub).
        digest = _state_digest(
            lags64, choice_b, counts_b, num_consumers, row_tab=tab_b
        )
        choice_b, tab_b, counts_b, totals, rounds, ex = (
            refine_rounds_resident(
                lags64, choice_b, tab_b, counts_b, totals,
                num_consumers=num_consumers, iters=iters,
                max_pairs=max_pairs, exchange_budget=exchange_budget,
                quality_limit=limit, bulk_transfer=True, fan=8,
            )
        )
        narrow = _narrow_choice(choice_b, num_consumers)
        return (narrow, choice_b, tab_b, counts_b, lags64, totals,
                rounds, ex, digest)

    return jax.vmap(one)(lags, choice, row_tab, cnt, limits)


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_consumers", "iters", "max_pairs", "exchange_budget"
    ),
)
def _megabatch_fused_resident(
    lags, choices, row_tabs, counts, limits, num_consumers: int,
    iters: int, max_pairs, exchange_budget: int,
):
    """The RE-STACK megabatch executable: N streams' per-stream resident
    buffers arrive as length-N tuples and are stacked onto the batch
    axis here, inside the executable.  This is the roster-establishment
    (and churn-recovery) path; a locked roster's steady state uses
    :func:`_megabatch_fused_locked` instead and never re-stacks."""
    choice = jnp.stack(choices)
    row_tab = jnp.stack(row_tabs)
    cnt = jnp.stack(counts)
    return _epoch_rows(
        lags, choice, row_tab, cnt, limits, num_consumers, iters,
        max_pairs, exchange_budget,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_consumers", "iters", "max_pairs", "exchange_budget"
    ),
    donate_argnums=(1, 2, 3),
)
def _megabatch_fused_locked(
    lags, choice, row_tab, counts, limits, num_consumers: int,
    iters: int, max_pairs, exchange_budget: int,
):
    """The LOCKED megabatch executable: the stacked ``[N, ...]`` resident
    batch goes in as DONATED buffers and comes back as its own
    successor — no per-stream gathers, no re-stack, the only H2D is the
    ``[N, B]`` lag staging (each stream's row placed by its stable index
    host-side) and the ``[N]`` limits.  (The batch's previous resident
    lag buffer is simply replaced by this wave's staged rows, so it is
    not passed/donated here.)"""
    return _epoch_rows(
        lags, choice, row_tab, counts, limits, num_consumers, iters,
        max_pairs, exchange_budget,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_consumers", "iters", "max_pairs", "exchange_budget"
    ),
    donate_argnums=(2, 3, 4, 5),
)
def _megabatch_fused_locked_delta(
    idx, vals, lags, choice, row_tab, counts, limits,
    num_consumers: int, iters: int, max_pairs, exchange_budget: int,
):
    """The LOCKED DELTA megabatch executable (ISSUE 8): the stacked
    ``[N, K]`` (index, value) updates scatter into the batch's DONATED
    resident ``[N, B]`` lag buffer, then the shared vmapped warm core
    runs — the only H2D is O(N·K) instead of O(N·B).  Per-row padding
    entries write index 0's new value (a duplicate of an identical
    value — a no-op; see :func:`..streaming._warm_fused_delta`);
    batch-padding rows carry (0, 0) onto their all-zero lag rows.  K is
    the coalescer's single configured ``delta_k`` (the ladder top), so
    the executable count stays one per (shape bucket, batch bucket) —
    warmed by :mod:`...warmup`'s megabatch job."""
    lags = jax.vmap(lambda l, i, v: l.at[i].set(v))(lags, idx, vals)
    return _epoch_rows(
        lags, choice, row_tab, counts, limits, num_consumers, iters,
        max_pairs, exchange_budget,
    )


class EpochResult(NamedTuple):
    """One stream's share of a flush: host-facing outputs materialized,
    resident successor still on device — a concrete ``(choice, row_tab,
    counts, lags)`` tuple on the re-stack path, a :class:`ResidentRow`
    handle (the row's ownership lives with the batch) once the roster
    locks."""

    narrow: np.ndarray  # int16-ish [B] padded choice (slice [:P] yourself)
    resident: Any  # device (choice, row_tab, counts, lags) OR ResidentRow
    totals: np.ndarray  # int64 [C] per-consumer totals under the new lags
    counts: np.ndarray  # int32 [C]
    rounds: int
    exchanges: int


class _ResidentBatch:
    """One locked roster's stacked device-resident warm state.

    ``choice [n_pad, B]`` / ``row_tab [n_pad, C, M]`` / ``counts
    [n_pad, C]`` / ``lags int64 [n_pad, B]`` are replaced by their
    successors on every locked flush (the executable donates them —
    the lag buffer is what the stacked delta path scatters into);
    ``lock`` serializes that swap against
    a :class:`ResidentRow` materializing a row from another thread (a
    stream leaving the batch for an inline dispatch).  ``valid`` False
    freezes the arrays — an invalidated batch is never donated again,
    so late materializations stay safe; ``poisoned`` True means the
    buffers were donated into a flush that then failed, and
    materialization must fail loudly instead of returning garbage."""

    __slots__ = (
        "shape_key", "choice", "row_tab", "counts", "lags", "n_real",
        "valid", "poisoned", "lock", "mesh",
    )

    def __init__(
        self, shape_key, choice, row_tab, counts, lags, n_real: int,
        mesh=None,
    ):
        self.shape_key = shape_key
        self.choice = choice
        self.row_tab = row_tab
        self.counts = counts
        self.lags = lags
        self.n_real = int(n_real)
        self.valid = True
        self.poisoned = False
        self.lock = threading.Lock()
        # Stream-axis mesh this batch's stacked buffers are sharded
        # over (sharded/megabatch.place_batch at lock time), or None
        # for the single-device placement.  Staged uploads follow it.
        self.mesh = mesh

    @property
    def n_pad(self) -> int:
        return self.choice.shape[0]

    def adopt_resident_buffers(self, choice, row_tab, counts, lags):
        """THE locked-wave swap site: install a flush's stacked
        successors (caller holds ``self.lock``).  Single-sourced — and
        the only place outside construction these fields may be
        assigned (lint L018) — so the scrubber's host-mirror truth can
        never drift from the device through an unaudited write."""
        self.choice = choice
        self.row_tab = row_tab
        self.counts = counts
        self.lags = lags


class ResidentRow:
    """A stream's resident-state handle while its roster is locked: the
    batch owns the buffers; this names the stream's stable row.  The
    streaming engine stores it exactly where it stored the concrete
    ``(choice, row_tab, counts)`` tuple and hands it back on the next
    :class:`EpochSubmission`; :meth:`materialize` (one gather per
    buffer) is paid only when the stream LEAVES the batch — an inline
    dispatch, a fallback single-row dispatch, or a churn-wave
    re-stack."""

    __slots__ = ("batch", "row")

    def __init__(self, batch: _ResidentBatch, row: int):
        self.batch = batch
        self.row = int(row)

    def matches(self, bucket: int, num_consumers: int, m_rows: int) -> bool:
        """Shape check, same contract as the engine's concrete-tuple
        check: does this row fit a (bucket, C, M) warm dispatch?"""
        b = self.batch
        return (
            b.choice.shape[1] == bucket
            and b.row_tab.shape[1:] == (num_consumers, m_rows)
        )

    def materialize(self) -> Tuple[Any, Any, Any, Any]:
        """Concrete per-stream device buffers for this row (four
        gathers).  Fault point ``coalesce.gather`` fires here — the
        roster-churn recovery path the chaos drills target."""
        faults.fire("coalesce.gather")
        b = self.batch
        with b.lock:
            if b.poisoned:
                raise RuntimeError(
                    "resident batch was poisoned (donated into a failed "
                    "flush); the row's warm state is gone"
                )
            return (b.choice[self.row], b.row_tab[self.row],
                    b.counts[self.row], b.lags[self.row])


class _Roster:
    """Per-shape-key roster tracking: the owner set of the last wave,
    its consecutive-wave streak, the locked batch (None until the
    streak reaches ``lock_waves``), and a recency tick for eviction."""

    __slots__ = ("owners", "streak", "batch", "last_used")

    def __init__(self, owners: frozenset):
        self.owners = owners
        self.streak = 1
        self.batch: Optional[_ResidentBatch] = None
        self.last_used = 0


# Retention caps: a locked batch pins its stacked [N, ...] device
# buffers and a staging pair pins two [n_pad, B] host arrays — a fleet
# whose shape key retires (departed tenants, a payload-dtype flip on
# lag-range drift) must not strand them forever.  Least-recently-used
# entries beyond the cap are dropped (the batch is invalidated first,
# so engine handles stay materializable until their owners re-stack).
_MAX_ROSTERS = 8
_MAX_STAGING = 16


class _StagingSlot:
    """One of the two rotating host staging buffers for a (shape key,
    batch bucket): preallocated lag/limit arrays plus the ``ready``
    event its wave's readback sets when the buffer may be reused."""

    __slots__ = ("lags", "limits", "ready")

    def __init__(self, n_pad: int, bucket: int, dtype):
        self.lags = np.zeros((n_pad, bucket), dtype=dtype)
        self.limits = np.zeros(n_pad, dtype=np.float64)
        self.ready = threading.Event()
        self.ready.set()


class _DeltaStagingSlot:
    """Rotating staging pair for the stacked [N, K] DELTA flush: pow2
    index/value arrays plus limits, same ``ready`` discipline as the
    dense slots (the wave's readback releases the buffer)."""

    __slots__ = ("idx", "vals", "limits", "ready")

    def __init__(self, n_pad: int, k: int):
        self.idx = np.zeros((n_pad, k), dtype=np.int32)
        self.vals = np.zeros((n_pad, k), dtype=np.int64)
        self.limits = np.zeros(n_pad, dtype=np.float64)
        self.ready = threading.Event()
        self.ready.set()


@dataclass
class EpochSubmission:
    """One stream's pending warm epoch (see the module docstring)."""

    payload: np.ndarray  # exact-shape [P] lags, already dtype-downcast
    bucket: int  # padded refine shape B (the engine's _bucket(P))
    resident: Any  # (choice, row_tab, counts) tuple OR ResidentRow handle
    limit: float  # device-side quality target (negative disables)
    num_consumers: int
    iters: int
    max_pairs: int
    exchange_budget: int
    scope: Any = None  # metrics.capture_scope() token of the submitter
    owner: Any = None  # stable stream identity (the engine) for rosters
    # SLO placement (utils/overload): rank orders every flush — chunks
    # are cut in (rank, remaining deadline) order, so a critical stream
    # never parks behind a full best-effort wave; ``deadline_at`` is
    # the absolute coalescer-clock instant the row's class budget
    # expires — a row that cannot survive a full flush is re-routed to
    # the inline single-stream path (or shed, once expired) instead of
    # slowing the wave.  Defaults reproduce the pre-SLO behavior.
    klass: str = "standard"
    rank: int = 1
    deadline_at: Optional[float] = None
    # "Is the parked waiter already abandoned?" — captured from the
    # submitter's watchdog call (utils/watchdog.capture_abandon_check);
    # None when no watchdog wraps the park (library use, tests).
    abandoned: Optional[Callable[[], bool]] = None
    # Delta-epoch plan (ISSUE 8; ops/streaming._delta_plan): the RAW
    # changed positions (int32 [n]) and their new int64 values, when
    # the submitting engine deemed this epoch delta-eligible.  A locked
    # wave whose every live row carries one (and fits the coalescer's
    # configured K) dispatches the stacked [N, K] delta executable;
    # re-stack waves and mixed waves ignore it and stage dense.
    delta_idx: Optional[np.ndarray] = None
    delta_vals: Optional[np.ndarray] = None
    # Host-side int64 lag sum (wrap-consistent with the device totals):
    # the per-row divergence check of a delta wave's readback.
    lag_sum: Optional[int] = None
    future: Future = field(default_factory=Future)
    enqueued_at: float = 0.0

    @property
    def shape_key(self) -> Tuple:
        """Everything that selects a distinct fused executable: only
        submissions agreeing on ALL of it can share a megabatch."""
        return (
            self.bucket, self.num_consumers, self.payload.dtype.str,
            self.iters, self.max_pairs, self.exchange_budget,
        )


class MegabatchCoalescer:
    """Admission-window device-dispatch coalescer (module docstring).

    ``window_s`` is the admission window measured from the OLDEST
    pending submission; ``max_batch`` pending epochs in one shape group
    (or a locked roster's full wave) flush immediately.  ``lock_waves``
    is how many consecutive identical-stream-set waves a shape group
    must serve before its roster locks (1 = lock on the first megabatch
    flush; a huge value disables the fast path).  ``pipeline`` False
    selects strict-serial flushes (readback inline on the flusher).
    The flusher is a lazily started daemon thread — a coalescer that
    never sees a submission costs nothing.  A wedged device inside a
    flush blocks only the flusher/readback pair (submitters' watchdog
    deadlines still fire and their requests descend the degraded-mode
    ladder on fresh engines, exactly like an abandoned inline solve).
    """

    def __init__(
        self,
        window_s: float = 0.0005,
        max_batch: int = 32,
        lock_waves: int = 1,
        pipeline: bool = True,
        # Delta-epoch K for the stacked [N, K] locked flush (ISSUE 8):
        # a locked wave whose every row carries a delta plan that fits
        # pads to this SINGLE K (the engines' ladder top), so the delta
        # executable count stays one per (shape bucket, batch bucket) —
        # unlike the inline path's per-rung ladder, the batch axis
        # already multiplies the executable count.  0 disables the
        # stacked delta path (every wave stages dense).
        delta_k: int = 512,
        # Stream-axis sharding (sharded/megabatch): the mesh manager
        # whose ("streams",) mesh locked rosters spread over — N
        # tenants' rows run on D devices instead of queueing on one.
        # The default "auto" follows the process-wide active manager
        # (sharded/mesh.activate — what a mesh-enabled service
        # installs at boot), which itself defaults to None =
        # single-device placement; an EXPLICIT None pins this
        # coalescer single-device regardless of any global manager (a
        # mesh-off service must not adopt a co-resident instance's
        # mesh).  A ``mesh.collective`` fault or a sharded dispatch
        # failure degrades the manager: in-flight rows resolve through
        # the existing single-stream fallback and later waves place
        # single-device.
        mesh_manager="auto",
    ):
        if window_s < 0:
            raise ValueError(f"window_s={window_s} must be >= 0")
        if max_batch < 1:
            raise ValueError(f"max_batch={max_batch} must be >= 1")
        if lock_waves < 1:
            raise ValueError(f"lock_waves={lock_waves} must be >= 1")
        if delta_k < 0:
            raise ValueError(f"delta_k={delta_k} must be >= 0")
        self.window_s = float(window_s)
        self.max_batch = int(max_batch)
        self.lock_waves = int(lock_waves)
        self.pipeline = bool(pipeline)
        self.delta_k = int(delta_k)
        self._mesh_manager = mesh_manager
        # Overload backpressure: the shed ladder's rung-1 action scales
        # the admission window down (smaller waves, lower parked
        # latency — batch efficiency yields before latency).  Plain
        # GIL-atomic writes/reads; the service sets them per its
        # overload controller's rung.  ``_window_scales`` is PER CLASS
        # (rank-ordered: critical/standard/best_effort — ROADMAP
        # overload (b)): each parked submission's admission deadline
        # uses its own class's scale, so the critical window stays
        # wide while best_effort shrinks.  ``_window_scale`` mirrors
        # the standard class (legacy single-scale surface + gauge).
        self._window_scale = 1.0
        self._window_scales = (1.0, 1.0, 1.0)
        # EWMA of a megabatch flush's dispatch->readback wall time: the
        # deadline-admission estimate of "can this row survive a full
        # flush".  Starts at 0 (no rerouting until measured).
        self._flush_cost_s = 0.0
        self._cond = threading.Condition()
        # noqa: L014 below — drained to empty by every flusher pass;
        # occupancy is bounded by the live-submitter count (each stream
        # parks at most one epoch) and dead submitters are dropped.
        self._pending: List[EpochSubmission] = []  # noqa: L014
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._clock = metrics.REGISTRY.clock
        # Roster + staging state: rosters are mutated by the flusher
        # (and invalidated by a failed readback), so dict access is
        # guarded by its own leaf lock; staging slots are flusher-only.
        self._roster_lock = threading.Lock()
        self._rosters: Dict[Tuple, _Roster] = {}
        self._staging: Dict[Tuple, list] = {}
        self._tick = 0  # flush-group counter driving LRU eviction
        self._rb_q: Optional[queue.Queue] = None
        self._rb_thread: Optional[threading.Thread] = None
        # Drain bookkeeping (graceful-drain quiesce, service lifecycle):
        # how many waves the flusher is inside (_busy) and how many
        # pipelined readback jobs are issued-but-unfinished.  Guarded by
        # its own leaf condition; :meth:`drain` waits on it.
        self._quiesce = threading.Condition()
        self._busy = 0
        self._rb_outstanding = 0
        # Pre-bound series: flushes run on the hot multi-tenant path.
        self._m_batch = metrics.REGISTRY.histogram(
            "klba_coalesce_batch_size"
        )
        self._m_path = {
            p: metrics.REGISTRY.counter(
                "klba_coalesce_flushes_total", {"path": p}
            )
            for p in ("megabatch", "single", "fallback")
        }
        self._m_hits = metrics.REGISTRY.counter(
            "klba_coalesce_roster_hits_total"
        )
        self._m_restack = metrics.REGISTRY.counter(
            "klba_coalesce_restack_total"
        )
        self._m_invalid = metrics.REGISTRY.counter(
            "klba_coalesce_roster_invalidations_total"
        )
        self._m_dead = metrics.REGISTRY.counter(
            "klba_coalesce_dead_rows_total"
        )
        self._m_reroutes = metrics.REGISTRY.counter(
            "klba_coalesce_deadline_reroutes_total"
        )
        self._m_window_scale = metrics.REGISTRY.gauge(
            "klba_coalesce_window_scale"
        )
        self._m_window_scale.set(1.0)
        # H2D byte accounting + delta-epoch outcomes for the staged
        # paths (same series the inline engine charges, so the
        # dense-vs-delta trade reads off one pair of counters).
        self._m_h2d_dense = metrics.REGISTRY.counter(
            "klba_h2d_bytes_total", {"path": "dense"}
        )
        self._m_h2d_delta = metrics.REGISTRY.counter(
            "klba_h2d_bytes_total", {"path": "delta"}
        )
        self._m_delta_applied = metrics.REGISTRY.counter(
            "klba_delta_epochs_total", {"outcome": "applied"}
        )
        self._m_delta_fallback = metrics.REGISTRY.counter(
            "klba_delta_epochs_total", {"outcome": "fallback"}
        )

    # -- submission --------------------------------------------------------

    def set_window_scale(self, scale: float) -> None:
        """Overload backpressure hook, legacy single-scale form: scale
        EVERY class's admission window to ``window_s * scale``
        (clamped to [0.05, 1.0]).  Safe from any thread."""
        scale = min(max(float(scale), 0.05), 1.0)
        self.set_window_scales((scale, scale, scale))

    def set_window_scales(self, scales) -> None:
        """Per-class window scales (rank order: critical, standard,
        best_effort — utils/overload's ``_Decision.window_scales``):
        each parked submission's admission deadline is computed with
        ITS class's scale, so rung-1 backpressure shrinks best_effort
        waves while critical epochs keep their full coalescing window.
        Safe from any thread."""
        scales = tuple(
            min(max(float(s), 0.05), 1.0) for s in scales
        )
        if len(scales) != 3:
            raise ValueError("window scales must be a (crit, std, be) triple")
        if scales == self._window_scales:
            # Called on every admitted request (service admission path):
            # the steady state at rung 0 must not pay the gauge lock.
            return
        self._window_scales = scales
        self._window_scale = scales[1]
        self._m_window_scale.set(scales[1])
        # Wake the flusher: a shrunk class window may already be due.
        with self._cond:
            self._cond.notify_all()

    def submit(self, sub: EpochSubmission) -> Future:
        """Enqueue one epoch; returns the future its flush resolves.
        Raises RuntimeError after :meth:`close` (the caller's ladder
        then degrades exactly as for any failed dispatch).  Fault point
        ``admit.park`` fires here — a parked-admission failure must
        surface on the submitting stream alone."""
        faults.fire("admit.park")
        with self._cond:
            if self._closed:
                raise RuntimeError("megabatch coalescer is closed")
            sub.enqueued_at = self._clock()
            self._pending.append(sub)
            if self._thread is None:
                if self.pipeline:
                    # Depth-2 queue = the double buffer: at most one
                    # wave in readback while the next uploads; a third
                    # backpressures the flusher, never unbounded memory.
                    self._rb_q = queue.Queue(maxsize=2)
                    self._rb_thread = threading.Thread(
                        target=self._readback_loop,
                        name="klba-coalesce-rb", daemon=True,
                    )
                    self._rb_thread.start()
                self._thread = threading.Thread(
                    target=self._run, name="klba-coalesce", daemon=True
                )
                self._thread.start()
            self._cond.notify_all()
        return sub.future

    def pending(self) -> int:
        with self._cond:
            return len(self._pending)

    def stats(self) -> Dict[str, Any]:
        """Roster-tracking snapshot for the service ``stats`` surface.
        Counter values are process-wide registry reads (the same series
        a scraper sees), not per-instance deltas."""
        with self._roster_lock:
            locked = sum(
                1 for r in self._rosters.values() if r.batch is not None
            )
            sharded = sum(
                1 for r in self._rosters.values()
                if r.batch is not None and r.batch.mesh is not None
            )
        return {
            "locked_rosters": locked,
            "stream_sharded_rosters": sharded,
            "roster_hits": self._m_hits.value,
            "restack_flushes": self._m_restack.value,
            "roster_invalidations": self._m_invalid.value,
            "dead_rows_dropped": self._m_dead.value,
        }

    def close(self) -> None:
        """Stop admitting; the flusher drains what is already queued
        (futures resolve) and exits, then the readback worker drains
        its queue and exits."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def drain(self, timeout_s: Optional[float] = 30.0) -> bool:
        """Quiesce for a graceful drain: wait until every admitted
        submission has flushed AND every pipelined readback completed
        (futures resolved — no wave is torn mid-flight when the final
        snapshot is written).  Does NOT stop admissions (the service's
        lifecycle gate rejects new work first) and does NOT close the
        coalescer (:meth:`close` still owns shutdown); safe to call on
        an idle or never-started coalescer.  Returns True when quiet,
        False on timeout.  Fault point ``drain.flush`` fires first and
        propagates — the service logs it and proceeds with the drain
        (a broken flush must never block the final snapshot)."""
        faults.fire("drain.flush")
        deadline = (
            self._clock() + timeout_s if timeout_s is not None else None
        )
        while not self._quiet():
            remaining = (
                None if deadline is None else deadline - self._clock()
            )
            if remaining is not None and remaining <= 0:
                return False
            with self._quiesce:
                self._quiesce.wait(
                    0.05 if remaining is None else min(0.05, remaining)
                )
        return True

    def _quiet(self) -> bool:
        """True when no submission is parked, no wave is inside the
        flusher, and no readback job is outstanding.  The two locks are
        taken sequentially, never nested here — the flusher nests
        ``_quiesce`` inside ``_cond`` (pop and busy-mark are one
        atomic step), so a pending pop can never hide between the two
        reads."""
        with self._cond:
            pending = len(self._pending)
        with self._quiesce:
            return (
                pending == 0
                and self._busy == 0
                and self._rb_outstanding == 0
            )

    # -- the flusher -------------------------------------------------------

    def _flush_ready(self) -> bool:
        """Caller holds ``self._cond``: a full shape group — or a locked
        roster whose whole wave is already pending — short-circuits the
        admission window (waiting longer cannot grow the batch)."""
        tally: Dict[Tuple, int] = {}
        for s in self._pending:
            tally[s.shape_key] = tally.get(s.shape_key, 0) + 1
        with self._roster_lock:
            for key, n in tally.items():
                if n >= self.max_batch:
                    return True
                roster = self._rosters.get(key)
                if (
                    roster is not None
                    and roster.batch is not None
                    and roster.batch.valid
                    and n >= roster.batch.n_real
                ):
                    return True
        return False

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if not self._pending:
                    if self._rb_q is not None:
                        self._rb_q.put(None)  # drain + stop the worker
                    return  # closed and drained
                if not self._closed and self.window_s > 0:
                    # Admission window from the OLDEST submission,
                    # scaled down under overload (shed ladder rung 1);
                    # a full shape group (or roster wave)
                    # short-circuits.
                    # Per-class deadlines (ROADMAP overload (b)): each
                    # parked submission's window uses ITS class's
                    # scale, so the wave flushes at the EARLIEST class
                    # deadline — recomputed per wakeup because a newly
                    # parked best_effort row (or a scale change) can
                    # tighten it below the oldest row's.
                    with metrics.span("coalesce.window"):
                        while not self._closed:
                            if self._flush_ready():
                                break
                            scales = self._window_scales
                            deadline = min(
                                s.enqueued_at + self.window_s * scales[
                                    s.rank if 0 <= s.rank < 3 else 1
                                ]
                                for s in self._pending
                            )
                            remaining = deadline - self._clock()
                            if remaining <= 0:
                                break
                            self._cond.wait(remaining)
                batch, self._pending = self._pending, []
                # Busy-mark INSIDE the admission lock: the pop and the
                # mark are one atomic step, so a drain's quiet check can
                # never observe "pending empty, flusher idle" while a
                # wave is actually in hand.
                with self._quiesce:
                    self._busy += 1
            try:
                self._flush(batch)
            except Exception as exc:  # noqa: BLE001 — delivered to waiters
                LOGGER.warning("coalescer flush crashed", exc_info=True)
                for s in batch:
                    if not s.future.done():
                        s.future.set_exception(exc)
            finally:
                with self._quiesce:
                    self._busy -= 1
                    self._quiesce.notify_all()

    def _readback_loop(self) -> None:
        while True:
            job = self._rb_q.get()
            if job is None:
                return
            try:
                job()
            except Exception:  # noqa: BLE001 — jobs resolve own futures
                LOGGER.warning(
                    "coalescer readback job crashed", exc_info=True
                )
            finally:
                with self._quiesce:
                    self._rb_outstanding -= 1
                    self._quiesce.notify_all()

    def _enqueue_readback(self, job: Callable[[], None]) -> None:
        if self._rb_q is None:
            job()  # strict-serial fallback: readback on the flusher
        else:
            with self._quiesce:
                self._rb_outstanding += 1
            self._rb_q.put(job)

    def _flush(self, batch: List[EpochSubmission]) -> None:
        # Dead-submitter drop (BEFORE grouping): a stream whose parked
        # waiter was abandoned by its watchdog between park and flush
        # must not keep a row in the wave — fail its future (unparking
        # the orphaned worker) and group only the live rows.  Deadline
        # triage rides the same pass: a row whose class budget already
        # expired is SHED (fails fast as DeadlineShed — warm state
        # intact, the service serves kept_previous), and a row whose
        # remaining budget cannot survive a full megabatch flush
        # (measured EWMA) is re-routed to the inline single-stream
        # path AFTER the waves dispatch — late, but not wave-poisoning.
        now = self._clock()
        live: List[EpochSubmission] = []
        laggards: List[EpochSubmission] = []
        for s in batch:
            abandoned = s.abandoned
            if abandoned is not None and abandoned():
                self._m_dead.inc()
                if not s.future.done():
                    s.future.set_exception(SubmitterGone(
                        "submitter abandoned its wait (deadline passed) "
                        "before the coalesced flush"
                    ))
                continue
            if s.deadline_at is not None:
                remaining = s.deadline_at - now
                if remaining <= 0:
                    # Shared shed accounting (utils/overload): served
                    # is None here — the submitter's recovery (the
                    # service's kept_previous / snake ladder) decides
                    # what the client actually gets, after this shed.
                    record_shed(
                        s.klass, "admit_deadline", None,
                        request_id=(
                            s.scope.request_id
                            if s.scope is not None else None
                        ),
                        scope=s.scope,
                    )
                    if not s.future.done():
                        s.future.set_exception(DeadlineShed(
                            f"{s.klass!r} epoch's deadline budget "
                            "expired while parked for the coalesced "
                            "flush"
                        ))
                    continue
                if remaining < self._flush_cost_s:
                    self._m_reroutes.inc()
                    laggards.append(s)
                    continue
            live.append(s)
        # SLO placement order: (class rank, remaining deadline) — the
        # max_batch chunking below then cuts waves in this order, so a
        # critical stream never parks behind a full best-effort wave.
        # Stable sort: rows with equal keys keep arrival order.
        live.sort(key=lambda s: (
            s.rank,
            (s.deadline_at - now) if s.deadline_at is not None
            else float("inf"),
        ))
        groups: Dict[Tuple, List[EpochSubmission]] = {}
        for s in live:
            groups.setdefault(s.shape_key, []).append(s)
        for group in groups.values():
            # Enforce the batch cap HERE, not only at the window break:
            # a group that outgrew max_batch while the flusher was busy
            # (or because a whole 64-stream fleet rebalanced at once)
            # flushes as max_batch-sized chunks — never padding past the
            # cap into a fresh, bigger executable on the serving path.
            for i in range(0, len(group), self.max_batch):
                self._flush_group(group[i: i + self.max_batch])
        for s in laggards:
            # Hand the row back to its own parked worker AFTER the
            # waves dispatch (waves carry the critical rows — they keep
            # device priority): the submitter catches the marker and
            # runs the inline dispatch itself, so k laggards resolve on
            # k threads in parallel and the flusher returns straight to
            # admission — a serial inline loop here would age every
            # parked wave by k x inline-cost exactly when budgets are
            # tightest, a self-reinforcing spiral the window-scale knob
            # cannot counter.
            if not s.future.done():
                s.future.set_exception(DeadlineReroute(
                    f"{s.klass!r} epoch's remaining budget cannot "
                    "survive a full flush; re-routed to the inline path"
                ))

    def _flush_group(self, rows: List[EpochSubmission]) -> None:
        self._tick += 1
        self._m_batch.observe(len(rows))
        path = "single"
        try:
            faults.fire("coalesce.flush")
            if len(rows) > 1:
                job = self._traced_wave(
                    rows, lambda: self._dispatch_megabatch(rows)
                )
                self._m_path["megabatch"].inc()
                self._enqueue_readback(job)
                return
        except Exception:  # noqa: BLE001 — isolated below, per row
            # Poisoned-ROW isolation: the batch is not poisoned by
            # one bad row (or a flush-level fault) — every row
            # re-dispatches the single-stream executable on its own
            # and only a row whose OWN dispatch fails sees an error.
            LOGGER.warning(
                "coalesced flush of %d epoch(s) failed; isolating "
                "rows via single-stream dispatch",
                len(rows), exc_info=True,
            )
            path = "fallback"
            # Whatever roster these rows served is stale now: the rows
            # leave the batch as concrete tuples via their single
            # dispatches; re-stack + re-lock on the next stable wave.
            self._invalidate(rows[0].shape_key, None)
        self._m_path[path].inc()
        # Single-row flushes and flush-fault fallbacks dispatch dense:
        # a delta-planned row completes with a fallback outcome (the
        # locked/re-stack paths count theirs at their own dispatch
        # sites, never inside _resolve_single — exactly once each).
        planned = sum(
            1 for s in rows
            if s.delta_idx is not None and not s.future.done()
        )
        if planned:
            self._m_delta_fallback.inc(planned)
        for s in rows:
            if not s.future.done():
                self._resolve_single(s)

    # -- roster bookkeeping ------------------------------------------------

    def _invalidate(
        self, key: Tuple, batch: Optional[_ResidentBatch]
    ) -> None:
        """Drop ``key``'s locked batch (if ``batch`` is given, only if
        it is still THE batch — a stale poison must not kill a
        successor roster).  The arrays freeze: an invalidated batch is
        never donated again, so engine handles pointing at it stay
        materializable."""
        with self._roster_lock:
            roster = self._rosters.get(key)
            if roster is None or roster.batch is None:
                return
            if batch is not None and roster.batch is not batch:
                return
            roster.batch.valid = False
            roster.batch = None
            self._m_invalid.inc()

    def _poison(self, batch: _ResidentBatch) -> None:
        """A flush that DONATED this batch failed: the buffers are gone.
        Mark it so materialization fails loudly, and invalidate the
        roster so the next wave re-stacks from the engines' ladders."""
        batch.poisoned = True
        self._invalidate(batch.shape_key, batch)

    def _covers(
        self, batch: _ResidentBatch, rows: List[EpochSubmission]
    ) -> bool:
        """True when this wave IS the locked roster: every submission
        carries a handle of this batch and together they cover every
        real row exactly once."""
        if not batch.valid or len(rows) != batch.n_real:
            return False
        seen = set()
        for s in rows:
            r = s.resident
            if not isinstance(r, ResidentRow) or r.batch is not batch:
                return False
            seen.add(r.row)
        return seen == set(range(batch.n_real))

    def _note_wave(
        self, key: Tuple, rows: List[EpochSubmission]
    ) -> Tuple[bool, _Roster]:
        """Streak accounting for a re-stack wave; returns (lock_now,
        roster).  Anonymous submissions (no owner) key on themselves,
        so they never accumulate a cross-wave streak by accident."""
        owners = frozenset(
            id(s.owner) if s.owner is not None else ("anon", id(s))
            for s in rows
        )
        with self._roster_lock:
            roster = self._rosters.get(key)
            if roster is None or roster.owners != owners:
                roster = self._rosters[key] = _Roster(owners)
            else:
                roster.streak += 1
            roster.last_used = self._tick
            if len(self._rosters) > _MAX_ROSTERS:
                stale_key = min(
                    (k for k in self._rosters if k != key),
                    key=lambda k: self._rosters[k].last_used,
                )
                stale = self._rosters.pop(stale_key)
                if stale.batch is not None:
                    stale.batch.valid = False
                    self._m_invalid.inc()
            return roster.streak >= self.lock_waves, roster

    @staticmethod
    def _materialize(resident) -> Tuple[Any, Any, Any]:
        m = getattr(resident, "materialize", None)
        return m() if m is not None else resident

    # -- stream-axis sharding (sharded/megabatch) --------------------------

    def _mesh_mgr(self):
        if self._mesh_manager != "auto":
            return self._mesh_manager  # explicit manager, or None = off
        from ..sharded import mesh as mesh_mod

        return mesh_mod.active_manager()

    def _stream_mesh(self, n_pad: int):
        """The ("streams",) mesh a batch of ``n_pad`` rows should shard
        over, or None for the single-device placement (no/degraded
        manager, or a batch axis the mesh does not divide)."""
        mgr = self._mesh_mgr()
        if mgr is None or not mgr.active or not mgr.streams_available:
            return None
        from ..sharded.megabatch import shardable

        mesh = mgr.streams_mesh()
        return mesh if shardable(mesh, n_pad) else None

    def _batch_mesh(self, n_pad: int):
        """The mesh a LOCKING batch should shard over, most capable
        rung first: the full 2-D ("streams", "p") mesh when the
        manager sits on the 2-D rung and the batch axis covers the
        flattened S*D grid (rows whole per chip over the entire pool
        — sharded/megabatch.place_batch2d), else the 1-D streams mesh,
        else None (single-device).  Returns ``(mesh, is2d)``."""
        mgr = self._mesh_mgr()
        if mgr is None or not mgr.active:
            return None, False
        from ..sharded.megabatch import shardable2d

        if mgr.mesh2d_available:
            mesh2d = mgr.mesh2d()
            if shardable2d(mesh2d, n_pad):
                return mesh2d, True
        return self._stream_mesh(n_pad), False

    def _degrade_mesh(self, reason: str) -> None:
        """A sharded flush failed: fall the PROCESS back to the
        single-device placement (the manager's ladder) — in-flight rows
        already resolve through the single-stream fallback."""
        mgr = self._mesh_mgr()
        if mgr is not None:
            mgr.degrade(reason)

    def _note_flush_cost(self, started: float, compiles_before: int) -> None:
        """EWMA of dispatch->readback wall time — the deadline-triage
        estimate of what one more full flush would cost a parked row.
        Plain float write (GIL-atomic); alpha 0.3 tracks regime shifts
        in a few waves without one outlier dominating.  A flush that
        compiled a fresh executable is excluded outright: folding a
        ~40 s compile into a millisecond-regime EWMA would reroute
        every tight-budget (critical) row to the inline path
        for the next ~10 waves — steady-state flushes never compile,
        so the sample carries no predictive value for the next wave."""
        if observability.compile_count() != compiles_before:
            return
        self._flush_cost_s += 0.3 * (
            (self._clock() - started) - self._flush_cost_s
        )

    # -- the three-stage dispatch ------------------------------------------

    def _staging_pair(self, k: Tuple, make: Callable[[], Any]):
        """Next of the two rotating staging buffers cached under ``k``
        (dense: (shape key, n_pad); delta: (shape key, n_pad, "delta"))
        — flusher-thread only."""
        pair = self._staging.get(k)
        if pair is None:
            pair = self._staging[k] = [make(), make(), 0, self._tick]
            if len(self._staging) > _MAX_STAGING:
                # Evict the stalest IDLE pair (both slots released by
                # their readbacks — never a pair with a wave in flight).
                idle = [
                    (p[3], key2) for key2, p in self._staging.items()
                    if key2 != k and p[0].ready.is_set()
                    and p[1].ready.is_set()
                ]
                if idle:
                    self._staging.pop(min(idle)[1])
        pair[3] = self._tick
        slot = pair[pair[2]]
        pair[2] ^= 1
        return slot

    def _staging_slot(
        self, key: Tuple, n_pad: int, bucket: int, dtype
    ) -> _StagingSlot:
        return self._staging_pair(
            (key, n_pad), lambda: _StagingSlot(n_pad, bucket, dtype)
        )

    def _delta_staging_slot(
        self, key: Tuple, n_pad: int, k_bucket: int
    ) -> _DeltaStagingSlot:
        return self._staging_pair(
            (key, n_pad, "delta"),
            lambda: _DeltaStagingSlot(n_pad, k_bucket),
        )

    def _stage_upload(
        self,
        rows: List[EpochSubmission],
        n_pad: int,
        row_of: Callable[[int], int],
        mesh=None,
    ):
        """Upload stage: fill a rotating staging buffer (row placement
        via ``row_of`` — wave order for re-stacks, the stable roster
        index for locked waves; pad rows stay zero-lag / 0.0-limit) and
        start the async H2D.  ``mesh`` (a stream-sharded locked batch's
        mesh) lands each row's slice directly on its device.  Returns
        (slot, lags_dev, limits_dev); the slot's ``ready`` is cleared
        and must be re-set by the wave's readback (or by the caller on
        a dispatch error)."""
        s0 = rows[0]
        slot = self._staging_slot(
            s0.shape_key, n_pad, s0.bucket, s0.payload.dtype
        )
        with metrics.span("coalesce.upload"):
            slot.ready.wait()  # prior wave's readback released it
            slot.ready.clear()
            slot.lags[:] = 0
            slot.limits[:] = 0.0
            for i, s in enumerate(rows):
                r = row_of(i)
                slot.lags[r, : s.payload.shape[0]] = s.payload
                slot.limits[r] = s.limit
            self._m_h2d_dense.inc(slot.lags.nbytes)
            try:
                if mesh is not None:
                    from ..sharded.megabatch import place_rows

                    lags_dev, limits_dev = place_rows(
                        mesh, slot.lags, slot.limits
                    )
                else:
                    lags_dev = jax.device_put(slot.lags)
                    limits_dev = jax.device_put(slot.limits)
            except Exception:
                slot.ready.set()
                raise
        return slot, lags_dev, limits_dev

    def _stage_delta_upload(
        self,
        rows: List[EpochSubmission],
        n_pad: int,
        row_of: Callable[[int], int],
        mesh=None,
    ):
        """Delta upload stage (locked waves only): fill the rotating
        [n_pad, K] index/value staging pair — per-row padding entries
        write index 0's new value (``payload[0]``), batch-padding rows
        write (0, 0) onto their all-zero lag rows — and start the async
        H2D.  O(N·K) bytes instead of the dense stage's O(N·B).  Same
        ``ready`` discipline as :meth:`_stage_upload`."""
        s0 = rows[0]
        slot = self._delta_staging_slot(s0.shape_key, n_pad, self.delta_k)
        with metrics.span("coalesce.upload"):
            slot.ready.wait()
            slot.ready.clear()
            slot.idx[:] = 0
            slot.vals[:] = 0
            slot.limits[:] = 0.0
            for i, s in enumerate(rows):
                r = row_of(i)
                n = s.delta_idx.shape[0]
                slot.idx[r, :n] = s.delta_idx
                slot.vals[r, :] = int(s.payload[0])
                slot.vals[r, :n] = s.delta_vals
                slot.limits[r] = s.limit
            self._m_h2d_delta.inc(slot.idx.nbytes + slot.vals.nbytes)
            try:
                if mesh is not None:
                    from ..sharded.megabatch import place_rows

                    idx_dev, vals_dev, limits_dev = place_rows(
                        mesh, slot.idx, slot.vals, slot.limits
                    )
                else:
                    idx_dev = jax.device_put(slot.idx)
                    vals_dev = jax.device_put(slot.vals)
                    limits_dev = jax.device_put(slot.limits)
            except Exception:
                slot.ready.set()
                raise
        return slot, idx_dev, vals_dev, limits_dev

    def _link_wave(self, wave, rows: List[EpochSubmission]) -> None:
        """Bidirectional fan-in links between the wave's own trace and
        every submitting request trace: each request trace records the
        wave it rode (``relation="wave"``) and the wave trace records
        every request it served (``relation="request"``) — including
        rows that later fall out through the single-row isolation path,
        whose re-dispatch still happened because of this wave."""
        wtr = getattr(wave, "trace", None)
        if wtr is None:
            return
        for s in rows:
            tr = (
                getattr(s.scope, "trace", None)
                if s.scope is not None else None
            )
            if tr is None:
                continue
            wtr.link(tr.trace_id, tr.root_span_id, relation="request")
            tr.link(wtr.trace_id, wtr.root_span_id, relation="wave")

    def _traced_wave(
        self,
        rows: List[EpochSubmission],
        dispatch: Callable[[], Callable[[], None]],
    ) -> Callable[[], None]:
        """Run ``dispatch`` (staging + device dispatch) and its returned
        readback job under ONE wave-rooted trace.  The wave spans two
        threads — the flusher stages/dispatches, the readback worker
        fetches — so each thread adopts the shared scope and the scope
        finishes exactly once: on the readback's exit, or here when the
        dispatch itself raises (the readback never runs; the submitters'
        isolation re-dispatches resolve under their own request traces
        because the wave scope is no longer active on the flusher)."""
        wave = metrics.begin_scope(kind="wave", root_name="coalesce.wave")
        self._link_wave(wave, rows)
        try:
            with metrics.adopt_scope(wave):
                inner = dispatch()
        except Exception:
            metrics.finish_scope(wave)
            raise

        def readback() -> None:
            try:
                with metrics.adopt_scope(wave):
                    inner()
            finally:
                metrics.finish_scope(wave)

        return readback

    def _dispatch_megabatch(
        self, rows: List[EpochSubmission]
    ) -> Callable[[], None]:
        """Upload + dispatch one multi-row group; returns the readback
        job (runs on the readback worker when pipelined)."""
        key = rows[0].shape_key
        with self._roster_lock:
            roster = self._rosters.get(key)
            batch = roster.batch if roster is not None else None
        if batch is not None and self._covers(batch, rows):
            with self._roster_lock:
                if roster is not None:
                    roster.last_used = self._tick
            return self._dispatch_locked(batch, rows)
        if batch is not None:
            # Roster churn (join/leave/poison/stale-rebuild): exactly
            # one invalidation, one re-stack wave, then re-lock.
            self._invalidate(key, batch)
        lock_now, roster = self._note_wave(key, rows)
        return self._dispatch_restack(rows, lock_now, roster)

    def _delta_wave_ok(self, rows: List[EpochSubmission]) -> bool:
        """True when this locked wave can dispatch the stacked [N, K]
        delta executable: the path is enabled, EVERY live row carries a
        delta plan that fits the configured K, and the padded delta
        staging is strictly smaller than the dense staging would be
        (same per-entry cost the inline byte gate uses)."""
        s0 = rows[0]
        return (
            self.delta_k > 0
            and all(
                s.delta_idx is not None
                and s.delta_idx.shape[0] <= self.delta_k
                for s in rows
            )
            and self.delta_k * _DELTA_ENTRY_BYTES
            < s0.bucket * s0.payload.dtype.itemsize
        )

    def _dispatch_locked(
        self, batch: _ResidentBatch, rows: List[EpochSubmission]
    ) -> Callable[[], None]:
        started = self._clock()
        compiles_before = observability.compile_count()
        s0 = rows[0]
        C = s0.num_consumers
        row_of = lambda i: rows[i].resident.row  # noqa: E731
        if batch.mesh is not None:
            # The sharded dispatch boundary: an injected (or real)
            # ``mesh.collective`` failure BEFORE any staging/donation
            # degrades the manager and raises — the batch is intact, so
            # _flush_group's isolation path resolves every row through
            # the single-stream executable (materializing its row from
            # the frozen batch) inside the same request budget, and the
            # next stable wave re-stacks on the single-device placement.
            mgr = self._mesh_mgr()
            if mgr is not None:
                mgr.check_collective()
        delta_wave = False
        slot = None
        if self._delta_wave_ok(rows):
            # Stacked delta flush (ISSUE 8): O(N·K) staged bytes onto
            # the batch's resident lag buffer.  The fault point fires
            # BEFORE staging — a failure here (or in staging) falls
            # back to the dense stage below with the batch untouched.
            try:
                faults.fire("delta.apply")
                slot, idx_dev, vals_dev, limits_dev = (
                    self._stage_delta_upload(
                        rows, batch.n_pad, row_of, mesh=batch.mesh
                    )
                )
                delta_wave = True
            except Exception:  # noqa: BLE001 — dense is the fallback
                LOGGER.warning(
                    "stacked delta staging failed; staging this wave "
                    "dense", exc_info=True,
                )
        if not delta_wave:
            slot, lags_dev, limits_dev = self._stage_upload(
                rows, batch.n_pad, row_of, mesh=batch.mesh
            )
            # Rows that PLANNED a delta but rode a dense wave (mixed
            # wave, oversized K, an injected staging fault) are
            # fallbacks: the hit-rate operators read must see them,
            # exactly once each.
            planned = sum(1 for s in rows if s.delta_idx is not None)
            if planned:
                self._m_delta_fallback.inc(planned)
        try:
            with metrics.span("coalesce.dispatch"):
                with batch.lock:
                    if delta_wave:
                        out = _megabatch_fused_locked_delta(
                            idx_dev, vals_dev, batch.lags, batch.choice,
                            batch.row_tab, batch.counts, limits_dev,
                            num_consumers=C, iters=s0.iters,
                            max_pairs=s0.max_pairs,
                            exchange_budget=s0.exchange_budget,
                        )
                    else:
                        out = _megabatch_fused_locked(
                            lags_dev, batch.choice, batch.row_tab,
                            batch.counts, limits_dev,
                            num_consumers=C, iters=s0.iters,
                            max_pairs=s0.max_pairs,
                            exchange_budget=s0.exchange_budget,
                        )
                    (narrow, choice_b, tab_b, counts_b, lags_b, totals,
                     rounds, ex, digest) = out
                    batch.adopt_resident_buffers(
                        choice_b, tab_b, counts_b, lags_b
                    )
        except Exception:
            self._poison(batch)  # donated state is unrecoverable
            if batch.mesh is not None:
                self._degrade_mesh("dispatch")
            slot.ready.set()
            raise
        self._m_hits.inc()
        self._record_flush(rows, batch.n_pad, roster=True)

        def readback() -> None:
            try:
                with metrics.span("coalesce.readback"):
                    with batch.lock:
                        with metrics.device_phase("megabatch"):
                            jax.block_until_ready(
                                (narrow, totals, rounds, ex)
                            )
                            narrow_np = np.asarray(narrow)
                            totals_np = np.asarray(totals)
                            counts_np = np.asarray(counts_b)
                            rounds_np = np.asarray(rounds)
                            ex_np = np.asarray(ex)
                            digest_np = np.asarray(digest)
                for s in rows:
                    r = s.resident.row
                    if s.future.done():
                        continue
                    if (
                        delta_wave
                        and s.lag_sum is not None
                        and int(totals_np[r].sum()) != s.lag_sum
                    ):
                        # Divergence check (the conservation law — see
                        # ops/streaming): this row's resident lag row
                        # drifted from its submitter's mirror.  The row
                        # falls out of the batch through the dense
                        # single-stream dispatch (which re-uploads its
                        # true payload); its engine then holds a
                        # concrete tuple, so the next wave re-stacks.
                        LOGGER.warning(
                            "delta wave row diverged from its host lag "
                            "sum; re-syncing the row dense"
                        )
                        self._m_delta_fallback.inc()
                        scrub_mod.record_quarantine(
                            ["lags"], "resynced", source="delta_wave"
                        )
                        self._resolve_single(s)
                        continue
                    if self._row_digest_failed(s, digest_np[r], batch):
                        if delta_wave:
                            # The planned epoch's one outcome: never
                            # applied (the row was quarantined).
                            self._m_delta_fallback.inc()
                        continue
                    if delta_wave:
                        # Counted HERE, after the divergence check, so
                        # applied + fallback sum to exactly one outcome
                        # per delta-planned epoch.
                        self._m_delta_applied.inc()
                    s.future.set_result(EpochResult(
                        narrow=narrow_np[r],
                        resident=s.resident,  # ownership stays batched
                        totals=totals_np[r],
                        counts=counts_np[r],
                        rounds=int(rounds_np[r]),
                        exchanges=int(ex_np[r]),
                    ))
                # Chaos injection (device.corrupt.*) at the readback
                # boundary: flip a seeded bit in one locked row's
                # freshly adopted stacked buffer — the integrity plane
                # (next wave's digest, or the scrubber's row audit)
                # must detect it.
                self._corrupt_resident_rows(batch, rows)
            except Exception:  # noqa: BLE001 — per-row outcome below
                LOGGER.warning(
                    "locked megabatch readback failed; poisoning the "
                    "resident batch", exc_info=True,
                )
                self._poison(batch)
                if batch.mesh is not None:
                    self._degrade_mesh("readback")
                for s in rows:
                    if not s.future.done():
                        if delta_wave:
                            self._m_delta_fallback.inc()
                        self._resolve_single(s)
            finally:
                self._note_flush_cost(started, compiles_before)
                slot.ready.set()

        return readback

    def _row_digest_failed(
        self, s: EpochSubmission, digest_row, batch
    ) -> bool:
        """Per-row integrity gate of a megabatch readback: compare the
        row's fused device digest against its submitter's host truth
        (utils/scrub).  On a mismatch the row's result is NEVER served:
        its future fails with :class:`CorruptStateDetected` (the
        submitter's engine quarantines and the service serves through
        the degraded ladder), and the roster is evicted exactly once —
        batchmates keep their results this wave and re-stack + re-lock
        on the next (the arrays freeze, so their handles stay
        materializable).  Returns True when the row was quarantined."""
        fails = scrub_mod.digest_failures(
            digest_row, s.payload.shape[0], s.lag_sum
        )
        if not fails:
            return False
        LOGGER.warning(
            "megabatch row digest FAILED (%s); quarantining the row "
            "and evicting the roster", ",".join(fails),
        )
        if batch is not None:
            self._invalidate(batch.shape_key, batch)
        if not s.future.done():
            s.future.set_exception(scrub_mod.CorruptStateDetected(
                f"megabatch row digest mismatch ({','.join(fails)}); "
                "row quarantined — the roster re-stacks and the "
                "stream heals from host truth",
                fails,
            ))
        return True

    def _corrupt_resident_rows(
        self, batch: _ResidentBatch, rows: List[EpochSubmission]
    ) -> None:
        """Chaos injection site (fault points ``device.corrupt.*``) for
        LOCKED megabatch rows: when a drill's plan fires, one seeded
        bit of the named stacked buffer is flipped in one real row —
        the submitting engine's host mirror is deliberately left
        intact, so the batch silently diverges exactly like a real
        device memory fault.  Zero-cost off (one global load)."""
        if faults.active() is None:
            return
        plan = scrub_mod.corruption_plan(limit=batch.n_real)
        if not plan:
            return
        with batch.lock:
            if not batch.valid or batch.poisoned:
                return
            arrays = {
                "choice": batch.choice,
                "row_tab": batch.row_tab,
                "counts": batch.counts,
                "lags": batch.lags,
            }
            for buffer, seed in plan:
                rng = np.random.default_rng(seed)
                # Pick the victim AMONG this wave's submissions so the
                # flip's real-prefix limit always comes from the row's
                # OWN payload — a roster row with no submitter here
                # would otherwise be scoped by an unrelated stream's
                # length and could flip only padding (undetectable by
                # design, a false bench failure).
                sub = rows[int(rng.integers(len(rows)))]
                r = sub.resident.row
                limit = (
                    None if buffer in ("counts", "row_tab")
                    else sub.payload.shape[0]
                )
                arr = arrays[buffer]
                flipped = scrub_mod.flip_bit(
                    np.asarray(arr[r]), seed + 1, limit=limit
                )
                arrays[buffer] = arr.at[r].set(flipped)
                LOGGER.warning(
                    "injected device.corrupt.%s bit flip into locked "
                    "row %d (seed %d)", buffer, r, seed,
                )
            batch.adopt_resident_buffers(
                arrays["choice"], arrays["row_tab"], arrays["counts"],
                arrays["lags"],
            )

    def _dispatch_restack(
        self,
        rows: List[EpochSubmission],
        lock_now: bool,
        roster: _Roster,
    ) -> Callable[[], None]:
        started = self._clock()
        compiles_before = observability.compile_count()
        s0 = rows[0]
        N = len(rows)
        C = s0.num_consumers
        # Batch-axis bucket: pad to a power of two so the executable
        # count per shape bucket stays log2(max_batch).  Padding rows
        # cycle the SURVIVING rows' buffers (never a dropped stream's)
        # and run at zero lags / 0.0 limit — bit-exact pass-through.
        n_pad = 1 << (N - 1).bit_length()
        residents = [self._materialize(s.resident) for s in rows]
        padded = residents + [
            residents[i % N] for i in range(n_pad - N)
        ]
        slot, lags_dev, limits_dev = self._stage_upload(
            rows, n_pad, lambda i: i
        )
        try:
            with metrics.span("coalesce.dispatch"):
                out = _megabatch_fused_resident(
                    lags_dev,
                    tuple(r[0] for r in padded),
                    tuple(r[1] for r in padded),
                    tuple(r[2] for r in padded),
                    limits_dev,
                    num_consumers=C, iters=s0.iters,
                    max_pairs=s0.max_pairs,
                    exchange_budget=s0.exchange_budget,
                )
        except Exception:
            slot.ready.set()
            raise
        self._m_restack.inc()
        # Delta-planned rows riding a re-stack (churn) wave stage dense:
        # count their fallback outcome here so applied + fallback still
        # sum to exactly one outcome per planned epoch (the hit-rate's
        # denominator stays honest through churn).
        planned = sum(1 for s in rows if s.delta_idx is not None)
        if planned:
            self._m_delta_fallback.inc(planned)
        (narrow, choice_b, tab_b, counts_b, lags_b, totals, rounds, ex,
         digest) = out
        batch: Optional[_ResidentBatch] = None
        handles: Optional[List[ResidentRow]] = None
        if lock_now:
            # The roster locks: this wave's stacked successors BECOME
            # the resident batch (the widened lag rows included — the
            # stacked delta path scatters into them); rows' ownership
            # moves to it.  With an active mesh the successors are
            # sharded over it ONCE here (sharded/megabatch) — the full
            # 2-D ("streams", "p") placement when the manager's rung
            # and both axes allow, else stream-axis only — and the
            # locked executable then donates sharded buffers and
            # returns sharded successors, so the steady state pays no
            # per-flush re-placement; a placement failure locks
            # single-device and degrades the manager.
            mesh, is2d = self._batch_mesh(n_pad)
            if mesh is not None:
                try:
                    from ..sharded.megabatch import (
                        place_batch,
                        place_batch2d,
                    )

                    place = place_batch2d if is2d else place_batch
                    choice_b, tab_b, counts_b, lags_b = place(
                        mesh, (choice_b, tab_b, counts_b, lags_b)
                    )
                except Exception:  # noqa: BLE001 — single-device locks
                    LOGGER.warning(
                        "%s placement failed; locking the roster on "
                        "the single-device placement",
                        "cross-axis" if is2d else "stream-axis",
                        exc_info=True,
                    )
                    self._degrade_mesh("place")
                    mesh = None
            batch = _ResidentBatch(
                s0.shape_key, choice_b, tab_b, counts_b, lags_b,
                n_real=N, mesh=mesh,
            )
            handles = [ResidentRow(batch, i) for i in range(N)]
            with self._roster_lock:
                roster.batch = batch
        self._record_flush(rows, n_pad, roster=False)

        def readback() -> None:
            try:
                with metrics.span("coalesce.readback"):
                    with metrics.device_phase("megabatch"):
                        jax.block_until_ready((narrow, totals, rounds, ex))
                        narrow_np = np.asarray(narrow)
                        totals_np = np.asarray(totals)
                        counts_np = np.asarray(counts_b)
                        rounds_np = np.asarray(rounds)
                        ex_np = np.asarray(ex)
                        digest_np = np.asarray(digest)
                for i, s in enumerate(rows):
                    if s.future.done():
                        continue
                    if self._row_digest_failed(s, digest_np[i], batch):
                        continue
                    # Unlocked waves slice per-row resident successors
                    # out of the batch output (the 4N gathers the locked
                    # fast path exists to eliminate).
                    resident = (
                        handles[i] if handles is not None
                        else (choice_b[i], tab_b[i], counts_b[i],
                              lags_b[i])
                    )
                    s.future.set_result(EpochResult(
                        narrow=narrow_np[i],
                        resident=resident,
                        totals=totals_np[i],
                        counts=counts_np[i],
                        rounds=int(rounds_np[i]),
                        exchanges=int(ex_np[i]),
                    ))
            except Exception:  # noqa: BLE001 — per-row outcome below
                LOGGER.warning(
                    "megabatch readback failed; isolating rows via "
                    "single-stream dispatch", exc_info=True,
                )
                if batch is not None:
                    self._poison(batch)
                for s in rows:
                    if not s.future.done():
                        self._resolve_single(s)
            finally:
                self._note_flush_cost(started, compiles_before)
                slot.ready.set()

        return readback

    def _record_flush(
        self, rows: List[EpochSubmission], n_pad: int, roster: bool
    ) -> None:
        s0 = rows[0]
        metrics.FLIGHT.record(
            "coalesce_flush",
            {
                "streams": len(rows),
                "padded_rows": n_pad,
                "bucket": s0.bucket,
                "consumers": s0.num_consumers,
                "roster_locked": roster,
                # SLO placement audit: the wave's classes in placement
                # order — a critical row showing up behind a
                # best-effort one here is the bug the ordered flush
                # exists to prevent.
                "classes": [s.klass for s in rows],
                "request_ids": [
                    s.scope.request_id for s in rows
                    if s.scope is not None
                ],
                "trace_ids": [
                    s.scope.trace.trace_id for s in rows
                    if s.scope is not None
                    and getattr(s.scope, "trace", None) is not None
                ],
            },
        )

    def _resolve_single(self, s: EpochSubmission) -> None:
        """One epoch on the SINGLE-stream resident executable — the
        single-row flush and the per-row isolation fallback (both reuse
        the exact executable the inline path warmed, so neither costs a
        fresh compile).  A handle resident materializes its row first
        (the stream leaves the batch).  Never raises: the outcome —
        result or the row's own exception — lands on the future.
        Adopts the submitter's request scope so solve-side telemetry
        keeps its request id."""
        with metrics.adopt_scope(s.scope):
            try:
                choice, row_tab, counts = self._materialize(s.resident)[:3]
                self._m_h2d_dense.inc(s.payload.nbytes)
                out = _warm_fused_resident(
                    s.payload, choice, row_tab, counts, s.limit,
                    num_consumers=s.num_consumers, iters=s.iters,
                    max_pairs=s.max_pairs,
                    exchange_budget=s.exchange_budget,
                )
                (narrow, choice_p, row_tab, counts, lags_p, totals,
                 rounds, ex, digest) = out
                if self._row_digest_failed(s, np.asarray(digest), None):
                    return
                s.future.set_result(
                    EpochResult(
                        narrow=np.asarray(narrow),
                        resident=(choice_p, row_tab, counts, lags_p),
                        totals=np.asarray(totals),
                        counts=np.asarray(counts),
                        rounds=int(rounds),
                        exchanges=int(ex),
                    )
                )
            except Exception as exc:  # noqa: BLE001 — the row's own error
                LOGGER.warning(
                    "coalesced single-row dispatch failed", exc_info=True
                )
                s.future.set_exception(exc)
