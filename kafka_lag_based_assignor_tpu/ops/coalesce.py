"""Cross-stream megabatch coalescer: ONE vmapped resident dispatch for
N concurrent consumer groups.

The streaming engine (ops/streaming.py) serves one consumer group per
rebalance, and each warm epoch that needs quality work costs one fused
device dispatch.  That is the right shape for a lone tenant — but a
sidecar serving 32 concurrent groups pays 32 serialized device
round-trips per rebalance wave even though the fused refine core is
shape-static and the epochs are independent.  On a tunneled/remote
accelerator the round-trip IS the cost (BASELINE.md: ~1.5 ms warm no-op
vs ~40+ ms dispatch+readback), so the multi-tenant fix is the
FlashSinkhorn playbook applied across tenants instead of within one:
amortize dispatch and H2D over every stream that is ready to go.

Mechanism
---------

:class:`MegabatchCoalescer` keeps a queue of pending epoch submissions
(:class:`EpochSubmission`: the exact-shape lag payload plus the stream's
device-resident ``(choice, row_tab, counts)`` warm state and its static
refine arguments).  A dedicated flusher thread admits submissions for a
short window (sub-millisecond by default; ``max_batch`` pending epochs
in one shape group flush immediately), then groups them by SHAPE BUCKET
— ``(padded P bucket, C, payload dtype, iters, max_pairs,
exchange_budget)``, everything that is a static argument of the fused
executable — and dispatches each multi-row group as ONE
:func:`_megabatch_fused_resident` call: the per-stream resident buffers
are stacked on a new leading batch axis INSIDE the executable and
``jax.vmap`` runs the exact single-stream warm core
(totals re-derivation, quality-target test, the resident bulk-exchange
round loop) over every row in one dispatch.  The batch's host-facing
outputs come back in ONE device->host fetch; the resident successors
stay on device and are handed back to each engine as rows of the batch
output.

Submitters park on a :class:`concurrent.futures.Future`
(:meth:`StreamingAssignor.submit_epoch` blocks on it inside the same
watchdog deadline that guards an inline dispatch), so the degraded-mode
ladder, per-solver breakers, and poisoned-stream handling from round 7
are untouched — they wrap the submit exactly as they wrapped the inline
call.

Isolation: a poisoned row falls OUT of the batch
------------------------------------------------

A flush that fails (an injected ``coalesce.flush`` fault, a megabatch
dispatch error) never fails its batchmates wholesale: every row of the
failed group re-dispatches the already-warmed SINGLE-stream resident
executable on its own, and only a row whose own dispatch fails sees an
exception on its future.  A single-row flush (window expired with one
submission, or the service's single-stream bypass never reaches here)
uses that same single-stream executable — zero extra compiles for the
lone-tenant path.

Executable-cache discipline: one megabatch executable per (shape bucket,
batch bucket) — the batch axis pads to a power of two (short groups
repeat their first row; padding results are discarded), so the compile
count per shape bucket is log2(max_batch), not one per group size.

Telemetry (utils/metrics): ``klba_coalesce_batch_size`` histogram (true
group size per flush), ``klba_coalesce_flushes_total{path=megabatch|
single|fallback}``, the ``coalesce.window`` / ``coalesce.dispatch``
spans, and a ``coalesce_flush`` flight record carrying the request ids
captured at submit time (``metrics.capture_scope``) so a flushed batch
is correlatable with every wire request it served.  Per-row fallback
dispatches adopt the submitting request's scope, keeping solve-side
telemetry tagged with the right request id.
"""

from __future__ import annotations

import functools
import logging
import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..utils import faults, metrics
from .batched import _narrow_choice
from .refine import refine_rounds_resident
from .streaming import _warm_fused_resident

LOGGER = logging.getLogger(__name__)


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_consumers", "iters", "max_pairs", "exchange_budget"
    ),
)
def _megabatch_fused_resident(
    lags, choices, row_tabs, counts, limits, num_consumers: int,
    iters: int, max_pairs, exchange_budget: int,
):
    """THE megabatch executable: N streams' fused warm epochs in ONE
    dispatch.

    ``lags`` is the host-stacked ``[N, B]`` padded payload (the only
    host->device transfer); ``choices``/``row_tabs``/``counts`` are
    length-N tuples of the per-stream DEVICE-resident buffers, stacked
    here INSIDE the executable so the gather into batch form fuses with
    the refine instead of costing N small host-side dispatches;
    ``limits`` is the per-row quality target (dynamic, ``[N]``).  The
    body vmaps the exact single-stream warm core
    (:func:`..ops.streaming._warm_fused_resident` minus its pad, which
    the host already applied): re-derive per-consumer totals under the
    new lags from the resident table, test against the target, run the
    resident bulk-exchange round loop.  ``vmap`` of the ``while_loop``
    runs until every row's exit condition holds, masking finished rows
    — each row's result is bit-identical to its single-stream dispatch
    (pinned by tests/test_coalesce.py).

    Returns ``(narrow [N, B], choice int32 [N, B], row_tab [N, C, M],
    counts [N, C], totals [N, C], rounds [N], exchanges [N])`` — narrow
    plus the stats rows are the host-facing fetch; the middle three stay
    device-resident as every stream's successor state."""
    choice = jnp.stack(choices)
    row_tab = jnp.stack(row_tabs)
    cnt = jnp.stack(counts)

    def one(lags_b, choice_b, tab_b, counts_b, limit):
        B = choice_b.shape[0]
        M = tab_b.shape[1]
        lags64 = lags_b.astype(jnp.int64)
        slot_ok = (
            jnp.arange(M, dtype=jnp.int32)[None, :] < counts_b[:, None]
        )
        totals = jnp.where(
            slot_ok, lags64[jnp.clip(tab_b, 0, B - 1)], 0
        ).sum(axis=1)
        choice_b, tab_b, counts_b, totals, rounds, ex = (
            refine_rounds_resident(
                lags64, choice_b, tab_b, counts_b, totals,
                num_consumers=num_consumers, iters=iters,
                max_pairs=max_pairs, exchange_budget=exchange_budget,
                quality_limit=limit, bulk_transfer=True, fan=8,
            )
        )
        narrow = _narrow_choice(choice_b, num_consumers)
        return narrow, choice_b, tab_b, counts_b, totals, rounds, ex

    return jax.vmap(one)(lags, choice, row_tab, cnt, limits)


class EpochResult(NamedTuple):
    """One stream's share of a flush: host-facing outputs materialized,
    resident successors still on device (rows of the batch buffers)."""

    narrow: np.ndarray  # int16-ish [B] padded choice (slice [:P] yourself)
    resident: Tuple[Any, Any, Any]  # device (choice, row_tab, counts)
    totals: np.ndarray  # int64 [C] per-consumer totals under the new lags
    counts: np.ndarray  # int32 [C]
    rounds: int
    exchanges: int


@dataclass
class EpochSubmission:
    """One stream's pending warm epoch (see the module docstring)."""

    payload: np.ndarray  # exact-shape [P] lags, already dtype-downcast
    bucket: int  # padded refine shape B (the engine's _bucket(P))
    choice: Any  # device-resident int32[B]
    row_tab: Any  # device-resident int32[C, M]
    counts: Any  # device-resident int32[C]
    limit: float  # device-side quality target (negative disables)
    num_consumers: int
    iters: int
    max_pairs: int
    exchange_budget: int
    scope: Any = None  # metrics.capture_scope() token of the submitter
    future: Future = field(default_factory=Future)
    enqueued_at: float = 0.0

    @property
    def shape_key(self) -> Tuple:
        """Everything that selects a distinct fused executable: only
        submissions agreeing on ALL of it can share a megabatch."""
        return (
            self.bucket, self.num_consumers, self.payload.dtype.str,
            self.iters, self.max_pairs, self.exchange_budget,
        )


class MegabatchCoalescer:
    """Admission-window device-dispatch coalescer (module docstring).

    ``window_s`` is the admission window measured from the OLDEST
    pending submission; ``max_batch`` pending epochs in one shape group
    flush immediately.  The flusher is a lazily started daemon thread —
    a coalescer that never sees a submission costs nothing.  A wedged
    device inside a flush blocks only the flusher (submitters' watchdog
    deadlines still fire and their requests descend the degraded-mode
    ladder on fresh engines, exactly like an abandoned inline solve).
    """

    def __init__(self, window_s: float = 0.0005, max_batch: int = 32):
        if window_s < 0:
            raise ValueError(f"window_s={window_s} must be >= 0")
        if max_batch < 1:
            raise ValueError(f"max_batch={max_batch} must be >= 1")
        self.window_s = float(window_s)
        self.max_batch = int(max_batch)
        self._cond = threading.Condition()
        self._pending: List[EpochSubmission] = []
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._clock = metrics.REGISTRY.clock
        # Pre-bound series: flushes run on the hot multi-tenant path.
        self._m_batch = metrics.REGISTRY.histogram(
            "klba_coalesce_batch_size"
        )
        self._m_path = {
            p: metrics.REGISTRY.counter(
                "klba_coalesce_flushes_total", {"path": p}
            )
            for p in ("megabatch", "single", "fallback")
        }

    # -- submission --------------------------------------------------------

    def submit(self, sub: EpochSubmission) -> Future:
        """Enqueue one epoch; returns the future its flush resolves.
        Raises RuntimeError after :meth:`close` (the caller's ladder
        then degrades exactly as for any failed dispatch)."""
        with self._cond:
            if self._closed:
                raise RuntimeError("megabatch coalescer is closed")
            sub.enqueued_at = self._clock()
            self._pending.append(sub)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="klba-coalesce", daemon=True
                )
                self._thread.start()
            self._cond.notify_all()
        return sub.future

    def pending(self) -> int:
        with self._cond:
            return len(self._pending)

    def close(self) -> None:
        """Stop admitting; the flusher drains what is already queued
        (futures resolve) and exits."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- the flusher -------------------------------------------------------

    def _largest_group(self) -> int:
        """Max same-shape-bucket pending count (caller holds the lock)."""
        tally: Dict[Tuple, int] = {}
        best = 0
        for s in self._pending:
            n = tally.get(s.shape_key, 0) + 1
            tally[s.shape_key] = n
            if n > best:
                best = n
        return best

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if not self._pending:
                    return  # closed and drained
                if not self._closed and self.window_s > 0:
                    # Admission window from the OLDEST submission; a
                    # full shape group short-circuits it.
                    with metrics.span("coalesce.window"):
                        deadline = (
                            self._pending[0].enqueued_at + self.window_s
                        )
                        while not self._closed:
                            if self._largest_group() >= self.max_batch:
                                break
                            remaining = deadline - self._clock()
                            if remaining <= 0:
                                break
                            self._cond.wait(remaining)
                batch, self._pending = self._pending, []
            try:
                self._flush(batch)
            except Exception as exc:  # noqa: BLE001 — delivered to waiters
                LOGGER.warning("coalescer flush crashed", exc_info=True)
                for s in batch:
                    if not s.future.done():
                        s.future.set_exception(exc)

    def _flush(self, batch: List[EpochSubmission]) -> None:
        groups: Dict[Tuple, List[EpochSubmission]] = {}
        for s in batch:
            groups.setdefault(s.shape_key, []).append(s)
        for group in groups.values():
            # Enforce the batch cap HERE, not only at the window break:
            # a group that outgrew max_batch while the flusher was busy
            # (or because a whole 64-stream fleet rebalanced at once)
            # flushes as max_batch-sized chunks — never padding past the
            # cap into a fresh, bigger executable on the serving path.
            for i in range(0, len(group), self.max_batch):
                self._flush_group(group[i: i + self.max_batch])

    def _flush_group(self, rows: List[EpochSubmission]) -> None:
        self._m_batch.observe(len(rows))
        path = "single"
        try:
            faults.fire("coalesce.flush")
            if len(rows) > 1:
                self._dispatch_megabatch(rows)
                self._m_path["megabatch"].inc()
                return
        except Exception:  # noqa: BLE001 — isolated below, per row
            # Poisoned-ROW isolation: the batch is not poisoned by
            # one bad row (or a flush-level fault) — every row
            # re-dispatches the single-stream executable on its own
            # and only a row whose OWN dispatch fails sees an error.
            LOGGER.warning(
                "coalesced flush of %d epoch(s) failed; isolating "
                "rows via single-stream dispatch",
                len(rows), exc_info=True,
            )
            path = "fallback"
        self._m_path[path].inc()
        for s in rows:
            if not s.future.done():
                self._resolve_single(s)

    def _dispatch_megabatch(self, rows: List[EpochSubmission]) -> None:
        s0 = rows[0]
        B, C = s0.bucket, s0.num_consumers
        N = len(rows)
        # Batch-axis bucket: pad to a power of two so the executable
        # count per shape bucket stays log2(max_batch).  Padding rows
        # repeat row 0's buffers; their results are dropped.
        n_pad = 1 << (N - 1).bit_length()
        lags = np.zeros((n_pad, B), dtype=s0.payload.dtype)
        limits = np.full(n_pad, s0.limit, dtype=np.float64)
        for i, s in enumerate(rows):
            lags[i, : s.payload.shape[0]] = s.payload
            limits[i] = s.limit
        padded = rows + [s0] * (n_pad - N)
        with metrics.span("coalesce.dispatch"):
            out = _megabatch_fused_resident(
                lags,
                tuple(s.choice for s in padded),
                tuple(s.row_tab for s in padded),
                tuple(s.counts for s in padded),
                limits,
                num_consumers=C, iters=s0.iters,
                max_pairs=s0.max_pairs,
                exchange_budget=s0.exchange_budget,
            )
            narrow, choice_b, tab_b, counts_b, totals, rounds, ex = out
            # ONE bulk device->host fetch covers every row's host-facing
            # outputs (the serialized per-stream round-trips this module
            # exists to amortize); the resident successors stay on
            # device as rows of the batch buffers.
            narrow = np.asarray(narrow)
            totals_np = np.asarray(totals)
            counts_np = np.asarray(counts_b)
            rounds_np = np.asarray(rounds)
            ex_np = np.asarray(ex)
        metrics.FLIGHT.record(
            "coalesce_flush",
            {
                "streams": N,
                "padded_rows": n_pad,
                "bucket": B,
                "consumers": C,
                "request_ids": [
                    s.scope.request_id for s in rows
                    if s.scope is not None
                ],
            },
        )
        for i, s in enumerate(rows):
            s.future.set_result(
                EpochResult(
                    narrow=narrow[i],
                    resident=(choice_b[i], tab_b[i], counts_b[i]),
                    totals=totals_np[i],
                    counts=counts_np[i],
                    rounds=int(rounds_np[i]),
                    exchanges=int(ex_np[i]),
                )
            )

    def _resolve_single(self, s: EpochSubmission) -> None:
        """One epoch on the SINGLE-stream resident executable — the
        single-row flush and the per-row isolation fallback (both reuse
        the exact executable the inline path warmed, so neither costs a
        fresh compile).  Never raises: the outcome — result or the
        row's own exception — lands on the future.  Adopts the
        submitter's request scope so solve-side telemetry keeps its
        request id."""
        with metrics.adopt_scope(s.scope):
            try:
                out = _warm_fused_resident(
                    s.payload, s.choice, s.row_tab, s.counts, s.limit,
                    num_consumers=s.num_consumers, iters=s.iters,
                    max_pairs=s.max_pairs,
                    exchange_budget=s.exchange_budget,
                )
                narrow, choice_p, row_tab, counts, totals, rounds, ex = out
                s.future.set_result(
                    EpochResult(
                        narrow=np.asarray(narrow),
                        resident=(choice_p, row_tab, counts),
                        totals=np.asarray(totals),
                        counts=np.asarray(counts),
                        rounds=int(rounds),
                        exchanges=int(ex),
                    )
                )
            except Exception as exc:  # noqa: BLE001 — the row's own error
                LOGGER.warning(
                    "coalesced single-row dispatch failed", exc_info=True
                )
                s.future.set_exception(exc)
