"""Streaming rebalance with warm start — the BASELINE config-5 loop.

The reference is stateless across generations (SURVEY §2.4.8): every
rebalance re-solves from scratch, so two consecutive rebalances under
slightly drifted lags can reshuffle many partitions (assignment churn =
state invalidation for the consumers).  The streaming engine keeps the
previous choice vector as a warm start (SURVEY §5 checkpoint/resume row —
the optional warm start for the streaming-rebalance benchmark):

* **cold start / shape change / guardrail trip** — full solve with the
  transfer-lean :func:`..ops.batched.assign_stream` path plus a
  quality-refinement pass (churn is unbounded on cold paths anyway, and
  refining makes a guardrail trip actually restore near-bound quality
  rather than resetting to plain greedy's slack);
* **warm rebalance** — keep the previous assignment; first evaluate its
  quality under the NEW lags host-side (one weighted bincount, ~1 ms at
  P=100k).  If the max/mean imbalance is still within
  ``refine_threshold`` of the input-driven bound, the epoch is a
  **no-op**: zero churn, zero device traffic — a rebalance that would
  move nothing should cost nothing (the reference re-solves O(P*C) every
  time regardless).  Otherwise dispatch one round-trip of the parallel
  pairwise-exchange refinement (:mod:`.refine`) under the new lags.  The
  count invariant is preserved by construction, imbalance is
  re-tightened, and only the exchanges' partitions move — ``refine_iters``
  is a total *exchange budget*, split into rounds of up to ``C // 2``
  concurrent disjoint exchanges, so churn is bounded by 2 x refine_iters
  instead of O(P).

  The refine dispatch itself is transfer-lean: the previous choice vector
  lives **device-resident** between refines (it is the engine's own
  state — re-uploading it every epoch would double the payload), lags
  upload as int32 when their range allows (as the cold path does), and
  the validity mask is derived on device from the static shape, so the
  round trip carries only the new lag vector in and the narrow choice
  out.

* **membership change** — :meth:`StreamingAssignor.remap_members` carries
  the warm state across a join/leave (the usual rebalance trigger, where
  the stateless reference reshuffles O(P) partitions): surviving members
  keep their partitions, a host-side repair pass re-seats only orphaned
  rows and capacity overflow (count-primary greedy over the moving rows),
  and the exchange refinement re-tightens balance — churn bounded by
  ``repaired_rows + 2 * refine_iters``.

The churn/quality trade-off is configurable per rebalance via
``refine_iters``.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..utils.observability import count_constrained_bound
from .batched import _narrow_choice, _stream_device, assign_stream, stream_payload
from .dispatch import ensure_x64, observe_pack_shift
from .packing import pad_bucket, pad_chunk
from .refine import refine_assignment


@dataclass
class StreamingStats:
    cold_start: bool = False
    guardrail_tripped: bool = False  # warm quality fell past the guardrail
    refined: bool = False  # a device refine dispatch ran this epoch
    churn: int = 0  # partitions whose consumer changed vs previous epoch
    repaired_rows: int = 0  # rows re-seated by the membership repair pass
    max_mean_imbalance: float = 1.0
    imbalance_bound: float = 1.0  # input-driven lower bound max_lag/mean
    count_spread: int = 0


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_consumers", "pack_shift", "iters", "max_pairs", "bucket",
        "interpret", "wide",
    ),
)
def _pallas_cold_chain(
    lags, num_consumers: int, pack_shift: int, iters: int, max_pairs,
    bucket: int, interpret: bool = False, wide: bool = False,
):
    """Cold solve -> refine as ONE dispatch with the Pallas round scan
    (the in-VMEM variant of :meth:`StreamingAssignor._cold_solve`'s
    chained path).  Same contract as solve + :func:`_refine_chain`:
    exact-shape lags in, (narrow choice[P], padded refined int32[bucket]
    kept device-resident by the caller) out.  Callers must have passed
    BOTH Pallas gates host-side."""
    from .batched import _pallas_solve_padded

    P = lags.shape[0]
    lags_p, valid, choice = _pallas_solve_padded(
        lags, int(bucket), num_consumers, pack_shift, wide,
        interpret=interpret,
    )
    refined, _, _ = refine_assignment(
        lags_p, valid, choice, num_consumers=num_consumers,
        iters=iters, max_pairs=max_pairs,
    )
    return _narrow_choice(refined[:P], num_consumers), refined


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "iters", "max_pairs", "bucket")
)
def _refine_chain(
    lags, choice, num_consumers: int, iters: int, max_pairs, bucket: int
):
    """One-dispatch refine over an exact-shape lag upload.

    ``lags`` is the exact [P] vector (int32 when the host downcast it,
    widened back here); ``choice`` is EITHER the device-resident padded
    int32[bucket] kept from the previous refine (no upload at all) or an
    exact-shape [P] start (the cold chain feeds assign_stream's narrow
    output without a host round-trip).  Padding and the validity mask are
    derived on device from the static shapes, so neither is transferred.

    Returns (narrow choice[P] — the one output the host materializes —
    and the padded refined int32[bucket], which the caller keeps
    device-resident for the next epoch).
    """
    P = lags.shape[0]
    B = int(bucket)
    lags_p = jnp.pad(lags.astype(jnp.int64), (0, B - P))
    if choice.shape[0] == B and choice.dtype == jnp.int32:
        choice_p = choice
    else:
        choice_p = jnp.pad(
            choice.astype(jnp.int32), (0, B - P), constant_values=-1
        )
    valid = jnp.arange(B, dtype=jnp.int32) < P
    refined, _, _ = refine_assignment(
        lags_p, valid, choice_p, num_consumers=num_consumers,
        iters=iters, max_pairs=max_pairs,
    )
    return _narrow_choice(refined[:P], num_consumers), refined


class StreamingAssignor:
    """Stateful engine for one topic's periodic rebalance at fixed scale.

    ``imbalance_guardrail`` bounds how far the bounded-churn warm path may
    drift from balance across epochs: after a warm rebalance, if
    ``max_mean_imbalance > guardrail * max(input bound, 1)`` the epoch is
    re-solved cold — greedy plus a refinement pass, so the trip restores
    near-bound quality (unbounded churn for that epoch).  ``None``
    disables the guardrail (pure bounded-churn behavior).
    """

    def __init__(
        self,
        num_consumers: int,
        refine_iters: int = 128,
        imbalance_guardrail: Optional[float] = None,
        # Refinement budget for cold solves (initial epoch, shape change,
        # guardrail trip): churn is unbounded on those paths anyway, and
        # refining makes a guardrail trip actually restore near-bound
        # quality instead of resetting to plain greedy's slack (observed
        # ratio 1.63 unrefined vs ~1.0x refined on a lognormal soak).
        # 0 disables (cold solves return plain greedy).
        cold_refine_iters: int = 64,
        # Warm epochs whose KEPT assignment still scores within this factor
        # of the input-driven bound skip the refine dispatch entirely —
        # zero churn, zero device traffic (see the module docstring).  1.02
        # sits well inside the framework's 1.05 quality target while
        # making steady-drift epochs ~free; None always refines.
        refine_threshold: Optional[float] = 1.02,
    ):
        self.num_consumers = int(num_consumers)
        self.refine_iters = int(refine_iters)
        self.cold_refine_iters = int(cold_refine_iters)
        if imbalance_guardrail is not None and imbalance_guardrail < 1.0:
            raise ValueError(
                f"imbalance_guardrail={imbalance_guardrail} must be >= 1.0"
            )
        if refine_threshold is not None and refine_threshold < 1.0:
            raise ValueError(
                f"refine_threshold={refine_threshold} must be >= 1.0"
            )
        self.imbalance_guardrail = imbalance_guardrail
        self.refine_threshold = refine_threshold
        self._prev_choice: Optional[np.ndarray] = None
        # Padded int32[bucket] copy of the previous choice, kept on device
        # between refines so a warm dispatch doesn't re-upload the
        # engine's own state.  None = stale (host-side edits happened).
        self._choice_dev = None
        self.last_stats = StreamingStats()

    def rebalance(self, lags: np.ndarray) -> np.ndarray:
        """Produce choice int32[P] for the current lag vector."""
        ensure_x64()  # int64 lags would silently downcast to int32 otherwise
        lags = np.ascontiguousarray(lags, dtype=np.int64)
        if lags.size and int(lags.min()) < 0:
            # Non-negative lags are a documented precondition of every
            # kernel downstream (packed sort keys, the int32 upload
            # downcast) AND of the exact_bincount guard below — with mixed
            # signs, cancellation can keep the f64 total small while
            # per-consumer partial sums exceed 2^53, making the fast
            # weighted bincount silently inexact.  The reference's lag
            # formula clamps at 0, so a negative lag here is a caller bug.
            raise ValueError("lags must be non-negative")
        P = lags.shape[0]
        stats = StreamingStats()

        # Input-driven quantities that cannot change within one rebalance:
        # computed once, shared by every quality evaluation below.
        bound = count_constrained_bound(lags, self.num_consumers)
        # f64 sum for the guard: an int64 sum could wrap past 2^63 and
        # spuriously select the inexact path in exactly the regime where
        # the exact fallback matters (f64 cannot wrap, only round — fine
        # for a > / < threshold check at the 2^53 boundary).
        exact_bincount = float(lags.sum(dtype=np.float64)) < float(1 << 53)

        prev = self._prev_choice
        if prev is None or prev.shape[0] != P:
            stats.cold_start = True
            choice = self._cold_solve(lags)
            prev_for_churn = None
            self._fill_quality_stats(stats, choice, lags, bound,
                                     exact_bincount)
        else:
            # Membership repair: after remap_members the previous choice
            # may hold orphaned rows (-1, owner left) or counts above the
            # new ceiling (group shrank/grew).  Re-seat ONLY the moving
            # rows host-side.  Repair is not an exchange — orphaned rows
            # must be owned regardless of the refine budget (the churn
            # bound reads repaired_rows + 2 * refine_iters).
            prev_for_churn = prev  # churn counts repair moves too
            choice, stats.repaired_rows = self._repair_choice(prev, lags)
            if stats.repaired_rows:
                self._choice_dev = None  # device copy is stale now

            # Evaluate the KEPT assignment under the new lags (host-side,
            # one weighted bincount) and dispatch the refinement only when
            # it is actually needed: a still-balanced epoch is a no-op —
            # zero churn, zero device traffic.
            self._fill_quality_stats(stats, choice, lags, bound,
                                     exact_bincount)
            needs_refine = self.refine_iters > 0 and (
                self.refine_threshold is None
                or stats.max_mean_imbalance
                > self.refine_threshold * max(stats.imbalance_bound, 1.0)
            )
            if needs_refine:
                choice = self._dispatch_warm_refine(lags, choice)
                stats.refined = True
                self._fill_quality_stats(stats, choice, lags, bound,
                                         exact_bincount)

        # Quality guardrail: a warm epoch whose imbalance drifted past the
        # allowance re-solves cold (the churn bound intentionally yields).
        # If the threshold skipped the bounded refine this epoch (possible
        # when the guardrail is tighter than refine_threshold), try the
        # cheap bounded-churn refine FIRST — only an epoch the refine
        # cannot rescue pays the unbounded cold re-solve.
        if self.imbalance_guardrail is not None and not stats.cold_start:
            allowance = self.imbalance_guardrail * max(
                stats.imbalance_bound, 1.0
            )
            if (
                stats.max_mean_imbalance > allowance
                and not stats.refined
                and self.refine_iters > 0
            ):
                choice = self._dispatch_warm_refine(lags, choice)
                stats.refined = True
                self._fill_quality_stats(stats, choice, lags, bound,
                                         exact_bincount)
            if stats.max_mean_imbalance > allowance:
                stats.guardrail_tripped = True
                stats.cold_start = True
                choice = self._cold_solve(lags)
                self._fill_quality_stats(stats, choice, lags, bound,
                                         exact_bincount)

        if prev_for_churn is not None:
            stats.churn = int((choice != prev_for_churn).sum())
        self._prev_choice = choice
        self.last_stats = stats
        return choice

    def _bucket(self, P: int) -> int:
        """Padded refine shape: pow2 bucket on accelerators (sort-network
        friendly), the finer 4096-chunk on CPU where a pow2 pad wastes up
        to ~2x sort work — either way the jit cache stays bounded across
        slowly-varying P."""
        return pad_chunk(P) if jax.default_backend() == "cpu" else pad_bucket(P)

    def _cold_solve(self, lags: np.ndarray) -> np.ndarray:
        """Fresh greedy solve + quality refinement (unbounded-churn path;
        budget = ``cold_refine_iters``, 0 disables).

        The refined path runs solve -> refine as one chained async
        dispatch with a single device->host readback at the end — on a
        high-latency transport a host round-trip between the two would
        double the cold cost.  The lag payload is uploaded once and shared
        by both kernels."""
        C = self.num_consumers
        if self.cold_refine_iters <= 0 or C < 2:
            self._choice_dev = None
            return np.asarray(
                assign_stream(lags, num_consumers=C)
            ).astype(np.int32)
        P = lags.shape[0]
        if jax.default_backend() == "cpu":
            # Host-presort fast path (see assign_stream); device_put is
            # free on CPU so there is no shared-upload concern.
            choice0 = assign_stream(lags, num_consumers=C)
            payload = lags
        else:
            from .batched import totals_rank_bits_for

            payload, shift = stream_payload(lags)
            rb = totals_rank_bits_for(payload, C)
            # Pallas in-VMEM solve + refine in one dispatch when both
            # gates pass (same condition set as assign_stream; the
            # probe-once gate never probes here — warm-up/bench resolve
            # it off the rebalance path).
            from .rounds_pallas import (
                pallas_mode_for,
                rounds_pallas_available,
            )

            mode = pallas_mode_for(lags, C, -(-P // C))
            if mode and rounds_pallas_available(mode=mode):
                observe_pack_shift(
                    ("cold_pallas", lags.shape, C), (shift, mode)
                )
                narrow, refined_pad = _pallas_cold_chain(
                    payload, num_consumers=C, pack_shift=shift,
                    iters=self.cold_refine_iters, max_pairs=None,
                    bucket=self._bucket(P), wide=(mode == "wide"),
                )
                self._choice_dev = refined_pad
                return np.asarray(narrow).astype(np.int32)
            observe_pack_shift(("stream", lags.shape, C), (shift, rb))
            payload = jax.device_put(payload)  # ONE upload, both kernels
            choice0 = _stream_device(
                payload, num_consumers=C, pack_shift=shift,
                totals_rank_bits=rb,
            )
        narrow, refined_pad = _refine_chain(
            payload, choice0, num_consumers=C,
            iters=self.cold_refine_iters, max_pairs=None,
            bucket=self._bucket(P),
        )
        self._choice_dev = refined_pad
        return np.asarray(narrow).astype(np.int32)

    def _dispatch_warm_refine(
        self, lags: np.ndarray, choice: np.ndarray
    ) -> np.ndarray:
        """Split the exchange budget into rounds x pairs (rounds * pairs <=
        refine_iters keeps the documented churn bound 2 * refine_iters)
        and dispatch one bounded refine.

        The split is BALANCED (pairs ~ rounds ~ sqrt(budget)) rather than
        maximally wide: a single stubborn peak consumer sheds at most ONE
        partition per round (pairs are disjoint — it sits in one pair),
        so a wide-shallow split stalls on concentrated drift (measured on
        the drained-hot-partition scenario: q 1.17 wide vs 1.07 balanced
        at the same budget/churn), while a deep split still fixes broad
        drift because each round repairs `pairs` consumers at once.  The
        extra sequential rounds ride inside one executable, so the wall
        cost on the target transport stays RTT-dominated."""
        import math

        pairs = max(
            1,
            min(self.num_consumers // 2, math.isqrt(self.refine_iters)),
        )
        rounds = max(1, self.refine_iters // pairs)
        return self._warm_refine(lags, choice, rounds, pairs)

    def _warm_refine(
        self,
        lags: np.ndarray,
        choice: np.ndarray,
        iters: int,
        max_pairs: Optional[int],
    ) -> np.ndarray:
        """One transfer-lean refine dispatch: exact-shape lags up (int32
        when the range allows), narrow choice back; the start assignment
        is the device-resident padded copy when it is current (the usual
        warm case — no choice upload at all)."""
        P = lags.shape[0]
        B = self._bucket(P)
        choice_in = self._choice_dev
        if (
            choice_in is None
            or choice_in.shape[0] != B
            or int(choice_in.dtype.itemsize) != 4
        ):
            choice_in = np.pad(
                choice.astype(np.int32), (0, B - P), constant_values=-1
            )
        payload, _ = stream_payload(lags)
        # A lag-range drift across the int32 boundary changes the payload
        # dtype and retraces _refine_chain — log it like every other
        # recompile-on-drift path (the "shift" here is the upload width).
        observe_pack_shift(
            ("warm_refine", lags.shape, self.num_consumers),
            int(payload.dtype.itemsize) * 8,
        )
        narrow, refined_pad = _refine_chain(
            payload, choice_in, num_consumers=self.num_consumers,
            iters=iters, max_pairs=max_pairs, bucket=B,
        )
        self._choice_dev = refined_pad
        return np.asarray(narrow).astype(np.int32)

    def _fill_quality_stats(
        self,
        stats: StreamingStats,
        choice: np.ndarray,
        lags: np.ndarray,
        bound: float,
        exact_bincount: bool,
    ) -> None:
        """``bound`` and ``exact_bincount`` depend only on the epoch's lags
        — the caller computes them once per rebalance (a refined epoch
        evaluates stats twice, a guardrail trip three times)."""
        # Weighted bincount accumulates in f64: exact while the total lag
        # stays below 2^53 (every partial sum is then an exact integer) —
        # and ~10x faster than np.add.at at P=100k, which matters because
        # this evaluation IS the no-op-epoch fast path.  Beyond 2^53 fall
        # back to the exact scatter-add.
        if exact_bincount:
            totals = np.bincount(
                choice, weights=lags, minlength=self.num_consumers
            ).astype(np.int64)
        else:
            totals = np.zeros(self.num_consumers, dtype=np.int64)
            np.add.at(totals, choice.astype(np.int64), lags)
        counts = np.bincount(choice, minlength=self.num_consumers)
        mean = totals.mean()
        stats.max_mean_imbalance = float(totals.max() / mean) if mean else 1.0
        stats.count_spread = int(counts.max() - counts.min())
        # Count-constrained input bound (shared with the benchmark's
        # quality_ratio, see utils/observability.count_constrained_bound):
        # a count-forced peak is not read as warm-path quality drift.
        stats.imbalance_bound = bound

    def remap_members(
        self, old_to_new: np.ndarray, new_num_consumers: int
    ) -> None:
        """Carry warm state across a MEMBERSHIP change with bounded churn.

        Kafka rebalances are usually triggered by a member joining or
        leaving, and the reference — stateless — reshuffles from scratch
        (O(P) churn).  This keeps every surviving member's partitions in
        place: ``old_to_new[i]`` is consumer i's new dense index (-1 if it
        left; joiners simply extend the range).  Orphaned rows (owners who
        left) are re-seated by the next :meth:`rebalance`'s repair pass,
        and joiners fill via the same pass, so churn is bounded by
        ``orphans + capacity overflow + 2 * refine_iters`` instead of P.

        Call this between rebalances when the group membership changed;
        call :meth:`reset` instead to force a full re-solve.
        """
        old_to_new = np.ascontiguousarray(old_to_new, dtype=np.int32)
        if self._prev_choice is not None:
            prev = self._prev_choice
            valid = (prev >= 0) & (prev < old_to_new.shape[0])
            remapped = np.full(prev.shape[0], -1, dtype=np.int32)
            remapped[valid] = old_to_new[prev[valid]]
            self._prev_choice = remapped
        self._choice_dev = None  # device copy predates the remap
        self.num_consumers = int(new_num_consumers)

    def _repair_choice(self, choice: np.ndarray, lags: np.ndarray):
        """Seat unowned rows and enforce the count invariant host-side.

        After :meth:`remap_members`, some rows are orphaned (-1) and the
        surviving members' counts may exceed the new ceiling
        ``ceil(P / C)``.  Overflowing owners release their SMALLEST-lag
        rows (cheapest churn); then orphans, largest lag first, go to the
        least-loaded open consumer — the count-primary greedy rule over
        only the moving rows, O(moving * C) host work on a few hundred
        rows, versus a full device re-solve.  A final correction pass
        restores ``max - min <= 1`` exactly: with a non-divisible P the
        cap-based release alone leaves every survivor at ceil while the
        joiner cannot reach floor (e.g. P=401, C 4->5: cap 81, survivors
        81,81,81,81, joiner 77 — spread 4, found by the
        operation-sequence fuzz; a join can also arrive with no cap
        overflow at all, e.g. counts 2,2,2,2,2,0), and the count
        invariant is the reference's PRIMARY semantic, so it must hold
        even when the quality threshold later skips the refine.

        Owns its trigger: returns ``(choice unchanged, 0)`` when there is
        nothing to repair.  Returns ``(repaired choice, rows moved)``.
        """
        C = self.num_consumers
        P = lags.shape[0]
        cap = -(-P // C)  # ceil: no consumer may exceed the new ceiling
        counts = np.bincount(choice[choice >= 0], minlength=C)
        has_orphans = bool((choice < 0).any())
        if (
            not has_orphans
            and counts.max() <= cap
            and counts.max() - counts.min() <= 1
        ):
            return choice, 0
        original = choice
        choice = choice.copy()
        totals = np.zeros(C, dtype=np.int64)
        sel = choice >= 0
        np.add.at(totals, choice[sel], lags[sel])
        # Release overflow (smallest lag first -> cheapest to move).
        for c in np.nonzero(counts > cap)[0]:
            rows = np.nonzero(choice == c)[0]
            release = rows[np.argsort(lags[rows])][: counts[c] - cap]
            choice[release] = -1
            counts[c] = cap
            totals[c] -= lags[release].sum()
        def least_total_of(cand: np.ndarray) -> int:
            """THE seating tie-break: least total lag among the candidate
            mask (shared by orphan seating and spread correction)."""
            return int(
                np.argmin(np.where(cand, totals, np.iinfo(np.int64).max))
            )

        # Seat orphans: largest lag first, least (count, total) open seat.
        orphans = np.nonzero(choice < 0)[0]
        for p in orphans[np.argsort(-lags[orphans])]:
            open_mask = counts < cap
            key = np.where(open_mask, counts, np.iinfo(np.int64).max)
            who = least_total_of(key == key.min())
            choice[p] = who
            counts[who] += 1
            totals[who] += lags[p]
        # Spread correction: move the heaviest-count member's smallest-lag
        # row to the lightest member until max - min <= 1.  Bounded by
        # O(C * initial spread) single-row moves.
        while counts.max() - counts.min() > 1:
            donor = int(np.argmax(counts))
            recv = least_total_of(counts == counts.min())
            rows = np.nonzero(choice == donor)[0]
            p = rows[np.argmin(lags[rows])]
            choice[p] = recv
            counts[donor] -= 1
            counts[recv] += 1
            totals[donor] -= lags[p]
            totals[recv] += lags[p]
        return choice, int((choice != original).sum())

    def reset(self) -> None:
        """Drop warm state (force the next rebalance to solve cold)."""
        self._prev_choice = None
        self._choice_dev = None
