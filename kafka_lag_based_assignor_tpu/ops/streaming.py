"""Streaming rebalance with warm start — the BASELINE config-5 loop.

The reference is stateless across generations (SURVEY §2.4.8): every
rebalance re-solves from scratch, so two consecutive rebalances under
slightly drifted lags can reshuffle many partitions (assignment churn =
state invalidation for the consumers).  The streaming engine keeps the
previous choice vector as a warm start (SURVEY §5 checkpoint/resume row —
the optional warm start for the streaming-rebalance benchmark):

* **cold start / shape change / guardrail trip** — full solve with the
  transfer-lean :func:`..ops.batched.assign_stream` path plus a
  quality-refinement pass (churn is unbounded on cold paths anyway, and
  refining makes a guardrail trip actually restore near-bound quality
  rather than resetting to plain greedy's slack);
* **warm rebalance** — keep the previous assignment and run only the
  parallel pairwise-exchange refinement (:mod:`.refine`) under the NEW
  lags.  The count invariant is preserved by construction, imbalance is
  re-tightened, and only the exchanges' partitions move — ``refine_iters``
  is a total *exchange budget*, split into rounds of up to ``C // 2``
  concurrent disjoint exchanges, so churn is bounded by 2 x refine_iters
  instead of O(P).

* **membership change** — :meth:`StreamingAssignor.remap_members` carries
  the warm state across a join/leave (the usual rebalance trigger, where
  the stateless reference reshuffles O(P) partitions): surviving members
  keep their partitions, a host-side repair pass re-seats only orphaned
  rows and capacity overflow (count-primary greedy over the moving rows),
  and the exchange refinement re-tightens balance — churn bounded by
  ``repaired_rows + 2 * refine_iters``.

The churn/quality trade-off is configurable per rebalance via
``refine_iters``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..utils.observability import count_constrained_bound
from .batched import assign_stream
from .dispatch import ensure_x64
from .packing import pad_bucket, pad_chunk
from .refine import refine_assignment


@dataclass
class StreamingStats:
    cold_start: bool = False
    guardrail_tripped: bool = False  # warm quality fell past the guardrail
    churn: int = 0  # partitions whose consumer changed vs previous epoch
    repaired_rows: int = 0  # rows re-seated by the membership repair pass
    max_mean_imbalance: float = 1.0
    imbalance_bound: float = 1.0  # input-driven lower bound max_lag/mean
    count_spread: int = 0


class StreamingAssignor:
    """Stateful engine for one topic's periodic rebalance at fixed scale.

    ``imbalance_guardrail`` bounds how far the bounded-churn warm path may
    drift from balance across epochs: after a warm rebalance, if
    ``max_mean_imbalance > guardrail * max(input bound, 1)`` the epoch is
    re-solved cold — greedy plus a refinement pass, so the trip restores
    near-bound quality (unbounded churn for that epoch).  ``None``
    disables the guardrail (pure bounded-churn behavior).
    """

    def __init__(
        self,
        num_consumers: int,
        refine_iters: int = 128,
        imbalance_guardrail: Optional[float] = None,
        # Refinement budget for cold solves (initial epoch, shape change,
        # guardrail trip): churn is unbounded on those paths anyway, and
        # refining makes a guardrail trip actually restore near-bound
        # quality instead of resetting to plain greedy's slack (observed
        # ratio 1.63 unrefined vs ~1.0x refined on a lognormal soak).
        # 0 disables (cold solves return plain greedy).
        cold_refine_iters: int = 64,
    ):
        self.num_consumers = int(num_consumers)
        self.refine_iters = int(refine_iters)
        self.cold_refine_iters = int(cold_refine_iters)
        if imbalance_guardrail is not None and imbalance_guardrail < 1.0:
            raise ValueError(
                f"imbalance_guardrail={imbalance_guardrail} must be >= 1.0"
            )
        self.imbalance_guardrail = imbalance_guardrail
        self._prev_choice: Optional[np.ndarray] = None
        self.last_stats = StreamingStats()

    def rebalance(self, lags: np.ndarray) -> np.ndarray:
        """Produce choice int32[P] for the current lag vector."""
        ensure_x64()  # int64 lags would silently downcast to int32 otherwise
        lags = np.ascontiguousarray(lags, dtype=np.int64)
        P = lags.shape[0]
        stats = StreamingStats()

        prev = self._prev_choice
        if prev is None or prev.shape[0] != P:
            stats.cold_start = True
            choice = self._cold_solve(lags)
            prev_for_churn = None
        elif self.refine_iters <= 0:
            # Zero exchange budget: keep the previous assignment untouched
            # up to MEMBERSHIP repair, which is not an exchange — orphaned
            # rows must be owned regardless of budget (the churn bound
            # reads repaired_rows + 2 * refine_iters).
            prev_for_churn = prev
            choice, stats.repaired_rows = self._repair_choice(prev, lags)
        else:
            # Membership repair: after remap_members the previous choice
            # may hold orphaned rows (-1, owner left) or counts above the
            # new ceiling (group shrank/grew).  Re-seat ONLY the moving
            # rows host-side before the exchange refinement.
            prev_for_churn = prev  # churn counts repair moves too
            prev, stats.repaired_rows = self._repair_choice(prev, lags)
            # refine_iters is the exchange budget: rounds * pairs <= budget
            # keeps the documented churn bound of 2 * refine_iters.
            pairs = max(1, min(self.num_consumers // 2, self.refine_iters))
            rounds = max(1, self.refine_iters // pairs)
            choice = self._refine_padded(lags, prev, rounds, pairs)

        self._fill_quality_stats(stats, choice, lags)

        # Quality guardrail: a warm epoch whose imbalance drifted past the
        # allowance re-solves cold (the churn bound intentionally yields).
        if (
            self.imbalance_guardrail is not None
            and not stats.cold_start
            and stats.max_mean_imbalance
            > self.imbalance_guardrail * max(stats.imbalance_bound, 1.0)
        ):
            stats.guardrail_tripped = True
            stats.cold_start = True
            choice = self._cold_solve(lags)
            self._fill_quality_stats(stats, choice, lags)

        if prev_for_churn is not None:
            stats.churn = int((choice != prev_for_churn).sum())
        self._prev_choice = choice
        self.last_stats = stats
        return choice

    def _cold_solve(self, lags: np.ndarray) -> np.ndarray:
        """Fresh greedy solve + quality refinement (unbounded-churn path;
        budget = ``cold_refine_iters``, 0 disables)."""
        choice = np.asarray(
            assign_stream(lags, num_consumers=self.num_consumers)
        ).astype(np.int32)
        if self.cold_refine_iters <= 0 or self.num_consumers < 2:
            return choice
        return self._refine_padded(
            lags, choice, self.cold_refine_iters, None
        )

    def _refine_padded(
        self,
        lags: np.ndarray,
        choice: np.ndarray,
        iters: int,
        max_pairs: Optional[int],
    ) -> np.ndarray:
        """THE pad-and-refine call both the warm path and the cold solve
        use.  Pads so the refine kernel's P-sized sorts hit fast shapes
        and the jit cache stays bounded across slowly-varying P: the
        power-of-two bucket on accelerators (sort-network-friendly), the
        fine 4096-chunk on CPU where a pow2 pad wastes up to ~2x sort
        work but the cache still needs bounding."""
        import jax

        P = lags.shape[0]
        B = pad_chunk(P) if jax.default_backend() == "cpu" else pad_bucket(P)
        lags_p = np.zeros(B, dtype=np.int64)
        lags_p[:P] = lags
        valid = np.zeros(B, dtype=bool)
        valid[:P] = True
        choice_p = np.full(B, -1, dtype=np.int32)
        choice_p[:P] = choice
        refined, _, _ = refine_assignment(
            lags_p, valid, choice_p, num_consumers=self.num_consumers,
            iters=iters, max_pairs=max_pairs,
        )
        return np.asarray(refined)[:P]

    def _fill_quality_stats(
        self, stats: StreamingStats, choice: np.ndarray, lags: np.ndarray
    ) -> None:
        totals = np.zeros(self.num_consumers, dtype=np.int64)
        np.add.at(totals, choice.astype(np.int64), lags)
        counts = np.bincount(choice, minlength=self.num_consumers)
        mean = totals.mean()
        stats.max_mean_imbalance = float(totals.max() / mean) if mean else 1.0
        stats.count_spread = int(counts.max() - counts.min())
        # Count-constrained input bound (shared with the benchmark's
        # quality_ratio, see utils/observability.count_constrained_bound):
        # a count-forced peak is not read as warm-path quality drift.
        stats.imbalance_bound = count_constrained_bound(
            lags, self.num_consumers
        )

    def remap_members(
        self, old_to_new: np.ndarray, new_num_consumers: int
    ) -> None:
        """Carry warm state across a MEMBERSHIP change with bounded churn.

        Kafka rebalances are usually triggered by a member joining or
        leaving, and the reference — stateless — reshuffles from scratch
        (O(P) churn).  This keeps every surviving member's partitions in
        place: ``old_to_new[i]`` is consumer i's new dense index (-1 if it
        left; joiners simply extend the range).  Orphaned rows (owners who
        left) are re-seated by the next :meth:`rebalance`'s repair pass,
        and joiners fill via the same pass, so churn is bounded by
        ``orphans + capacity overflow + 2 * refine_iters`` instead of P.

        Call this between rebalances when the group membership changed;
        call :meth:`reset` instead to force a full re-solve.
        """
        old_to_new = np.ascontiguousarray(old_to_new, dtype=np.int32)
        if self._prev_choice is not None:
            prev = self._prev_choice
            valid = (prev >= 0) & (prev < old_to_new.shape[0])
            remapped = np.full(prev.shape[0], -1, dtype=np.int32)
            remapped[valid] = old_to_new[prev[valid]]
            self._prev_choice = remapped
        self.num_consumers = int(new_num_consumers)

    def _repair_choice(self, choice: np.ndarray, lags: np.ndarray):
        """Seat unowned rows and enforce the count invariant host-side.

        After :meth:`remap_members`, some rows are orphaned (-1) and the
        surviving members' counts may exceed the new ceiling
        ``ceil(P / C)``.  Overflowing owners release their SMALLEST-lag
        rows (cheapest churn); then orphans, largest lag first, go to the
        least-loaded open consumer — the count-primary greedy rule over
        only the moving rows, O(moving * C) host work on a few hundred
        rows, versus a full device re-solve.

        Owns its trigger: returns ``(choice unchanged, 0)`` when there is
        nothing to repair.  Returns ``(repaired choice, rows moved)``.
        """
        C = self.num_consumers
        P = lags.shape[0]
        cap = -(-P // C)  # ceil: no consumer may exceed the new ceiling
        counts = np.bincount(choice[choice >= 0], minlength=C)
        has_orphans = bool((choice < 0).any())
        if not has_orphans and counts.max() <= cap:
            return choice, 0
        original = choice
        choice = choice.copy()
        totals = np.zeros(C, dtype=np.int64)
        sel = choice >= 0
        np.add.at(totals, choice[sel], lags[sel])
        # Release overflow (smallest lag first -> cheapest to move).
        for c in np.nonzero(counts > cap)[0]:
            rows = np.nonzero(choice == c)[0]
            release = rows[np.argsort(lags[rows])][: counts[c] - cap]
            choice[release] = -1
            counts[c] = cap
            totals[c] -= lags[release].sum()
        # Seat orphans: largest lag first, least (count, total) open seat.
        orphans = np.nonzero(choice < 0)[0]
        for p in orphans[np.argsort(-lags[orphans])]:
            open_mask = counts < cap
            key = np.where(open_mask, counts, np.iinfo(np.int64).max)
            cand = key == key.min()
            who = int(
                np.argmin(np.where(cand, totals, np.iinfo(np.int64).max))
            )
            choice[p] = who
            counts[who] += 1
            totals[who] += lags[p]
        return choice, int((choice != original).sum())

    def reset(self) -> None:
        """Drop warm state (force the next rebalance to solve cold)."""
        self._prev_choice = None
